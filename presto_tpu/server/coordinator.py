"""Coordinator process: SQL frontend, discovery, stage scheduling,
exchange client, paged client protocol.

Reference parity: the coordinator half of SURVEY.md §1/§3 —
``POST /v1/statement`` with paged ``nextUri`` results (L0),
parse/plan/fragment (L1-L2), stage scheduling to workers over the task
protocol (L3), the consumer side of the paged exchange
(``ExchangeClient``), embedded discovery with TTL-expiring worker
announcements and failure detection (SURVEY.md §5.3).

Round-1 multihost shape documented in server.scheduler.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import sys
import threading
import time
import traceback
import urllib.error
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.exec.staging import stage_page
from presto_tpu.exec.stats import QueryStats, StageStats, TaskStats
from presto_tpu.plan import nodes as N
from presto_tpu.server import pages_wire, rpc, task_ids
from presto_tpu.server.journal import CoordinatorJournal
from presto_tpu.server.protocol import FragmentSpec
from presto_tpu.server.scheduler import (
    assign_ranges,
    plan_stage,
    select_exchange_edges,
    select_exchange_transport,
    stable_workers,
)
from presto_tpu.server.spool import ExchangeSpool
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY, DistributionStat
from presto_tpu.utils.tracing import Trace

log = logging.getLogger("presto_tpu.coordinator")

#: announcement TTL: a worker silent this long is dropped (reference:
#: discovery TTL expiry removing dead nodes from scheduling)
NODE_TTL_S = 10.0
RESULT_PAGE_ROWS = 4096
#: completed queries kept for /v1/query + system.runtime (reference:
#: query.max-history); running/queued queries are never evicted
MAX_QUERY_HISTORY = 100
#: a finished query whose client has NOT drained its results survives
#: eviction this long past end_time
DRAIN_GRACE_S = 900.0


class NoLiveWorkers(RuntimeError):
    """Every candidate worker is dead or circuit-open — the trigger
    for coordinator-local fallback execution."""


class MemoryPressureKilled(RuntimeError):
    """The cluster memory manager killed this query (victim + policy
    in the message) and no re-admission budget remained."""


def _prepare_text(sql: str, name: str) -> str:
    """The inner statement TEXT of ``PREPARE name FROM <statement>`` —
    what the added-prepare response header carries (the parse tree has
    already validated it; the client replays the text verbatim)."""
    import re

    m = re.match(
        r"\s*prepare\s+" + re.escape(name) + r"\s+from\s+(.*)$",
        sql,
        re.IGNORECASE | re.DOTALL,
    )
    if not m:
        raise RuntimeError(f"malformed PREPARE statement: {sql!r}")
    return m.group(1).strip().rstrip(";")


def _is_draining_503(exc) -> bool:
    """A DRAINING worker's task rejection: recoverable AND free — the
    task was never created, so re-routing it is not a recovery and
    must neither charge the retry budget nor penalize the breaker."""
    return (
        isinstance(exc, urllib.error.HTTPError) and exc.code == 503
    )


@dataclasses.dataclass
class _WorkerNode:
    node_id: str
    uri: str
    last_seen: float
    version: str = "presto-tpu-0.1"
    coordinator: bool = False
    state: str = "ACTIVE"
    #: preemptible capacity (elastic pools): gather/merge stages are
    #: placed on stable nodes when any exist (scheduler.stable_workers)
    preemptible: bool = False
    #: slice identity announced on discovery (in-slice collective
    #: shuffle): workers sharing one non-empty slice id are co-located
    #: — the scheduler plans their partitioned exchanges as device
    #: collectives (scheduler.select_exchange_transport); "" = unknown
    #: topology, HTTP only
    slice_id: str = ""
    #: device coordinates announced beside the slice id (topology
    #: observability only)
    device_coords: tuple = ()
    #: the worker's boot-time device probe (utils/devicediag.py):
    #: which phase failed (enumerate/compile/execute), the error
    #: class, and any fallback decision — surfaced verbatim on
    #: system.runtime.nodes so a silently-degraded node is visible
    #: from the coordinator
    backend_diag: dict = dataclasses.field(default_factory=dict)


class _Query:
    def __init__(self, qid: str, sql: str):
        self.qid = qid
        self.sql = sql
        self.state = "QUEUED"
        self.error: Optional[str] = None
        self.columns: List[dict] = []
        self.rows: List[list] = []
        self.done = threading.Event()
        # observability: per-query span tree + the QueryInfo stats
        # rollup served at GET /v1/query/{id}
        self.trace = Trace()
        self.stats = QueryStats(
            query_id=qid, sql=sql, create_time=time.time(),
            trace_id=self.trace.trace_id, trace=self.trace,
        )
        self._stats_lock = threading.Lock()
        self._stage_seq = itertools.count(0)
        #: logical-task sequence for deterministic attempt ids
        #: (server.task_ids — the spool recovery key space)
        self._task_seq = itertools.count(0)
        #: adaptive partitioned->broadcast handoff: build-subtree
        #: fingerprint -> (FilterSummary, summarized keys) observed by
        #: the probe stage, reused by the replicated join's
        #: dynamic-filter plane instead of a second summary stage
        self._df_probe_reuse: Dict[str, tuple] = {}
        self._task_stage: Dict[str, StageStats] = {}
        self._recorded: set = set()
        self._adopted = False  # registered in the runner's QueryHistory
        self._plan_root = None  # pruned plan root (distributed EXPLAIN)
        #: output_rows already holds the real result count (distributed
        #: EXPLAIN ANALYZE, where q.rows is plan text, not the result)
        self._output_rows_final = False
        #: the client consumed the last result page (or the error):
        #: history eviction must not drop a query mid-pagination
        self._drained = False
        #: per-query task-retry budget (None until first use: the
        #: session default is read lazily so SET SESSION applies)
        self._retry_budget: Optional[int] = None
        #: task ids of speculative (backup) attempts, for accounting
        self._speculative: set = set()
        #: cluster memory manager kill notice (the MEMORY_PRESSURE
        #: message): set by _apply_memory_kill when the victim may
        #: re-admit; consumed by the restart loop's re-admission lane
        self._mem_kill: Optional[str] = None
        #: the admission high-water hold PARKED this query before
        #: dispatch (memory governance): a parked statement must not
        #: also accrue the micro-batch window after release — the
        #: batch window starts at dispatch-eligibility, not submit
        self._admission_parked = False
        #: prepared statements supplied by the CLIENT on this request
        #: (X-Presto-Prepared-Statement headers — the client owns the
        #: map; see server.protocol)
        self.prepared: Dict[str, str] = {}
        #: response-header payloads: (name, sql) registered by a
        #: PREPARE in this query / name dropped by a DEALLOCATE
        self.added_prepare: Optional[Tuple[str, str]] = None
        self.deallocated_prepare: Optional[str] = None
        #: serving-plane result reuse (server/result_cache.py): the
        #: minted fingerprint×literal key, the statement it was minted
        #: from (a background refresh re-plans it), and the plan whose
        #: pinned snapshot handles key the stored entry
        self._rc_key: Optional[tuple] = None
        self._rc_stmt = None
        self._rc_plan = None

    def fail(self, error: str) -> None:
        """Terminal rejection/kill close-out — one place for the
        state/stats/clock contract (rejected and killed queries never
        reach _finish_query_stats)."""
        self.state = "FAILED"
        self.error = error
        self.stats.state = "FAILED"
        self.stats.error = error
        self.stats.end_time = time.time()


class _MicrobatchMember:
    """One statement parked in the batch queue: its cached canonical
    plan (bound values included), its stats sink, and the event the
    leader signals when the batched dispatch delivered (or dropped)
    this member's lane.

    ``claim()`` is the exactly-once ownership handshake: the LEADER
    claims every member before dispatching, an abandoning FOLLOWER
    (its belt-timeout fired) claims before falling back to scalar —
    whoever claims serves the member, so a late leader can never
    write batch results/stats into a query its own thread already
    answered scalar."""

    __slots__ = ("plan", "qs", "result", "event", "joined_at", "_own")

    def __init__(self, plan, qs):
        self.plan = plan
        self.qs = qs
        self.result = None
        self.event = threading.Event()
        self.joined_at = time.monotonic()
        self._own = threading.Lock()

    def claim(self) -> bool:
        return self._own.acquire(blocking=False)


class _MicrobatchGroup:
    def __init__(self, key: str):
        self.key = key
        self.members: List[_MicrobatchMember] = []
        #: set when the group hits microbatch_max — wakes the leader
        #: before the window expires
        self.full = threading.Event()
        self.closed = False


class MicrobatchQueue:
    """Coordinator-side micro-batch serving plane: the batch queue in
    front of local dispatch (ROADMAP item 1 — many point lookups, one
    device dispatch).

    The FIRST statement of a canonical fingerprint to reach dispatch
    becomes its group's leader: it holds the window open for
    ``microbatch_wait_ms`` (or until ``microbatch_max`` members join),
    then answers the whole group with ONE vmapped device dispatch
    (LocalQueryRunner.execute_plan_microbatch; the batch-axis stacking
    and the vmapped compile entry live in plan/canonical.py).
    Followers park on an event and receive their lane's result. Any
    member whose lane fell out of the batch — trace failure,
    non-hoistable shape, capacity overflow, over-capacity output —
    re-runs the existing scalar path on its own thread: batching can
    cost a wait, never a wrong answer or a failed query."""

    def __init__(self, runner):
        self._runner = runner
        self._lock = threading.Lock()
        self._groups: Dict[str, _MicrobatchGroup] = {}

    def execute(
        self,
        key: str,
        plan,
        qs,
        wait_ms: float,
        max_size: int,
        no_wait: bool = False,
    ):
        """-> QueryResult, or None (the caller runs the scalar path).

        ``no_wait``: the statement already waited once (PR 9's
        admission high-water hold parked it before dispatch) — it must
        not accrue the batch window on top of the hold, so it neither
        opens nor joins a window (the batch window starts at
        dispatch-eligibility, not submit)."""
        if no_wait:
            return None
        member = _MicrobatchMember(plan, qs)
        with self._lock:
            g = self._groups.get(key)
            if (
                g is not None
                and not g.closed
                and len(g.members) < max_size
            ):
                g.members.append(member)
                if len(g.members) >= max_size:
                    g.full.set()
                leader = False
            else:
                g = _MicrobatchGroup(key)
                g.members.append(member)
                self._groups[key] = g
                leader = True
        if not leader:
            # the leader delivers this lane's result at dispatch; the
            # timeout is a belt — a wedged leader (a minutes-long cold
            # vmapped compile on a tunneled backend) must never wedge
            # a query. On timeout the follower CLAIMS itself: claim
            # won -> the leader will skip this lane, scalar path here;
            # claim lost -> the leader owns the lane and always
            # delivers (finally below), so wait it out
            if not member.event.wait(wait_ms / 1000.0 + 60.0):
                if member.claim():
                    self._note_wait(member)
                    return None
                member.event.wait()
            self._note_wait(member)
            return member.result
        g.full.wait(wait_ms / 1000.0)
        with self._lock:
            g.closed = True
            if self._groups.get(key) is g:
                del self._groups[key]
            members = list(g.members)
        self._note_wait(member)
        # exactly-once ownership: the leader claims every member it
        # will serve; one whose claim is lost already abandoned (it is
        # answering itself scalar) and must not be touched again
        claimed = [m for m in members if m.claim()]
        if len(claimed) < 2:
            for m in claimed:
                if m is not member:
                    m.event.set()  # result stays None: scalar path
            return None  # nobody to share the dispatch with
        results = [None] * len(claimed)
        try:
            try:
                results = self._runner.execute_plan_microbatch(
                    [m.plan for m in claimed],
                    [m.qs for m in claimed],
                )
            except Exception:
                # a batch-plane bug must never fail a member:
                # everyone falls back to the scalar path
                log.exception(
                    "micro-batch dispatch failed; members fall back"
                )
        finally:
            # delivery is unconditional — followers whose claim the
            # leader won are parked on this event
            for m, r in zip(claimed, results):
                m.result = r
            for m in claimed:
                m.event.set()
        return member.result

    @staticmethod
    def _note_wait(member: _MicrobatchMember) -> None:
        REGISTRY.distribution("serving.batch_wait_ms").add(
            (time.monotonic() - member.joined_at) * 1000.0
        )


class CoordinatorServer:
    """Coordinator: embedded discovery + dispatcher + exchange client.

    Admission control (reference: DispatchManager + resource-group
    queueing, SURVEY.md §2.1 "Dispatch/queue"): at most
    ``max_concurrent_queries`` run at once; up to ``max_queued_queries``
    wait; beyond that submissions are REJECTED immediately instead of
    accumulating unbounded threads."""

    def __init__(
        self,
        port: int = 0,
        catalogs=None,
        session=None,
        max_concurrent_queries: int = 4,
        max_queued_queries: int = 100,
        config=None,
        resource_groups=None,
    ):
        from presto_tpu.exec.local_runner import LocalQueryRunner
        from presto_tpu.utils.memory import MemoryPool, parse_bytes

        # memory accounting ALWAYS on (reference: MemoryPool +
        # ClusterMemoryManager kill-largest policy; limit from tier-1
        # config query.max-memory-per-node)
        limit = parse_bytes(
            (config.get("query.max-memory-per-node") if config else None)
            or "8GB"
        )
        self.memory_pool = MemoryPool(
            limit, kill_largest=self._kill_largest_query
        )
        self.memory_pool.node_id = "coordinator"
        # gather-side staging knobs: the coordinator's embedded runner
        # stages gathered pages and coordinator-local scans through the
        # same device-resident split cache / prefetch pipeline the
        # workers use (tier-1: staging.cache-bytes, staging.prefetch-depth)
        from presto_tpu.exec.staging import DEFAULT_CACHE_BYTES

        cache_raw = (
            config.get("staging.cache-bytes") if config else None
        )
        self.local = LocalQueryRunner(
            catalogs=catalogs, session=session,
            memory_pool=self.memory_pool,
            staging_cache_bytes=(
                parse_bytes(cache_raw)
                if cache_raw is not None
                else DEFAULT_CACHE_BYTES
            ),
            # history-based statistics (plan/history.py): the
            # coordinator owns the store — queries complete here, and
            # estimate_rows reads it during planning
            history_path=(
                config.get("history.path") if config else None
            ),
            history_max_entries=int(
                config.get("history.max-entries", 256) if config else 256
            ),
        )
        prefetch = (
            config.get("staging.prefetch-depth") if config else None
        )
        if prefetch is not None:
            self.local.session.set(
                "staging_prefetch_depth", int(prefetch)
            )
        # distributed dynamic filtering (exec/dynfilter.py): tier-1
        # keys seed the session defaults, like the staging knobs
        df_wait = (
            config.get("dynamic-filtering.wait-ms") if config else None
        )
        if df_wait is not None:
            self.local.session.set(
                "dynamic_filtering_wait_ms", float(df_wait)
            )
        df_ndv = (
            config.get("dynamic-filtering.ndv-limit") if config else None
        )
        if df_ndv is not None:
            self.local.session.set(
                "dynamic_filtering_ndv_limit", int(df_ndv)
            )
        self.local.cluster = self  # system.runtime.nodes source
        # config-wired query-completed JSONL sink (the env-var hook in
        # LocalQueryRunner covers bench/embedded runs; add_listener
        # dedups same-file sinks, so both naming one path is fine)
        event_log = config.get("event-listener.path") if config else None
        if event_log:
            from presto_tpu.exec.stats import JsonlQueryEventListener

            self.local.history.add_listener(
                JsonlQueryEventListener(event_log)
            )
        # slow-query JSONL sidecar: queries over the threshold append
        # their EXPLAIN ANALYZE text + canonical plan fingerprint
        # (exec/stats.SlowQueryLog; default off)
        slow_ms = (
            config.get("slow-query.threshold-ms") if config else None
        )
        if slow_ms is not None and float(slow_ms) > 0:
            from presto_tpu.exec.stats import SlowQueryLog

            slow_path = (config.get("slow-query.path") if config else None) or (
                (event_log + ".slow") if event_log else None
            )
            if slow_path:
                self.local.history.add_listener(
                    SlowQueryLog(slow_path, float(slow_ms))
                )
        # per-operator observability gate (exec/stats.OperatorStats):
        # tier-1 seed for the enable_operator_stats session default
        opstats = (
            config.get("operator-stats.enabled") if config else None
        )
        if opstats is not None:
            self.local.session.set(
                "enable_operator_stats", bool(opstats)
            )
        self.workers: Dict[str, _WorkerNode] = {}
        self.queries: Dict[str, _Query] = {}
        # fault-tolerance plane: one RPC policy for every
        # coordinator->worker call, and per-worker circuit breakers
        # (consecutive-failure scoring) folded into scheduling
        self._rpc_policy = rpc.RpcPolicy.from_config(config)
        self.breakers: Dict[str, rpc.CircuitBreaker] = {}
        self._breaker_threshold = int(
            config.get("failure-detector.threshold", 3) if config else 3
        )
        self._breaker_open_s = float(
            config.get("failure-detector.open-s", 5.0) if config else 5.0
        )
        fault_spec = (
            config.get("fault-injection.spec") if config else None
        )
        if fault_spec:
            faults.configure(fault_spec)
        # fault-tolerant execution: tier-1 retry-policy seeds the
        # session default; the durable-exchange spool (shared dir with
        # the workers) backs TASK-level recovery and the occupancy row
        # in system.runtime.caches
        rp = config.get("retry-policy") if config else None
        if rp is not None:
            self.local.session.set("retry_policy", rp)
        # ICI-native collective shuffle (server/exchange_spi.py):
        # tier-1 exchange.ici-enabled seeds the session default; off
        # (the default) keeps the HTTP shuffle bit-exact
        ici_on = (
            config.get("exchange.ici-enabled") if config else None
        )
        if ici_on is not None:
            self.local.session.set(
                "exchange_ici_enabled", bool(ici_on)
            )
        # single-program collective stages: tier-1
        # exchange.single-program seeds the session default (on by
        # default; only meaningful when the ICI gate above is on)
        sp_on = (
            config.get("exchange.single-program") if config else None
        )
        if sp_on is not None:
            self.local.session.set(
                "exchange_single_program", bool(sp_on)
            )
        # the coordinator's own slice announcement — the ICI gather
        # edge (exchange_spi.ici_gather) compares it to the root
        # stage's planned slice; config override first so tests can
        # pin topology, else derived from the local device mesh
        from presto_tpu.server import exchange_spi as _spi

        self.slice_id = str(
            (config.get("exchange.slice-id") if config else None)
            or _spi.default_slice_id()
        )
        # parameterized plan cache (plan/canonical.py): tier-1 keys
        # bound the statement-level LRU and seed the session default
        pce = config.get("plan.cache-entries") if config else None
        if pce is not None:
            self.local.plan_cache.resize(int(pce))
        pcen = config.get("plan.cache-enabled") if config else None
        if pcen is not None:
            self.local.session.set("enable_plan_cache", bool(pcen))
        # adaptive execution (epoch-versioned replanning + runtime
        # join-strategy switching): tier-1 keys seed the session
        # defaults, and the divergence factor also drives the history
        # store's epoch bumps (one factor, both layers)
        ad_on = config.get("adaptive.enabled") if config else None
        if ad_on is not None:
            self.local.session.set("adaptive_enabled", bool(ad_on))
        ad_factor = (
            config.get("adaptive.divergence-factor") if config else None
        )
        if ad_factor is not None:
            self.local.session.set(
                "adaptive_divergence_factor", float(ad_factor)
            )
            if self.local.history_store is not None:
                self.local.history_store.divergence_factor = max(
                    float(ad_factor), 1.0
                )
        # micro-batched serving: tier-1 serving.* keys seed the session
        # defaults (0 = off = bit-exact pre-batching dispatch), and the
        # ONE batch queue fronts this coordinator's local dispatch
        mb_wait = (
            config.get("serving.microbatch-wait-ms") if config else None
        )
        if mb_wait is not None:
            self.local.session.set(
                "microbatch_wait_ms", float(mb_wait)
            )
        mb_max = (
            config.get("serving.microbatch-max") if config else None
        )
        if mb_max is not None:
            self.local.session.set("microbatch_max", int(mb_max))
        self.microbatch = MicrobatchQueue(self.local)
        # streaming ingest lane (server/ingest.py): WAL'd micro-batch
        # commits with snapshot reads + incrementally-maintained
        # materialized views. Unset = none of it constructs — the
        # legacy INSERT/CTAS write path is bit-exact pre-ingest
        self.ingest = None
        ing_path = config.get("ingest.wal-path") if config else None
        mv_stale = (
            config.get("mview.max-staleness-s") if config else None
        )
        mv_inc = (
            config.get("mview.incremental-enabled") if config else None
        )
        if mv_stale is not None:
            self.local.mview_registry.max_staleness_s = float(mv_stale)
        if mv_inc is not None:
            self.local.mview_registry.incremental_enabled = bool(mv_inc)
        # serving-plane result reuse (server/result_cache.py): tier-1
        # result-cache.* / mview.auto-rewrite keys seed the session
        # gates; the ONE coordinator cache constructs unconditionally
        # (idle = zero bytes, zero lookups — the session gate decides
        # whether any path consults it) so the write fan-in and
        # system.runtime.caches always see a stable object
        from presto_tpu.server.result_cache import ResultCache

        rc_on = config.get("result-cache.enabled") if config else None
        if rc_on is not None:
            self.local.session.set("enable_result_cache", bool(rc_on))
        rc_stale = (
            config.get("result-cache.max-staleness-s")
            if config
            else None
        )
        if rc_stale is not None:
            self.local.session.set(
                "result_cache_max_staleness_s", float(rc_stale)
            )
        mv_rw = config.get("mview.auto-rewrite") if config else None
        if mv_rw is not None:
            self.local.session.set("mview_auto_rewrite", bool(mv_rw))
        rc_bytes = config.get("result-cache.bytes") if config else None
        self.result_cache = ResultCache(
            self.local,
            parse_bytes(rc_bytes)
            if rc_bytes is not None
            else 256 * 1024 * 1024,
            pool=self.memory_pool,
        )
        self.local.result_cache = self.result_cache
        # constructed in start(), AFTER the embedder registered its
        # catalogs (WAL replay resolves tables through them) and
        # alongside journal recovery — recover before serving
        lake_fb = (
            config.get("lakehouse.target-file-bytes") if config else None
        )
        self._ingest_cfg = (
            (
                ing_path,
                float(config.get("ingest.commit-interval-ms", 50.0)),
                {
                    "lakehouse_path": config.get("lakehouse.path"),
                    "lakehouse_target_file_bytes": (
                        parse_bytes(lake_fb)
                        if lake_fb is not None
                        else None
                    ),
                    "lakehouse_compaction_interval_s": float(
                        config.get("lakehouse.compaction.interval-s", 0.0)
                    ),
                    "lakehouse_compaction_min_files": int(
                        config.get("lakehouse.compaction.min-files", 4)
                    ),
                    "lakehouse_orphan_ttl_s": float(
                        config.get("lakehouse.orphan-ttl-s", 86400.0)
                    ),
                },
            )
            if ing_path
            else None
        )
        #: coordinator-global prepared statements (PREPARE over plain
        #: HTTP without a header-aware client); header-supplied maps on
        #: the request take precedence. Bounded: a serving fleet cycles
        #: thousands of ad-hoc names
        self._prepared_sql: "OrderedDict[str, str]" = OrderedDict()
        self._prepared_mu = threading.Lock()
        self.spool = ExchangeSpool.from_config(config)
        # durable coordinator state (server.journal): admitted/queued/
        # running queries + the prepared registry survive a bounce —
        # start() replays the journal and re-admits open queries
        jp = config.get("coordinator.journal-path") if config else None
        # multi-coordinator control plane: with coordinator.peers set,
        # the journal path is a SHARED directory — each coordinator
        # journals under its own subdirectory and publishes an
        # atomic-rename lease beside it (server/lease.py). Peers fold
        # each other's lease payloads into admission (memory arbiter,
        # resource-group quotas, QoS lanes) and claim+resume a dead
        # peer's journal on lease expiry. Without peers the lease
        # plane never constructs and the journal lives at the path
        # root — bit-exact single-coordinator behavior.
        self.coord_id = (
            (config.get("node.id") if config else None)
            or f"coord-{uuid.uuid4().hex[:6]}"
        )
        peers_raw = config.get("coordinator.peers") if config else None
        self._peer_uris = [
            u.strip()
            for u in str(peers_raw or "").split(",")
            if u.strip()
        ]
        self.lease = None
        self._control_dir = None
        self._lease_thread = None
        #: dead-peer journals this incarnation claimed / queries it
        #: resumed from them (nodes + failover observability)
        self.failover_claims = 0
        self.failover_resumed = 0
        if jp and self._peer_uris:
            from presto_tpu.server.lease import LeasePlane

            self._control_dir = jp
            self.journal = CoordinatorJournal(
                os.path.join(jp, self.coord_id)
            )
            self.lease = LeasePlane(
                jp,
                self.coord_id,
                ttl_s=float(
                    config.get("lease.ttl-s", 10.0) if config else 10.0
                ),
            )
        else:
            self.journal = CoordinatorJournal(jp) if jp else None
        #: queries re-admitted from the journal at this boot
        self.resumed_queries = 0
        #: old-boot qid -> this boot's qid: statement/query-info URLs
        #: minted by a dead incarnation stay routable after a restart
        self._qid_alias: Dict[str, str] = {}
        # elastic worker pool (server.pool): bounds + control cadence
        # from tier-1 config; attach_pool() supplies the provider and
        # starts the autoscaler
        self._pool_cfg = {
            "min_workers": int(
                config.get("pool.min-workers", 0) if config else 0
            ),
            "max_workers": int(
                config.get("pool.max-workers", 0) if config else 0
            ),
            "interval_s": float(
                config.get("pool.scale-interval-s", 1.0) if config else 1.0
            ),
            "scale_down_ticks": int(
                config.get("pool.scale-down-ticks", 3) if config else 3
            ),
            "cooldown_s": (
                float(config.get("pool.cooldown-s"))
                if config and config.get("pool.cooldown-s") is not None
                else None
            ),
        }
        self.autoscaler = None
        #: node ids spawned by the autoscaler that have not announced
        #: yet (the SCALING_UP pool state in system.runtime.nodes)
        self._pool_scaling: set = set()
        #: the autoscaler's last decision (nodes view)
        self.pool_decision = ""
        self._lock = threading.Lock()
        self._qid = itertools.count(1)
        #: per-boot nonce folded into every query id: deterministic
        #: task-attempt ids must never COLLIDE across coordinator
        #: restarts — a restarted coordinator's q_c1 minting the same
        #: attempt ids as its previous incarnation would let the shared
        #: spool serve (or interleave with) a dead run's pages inside
        #: the TTL window
        self._boot = uuid.uuid4().hex[:6]
        self._shutting_down = False
        self._admit = threading.Semaphore(max_concurrent_queries)
        self._max_queued = max_queued_queries
        self._pending = 0  # queued + running, admission-gated
        # weighted-fair resource groups (reference: resource-group
        # managers; SURVEY.md §2.1 "Dispatch/queue"). dict spec or a
        # path to an etc/resource-groups.json-style file; None = the
        # flat admission gate only.
        self.resource_groups = None
        if resource_groups is not None:
            from presto_tpu.server.resource_groups import (
                ResourceGroupManager,
            )

            self.resource_groups = (
                ResourceGroupManager.from_file(resource_groups)
                if isinstance(resource_groups, str)
                else ResourceGroupManager(resource_groups)
            )
            self.resource_groups.memory_usage_fn = self._group_memory
        # governance wiring for the coordinator's OWN pool: with the
        # gate on, over-budget local reservations (gather splices,
        # local fallback) join the blocked lane — visible to the
        # arbiter, resolvable by the killer, cancellable on readmit —
        # and the local split cache gets the host-spill budget, like
        # any worker. (Enforcement rides worker heartbeats; a
        # worker-less coordinator still bounds blocked waits by
        # memory.reserve-block-max-s.)
        if config and config.get("memory.governance-enabled", False):
            self.memory_pool.block_timeout_s = float(
                config.get("memory.reserve-block-max-s", 30.0)
            )
            spill_raw = config.get("memory.host-spill-bytes")
            if spill_raw is not None:
                self.local.split_cache.set_spill_budget(
                    parse_bytes(spill_raw)
                )
        # cluster memory arbiter (server/memory_arbiter.py): folds the
        # workers' heartbeat memory reports into one cluster view.
        # Accounting is ALWAYS on (resource-group quotas and
        # system.runtime.memory read it); enforcement — admission
        # high-water, per-query quotas, the low-memory killer — only
        # under memory.governance-enabled
        from presto_tpu.server.memory_arbiter import ClusterMemoryArbiter

        self.arbiter = ClusterMemoryArbiter(self, config)
        # tail-latency QoS plane (server/qos.py): priority admission
        # lanes + preempt-and-resume + per-group SLOs. Disabled
        # (default) the controller is never constructed and admission
        # stays the bit-exact legacy semaphore below
        self.qos = None
        if config and config.get("qos.enabled", False):
            from presto_tpu.server.qos import QosController

            self.qos = QosController(
                self, config, max_concurrent_queries
            )
        # multi-coordinator shared admission: live peers' lease
        # payloads fold into the memory view and the QoS lane columns
        # (both hooks default None — single-coordinator stays bit-exact)
        if self.lease is not None:
            self.arbiter.peer_reports_fn = self._peer_memory_reports
            if self.qos is not None:
                self.qos.peer_lanes_fn = self.peer_lane_occupancy

        # device-plane telemetry (utils/telemetry.py): federation of
        # the workers' /v1/metrics expositions behind
        # /v1/metrics/cluster, plus the bounded time-series sampler
        # backing system.runtime.metrics_history. Sampling and
        # persistence are off by default; the DEVICE counter plane
        # itself follows telemetry.enabled so a disabled cluster stays
        # bit-exact pre-telemetry.
        from presto_tpu.utils.telemetry import (
            DEVICE,
            MetricsFederation,
            MetricsSampler,
        )

        if config is not None:
            t_enabled = config.get("telemetry.enabled")
            if t_enabled is not None:
                DEVICE.set_enabled(bool(t_enabled))
        self.federation = MetricsFederation(
            lambda uri: rpc.call("GET", uri).body.decode(
                "utf-8", "replace"
            )
        )
        self.telemetry_sampler = None
        self._telemetry_interval_s = float(
            (config.get("telemetry.sample-interval-s", 0.0) or 0.0)
            if config
            else 0.0
        )
        if self._telemetry_interval_s > 0:
            self.telemetry_sampler = MetricsSampler(
                retention=int(
                    config.get("telemetry.retention", 4096) or 4096
                ),
                path=config.get("telemetry.path") or None,
            )
        self._telemetry_stop = threading.Event()
        self._telemetry_thread = None

        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        if self.lease is not None:
            # the serving URI exists only after the bind: peers reach
            # a claimed incarnation's clients through this lease field
            self.lease.uri = self.uri
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> "CoordinatorServer":
        # journal recovery BEFORE the server accepts requests: a client
        # reconnecting mid-pagination must never observe the window
        # between serving and alias registration (its old statement id
        # would 404 instead of resolving to the resumed run)
        if self.journal is not None:
            self._recover_from_journal()
        # ingest-lane recovery rides the same before-serving seam (and
        # AFTER catalog registration — WAL replay recreates tables
        # through the mounted connectors)
        if self._ingest_cfg is not None and self.ingest is None:
            from presto_tpu.server.ingest import IngestManager

            path, interval, lake_kw = self._ingest_cfg
            self.ingest = IngestManager(
                self.local, path, commit_interval_ms=interval, **lake_kw
            )
        # time-series sampler (telemetry.sample-interval-s > 0): a
        # daemon loop folding node scrapes into the metrics_history
        # ring. Started with the server, never before — an unstarted
        # coordinator must stay thread-free for in-process tests.
        if (
            self.telemetry_sampler is not None
            and self._telemetry_thread is None
        ):
            self._telemetry_thread = threading.Thread(
                target=self._telemetry_loop, daemon=True
            )
            self._telemetry_thread.start()
        # multi-coordinator lease: publish BEFORE serving (a peer must
        # never observe this incarnation's statements without a lease
        # to locate them through), then heartbeat + peer-watch loop
        if self.lease is not None and self._lease_thread is None:
            try:
                self.lease.renew(self._lease_state())
            except Exception:
                log.exception("initial lease publish failed")
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True
            )
            self._lease_thread.start()
        self._serve_thread.start()
        return self

    def shutdown(self) -> None:
        self._shutting_down = True
        self._telemetry_stop.set()
        if self.lease is not None:
            # clean shutdown WITHDRAWS the lease: peers see an absent
            # file, not an expiring one, and claim nothing
            self.lease.stop()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.ingest is not None:
            # stop the commit loop and fold the pending tail (the WAL
            # has it either way — replay would re-admit)
            self.ingest.close()
        # httpd.shutdown() handshakes with the serve_forever loop and
        # blocks forever if that loop never ran (server constructed but
        # not .start()ed, e.g. in-process submit()-only tests).
        if self._serve_thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()

    # ------------------------------------------- coordinator HA (journal)

    def _recover_from_journal(self) -> None:
        """Replay the admission journal: re-register the prepared
        registry, then re-admit every query that never reached a
        terminal state — under THIS boot's query ids (the per-boot qid
        nonce keeps the re-run's task-attempt ids collision-free
        against the dead incarnation's spooled pages), with the old id
        aliased so clients paginating across the bounce reconnect
        transparently. The replacement's submit frame is written (by
        ``submit``) BEFORE the old id's RESUMED close-out: a crash
        between the two can only duplicate a resume, never lose the
        query — at-least-once, the right failure for a query plane."""
        state = self.journal.replay()
        for name, text in state.prepared.items():
            with self._prepared_mu:
                self._prepared_sql[name] = text
                self._prepared_sql.move_to_end(name)
            try:
                from presto_tpu.sql import parse_statement

                self.local._prepared[name] = parse_statement(text)
            except Exception:
                pass  # EXECUTE re-parses from the registry text
        resumed: Dict[str, str] = {}
        # recovery re-admission must not lose to the queued-queries
        # gate: every replayed query was ALREADY admitted by the dead
        # incarnation under the same cap (replay runs before serving,
        # so nothing external races the temporary headroom)
        prev_max = self._max_queued
        self._max_queued = prev_max + len(state.open)
        try:
            for rec in state.open:
                old_qid = rec.get("qid", "")
                q = self.submit(
                    rec.get("sql", ""),
                    user=rec.get("user") or "presto_tpu",
                    prepared=rec.get("prepared") or {},
                )
                if q.done.is_set() and q.state == "FAILED" and (
                    q.error or ""
                ).startswith("Query rejected"):
                    # re-admission lost after all (no submit frame was
                    # written): close the old id out HONESTLY so the
                    # journal never claims a resume that is not running
                    self.journal.record_finish(old_qid, "FAILED")
                    log.warning(
                        "journal recovery: re-admission of %s rejected",
                        old_qid,
                    )
                    continue
                self.journal.record_finish(
                    old_qid, "RESUMED", resumed_as=q.qid
                )
                resumed[old_qid] = q.qid
                with self._lock:
                    self._qid_alias[old_qid] = q.qid
                q.resumed_from = old_qid
                self.resumed_queries += 1
                REGISTRY.counter("coordinator.resumed_queries").update()
                REGISTRY.counter("pool.resumed_queries").update()
                log.info(
                    "journal recovery: resumed %s as %s", old_qid, q.qid
                )
        finally:
            self._max_queued = prev_max
        # transitive restart aliases: ids minted N bounces ago chain
        # through every intermediate resume (the journal collapses the
        # chain to its open tip; map that tip to THIS boot's run)
        with self._lock:
            for old, tip in state.aliases.items():
                if tip in resumed:
                    self._qid_alias[old] = resumed[tip]
        if state.open:
            log.info(
                "journal recovery: re-admitted %d quer%s",
                len(state.open),
                "y" if len(state.open) == 1 else "ies",
            )

    def lookup_query(self, qid: str) -> Optional[_Query]:
        """Query by id, following restart aliases (a nextUri minted by
        a dead coordinator incarnation resolves to the resumed run)."""
        q = self.queries.get(qid)
        if q is None:
            new = self._qid_alias.get(qid)
            if new:
                q = self.queries.get(new)
        return q

    # --------------------------------- multi-coordinator control plane

    def _lease_state(self) -> dict:
        """This coordinator's lease payload (server/lease.py): the
        shared-state channel peers fold into THEIR admission view —
        open statement ids (plus aliases, so any peer can redirect a
        sprayed client), admission occupancy, the local-pool memory
        report, per-resource-group usage, and QoS-lane counts."""
        with self._lock:
            open_q = [
                (qid, getattr(q, "resource_group", None))
                for qid, q in self.queries.items()
                if not q.done.is_set()
            ]
            aliases = list(self._qid_alias.keys())
            pending = self._pending
        groups: Dict[str, dict] = {}
        for qid, g in open_q:
            if not g:
                continue
            d = groups.setdefault(g, {"qids": [], "local_bytes": 0})
            d["qids"].append(qid)
            d["local_bytes"] += self.memory_pool.used_bytes(qid)
        state = {
            "uri": self.uri,
            "boot": self._boot,
            "qids": [qid for qid, _ in open_q] + aliases,
            "running": pending,
            "local": self.arbiter.local_report(),
            "groups": groups,
        }
        if self.qos is not None:
            state["lanes"] = self.qos.lane_occupancy()
        return state

    def _peer_memory_reports(self) -> Dict[str, dict]:
        """Live peers' LOCAL-pool reports for the arbiter's cluster
        view, keyed ``coord:<id>``. Worker bytes are NOT re-folded
        (workers heartbeat every coordinator directly); the blocked
        lane is cleared — kill/unblock decisions stay local-evidence
        only, a stale peer payload must never nominate victims here."""
        out: Dict[str, dict] = {}
        for pl in self.lease.peers(live_only=True):
            rep = (pl.state or {}).get("local")
            if not isinstance(rep, dict):
                continue
            rep = dict(rep)
            rep["ts"] = pl.ts
            rep["blocked"] = []
            out[f"coord:{pl.owner}"] = rep
        return out

    def peer_lane_occupancy(self) -> Dict[str, dict]:
        """Live peers' QoS-lane occupancy keyed by peer id — the
        ``system.runtime.qos`` cluster fold (server/qos.py)."""
        out: Dict[str, dict] = {}
        for pl in self.lease.peers(live_only=True):
            lanes = (pl.state or {}).get("lanes")
            if isinstance(lanes, dict):
                out[pl.owner] = lanes
        return out

    def locate_peer(self, qid: str) -> str:
        """URI of the live peer serving ``qid`` (its lease payload
        lists it as open or aliased), or "". The statement route uses
        this to redirect a sprayed/failed-over client that landed on
        the wrong coordinator."""
        if self.lease is None:
            return ""
        for pl in self.lease.peers(live_only=True):
            st = pl.state or {}
            if qid in (st.get("qids") or ()):
                return str(st.get("uri") or pl.uri)
        return ""

    def _lease_loop(self) -> None:
        """Heartbeat + peer watch, at TTL/3 cadence (two missed beats
        never expire a healthy owner): renew the lease with fresh
        shared state, announce this coordinator to every peer (they
        surface it in system.runtime.nodes), and claim + fail over any
        peer whose lease expired."""
        interval = max(self.lease.ttl_s / 3.0, 0.05)
        policy = rpc.RpcPolicy(timeout_s=2.0, retries=0)
        while not self._shutting_down:
            try:
                self.lease.renew(self._lease_state())
            except Exception:
                log.exception("lease renewal failed")
            for peer in self._peer_uris:
                if self._shutting_down:
                    break
                try:
                    rpc.call_json(
                        "PUT",
                        peer + "/v1/announcement",
                        {
                            "node_id": self.coord_id,
                            "uri": self.uri,
                            "state": "ACTIVE",
                            "role": "coordinator",
                        },
                        policy=policy,
                    )
                except Exception:
                    pass  # the lease file is the durable signal
            try:
                self._scan_expired_peers()
            except Exception:
                log.exception("peer lease scan failed")
            deadline = time.monotonic() + interval
            while (
                not self._shutting_down
                and time.monotonic() < deadline
            ):
                time.sleep(min(0.05, interval))

    def _scan_expired_peers(self) -> None:
        if self._shutting_down:
            return
        for pl in self.lease.peers(live_only=False):
            if not self.lease.is_expired(pl):
                continue
            claim = self.lease.claim_expired(pl.owner)
            if claim is None:
                continue  # still live, retired, or another claimant won
            self.failover_claims += 1
            REGISTRY.counter("coordinator.failover_claims").update()
            log.warning(
                "lease of %s expired (age %.1fs): claimed its journal "
                "at fencing epoch %d",
                pl.owner,
                pl.age(),
                claim.epoch,
            )
            self._failover_from(pl.owner, claim)

    def _failover_from(self, owner: str, claim) -> None:
        """Replay a dead peer's claimed journal: re-admit every
        non-terminal query under THIS boot's qids, close the old ids
        out as RESUMED (with ``resumed_as``) in the DEAD journal, and
        alias them locally + in OUR journal so the dead incarnation's
        statement URIs resolve here — for clients landing directly
        (reconnect spray) and via any peer's alias redirect. Every
        write into claimed state is fence-checked: a superseded
        claimant abandons the failover instead of double-resuming."""
        from presto_tpu.server.lease import FencedError

        dead_dir = os.path.join(self._control_dir, owner)
        if not os.path.isdir(dead_dir):
            # peer never journaled (no queries): nothing to replay
            self.lease.retire(owner)
            return
        try:
            self.lease.check_fence(claim)
            dead = CoordinatorJournal(dead_dir)
            # stamp the claim INTO the claimed journal first: a
            # replayer (including the dead owner restarting) sees who
            # took the queries and at what epoch
            dead.record_claim(self.coord_id, claim.epoch)
            state = dead.replay()
        except FencedError:
            log.warning(
                "failover from %s abandoned: claim superseded", owner
            )
            return
        resumed: Dict[str, str] = {}
        # same temporary-headroom rule as _recover_from_journal: the
        # dead peer already admitted these under its own queue cap
        prev_max = self._max_queued
        self._max_queued = prev_max + len(state.open)
        try:
            for rec in state.open:
                old_qid = rec.get("qid", "")
                try:
                    self.lease.check_fence(claim)
                except FencedError:
                    log.warning(
                        "failover from %s fenced mid-replay "
                        "(resumed %d of %d)",
                        owner,
                        len(resumed),
                        len(state.open),
                    )
                    return
                q = self.submit(
                    rec.get("sql", ""),
                    user=rec.get("user") or "presto_tpu",
                    prepared=rec.get("prepared") or {},
                )
                if q.done.is_set() and q.state == "FAILED" and (
                    q.error or ""
                ).startswith("Query rejected"):
                    dead.record_finish(old_qid, "FAILED")
                    log.warning(
                        "failover: re-admission of %s rejected", old_qid
                    )
                    continue
                # our submit frame is on disk before the dead id's
                # RESUMED close-out — a crash between the two can only
                # duplicate a resume, never lose the query
                dead.record_finish(
                    old_qid, "RESUMED", resumed_as=q.qid
                )
                if self.journal is not None:
                    self.journal.record_alias(old_qid, q.qid)
                resumed[old_qid] = q.qid
                with self._lock:
                    self._qid_alias[old_qid] = q.qid
                q.resumed_from = old_qid
                self.failover_resumed += 1
                self.resumed_queries += 1
                REGISTRY.counter("coordinator.failover_resumed").update()
                REGISTRY.counter("coordinator.resumed_queries").update()
                log.info(
                    "failover: resumed %s (from %s) as %s",
                    old_qid,
                    owner,
                    q.qid,
                )
        finally:
            self._max_queued = prev_max
        # transitive aliases: ids the DEAD peer was itself serving by
        # alias chain land on this boot's runs too (journal writes
        # happen OUTSIDE the discovery lock)
        trans = [
            (old, resumed[tip])
            for old, tip in state.aliases.items()
            if tip in resumed
        ]
        with self._lock:
            for old, new in trans:
                self._qid_alias[old] = new
        if self.journal is not None:
            for old, new in trans:
                self.journal.record_alias(old, new)
        # adopt the dead peer's prepared registry (names a sprayed
        # client may EXECUTE against any coordinator)
        adopted = []
        for name, text in state.prepared.items():
            with self._prepared_mu:
                if name in self._prepared_sql:
                    continue
                self._prepared_sql[name] = text
                self._prepared_sql.move_to_end(name)
            adopted.append((name, text))
        if self.journal is not None:
            for name, text in adopted:
                self.journal.record_prepare(name, text)
        # fully failed over: drop the lease + claim files so restarts
        # of the dead owner rejoin fresh instead of re-claiming
        self.lease.retire(owner)
        if state.open:
            log.info(
                "failover from %s complete: resumed %d quer%s",
                owner,
                len(resumed),
                "y" if len(resumed) == 1 else "ies",
            )

    def _fault_kill(self) -> None:
        """Abrupt crash for the fault plane's ``kill_coordinator``
        action: drop the journal handle (no FAILED close-out may reach
        disk — the open frames are what a survivor resumes), leave the
        lease to EXPIRE (survivors must take the TTL path, exactly
        like a real crash), and close the socket so clients see a dead
        peer, not a clean error."""
        self._shutting_down = True
        self.journal = None
        try:
            if self._serve_thread.is_alive():
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass
        log.warning(
            "node=%s fault plane killed this coordinator", self.coord_id
        )

    # ------------------------------------------------ elastic worker pool

    def attach_pool(self, provider, **overrides) -> "object":
        """Wire a WorkerPoolProvider and start the autoscaler
        (``pool.min/max-workers`` bounds, ``pool.scale-interval-s``
        cadence; see server.pool). Keyword overrides replace the
        config-derived knobs — the test/bench hook."""
        from presto_tpu.server.pool import Autoscaler

        if self.autoscaler is not None:
            self.autoscaler.stop()
        cfg = dict(self._pool_cfg)
        cfg.update(overrides)
        self.autoscaler = Autoscaler(self, provider, **cfg).start()
        return self.autoscaler

    def load_snapshot(self) -> dict:
        """The autoscaler's control signals, read off the existing
        stats plane: admission queue depth, running-query count, and
        stage backlog (QUEUED/RUNNING tasks of live queries)."""
        with self._lock:
            qs = list(self.queries.values())
        queued = running = backlog = 0
        seen: set = set()
        for q in qs:
            if id(q) in seen:  # restart aliases map to one query
                continue
            seen.add(id(q))
            if q.done.is_set():
                continue
            if q.state == "QUEUED":
                queued += 1
            elif q.state == "RUNNING":
                running += 1
            with q._stats_lock:
                for st in q.stats.stages:
                    for t in st.tasks:
                        if t.state in ("QUEUED", "RUNNING"):
                            backlog += 1
        return {"queued": queued, "running": running, "backlog": backlog}

    def pool_state(self, w: _WorkerNode) -> str:
        """Pool lifecycle state of one node for system.runtime.nodes:
        DRAINING (scale-down/preemption in flight), SCALING_UP (spawned
        by the autoscaler, not yet announced-and-acknowledged), else
        STABLE."""
        if w.state == "DRAINING":
            return "DRAINING"
        if w.node_id in self._pool_scaling:
            return "SCALING_UP"
        return "STABLE"

    def _kill_largest_query(self, holders, requester):
        """ClusterMemoryManager policy: on pool exhaustion, abort the
        largest memory holder that is a *running query* (never the
        shared table cache, never the requester) and free its
        reservation so the requester can proceed."""
        candidates = {
            qid: b
            for qid, b in holders.items()
            if qid != requester
            and qid in self.queries
            and not self.queries[qid].done.is_set()
        }
        if not candidates:
            return None
        victim = max(candidates, key=candidates.get)
        vq = self.queries[victim]
        vq.fail(
            "Query killed by the cluster memory manager: largest "
            f"holder ({candidates[victim]}B) when the pool was exhausted"
        )
        vq.done.set()
        # cooperative cancel: the victim's thread fails at its next
        # reservation instead of silently recomputing to completion
        self.memory_pool.mark_dead(victim)
        REGISTRY.counter("coordinator.queries_killed_oom").update()
        return victim

    # -------------------------------------- cluster memory manager (kills)

    def _apply_memory_kill(
        self, victim: str, policy: str, reason: str
    ) -> None:
        """Apply one arbiter kill decision: journal it, cancel the
        victim cluster-wide through the workers' task-DELETE path with
        a MEMORY_PRESSURE error naming victim and policy, and — under
        ``retry_policy=QUERY`` with restart budget left — leave the
        query alive for its own execution thread to re-admit once
        pressure subsides."""
        q = self.queries.get(victim)
        if q is None or q.done.is_set():
            self.arbiter.forget_query(victim)
            return
        cur, _peak = self.arbiter.query_bytes(victim)
        cur += self.memory_pool.used_bytes(victim)
        msg = (
            f"Query {victim} killed by the cluster memory manager: "
            f"MEMORY_PRESSURE (victim {victim}, policy {policy}): "
            f"{reason}"
        )
        readmit = (
            self._retry_policy() == "QUERY"
            and int(self.local.session.get("query_retry_count")) > 0
        )
        log.warning(
            "memory kill: %s (readmit=%s)", msg, readmit
        )
        if self.journal is not None:
            self.journal.record_kill(victim, policy, reason, cur)
        self.arbiter.record_kill(victim, policy, reason, cur)
        # the flag gates task-retry/speculation/local-fallback in both
        # modes: a killed attempt's DELETEd tasks look like lost
        # workers, and resurrecting them would re-consume the memory
        # the kill just freed
        q._mem_kill = msg
        if readmit:
            # in-thread re-admission: _run_sql_with_restart waits out
            # the pressure and re-runs within query_retry_count
            self.memory_pool.cancel_blocked(victim)
        else:
            q.fail(msg)
            q.done.set()
            # cooperative cancel, exactly like the local kill-largest
            # policy: the victim cannot grow, its thread fails at the
            # next reservation
            self.memory_pool.mark_dead(victim)
            self.memory_pool.cancel_blocked(victim)
        self._cancel_query_on_workers(victim)

    def _cancel_query_on_workers(self, qid: str) -> None:
        """Tear the victim's tasks down on every discovered worker
        (each worker routes the abort through its task-DELETE path and
        fails the victim's blocked reservations). Best-effort and
        off-thread: a hung worker must not stall the kill."""

        def run():
            policy = rpc.RpcPolicy(timeout_s=5.0, retries=0)
            for w in self._ttl_workers():
                try:
                    rpc.call_json(
                        "PUT",
                        w.uri + "/v1/memory/abort",
                        {"query_id": qid},
                        policy=policy,
                    )
                except Exception:
                    pass

        threading.Thread(target=run, daemon=True).start()

    def _await_memory_calm(self, q: _Query) -> None:
        """Hold a killed-but-re-admittable victim until cluster
        pressure subsides (below low-water, nothing blocked), bounded
        by the query's own run-time limit."""
        deadline = time.monotonic() + float(
            self.local.session.get("query_max_run_time_s")
        )
        while (
            not q.done.is_set()
            and not self._shutting_down
            and time.monotonic() < deadline
        ):
            if self.arbiter.pressure_subsided():
                return
            time.sleep(0.05)

    def _fold_memory_stats(self, q: _Query) -> None:
        """Roll the query's cluster-wide memory view (coordinator pool
        + worker-reported bytes) into its stats — the QueryInfo /
        EXPLAIN ANALYZE "memory:" numbers."""
        cur, peak = self.arbiter.query_bytes(q.qid)
        cur += self.memory_pool.used_bytes(q.qid)
        peak += self.memory_pool.peak_bytes(q.qid)
        q.stats.current_memory_bytes = cur
        if peak > q.stats.peak_memory_bytes:
            q.stats.peak_memory_bytes = peak

    # ---------------------------------------------------------- discovery

    def announce(
        self,
        node_id: str,
        uri: str,
        state: str = "ACTIVE",
        preemptible: bool = False,
        memory: Optional[dict] = None,
        slice_id: str = "",
        device_coords=(),
        backend_diag: Optional[dict] = None,
        role: str = "",
    ) -> None:
        # peer coordinators announce like workers (role=coordinator on
        # the discovery body): visible in system.runtime.nodes, but
        # NEVER schedulable — _ttl_workers filters them out
        is_coord = role == "coordinator"
        with self._lock:
            w = self.workers.get(node_id)
            if w is None:
                self.workers[node_id] = _WorkerNode(
                    node_id=node_id, uri=uri, last_seen=time.time(),
                    state=state, preemptible=bool(preemptible),
                    slice_id=str(slice_id or ""),
                    device_coords=tuple(device_coords or ()),
                    backend_diag=dict(backend_diag or {}),
                    coordinator=is_coord,
                )
            else:
                w.last_seen = time.time()
                w.uri = uri
                w.state = state
                w.preemptible = bool(preemptible)
                w.slice_id = str(slice_id or "")
                w.device_coords = tuple(device_coords or ())
                w.coordinator = is_coord
                if backend_diag:
                    w.backend_diag = dict(backend_diag)
        # fold the heartbeat's memory report into the cluster view —
        # OUTSIDE the discovery lock (enforcement may scan queries)
        if memory is not None:
            self.arbiter.observe(node_id, memory)

    def _ttl_workers(self) -> List[_WorkerNode]:
        """Workers announced within the discovery TTL (no breaker
        filtering — callers that must not consume half-open probe
        slots use this directly). Peer coordinators announce through
        the same channel but are NOT workers: nothing schedules on
        them, probes them, or expects task routes there."""
        now = time.time()
        with self._lock:
            return [
                w
                for w in self.workers.values()
                if now - w.last_seen <= NODE_TTL_S
                and not w.coordinator
            ]

    def active_workers(self, exclude=()) -> List[_WorkerNode]:
        """Schedulable workers: announced within the discovery TTL,
        not DRAINING (the drain protocol — a draining worker finishes
        what it has but accepts nothing new), AND not circuit-open (an
        OPEN breaker excludes the worker; after its cool-off,
        ``allow()`` admits one half-open probe here). ``exclude``
        filters BEFORE the breaker check, so asking for a spare never
        consumes an excluded worker's probe slot."""
        return [
            w
            for w in self._ttl_workers()
            if w.state == "ACTIVE"
            and w.node_id not in exclude
            and self._breaker(w.node_id).allow()
        ]

    # ------------------------------------------------- worker health

    def _breaker(self, node_id: str) -> "rpc.CircuitBreaker":
        with self._lock:
            b = self.breakers.get(node_id)
            if b is None:
                b = rpc.CircuitBreaker(
                    threshold=self._breaker_threshold,
                    open_s=self._breaker_open_s,
                )
                self.breakers[node_id] = b
            return b

    def _worker_ok(self, w) -> None:
        if self._breaker(w.node_id).record_success():
            REGISTRY.counter("coordinator.circuit_closed").update()
            log.info("circuit CLOSED for worker %s", w.node_id)

    def _worker_failed(self, w) -> None:
        REGISTRY.counter("coordinator.worker_failures").update()
        if self._breaker(w.node_id).record_failure():
            REGISTRY.counter("coordinator.circuit_opened").update()
            log.warning("circuit OPEN for worker %s", w.node_id)

    def _any_worker_alive(self) -> bool:
        """Directly probe every TTL-fresh worker (``GET /v1/status``,
        short timeout, no retries): the graceful-degradation gate must
        distinguish 'the cluster is down' from 'one task hit a dead
        socket before its breaker opened'. Iterates _ttl_workers, not
        active_workers: a liveness sweep must not consume half-open
        probe slots it may never resolve — each worker probed here
        gets a real verdict recorded instead."""
        probe = rpc.RpcPolicy(timeout_s=2.0, retries=0)
        for w in self._ttl_workers():
            if w.state != "ACTIVE":
                # a DRAINING worker answers /v1/status but accepts no
                # work: it must not veto coordinator-local fallback
                continue
            try:
                rpc.call_json(
                    "GET", w.uri + "/v1/status", policy=probe
                )
                # the probe IS the verdict: a half-open slot consumed
                # by active_workers() above must resolve, or the
                # breaker stays wedged in HALF_OPEN
                self._worker_ok(w)
                return True
            except Exception:
                self._worker_failed(w)
        return False

    # ------------------------------------------- fault-tolerant execution

    def _retry_policy(self) -> str:
        """Session ``retry_policy``, normalized (NONE | TASK | QUERY)."""
        return str(self.local.session.get("retry_policy")).upper()

    def _spooling(self) -> bool:
        """Should task specs carry the spool flag? TASK/QUERY policy
        with a configured shared spool directory; NONE never spools
        (bit-for-bit legacy behavior)."""
        return self.spool is not None and self._retry_policy() in (
            "TASK",
            "QUERY",
        )

    def _select_transport(self, workers, schemas) -> str:
        """Stage transport decision, delegated to the scheduler: the
        per-EDGE dominant-slice rule when single-program collective
        stages are on (the default), the legacy all-or-nothing
        per-stage rule otherwise."""
        enabled = bool(self.local.session.get("exchange_ici_enabled"))
        if bool(self.local.session.get("exchange_single_program")):
            return select_exchange_edges(
                workers, enabled, schemas=schemas
            )
        return select_exchange_transport(
            workers, enabled, schemas=schemas
        )

    def _retry_spec(
        self, q: Optional[_Query], prior: FragmentSpec, **overrides
    ) -> FragmentSpec:
        """Replacement attempt of a logical task: the SAME logical id
        with attempt+1 (server.task_ids), so spool attempt-dedup and
        the per-stage attempt counters line up, registered to the same
        stage as the prior attempt."""
        spec = dataclasses.replace(
            prior,
            task_id=task_ids.next_attempt(prior.task_id),
            **overrides,
        )
        if q is not None:
            with q._stats_lock:
                st = q._task_stage.get(prior.task_id)
                if st is not None:
                    q._task_stage[spec.task_id] = st
        return spec

    def _record_recovery(self, q: Optional[_Query]) -> None:
        REGISTRY.counter("coordinator.tasks_retried").update()
        if q is not None:
            with q._stats_lock:
                q.stats.task_recoveries += 1

    def _take_retry(self, q: _Query) -> bool:
        """Consume one unit of the query's task-retry budget (the
        generalization of the old retry-once: bounded per QUERY, not
        per range)."""
        # a memory-pressure-killed query must not resurrect through
        # task-level recovery: its DELETEd tasks look like lost
        # workers, but re-running them would re-consume the memory the
        # kill just freed
        if getattr(q, "_mem_kill", None) is not None:
            return False
        with q._stats_lock:
            if q._retry_budget is None:
                q._retry_budget = int(
                    self.local.session.get("task_retry_budget")
                )
            if q._retry_budget <= 0:
                return False
            q._retry_budget -= 1
            return True

    def nodes(self) -> List[_WorkerNode]:
        """All nodes incl. self, for system.runtime.nodes."""
        from presto_tpu.utils.devicediag import last_diag_dict

        me = _WorkerNode(
            node_id="coordinator",
            uri=self.uri,
            last_seen=time.time(),
            coordinator=True,
            backend_diag=last_diag_dict(),
        )
        now = time.time()
        with self._lock:
            others = [
                dataclasses.replace(
                    w,
                    state=(
                        w.state
                        if now - w.last_seen <= NODE_TTL_S
                        else "GONE"
                    ),
                )
                for w in self.workers.values()
            ]
        return [me] + others

    # ------------------------------------------------------------ queries

    def _group_memory(self, group_name: str) -> int:
        """Bytes reserved by running queries of one resource group (the
        manager's softMemoryLimit eligibility hook): coordinator-local
        reservations PLUS the worker-reported bytes the arbiter folds
        from heartbeats — a distributed memory hog trips its group
        quota even when every byte lives worker-side (the historical
        under-accounting counted only coordinator-local bytes)."""
        with self._lock:
            # live queries only: finished queries hold no reservations
            qids = [
                q.qid
                for q in self.queries.values()
                if not q.done.is_set()
                and getattr(q, "resource_group", None) == group_name
            ]
        local = sum(self.memory_pool.used_bytes(qid) for qid in qids)
        # multi-coordinator shared quotas: fold live peers' published
        # per-group usage (their coordinator-local bytes directly;
        # their qids through the arbiter, which holds every worker's
        # heartbeat once) so one group's softMemoryLimit holds across
        # N admitters
        if self.lease is not None:
            for pl in self.lease.peers(live_only=True):
                g = ((pl.state or {}).get("groups") or {}).get(
                    group_name
                )
                if not isinstance(g, dict):
                    continue
                qids.extend(g.get("qids") or [])
                try:
                    local += int(g.get("local_bytes") or 0)
                except (TypeError, ValueError):
                    pass
        return local + self.arbiter.queries_bytes(qids)

    def submit(
        self,
        sql: str,
        user: str = "presto_tpu",
        prepared: Optional[Dict[str, str]] = None,
    ) -> _Query:
        # "q_c" namespace: distributed queries join the runner's
        # QueryHistory (adopt), whose own ids are "q_N" — the two
        # counters are independent and must not collide there. The
        # boot nonce keeps ids (and the task-attempt ids minted from
        # them) unique across coordinator restarts sharing one spool
        q = _Query(f"q_c{next(self._qid)}_{self._boot}", sql)
        q.user = user
        q.prepared = dict(prepared or {})
        q.resource_group = None
        # snapshot the journal handle: a fault-plane kill racing this
        # submit nulls self.journal (no close-out may reach disk), but
        # a statement already past the handler's shutdown gate must
        # still land its submit frame — an ACKed query with no frame
        # would be unresumable by any survivor
        j = self.journal
        with self._lock:
            self.queries[q.qid] = q
            # bounded retention (reference: query.max-history): evict
            # the oldest COMPLETED queries — their stats/spans/result
            # rows must not accumulate on a long-running coordinator.
            # Un-drained queries (client still paginating) get a grace
            # window before they too age out (abandoned clients must
            # not pin memory forever).
            now = time.time()
            done = [
                qid
                for qid, old in self.queries.items()
                if old.done.is_set()
                and (
                    old._drained
                    or now - (old.stats.end_time or now) > DRAIN_GRACE_S
                )
            ]
            for qid in done[: max(0, len(done) - MAX_QUERY_HISTORY)]:
                del self.queries[qid]
            if self._qid_alias:
                # restart aliases die with their resumed target
                self._qid_alias = {
                    a: t
                    for a, t in self._qid_alias.items()
                    if t in self.queries
                }
            if self._pending >= self._max_queued:
                q.fail(
                    "Query rejected: too many queued queries "
                    f"(max {self._max_queued})"
                )
                REGISTRY.counter("coordinator.queries_rejected").update()
                q.done.set()
                return q
            self._pending += 1
        if self.resource_groups is None:
            # journal BEFORE the execution thread can start: finish
            # must never precede submit on disk
            if j is not None:
                j.record_submit(q.qid, sql, user, q.prepared, None)
            threading.Thread(
                target=self._execute_query, args=(q,), daemon=True
            ).start()
            return q

        def start(_q=q):
            threading.Thread(
                target=self._execute_query, args=(_q,), daemon=True
            ).start()

        # group assignment is deterministic: record it before the
        # thread can race to the finish hook
        q.resource_group = self.resource_groups.group_of(user).name
        if j is not None:
            # before resource_groups.submit — a run-now admission
            # starts the thread synchronously inside it
            j.record_submit(
                q.qid, sql, user, q.prepared, q.resource_group
            )
        state, info = self.resource_groups.submit(user, start)
        if state == "rejected":
            with self._lock:
                self._pending -= 1
            q.fail(info)
            REGISTRY.counter("coordinator.queries_rejected").update()
            q.done.set()
            if j is not None:
                j.record_finish(q.qid, "FAILED")
            return q
        q.resource_group = info
        return q

    def _execute_query(self, q: _Query) -> None:
        # admission gate: the QoS plane's priority lanes when enabled
        # (strict-priority dequeue, weighted-fair within a lane,
        # preempt-and-resume of lower-priority running work), else the
        # legacy bounded semaphore — qos.enabled=false is bit-exact
        # legacy admission
        if self.qos is not None:
            admitted = self.qos.qos_admit(q)
            try:
                if not admitted and not q.done.is_set():
                    # shutdown while lane-queued: never execute — fail
                    # the query so _admitted_execute's queued-death
                    # branch closes it out (pending count, group slot,
                    # journal finish)
                    q.fail(
                        "Query rejected: coordinator shut down before "
                        "admission"
                    )
                    q.done.set()
                self._admitted_execute(q)
            finally:
                self.qos.qos_release(q)
        else:
            with self._admit:
                self._admitted_execute(q)

    def _qos_checkpoint(self, q: Optional[_Query]) -> None:
        """Cooperative QoS suspension point (server/qos.py): a
        suspended query's stage threads park here between ranges.
        No-op when the plane is off."""
        if self.qos is not None and q is not None:
            self.qos.qos_checkpoint(q)

    def _admitted_execute(self, q: _Query) -> None:
        # admission high-water (cluster memory governance): while
        # the cluster's query-attributed usage is over
        # memory.admission-high-water, QUEUED queries are HELD —
        # never failed — and release on the low-water hysteresis
        while (
            not q.done.is_set()
            and not self._shutting_down
            and self.arbiter.admission_held()
        ):
            q._admission_parked = True
            time.sleep(0.05)
        if q.done.is_set():  # killed while queued (memory manager)
            with self._lock:
                self._pending -= 1
            if (
                self.resource_groups is not None
                and getattr(q, "resource_group", None) is not None
            ):
                self.resource_groups.finish(q.resource_group)
            if self.journal is not None:
                self.journal.record_finish(q.qid, q.state)
            return
        # chaos hook (utils/faults.py kill_coordinator): fires at the
        # admitted-but-not-yet-RUNNING seam — the journal holds the
        # submit frame with no close-out, exactly the state a real
        # crash strands. The "dead" coordinator returns silently: no
        # FAILED transition, no journal write, no client answer — a
        # surviving peer claims and resumes the query
        try:
            faults.maybe_inject_coordinator(
                self.coord_id, q.qid, kill=self._fault_kill
            )
        except faults.FaultInjectedError:
            return
        q.state = "RUNNING"
        q.stats.state = "RUNNING"
        log.info(
            "trace=%s query=%s state=RUNNING", q.trace.trace_id, q.qid
        )
        # pool reservations this thread makes are owned by THIS
        # query id (one id space for holders, kills, and clients);
        # the stats sink makes coordinator-local staging (gather
        # splices, local fallback) pin the cache entries it
        # executes over — released in the finally below
        self.local._owner_override.value = q.qid
        self.local._qs_local.value = q.stats
        try:
            with REGISTRY.timer("coordinator.query_time").time():
                with q.trace.span("query", query_id=q.qid):
                    self._run_sql_with_restart(q)
            if not q.done.is_set():  # a killed query stays FAILED
                q.state = "FINISHED"
        except Exception as e:
            if not q.done.is_set():
                q.state = "FAILED"
                q.error = (
                    f"{type(e).__name__}: {e}\n"
                    f"{traceback.format_exc()[-1000:]}"
                )
            REGISTRY.counter("coordinator.queries_failed").update()
        finally:
            self._finish_query_stats(q)
            self.local._owner_override.value = None
            self.local._qs_local.value = None
            self.local.release_pins(q.stats)
            self.memory_pool.release(q.qid)
            with self._lock:
                self._pending -= 1
            if self.journal is not None:
                # terminal close-out BEFORE done is observable: a
                # restart must never re-admit a query whose client
                # already saw the outcome
                self.journal.record_finish(q.qid, q.state)
            q.done.set()
            if (
                self.resource_groups is not None
                and getattr(q, "resource_group", None) is not None
            ):
                # frees the group slot and admits the next queued
                # query by weighted fairness
                self.resource_groups.finish(q.resource_group)

    def _run_sql_with_restart(self, q: _Query) -> None:
        """``retry_policy=QUERY``: a bounded full-query restart is the
        LAST resort when task-level recovery could not save the query
        (reference: Tardigrade's QUERY retry policy). Only failures
        that mean "the cluster changed under us" (connection-level, a
        draining/lost worker, no live workers) are restartable —
        execution errors would fail again identically."""
        budget = (
            int(self.local.session.get("query_retry_count"))
            if self._retry_policy() == "QUERY"
            else 0
        )
        attempt = 0
        while True:
            try:
                if attempt == 0:
                    return self._run_sql(q)
                with q.trace.span(
                    "recovery", phase="query-restart", attempt=attempt
                ):
                    return self._run_sql(q)
            except Exception as e:
                mem_kill = getattr(q, "_mem_kill", None)
                restartable = rpc.is_task_recoverable(e) or isinstance(
                    e, NoLiveWorkers
                )
                if mem_kill is not None:
                    # cluster memory manager kill: re-admit the victim
                    # after pressure subsides — within the SAME bounded
                    # query_retry_count budget as connection restarts
                    if attempt >= budget or q.done.is_set():
                        raise MemoryPressureKilled(mem_kill) from e
                    attempt += 1
                    REGISTRY.counter(
                        "memory.victims_readmitted"
                    ).update()
                    log.warning(
                        "query=%s re-admitting memory-pressure victim "
                        "(attempt %d/%d)", q.qid, attempt, budget,
                    )
                    # surrender this attempt's residency before the
                    # wait: the victim must not hold bytes while the
                    # cluster drains
                    self.local.release_pins(q.stats)
                    self.memory_pool.release(q.qid)
                    self._await_memory_calm(q)
                    q._mem_kill = None
                    self.arbiter.forget_query(q.qid)
                elif (
                    attempt >= budget
                    or not restartable
                    or q.done.is_set()
                ):
                    raise
                else:
                    attempt += 1
                    REGISTRY.counter(
                        "coordinator.query_restarts"
                    ).update()
                    log.warning(
                        "query=%s restarting (attempt %d/%d) after "
                        "%s: %s",
                        q.qid, attempt, budget, type(e).__name__, e,
                    )
                # close out the failed attempt's partial state: stages
                # left RUNNING become ABORTED, partial results dropped
                with q._stats_lock:
                    q.stats.query_restarts = attempt
                    for st in q.stats.stages:
                        if st.state == "RUNNING":
                            st.state = "ABORTED"
                        for t in st.tasks:
                            if t.state in ("QUEUED", "RUNNING"):
                                t.state = "FAILED"
                    # drop the failed attempt's coordinator-local
                    # operator folds: the retry re-executes the same
                    # local programs, and keeping both would teach the
                    # history store doubled cardinalities
                    q.stats.operators = []
                    q.stats.__dict__.pop("_op_index", None)
                    q.stats.__dict__.pop("_op_pins", None)
                q.columns, q.rows = [], []

    def _run_sql(self, q: _Query) -> None:
        from presto_tpu.sql import ast, parse_statement

        stmt = parse_statement(q.sql)
        if isinstance(stmt, (ast.Prepare, ast.Execute, ast.Deallocate)):
            return self._run_prepared_stmt(q, stmt)
        workers = self.active_workers()
        if (
            isinstance(stmt, ast.Explain)
            and stmt.analyze
            and isinstance(stmt.statement, ast.Select)
            and workers
        ):
            # distributed EXPLAIN ANALYZE: run the inner SELECT through
            # the real scheduler, then render the plan with the
            # per-stage/per-task rollup and the span tree
            from presto_tpu.exec.explain import render_distributed_analyze

            res = self._run_select(q, stmt.statement, workers)
            q.stats.output_rows = int(res.page.num_valid)
            q._output_rows_final = True
            self._fold_memory_stats(q)
            q.stats.roll_up()
            # provisionally close the root span for the rendering (the
            # context manager records the real end on exit), so the
            # printed tree doesn't show the query span as open
            if q.trace.root is not None and not q.trace.root.end:
                q.trace.root.end = time.time()
            text = render_distributed_analyze(
                q._plan_root, q.stats, q.trace, int(res.page.num_valid),
                runner=self.local,
            )
            q.columns = [{"name": "Query Plan"}]
            q.rows = [[line] for line in text.split("\n")]
            return
        if not isinstance(stmt, ast.Select) or not workers:
            if isinstance(stmt, ast.Select):
                # micro-batch lane (coordinator-local dispatch);
                # None = lane off, keep the bit-exact legacy path
                with q.trace.span("execute-local"):
                    res = self._microbatch_local_select(
                        q, stmt, adopt=True
                    )
                if res is not None:
                    self._store_result(q, res)
                    return
            # non-SELECT (SET SESSION / SHOW / EXPLAIN) or empty cluster:
            # run on the coordinator's local engine
            with q.trace.span("execute-local"):
                res = self.local.execute(q.sql)
            self._store_result(q, res)
            return
        res = None
        if bool(self.local.session.get("enable_result_cache")):
            # tier-a in front of distributed dispatch (the EXPLAIN
            # ANALYZE branch above bypasses on purpose: an analyze
            # always executes)
            res = self._result_cache_lookup(q, stmt, adopt=True)
            if res is None:
                res = self._run_select(q, stmt, workers)
                self._result_cache_store(q, q._rc_plan, res)
        else:
            res = self._run_select(q, stmt, workers)
        self._store_result(q, res)

    #: coordinator-global prepared registry bound (names cycle on a
    #: serving fleet; the client-header path carries its own map)
    MAX_PREPARED = 256

    def _run_prepared_stmt(self, q: _Query, stmt) -> None:
        """PREPARE / EXECUTE / DEALLOCATE over HTTP (server.protocol
        prepared-statement headers). PREPARE registers the statement
        TEXT (response header ``X-Presto-Added-Prepare`` hands it to
        the client, which replays it per request); EXECUTE parses the
        registered text through a bounded AST cache, binds the
        arguments, and runs the bound statement through the normal
        distributed/local path — whose plan cache makes a warm EXECUTE
        zero-planning, zero-compilation."""
        from presto_tpu.exec.local_runner import (
            _bind_param_markers,
            _count_param_markers,
        )
        from presto_tpu.sql import ast

        if isinstance(stmt, ast.Prepare):
            text = _prepare_text(q.sql, stmt.name)
            with self._prepared_mu:
                self._prepared_sql[stmt.name] = text
                self._prepared_sql.move_to_end(stmt.name)
                while len(self._prepared_sql) > self.MAX_PREPARED:
                    evicted, _ = self._prepared_sql.popitem(last=False)
                    # keep the runner-side mirror bounded too: an
                    # LRU-evicted name must not pin its parsed AST
                    self.local._prepared.pop(evicted, None)
            # the embedded runner serves the non-distributed EXECUTE
            # path: keep its per-runner registry in step
            self.local._prepared[stmt.name] = stmt.statement
            if self.journal is not None:
                # the coordinator-GLOBAL registry is coordinator state
                # and survives a bounce (client-header-owned maps are
                # the client's to replay)
                self.journal.record_prepare(stmt.name, text)
            q.added_prepare = (stmt.name, text)
            q.columns = [{"name": "result"}]
            q.rows = [["PREPARE"]]
            return
        if isinstance(stmt, ast.Deallocate):
            with self._prepared_mu:
                self._prepared_sql.pop(stmt.name, None)
            self.local._prepared.pop(stmt.name, None)
            if self.journal is not None:
                self.journal.record_deallocate(stmt.name)
            q.deallocated_prepare = stmt.name
            q.columns = [{"name": "result"}]
            q.rows = [["DEALLOCATE"]]
            return
        # EXECUTE: client-supplied statements take precedence (the
        # client owns its session's prepared map)
        text = q.prepared.get(stmt.name)
        if text is None:
            with self._prepared_mu:
                text = self._prepared_sql.get(stmt.name)
        if text is None:
            raise RuntimeError(
                f"prepared statement {stmt.name!r} not found"
            )
        inner = self._parse_prepared(text)
        n_markers = _count_param_markers(inner)
        if n_markers != len(stmt.params):
            raise RuntimeError(
                f"EXECUTE {stmt.name}: statement has {n_markers} "
                f"parameter(s), {len(stmt.params)} given"
            )
        from presto_tpu.sql import ast as A

        bound = _bind_param_markers(inner, stmt.params)
        workers = self.active_workers()
        if isinstance(bound, A.Select) and workers:
            res = None
            if bool(self.local.session.get("enable_result_cache")):
                res = self._result_cache_lookup(q, bound, adopt=True)
            if res is None:
                res = self._run_select(q, bound, workers)
                self._result_cache_store(q, q._rc_plan, res)
        else:
            # plan_cached marks q.stats.plan_cache_hit through the
            # thread-local stats sink _execute_query installed
            with q.trace.span("execute-local"):
                res = None
                if isinstance(bound, A.Select):
                    # micro-batch lane: concurrent same-fingerprint
                    # EXECUTEs share one vmapped dispatch (None when
                    # the lane is off — the legacy path below is then
                    # bit-exact pre-batching)
                    res = self._microbatch_local_select(q, bound)
                if res is None:
                    res = self.local.execute_bound(bound)
        self._store_result(q, res)

    def _parse_prepared(self, text: str):
        """Parse a prepared statement's text through a bounded AST
        cache: a warm EXECUTE re-parses nothing."""
        from presto_tpu.sql import parse_statement

        cache = getattr(self, "_ast_cache", None)
        if cache is None:
            cache = self._ast_cache = OrderedDict()
        with self._prepared_mu:
            got = cache.get(text)
            if got is not None:
                cache.move_to_end(text)
                return got
        parsed = parse_statement(text)
        with self._prepared_mu:
            cache[text] = parsed
            cache.move_to_end(text)
            while len(cache) > self.MAX_PREPARED:
                cache.popitem(last=False)
        return parsed

    def _microbatch_key(self, stmt_key: str) -> str:
        """The batch-queue grouping key — constructed HERE and only
        here (tools/analyze.py ``serving-batch`` rule): the canonical
        statement cache key already carries catalog/schema and the
        value-erased statement shape, so same-key statements are
        literally the same compiled program with different parameter
        vectors; the prefix keeps queue keys out of every other key
        space."""
        return f"mb|{stmt_key}"

    def _microbatch_local_select(self, q: _Query, stmt, adopt=False):
        """Coordinator-local SELECT through the micro-batch lane:
        -> QueryResult, or None when the lane is OFF (the caller keeps
        the bit-exact legacy path). With the lane on, an eligible
        statement always returns here — its lane of a batched dispatch
        when a group formed, the existing scalar path otherwise.

        ``adopt``: the plain-SELECT caller bypasses the runner's own
        execute() bookkeeping, so the lane adopts the coordinator
        stats into the runner history (system.runtime.queries must
        still see the query). Adoption happens AFTER the one wait-ms
        read below — a None return must leave no adopted twin behind
        for the legacy path to duplicate."""
        runner = self.local
        wait_ms = float(runner.session.get("microbatch_wait_ms"))
        rc_on = bool(runner.session.get("enable_result_cache"))
        if wait_ms <= 0 and not rc_on:
            return None
        if adopt:
            runner.history.adopt(q.stats)
            q._adopted = True
        if rc_on:
            # result cache UNDER the batch queue: a hot fingerprint's
            # first batch executes ONCE, every later statement answers
            # here with zero planning and zero dispatch
            res = self._result_cache_lookup(q, stmt)
            if res is not None:
                return res
        plan, _hit, key = runner.plan_cached_keyed(stmt)
        res = None
        if (
            wait_ms > 0
            and key is not None
            and runner.microbatch_plan_eligible(plan)
        ):
            max_size = min(
                int(runner.session.get("microbatch_max")), 128
            )
            res = self.microbatch.execute(
                self._microbatch_key(key),
                plan,
                q.stats,
                wait_ms,
                max_size,
                no_wait=q._admission_parked,
            )
        if res is None:
            # ineligible statement, empty window, or a lane that fell
            # out of the batch: the one scalar path (capacity retries,
            # error surfacing, full materialization)
            res = runner.execute_plan(plan, qs=q.stats)
        if rc_on:
            self._result_cache_store(q, plan, res)
        return res

    def _result_cache_lookup(self, q: _Query, stmt, adopt=False):
        """Tier-a lookup in front of planning and dispatch: -> a
        served result on a usable entry (fresh, or stale within the
        session's bounded-staleness window — which also spawns the ONE
        background refresh), else None with the minted key stashed on
        ``q`` for the post-execution store. Every failure lane
        degrades to a miss."""
        rc = self.result_cache
        if rc is None:
            return None
        from presto_tpu.server import result_cache as rc_mod

        key = rc_mod.statement_key(stmt, self.local.session)
        q._rc_key = key
        q._rc_stmt = stmt
        if key is None:
            return None
        max_stale = float(
            self.local.session.get("result_cache_max_staleness_s")
        )
        got = rc.get(key, max_staleness_s=max_stale)
        if got is None:
            q.stats.result_cache = "miss"
            return None
        entry, stale = got
        if adopt and not q._adopted:
            # the distributed path adopts inside _run_select, which a
            # hit never reaches — system.runtime.queries must still
            # see the query
            self.local.history.adopt(q.stats)
            q._adopted = True
        q.stats.result_cache = "stale" if stale else "hit"
        q.stats.result_cache_age_ms = (
            time.time() - entry.created_at
        ) * 1000.0
        q.stats.result_cache_snapshot = entry.snapshot_label
        q.stats.output_rows = len(entry.rows)
        if stale:
            self._spawn_result_refresh(entry)
        return rc_mod.CachedResult(entry.columns, entry.rows)

    def _result_cache_store(self, q: _Query, plan, res) -> None:
        """Post-execution put: the entry keys on the statement key
        minted at lookup and the snapshot vector pinned into the
        executed plan. No-op (fail open) without a key, on any
        non-cacheable scan, or on estimation errors."""
        rc = self.result_cache
        key = getattr(q, "_rc_key", None)
        if rc is None or key is None or plan is None or res is None:
            return
        try:
            from presto_tpu.plan import canonical

            rc.put(
                key,
                q._rc_stmt,
                res.columns,
                res.rows(),
                canonical.plan_handles(plan),
            )
        except Exception:
            pass

    def _spawn_result_refresh(self, entry) -> None:
        """Tier-c background refresh: exactly ONE re-execution per
        stale entry (per-entry CAS), off the serving hot path, through
        the normal plan/execute seam — the rewrite and snapshot
        pinning re-apply themselves, and the re-put replaces the stale
        entry with a fresh vector."""
        rc = self.result_cache
        if rc is None or not rc.claim_refresh(entry):
            return

        def _refresh():
            try:
                runner = self.local
                plan, _hit, _key = runner.plan_cached_keyed(entry.stmt)
                res = runner.execute_plan(plan)
                from presto_tpu.plan import canonical

                rc.put(
                    entry.key,
                    entry.stmt,
                    res.columns,
                    res.rows(),
                    canonical.plan_handles(plan),
                )
            except Exception:
                pass
            finally:
                rc.finish_refresh(entry)

        threading.Thread(
            target=_refresh, name="result-cache-refresh", daemon=True
        ).start()

    def _run_select(self, q: _Query, stmt, workers):
        """Distributed SELECT: plan -> fragment -> schedule stages ->
        gather, each phase a span on the query's trace; returns the
        QueryResult. Falls back to the local engine when fragmenting
        yields no remote sources."""
        from presto_tpu.exec.host_ops import apply_host_ops, peel_host_ops
        from presto_tpu.parallel.fragmenter import insert_gathers
        from presto_tpu.plan.optimizer import prune_columns

        # distributed queries share the runner's QueryHistory (one
        # system.runtime.queries across both tiers) and fire the
        # query-completed event through it
        self.local.history.adopt(q.stats)
        q._adopted = True
        q.stats.retry_policy = self._retry_policy()
        t0 = time.perf_counter()
        with q.trace.span("plan"):
            # statement-level plan cache: a warm shape skips planning
            # and optimization; the execution's literal values then
            # substitute back in (materialize) so fragments ship plain
            # literals — wire protocol and workers unchanged, and each
            # worker re-hoists locally, so literal-variant fragments
            # hit the WORKER compile caches too
            plan, q.stats.plan_cache_hit = self.local.plan_cached(stmt)
            # result-cache store site (the caller): the entry keys on
            # THIS plan's snapshot-pinned scan handles
            q._rc_plan = plan
            if plan.bound_values:
                from presto_tpu.plan import canonical

                plan = canonical.materialize_plan(plan)
            t_opt = time.perf_counter()
            with self.local._history_scope():
                root = prune_columns(self.local._bind_params(plan))
            q.stats.optimization_ms += (
                time.perf_counter() - t_opt
            ) * 1000.0
        q.stats.planning_ms = (time.perf_counter() - t0) * 1000.0
        REGISTRY.distribution("plan.planning_ms").add(
            q.stats.planning_ms
        )
        if not q.stats.plan_fingerprint:
            # canonical statement identity for the history store and
            # the event-sink enrichment
            try:
                from presto_tpu.plan import history as plan_history

                q.stats.plan_fingerprint = (
                    plan_history.plan_fingerprint(root)
                )
            except Exception:
                pass
        scans = [
            n for n in N.walk(root) if isinstance(n, N.TableScanNode)
        ]
        if any(
            self.local.catalogs.get(s.handle.catalog).coordinator_only()
            for s in scans
        ):
            # system.runtime.* data lives in THIS process; a worker's
            # copy of those tables is empty
            t1 = time.perf_counter()
            try:
                with q.trace.span("execute-local"):
                    # qs keeps the thread's stats sink live inside
                    # execute_plan (it swaps in its qs argument), so
                    # coordinator-local staging pins + attributes
                    return self.local.execute_plan(plan, qs=q.stats)
            finally:
                q.stats.execution_ms = (
                    time.perf_counter() - t1
                ) * 1000.0
        with q.trace.span("fragment"):
            host_ops: List[N.PlanNode] = []
            if self.local.session.get("host_root_stage"):
                root, host_ops = peel_host_ops(root)
            froot = insert_gathers(root)
        q._plan_root = root
        remotes = [
            n for n in N.walk(froot) if isinstance(n, N.RemoteSourceNode)
        ]
        t1 = time.perf_counter()
        try:
            return self._run_select_fragments(
                q, plan, root, froot, host_ops, remotes, workers
            )
        finally:
            q.stats.execution_ms = (time.perf_counter() - t1) * 1000.0

    def _run_select_fragments(
        self, q: _Query, plan, root, froot, host_ops, remotes, workers
    ):
        from presto_tpu.exec.host_ops import apply_host_ops

        if not remotes:
            return self.local.execute_plan(plan, qs=q.stats)
        # ordered MERGE exchange (reference: MergeOperator): when the
        # peeled root sort sits directly over a single no-cut fragment,
        # push the sort into the worker fragment (per-batch sorted runs)
        # and k-way merge the runs at the gather instead of re-sorting
        merge_sort = None
        merge_stage = None
        if len(remotes) == 1 and isinstance(froot, N.RemoteSourceNode):
            sorts = [op for op in host_ops if isinstance(op, N.SortNode)]
            if len(sorts) == 1:
                merge_stage = plan_stage(
                    remotes[0].fragment_root, self.local.catalogs
                )
                # merge requires raw worker rows: a stage with an
                # aggregation cut emits PARTIAL states whose sorted
                # runs would be meaningless
                if merge_stage is not None and isinstance(
                    merge_stage.final_root, N.RemoteSourceNode
                ):
                    merge_sort = sorts[0]
        if merge_sort is not None:
            page = self._run_stage(
                remotes[0].fragment_root, workers, q,
                order_by=merge_sort, stage=merge_stage,
            )
            host_ops = [op for op in host_ops if op is not merge_sort]
            if host_ops:
                page = apply_host_ops(page, host_ops)
            from presto_tpu.exec.local_runner import QueryResult

            return QueryResult(plan.output_names, page)
        if len(remotes) == 1:
            pages = [self._run_stage(remotes[0].fragment_root, workers, q)]
        else:
            # overlap independent fragments (reference: all stages of a
            # query run concurrently — inter-stage pipelining)
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(len(remotes)) as pool:
                futs = [
                    pool.submit(self._run_stage, r.fragment_root, workers, q)
                    for r in remotes
                ]
                pages = [f.result() for f in futs]
        with q.trace.span("gather", phase="final-splice"):
            page = self.local._run_with_pages(froot, remotes, pages)
            if host_ops:
                page = apply_host_ops(page, host_ops)
        from presto_tpu.exec.local_runner import QueryResult

        return QueryResult(plan.output_names, page)

    # --------------------------------------------------- stats collection

    def _finish_query_stats(self, q: _Query) -> None:
        """Close out the query's stats object and, for distributed
        queries (adopted into the runner's history), fire the
        query-completed event through the history."""
        # distributed EXPLAIN ANALYZE already set the inner SELECT's
        # real output count; q.rows there holds plan-text lines
        if not q._output_rows_final:
            q.stats.output_rows = len(q.rows)
        # final memory rollup while the reservations are still live
        # (the pool releases right after this in _execute_query)
        self._fold_memory_stats(q)
        # close any stage a failed (or early-exited) path left open:
        # a finished query must not report RUNNING stages — and no
        # task may stay RUNNING either (a timed-out pull records a
        # provisional snapshot; the task was DELETEd on the worker)
        with q._stats_lock:
            for st in q.stats.stages:
                if st.state == "RUNNING":
                    st.state = q.state
                for t in st.tasks:
                    if t.state in ("QUEUED", "RUNNING"):
                        t.state = (
                            "ABORTED" if q.state == "FINISHED"
                            else "FAILED"
                        )
        q.stats.roll_up()
        if q._adopted:
            self.local.history.finish(q.stats, error=q.error)
        else:
            q.stats.end_time = time.time()
            q.stats.state = q.state
            q.stats.error = q.error
        log.info(
            "trace=%s query=%s state=%s elapsed_ms=%.1f",
            q.trace.trace_id, q.qid, q.state, q.stats.elapsed_ms,
        )

    def _new_stage(self, q: _Query, kind: str) -> StageStats:
        with q._stats_lock:
            st = StageStats(stage_id=next(q._stage_seq), kind=kind)
            q.stats.stages.append(st)
        return st

    def _register_task(
        self, q: _Query, stage: StageStats, spec: FragmentSpec
    ) -> FragmentSpec:
        """Remember which stage a task belongs to, so its final status
        rolls up into the right StageStats."""
        with q._stats_lock:
            q._task_stage[spec.task_id] = stage
        return spec

    def _record_task_status(self, q: _Query, task_id: str, st: dict):
        """Fold one task's status JSON into the query rollup and graft
        its worker-side spans into the query trace. Only a TERMINAL
        status seals the task: a non-terminal snapshot (a timed-out
        pull reading a still-RUNNING worker) is folded provisionally
        and replaced if the real final status arrives later."""
        d = st.get("stats") or {}
        ts = (
            TaskStats.from_dict(d)
            if d
            else TaskStats(task_id=task_id, query_id=q.qid)
        )
        ts.state = st.get("state", ts.state)
        terminal = ts.state in ("FINISHED", "FAILED", "ABORTED")
        # a finished query's stats are closed: a straggling teardown
        # thread (hung worker finally answering) must not fold a
        # provisional RUNNING snapshot back into them
        if not terminal and q.done.is_set():
            return
        with q._stats_lock:
            if task_id in q._recorded:
                return
            if terminal:
                q._recorded.add(task_id)
            ts.speculative = task_id in q._speculative
            stage = q._task_stage.get(task_id)
            if stage is not None:
                ts.stage_id = stage.stage_id
                # replace an earlier provisional snapshot of this task
                stage.tasks = [
                    t for t in stage.tasks if t.task_id != task_id
                ] + [ts]
        q.trace.graft(st.get("spans"))

    def _finish_task(
        self, q: _Query, w, task_id: str, traceparent: str = "",
        presumed: str = "FAILED",
    ) -> None:
        """Collect a task's final stats, then DELETE it on the worker
        (the one task-teardown path: stats must be read BEFORE the
        DELETE removes the task). ``presumed`` labels the attempt when
        the worker can no longer answer the status GET: callers on a
        success path (pages fully pulled) pass FINISHED — the rows ARE
        in the result — while failure/abort paths keep FAILED, so
        QueryInfo, system.runtime.tasks, and EXPLAIN ANALYZE account
        for every scheduled attempt (speculated losers included)
        without inventing phantom failures."""
        try:
            st = self._rpc_json(
                "GET",
                f"{w.uri}/v1/task/{task_id}/status",
                traceparent=traceparent,
            )
            self._record_task_status(q, task_id, st)
        except Exception:
            # the worker is gone: synthesize the presumed terminal
            # TaskStats for the lost attempt
            self._record_task_status(
                q,
                task_id,
                {
                    "state": presumed,
                    "stats": {
                        "task_id": task_id,
                        "query_id": q.qid,
                        "node_id": w.node_id,
                        "state": presumed,
                    },
                },
            )
        try:
            self._rpc_json(
                "DELETE",
                f"{w.uri}/v1/task/{task_id}",
                traceparent=traceparent,
            )
        except Exception:
            pass

    def _abort_task(self, q: _Query, w, spec: FragmentSpec) -> None:
        """Tear a losing/failed attempt down OFF the calling thread:
        the winner must not wait out status/DELETE timeouts against a
        worker that may be hung (any still-open task state is closed
        when the query finishes)."""
        threading.Thread(
            target=self._finish_task,
            args=(q, w, spec.task_id, spec.traceparent),
            daemon=True,
        ).start()

    def query_info(self, q: _Query) -> dict:
        """Full QueryInfo (reference: ``GET /v1/query/{id}``): the
        stats rollup, per-stage task stats, and the span tree —
        servable while the query is RUNNING."""
        if not q.done.is_set():
            self._fold_memory_stats(q)
        q.stats.roll_up()
        info = q.stats.to_dict(include_stages=True)
        info["state"] = q.state  # _Query.state is authoritative
        info["error"] = q.error
        info["user"] = getattr(q, "user", None)
        info["resource_group"] = getattr(q, "resource_group", None)
        if self.qos is not None:
            # QoS plane: lane/SLO identity + suspension/resume counters
            info["qos"] = self.qos.query_info(q)
        info["trace"] = q.trace.to_tree()
        return info

    def query_summary(self, q: _Query) -> dict:
        return {
            "query_id": q.qid,
            "state": q.state,
            "query": q.sql,
            "trace_id": q.trace.trace_id,
            "elapsed_ms": q.stats.elapsed_ms,
            "user": getattr(q, "user", None),
            "stages": len(q.stats.stages),
        }

    def query_progress(self, q: _Query) -> dict:
        """Live progress view (``GET /v1/query/{id}/progress``),
        consumable MID-query: per-stage task completion + the
        rows/bytes/dispatch counters accumulated so far, a completion
        fraction, and an ETA.

        The ETA numerator is split completion (tasks FINISHED over
        tasks scheduled — stages appear as the scheduler creates them,
        so ``splits_total`` grows while the query plans new stages and
        the fraction is a floor, never an overestimate of progress).
        When the plan shape has history (the PR-7 store), the
        history-observed root cardinality rides along as
        ``expected_rows`` and backstops the fraction before any task
        has finished. All the ``*_done``/rows/bytes/dispatch counters
        are monotone over a query's lifetime."""
        if not q.done.is_set():
            self._fold_memory_stats(q)
        q.stats.roll_up()
        stages = []
        splits_done = splits_total = 0
        rows = nbytes = dispatches = spilled = 0
        for s in q.stats.stages:
            r = s.rollup()
            s_total = len(s.tasks)
            s_done = sum(
                1 for t in s.tasks if t.state == "FINISHED"
            )
            splits_done += s_done
            splits_total += s_total
            rows += r["output_rows"]
            nbytes += r["output_bytes"]
            dispatches += r["device_dispatches"]
            spilled += r["spilled_bytes"]
            stages.append(
                {
                    "stage_id": s.stage_id,
                    "kind": s.kind,
                    "state": s.state,
                    "splits_done": s_done,
                    "splits_total": s_total,
                    "rows": r["output_rows"],
                    "bytes": r["output_bytes"],
                    "dispatches": r["device_dispatches"],
                    "spilled_bytes": r["spilled_bytes"],
                }
            )
        from presto_tpu.plan.history import progress_total_rows

        expected = progress_total_rows(
            self.local.history_store, q._plan_root
        )
        frac: Optional[float] = None
        if q.done.is_set():
            frac = 1.0
        elif splits_total > 0:
            frac = splits_done / splits_total
        elif expected and rows > 0:
            # no tasks scheduled yet but history knows the shape:
            # cardinality-based floor, capped below 1 (history can
            # underestimate today's data)
            frac = min(rows / expected, 0.99)
        elapsed_ms = q.stats.elapsed_ms
        eta_ms: Optional[float] = None
        if frac is not None:
            if frac >= 1.0:
                eta_ms = 0.0
            elif frac > 0 and elapsed_ms > 0:
                eta_ms = elapsed_ms * (1.0 - frac) / frac
        return {
            "query_id": q.qid,
            "state": q.state,
            "done": q.done.is_set(),
            "elapsed_ms": elapsed_ms,
            "splits_done": splits_done,
            "splits_total": splits_total,
            "rows": rows,
            "bytes": nbytes,
            "device_dispatches": dispatches,
            "spilled_bytes": spilled,
            "expected_rows": expected,
            "progress": frac,
            "eta_ms": eta_ms,
            "stages": stages,
        }

    # ------------------------------------------------- metrics federation

    def cluster_metrics(self) -> str:
        """One federated exposition (``GET /v1/metrics/cluster``): the
        coordinator's own registry plus every TTL-live worker's scrape,
        per-node labeled, with ``node="cluster"`` sums of the additive
        families."""
        from presto_tpu.utils.telemetry import parse_prometheus

        by_node = {
            "coordinator": parse_prometheus(
                REGISTRY.render_prometheus()
            )
        }
        by_node.update(
            self.federation.scrape(
                (w.node_id, w.uri + "/v1/metrics")
                for w in self._ttl_workers()
            )
        )
        return self.federation.render(by_node)

    def _telemetry_tick(self) -> None:
        """One sampler round: fold the coordinator's registry and every
        TTL-live worker's scrape into the ring buffer (monotone,
        label-free streams only — quantile samples don't rate)."""
        from presto_tpu.utils.telemetry import (
            _monotone,
            parse_prometheus,
        )

        samp = self.telemetry_sampler
        if samp is None:
            return
        by_node = {
            "coordinator": parse_prometheus(
                REGISTRY.render_prometheus()
            )
        }
        by_node.update(
            self.federation.scrape(
                (w.node_id, w.uri + "/v1/metrics")
                for w in self._ttl_workers()
            )
        )
        ts = time.time()
        for node_id, samples in by_node.items():
            samp.observe(
                node_id,
                [
                    (name, value)
                    for name, labels, value in samples
                    if _monotone(name) and not labels
                ],
                ts=ts,
            )

    def _telemetry_loop(self) -> None:
        while not self._telemetry_stop.wait(
            self._telemetry_interval_s
        ):
            try:
                self._telemetry_tick()
            except Exception:
                log.debug("telemetry tick failed", exc_info=True)

    # ------------------------------------------- dynamic filtering plane

    def _stage_dynamic_filter(self, q: _Query, stage, workers):
        """Distributed dynamic filtering (reference: runtime filters
        flowing build->probe across the cluster, Sethi et al. ICDE'19
        §III-C; exec/dynfilter.py owns the summary vocabulary).

        When the stage's partitioned (probe) scan feeds the PROBE side
        of an inner/semi join, schedule a build-side SUMMARY stage
        first: workers execute the build subtree over split ranges and
        report per-key summaries (min/max + NDV-capped distinct sets)
        on the task-status plane; the coordinator merges the partials
        and applies the completed filter twice —

        1. a ``FilterNode(dynamic=True)`` fused into the probe fragment
           (pre-join row pruning, pruned counts traced), and
        2. a TupleDomain-lite constraint into ``Connector.get_splits``
           so hive partition pruning and parquet/ORC min-max stats
           skip whole splits before any byte is read.

        The wait is BOUNDED by ``dynamic_filtering_wait_ms``: build
        slowness, task failure, or worker death degrade to ``None`` —
        the caller runs the exact unfiltered plan (never blocks, never
        fails the query). Returns None or a
        ``(fragment, partition_scan, ranges, adapt_obs)`` override
        tuple — ``adapt_obs`` (adaptive execution) carries the build
        side's OBSERVED cardinality beside the estimate it was planned
        on, turning this barrier into the runtime decision point
        ``_run_stage`` consults before the probe schedules."""
        from presto_tpu.exec import dynfilter
        from presto_tpu.server.scheduler import _path_to, _replace_on_path

        session = self.local.session
        if not session.get("enable_dynamic_filtering"):
            return None
        frag = stage.worker_fragment
        walk = list(N.walk(frag))
        if not (0 <= stage.partition_scan < len(walk)):
            return None
        part_scan = walk[stage.partition_scan]
        if not isinstance(part_scan, N.TableScanNode):
            return None
        path = _path_to(frag, part_scan)
        if path is None:
            return None
        # nearest JoinNode ancestor decides: usable only when the probe
        # (left) side of an inner/semi join holds the partitioned scan
        J = None
        probe_steps = None
        for i in range(len(path) - 2, -1, -1):
            n = path[i]
            if isinstance(n, (N.JoinNode, N.CrossJoinNode)):
                if (
                    isinstance(n, N.JoinNode)
                    and n.join_type in ("inner", "semi")
                    and path[i + 1] is n.left
                    and n.left_keys
                ):
                    J = n
                    probe_steps = path[i + 1 : -1]  # J.left -> scan
                break
        if J is None:
            return None
        left_schema = J.left.output_schema()
        build_schema = J.right.output_schema()
        # keys a summary can act on: probe/build types must agree
        # (scales and dictionary id spaces), no long decimals/arrays
        pairs = []
        for lk, rk in zip(J.left_keys, J.right_keys):
            lt = left_schema.get(lk)
            bt = build_schema.get(rk)
            if (
                lt is None
                or bt is None
                or lt != bt
                or lt.is_long_decimal
                or lt.is_array
            ):
                continue
            pairs.append((lk, rk))
        if not pairs:
            return None
        bstage = plan_stage(J.right, self.local.catalogs)
        if bstage is None or not isinstance(
            bstage.final_root, N.RemoteSourceNode
        ):
            # the build subtree has an aggregation cut (partial states
            # would summarize aggregate VALUES, not key domains) or no
            # partitionable scan: skip, keep today's plan
            return None
        ndv = int(session.get("dynamic_filtering_ndv_limit"))
        # adaptive partitioned->broadcast handoff: the probe stage
        # already summarized THIS build subtree — reorder its observed
        # per-key columns onto the keys requested here instead of
        # paying a second summary stage (and its wait budget)
        summary = None
        want = [rk for _, rk in pairs]
        if q._df_probe_reuse:
            from presto_tpu.plan import history as plan_history

            try:
                stash = q._df_probe_reuse.get(
                    plan_history.node_fingerprint(J.right)
                )
            except Exception:
                stash = None
            if stash is not None:
                s_sum, s_keys = stash
                if set(want) <= set(s_keys):
                    summary = dynfilter.subset_summary(
                        (
                            s_sum.columns[s_keys.index(rk)]
                            for rk in want
                        ),
                        rows=s_sum.rows,
                    )
                    REGISTRY.counter(
                        "dynamic_filter.summary_reused"
                    ).update()
        if summary is None:
            wait_s = (
                float(session.get("dynamic_filtering_wait_ms")) / 1000.0
            )
            if wait_s <= 0:
                # "don't wait" knob: no budget to ever read a summary,
                # so don't pay for posting + aborting a build stage
                # either
                REGISTRY.counter("dynamic_filter.wait_expired").update()
                return None
            t0 = time.monotonic()
            with q.trace.span("dynfilter"):
                summary = self._run_dynfilter_summary(
                    q, bstage, workers, want, ndv,
                    deadline=t0 + wait_s,
                )
            waited_ms = (time.monotonic() - t0) * 1000.0
            REGISTRY.distribution("dynamic_filter.wait_ms").add(
                waited_ms
            )
            with q._stats_lock:
                q.stats.dynamic_filter_wait_ms += waited_ms
            if summary is None:
                REGISTRY.counter("dynamic_filter.wait_expired").update()
                return None
        REGISTRY.counter("dynamic_filter.built").update()
        # adaptive execution: the merged summary's observed build
        # cardinality is runtime TRUTH about the estimate this join's
        # distribution was chosen on — hand it to the decision point
        # in _run_stage (returned, not stashed on q: independent
        # fragments run _run_stage concurrently on one query)
        adapt_obs = None
        if session.get("adaptive_enabled") and summary.rows >= 0:
            from presto_tpu.plan import optimizer

            try:
                with self.local._history_scope():
                    est = float(
                        optimizer.estimate_rows(
                            J.right, self.local.catalogs
                        )
                    )
            except Exception:
                est = None
            adapt_obs = {
                "join": J,
                "observed": int(summary.rows),
                "estimate": est,
            }
        probe_cols = [(lk, left_schema[lk]) for lk, _ in pairs]
        pred = dynfilter.to_predicate(summary, probe_cols)
        if pred is None:
            return None, None, None, adapt_obs
        # count the conjuncts actually fused (a merged summary column
        # can lose its value set past the NDV cap and contribute none)
        n_filters = dynfilter.applicable_count(summary, probe_cols)
        REGISTRY.counter("dynamic_filter.applied").update(n_filters)
        # _roll_lock, not _stats_lock: roll_up folds task-side filter
        # counts into the same field under it (see stats.QueryStats)
        with q.stats._roll_lock:
            q.stats.dynamic_filters += n_filters
        # 1. fuse the filter into the probe fragment, directly under
        # the join (names are J.left's output schema there)
        new_J = dataclasses.replace(
            J,
            left=N.FilterNode(
                source=J.left, predicate=pred, dynamic=True
            ),
        )
        jpath = _path_to(frag, J)
        new_frag = _replace_on_path(jpath[:-1], J, new_J)
        new_idx = next(
            i
            for i, n in enumerate(N.walk(new_frag))
            if n is part_scan
        )
        # 2. connector-level split pruning: only keys that reach the
        # probe SCAN unchanged (Filter/Project pass-through of the bare
        # column) may constrain split enumeration
        scan_schema = dict(part_scan.schema)
        scan_pairs = []
        for (lk, _rk), cf in zip(pairs, summary.columns):
            if scan_schema.get(lk) != left_schema[lk]:
                continue
            if all(
                _passes_through(step, lk) for step in (probe_steps or ())
            ):
                scan_pairs.append(((lk, left_schema[lk]), cf))
        ranges = None
        if scan_pairs:
            con = dynfilter.to_constraint(
                dynfilter.subset_summary(
                    [cf for _, cf in scan_pairs]
                ),
                [pc for pc, _ in scan_pairs],
            )
            if con:
                ranges = self._pruned_ranges(
                    q, stage, part_scan, con,
                    deadline=t0 + 2.0 * wait_s,
                )
        return new_frag, new_idx, ranges, adapt_obs

    def _run_dynfilter_summary(
        self, q: _Query, bstage, workers, keys, ndv, deadline
    ):
        """Run the build-summary tasks (one range per worker) and merge
        their reported summaries, all within ``deadline`` (monotonic).
        ANY failure — POST/status errors, task failure, worker death,
        deadline expiry — returns None: the probe proceeds unfiltered.
        Posted tasks are always collected + DELETEd (off-thread)."""
        from presto_tpu.exec import dynfilter

        ranges = assign_ranges(bstage.partition_rows, len(workers))
        ranges = [r for r in ranges if r[1] > r[0]] or [(0, 0)]
        dstage = self._new_stage(q, "dynfilter")
        posted: List[tuple] = []
        merged = None
        ok = False

        def df_policy() -> rpc.RpcPolicy:
            """Every summary-plane RPC is capped by the REMAINING wait
            budget (no retries): a stalled — not cleanly dead — build
            worker must not hold probe scheduling past the bound the
            session promised (rpc.request-timeout-s x retries would)."""
            return rpc.RpcPolicy(
                timeout_s=max(deadline - time.monotonic(), 0.05),
                retries=0,
            )

        try:
            for i, (lo, hi) in enumerate(ranges):
                if time.monotonic() > deadline:
                    return None
                w = workers[i % len(workers)]
                spec = self._register_task(q, dstage, FragmentSpec(
                    task_id=task_ids.mint(
                        q.qid, task_ids.DYNFILTER, next(q._task_seq)
                    ),
                    query_id=q.qid,
                    fragment=bstage.worker_fragment,
                    partition_scan=bstage.partition_scan,
                    split_start=lo,
                    split_end=hi,
                    split_batch_rows=int(
                        self.local.session.get("page_capacity")
                    ),
                    dynfilter_keys=tuple(keys),
                    dynfilter_ndv=ndv,
                    traceparent=q.trace.traceparent(),
                ))
                rpc.call_json(
                    "POST", w.uri + "/v1/task", spec.to_json(),
                    policy=df_policy(),
                    traceparent=spec.traceparent,
                )
                posted.append((w, spec))
            for w, spec in posted:
                while True:
                    if time.monotonic() > deadline:
                        return None
                    st = rpc.call_json(
                        "GET",
                        f"{w.uri}/v1/task/{spec.task_id}/status",
                        policy=df_policy(),
                        traceparent=spec.traceparent,
                    )
                    state = st.get("state")
                    if state == "FINISHED":
                        d = st.get("dynamic_filter")
                        if not d:
                            return None
                        s = dynfilter.FilterSummary.from_json(d)
                        merged = (
                            s if merged is None else merged.merge(s, ndv)
                        )
                        break
                    if state in ("FAILED", "ABORTED"):
                        return None
                    time.sleep(0.02)
            ok = merged is not None
            return merged
        except Exception:
            # injected faults / dead workers / RPC timeouts: degrade
            return None
        finally:
            dstage.state = "FINISHED" if ok else "ABORTED"
            for w, spec in posted:
                self._abort_task(q, w, spec)

    def _pruned_ranges(
        self, q: _Query, stage, part_scan, con, deadline=None
    ):
        """Enumerate the probe scan's splits WITH the dynamic-filter
        constraint and turn the survivors into worker ranges; record
        ``dynamic_filter.splits_pruned``. Returns None (nothing pruned
        — keep the legacy uniform ranges) or the range list.

        ``deadline`` (monotonic) bounds coordinator-side enumeration
        WALL TIME: a constraint-aware connector may probe statistics
        it has not cached yet (ORC decodes the join-key column once
        per stripe), and split pruning is an OPTIMIZATION — so the
        enumeration runs on a background thread and the query stops
        waiting at the deadline, scanning the legacy uniform ranges
        instead. The abandoned probe still completes and warms the
        connector's stats cache, so later queries prune for free."""
        from presto_tpu.exec import dynfilter as DF

        if deadline is not None and time.monotonic() > deadline:
            return None
        conn = self.local.catalogs.get(part_scan.handle.catalog)
        over = max(1, int(self.local.session.get("split_queue_factor")))
        n_ranges = max(len(self.active_workers()) * over, 1)
        chunk = -(-max(stage.partition_rows, 1) // n_ranges)
        base = tuple(part_scan.constraint)

        def collect(c):
            src = conn.get_splits(
                part_scan.handle,
                target_split_rows=chunk,
                constraint=c,
            )
            out = []
            while not src.exhausted:
                out.extend(src.next_batch(256))
            return [s for s in out if s.row_end > s.row_start]

        def enumerate_both():
            return (
                collect(base),
                collect(DF.merge_constraints(base, con)),
            )

        if deadline is None:
            try:
                all_splits, kept = enumerate_both()
            except Exception:
                return None  # enumeration trouble: legacy ranges
        else:
            # timed: the connector's stats probe cannot be interrupted
            # mid-read, so it runs detached — the query gives up at
            # the deadline (unfiltered, correct) while the probe
            # finishes and caches for the next query
            import queue as _queue

            cell: "_queue.Queue" = _queue.Queue()

            def run():
                try:
                    cell.put(("ok", enumerate_both()))
                except Exception as e:
                    cell.put(("err", e))

            threading.Thread(target=run, daemon=True).start()
            try:
                kind, payload = cell.get(
                    timeout=max(deadline - time.monotonic(), 0.05)
                )
            except _queue.Empty:
                REGISTRY.counter(
                    "dynamic_filter.enumeration_timeouts"
                ).update()
                return None
            if kind == "err":
                return None
            all_splits, kept = payload
        # decide by COVERED ROWS, not split counts: pruning the middle
        # of a coalesced split INCREASES the count while still saving
        # reads (one [0,300) split can become [0,100)+[200,300))
        rows_pruned = sum(
            s.row_end - s.row_start for s in all_splits
        ) - sum(s.row_end - s.row_start for s in kept)
        if rows_pruned <= 0:
            return None
        # coalesce survivors into runs (also the overlap basis for the
        # pruned-split count), then chop each run to the legacy chunk
        # size so split placement stays dynamic
        runs: List[List[int]] = []
        for s in sorted(kept, key=lambda s: s.row_start):
            if runs and s.row_start <= runs[-1][1]:
                runs[-1][1] = max(runs[-1][1], s.row_end)
            else:
                runs.append([s.row_start, s.row_end])
        pruned = sum(
            1
            for s in all_splits
            if not any(
                lo < s.row_end and hi > s.row_start for lo, hi in runs
            )
        )
        REGISTRY.counter("dynamic_filter.splits_pruned").update(pruned)
        with q._stats_lock:
            q.stats.dynamic_filter_splits_pruned += pruned
        ranges = []
        for lo, hi in runs:
            while lo < hi:
                ranges.append((lo, min(lo + chunk, hi)))
                lo += chunk
        return ranges or [(0, 0)]

    # -------------------------------------------- adaptive execution
    #
    # Runtime strategy switching at the build-summary barrier (ROADMAP
    # item 2, Presto's adaptive-execution direction): the dynamic-
    # filter plane already runs a join's build subtree FIRST and
    # reports its true cardinality before the probe schedules — these
    # helpers turn that into a decision point. Strategy-switch
    # construction lives HERE and in exec/dynfilter.py only
    # (tools/analyze.py ``adaptive-plane`` rule); every lane fails
    # OPEN to the original plan, and ``adaptive.enabled=false`` never
    # reaches any of it.

    def _adaptive_note(self, q: _Query, note: str) -> None:
        """Record one adaptive decision on the query (the ``adapted``
        QueryInfo flag + the EXPLAIN ANALYZE ``adaptive:`` line)."""
        with q._stats_lock:
            q.stats.adapted = True
            q.stats.adaptive_notes.append(note)

    def _adaptive_nparts(self, observed: int, workers) -> int:
        """Resize the shuffle partition count to the OBSERVED build
        cardinality: one partition per ``page_capacity`` rows, clamped
        to the worker pool — a small-but-mispredicted build must not
        fan a near-empty hash exchange across every worker."""
        cap = max(int(self.local.session.get("page_capacity")), 1)
        return max(1, min(len(workers), -(-int(observed) // cap)))

    def _adaptive_maybe_switch(
        self, q: _Query, fragment_root, obs: dict, workers
    ):
        """Broadcast->partitioned direction: the stage was headed for
        a replicated-build join, and the build summary observed a
        cardinality that contradicts the estimate beyond the
        divergence factor AND exceeds the broadcast bound. Returns the
        fragment's result page (the switched join ran + the remainder
        spliced), or None — keep the original plan."""
        from presto_tpu.plan import history as plan_history

        session = self.local.session
        est, observed = obs.get("estimate"), obs.get("observed")
        factor = float(session.get("adaptive_divergence_factor"))
        if est is None or observed is None:
            return None
        if not plan_history.diverged(est, observed, factor):
            return None
        REGISTRY.counter("adaptive.divergence_detected").update()
        jdt = str(session.get("join_distribution_type")).upper()
        if (
            observed <= int(session.get("join_max_broadcast_rows"))
            or len(workers) <= 1
            or jdt not in ("AUTOMATIC", "AUTO")
        ):
            return None
        J = obs["join"]
        # both sides must admit cut-free source-partitioned producer
        # stages — the same qualification _choose_partitioned_join
        # applies (estimates said "broadcast" so it never planned them)
        side_stages = []
        for side in (J.left, J.right):
            st = plan_stage(side, self.local.catalogs)
            if st is None or not isinstance(
                st.final_root, N.RemoteSourceNode
            ):
                return None
            side_stages.append(st)
        from presto_tpu.server.scheduler import (
            _path_to,
            _replace_on_path,
        )

        path = None
        if J is not fragment_root:
            # resolve the remainder splice BEFORE running anything: a
            # join we cannot splice back must not execute twice
            path = _path_to(fragment_root, J)
            if path is None:
                return None
        nparts = self._adaptive_nparts(observed, workers)
        page = self._run_one_partitioned_join(
            J, side_stages, workers, q, nparts=nparts
        )
        if path is not None:
            # re-plan ONLY the not-yet-scheduled remainder: the
            # executed join splices in as a remote page and everything
            # above it runs over the splice
            remote = N.RemoteSourceNode(fragment_root=J)
            root = _replace_on_path(path[:-1], J, remote)
            leaves, pages = self.local.leaf_pages(
                root, {id(remote): page}
            )
            page = self.local._run_with_pages(root, leaves, pages)
        # count + note only once the switched plan ACTUALLY answered:
        # a splice failure falls back to the original plan (the
        # caller's fail-open catch), and stats must not claim a switch
        # that was rolled back
        REGISTRY.counter("adaptive.strategy_switches").update()
        self._adaptive_note(
            q,
            f"SWITCHED broadcast→partitioned (est {est:.0f} rows, "
            f"observed {observed}, parts {nparts})",
        )
        return page

    def _adaptive_probe_build(
        self, q: _Query, J, side_stages, workers, observed_fp: dict
    ):
        """Partitioned->broadcast direction's evidence gatherer: before
        committing a candidate join's two sides to producer stages, run
        the BUILD subtree as a dynamic-filter-style summary stage (the
        same machinery and the same ``dynamic_filtering_wait_ms``
        budget as PR 4's plane) and report its observed cardinality
        beside the estimate. The observation also lands in
        ``observed_fp`` so the remaining join sequence re-ranks by
        runtime truth. Returns ``{"estimate", "observed"}`` or None —
        no budget, or any failure (fail-open: the partitioned plan
        proceeds as estimated)."""
        from presto_tpu.plan import history as plan_history
        from presto_tpu.plan import optimizer

        wait_s = (
            float(self.local.session.get("dynamic_filtering_wait_ms"))
            / 1000.0
        )
        if wait_s <= 0:
            return None
        bstage = side_stages[1]
        build_schema = dict(bstage.worker_fragment.output_schema())
        keys = [rk for rk in J.right_keys if rk in build_schema]
        if not keys:
            return None
        try:
            with plan_history.with_overrides(observed_fp):
                with self.local._history_scope():
                    est = float(
                        optimizer.estimate_rows(
                            J.right, self.local.catalogs
                        )
                    )
            ndv = int(
                self.local.session.get("dynamic_filtering_ndv_limit")
            )
            summary = self._run_dynfilter_summary(
                q, bstage, workers, keys, ndv,
                deadline=time.monotonic() + wait_s,
            )
        except Exception:
            return None
        if summary is None or summary.rows < 0:
            return None
        try:
            observed_fp[plan_history.node_fingerprint(J.right)] = float(
                summary.rows
            )
        except Exception:
            pass
        # the summary itself rides along: a partitioned->broadcast
        # switch hands it to the replicated join's dynamic-filter
        # plane so the build subtree is not summarized twice
        return {
            "estimate": est,
            "observed": int(summary.rows),
            "summary": summary,
            "keys": tuple(keys),
        }

    # ------------------------------------------------------- stage runner

    def _run_stage(
        self, fragment_root, workers, q: _Query, order_by=None, stage=None
    ):
        """Schedule one fragment across workers; gather + finalize.

        ``order_by`` (ordered MERGE exchange): wrap the worker fragment
        in the given root SortNode so workers emit sorted runs, and
        k-way merge the runs at the gather instead of re-sorting. The
        caller guarantees the stage has no aggregation cut."""
        # QoS: stage boundaries are suspension points too — a query
        # suspended between stages parks before scheduling the next
        self._qos_checkpoint(q)
        jdt = str(
            self.local.session.get("join_distribution_type")
        ).upper()
        if (
            order_by is None
            and len(workers) > 1
            and jdt in ("PARTITIONED", "AUTOMATIC", "AUTO")
        ):
            # PARTITIONED forces the hash-partitioned stage for every
            # qualifying join; AUTOMATIC chooses it per join from stats
            # (reference: AddExchanges' cost-driven distribution choice)
            # — partitioned only when BOTH sides exceed the broadcast
            # bound, so small-table plans keep the replicated fast path
            out = self._run_join_partitioned(
                fragment_root, workers, q,
                auto=jdt != "PARTITIONED",
            )
            if out is not None:
                return out
        if stage is None:
            stage = plan_stage(fragment_root, self.local.catalogs)
        if stage is None:
            # no scan admits a semantics-preserving partitioning:
            # single-task fallback on the coordinator's local engine
            return self.local._run(fragment_root)
        # dynamic filtering: a build-summary stage may rewrite the
        # probe fragment (fused filter) and override the split ranges
        # (connector-level pruning); None = today's plan, exactly.
        # FAIL-OPEN at the boundary: the filter is an optimization and
        # must never fail a query that would succeed unfiltered
        try:
            dyn = self._stage_dynamic_filter(q, stage, workers)
        except Exception:
            REGISTRY.counter("dynamic_filter.plan_errors").update()
            log.warning(
                "query=%s dynamic-filter planning failed; running "
                "unfiltered", q.qid, exc_info=True,
            )
            dyn = None
        dyn_fragment, dyn_scan_idx, dyn_ranges, adapt_obs = (
            dyn if dyn is not None else (None, None, None, None)
        )
        # adaptive execution: the build-summary barrier just reported
        # the build side's TRUE cardinality. When it contradicts the
        # estimate this join's broadcast distribution was chosen on
        # (beyond the divergence factor) and the build is too big to
        # replicate, flip to a hash-partitioned join and run only the
        # not-yet-scheduled remainder over its output — fail-open to
        # the original (possibly dyn-filtered) plan on any error,
        # exactly like the dynamic-filter plane itself
        if adapt_obs is not None and order_by is None:
            try:
                out = self._adaptive_maybe_switch(
                    q, fragment_root, adapt_obs, workers
                )
            except Exception:
                REGISTRY.counter("adaptive.plan_errors").update()
                log.warning(
                    "query=%s adaptive strategy switch failed; keeping "
                    "the original plan", q.qid, exc_info=True,
                )
                out = None
            if out is not None:
                return out
        worker_fragment = (
            dyn_fragment
            if dyn_fragment is not None
            else stage.worker_fragment
        )
        partition_scan_idx = (
            dyn_scan_idx
            if dyn_scan_idx is not None
            else stage.partition_scan
        )
        if order_by is not None:
            worker_fragment = dataclasses.replace(
                order_by, source=worker_fragment
            )
        # worker<->worker shuffle (reference: intermediate stages read
        # their hash partition straight from upstream tasks' partitioned
        # output buffers; the coordinator only sees final-stage output).
        # Applies when the stage cuts at a keyed agg/distinct and >1
        # worker is up; single-worker / global-agg / merge-exchange
        # stages keep the direct gather (nothing to repartition).
        from presto_tpu.exec import streaming as S

        key_names = S._bucket_key_names(stage.worker_fragment)
        if (
            order_by is None
            and len(workers) > 1
            and key_names
            and bool(self.local.session.get("distributed_final"))
        ):
            bucket_root, rest_root, _, _ = S._split_final(
                stage.final_root, stage.worker_fragment
            )
            if bucket_root is not None:
                try:
                    return self._run_stage_shuffled(
                        stage, workers, q, key_names, bucket_root,
                        rest_root,
                        worker_fragment=worker_fragment,
                        partition_scan_idx=partition_scan_idx,
                        ranges_override=dyn_ranges,
                    )
                except Exception as e:
                    out = self._local_fallback(q, fragment_root, None, e)
                    if out is None:
                        raise
                    return out
        # dynamic split placement (reference: SourcePartitionedScheduler
        # handing split batches to whichever task has capacity): cut the
        # scan into more ranges than workers and let each worker thread
        # pull the next unclaimed range when it finishes — a straggler
        # naturally processes fewer ranges (work stealing by queue)
        over = max(1, int(self.local.session.get("split_queue_factor")))
        ranges = (
            dyn_ranges
            if dyn_ranges is not None
            else assign_ranges(
                stage.partition_rows, max(len(workers) * over, 1)
            )
        )
        ranges = [r for r in ranges if r[1] > r[0]] or [(0, 0)]
        stage_stats = self._new_stage(q, "source")

        def make_spec(lo: int, hi: int) -> FragmentSpec:
            return self._register_task(q, stage_stats, FragmentSpec(
                task_id=task_ids.mint(
                    q.qid, task_ids.SOURCE, next(q._task_seq)
                ),
                query_id=q.qid,
                fragment=worker_fragment,
                partition_scan=partition_scan_idx,
                split_start=lo,
                split_end=hi,
                split_batch_rows=int(
                    self.local.session.get("page_capacity")
                ),
                task_concurrency=int(
                    self.local.session.get("task_concurrency")
                ),
                prefetch_depth=int(
                    self.local.session.get("staging_prefetch_depth")
                ),
                traceparent=q.trace.traceparent(),
            ))

        # pull every worker concurrently (reference: the ExchangeClient
        # keeps all upstream tasks in flight; serial draining would
        # block worker 2's bounded buffer on worker 1's drain) and
        # retry a DEAD worker's range on a live one (recoverable
        # execution: reassign, don't fail the query)
        def pull_and_delete(w, spec):
            try:
                out = self._pull_task(w, spec)
            except Exception:
                # the failed attempt's stats/spans still fold into the
                # rollup and its buffered pages get DELETEd — but OFF
                # this thread (see _abort_task)
                self._abort_task(q, w, spec)
                raise
            # success path: all pages pulled — if the worker dies
            # before answering the status GET, the attempt still
            # FINISHED (its rows are in the result)
            self._finish_task(
                q, w, spec.task_id, spec.traceparent,
                presumed="FINISHED",
            )
            return out

        try:
            with q.trace.span("schedule", stage_id=stage_stats.stage_id):
                results = self._ranged_tasks(
                    workers, ranges, make_spec, pull_and_delete,
                    q=q, speculate=True,
                )
        except Exception as e:
            out = self._local_fallback(q, fragment_root, order_by, e)
            if out is None:
                raise
            stage_stats.state = "ABORTED"
            return out
        stage_stats.state = "FINISHED"
        payloads = [p for out in results for p in out]

        schema = dict(stage.worker_fragment.output_schema())
        with q.trace.span("gather", stage_id=stage_stats.stage_id):
            if order_by is not None:
                merged = _merge_sorted_runs(payloads, schema, order_by)
                return stage_page(merged, schema)
            remote = [
                n
                for n in N.walk(stage.final_root)
                if isinstance(n, N.RemoteSourceNode)
            ]
            # bucketed gather (reference: grouped execution at the
            # merge): partial states beyond the device budget
            # hash-bucket by group key and merge one bucket at a time
            # instead of funnelling everything into one staged page
            # (exec.streaming owns the policy, shared with the local
            # streamed path)
            from presto_tpu.exec import streaming as S

            bucketed = S.grouped_final_merge(
                self.local,
                payloads,
                schema,
                stage.final_root,
                stage.worker_fragment,
                int(self.local.session.get("max_device_rows")),
            )
            if bucketed is not None:
                return bucketed
            merged = pages_wire.merge_payloads(payloads, schema)
            page = stage_page(merged, schema)
            # the final plan may contain real scans above the cut (e.g.
            # a join against another table after the final aggregation)
            # — load those locally alongside the gathered remote page
            local_scans = [
                n
                for n in N.walk(stage.final_root)
                if isinstance(n, N.TableScanNode)
            ]
            leaves = remote + local_scans
            pages = [page] + [
                self.local._load_table(s) for s in local_scans
            ]
            return self.local._run_with_pages(
                stage.final_root, leaves, pages
            )

    def _local_fallback(self, q: _Query, fragment_root, order_by, exc):
        """Graceful degradation, last resort: when a distributed stage
        died of connection-level failures and NO worker remains
        alive/circuit-closed, execute the fragment on the coordinator's
        local engine instead of failing the query. Returns None when
        degradation does NOT apply — execution errors, or live workers
        remaining — so the caller re-raises."""
        degradable = rpc.is_task_recoverable(exc) or isinstance(
            exc, NoLiveWorkers
        )
        # a memory-pressure kill DELETEs the victim's tasks — that
        # must surface as the kill, not trigger a local resurrection
        if getattr(q, "_mem_kill", None) is not None:
            return None
        if not degradable or self._any_worker_alive():
            return None
        REGISTRY.counter("coordinator.local_fallbacks").update()
        log.warning(
            "query=%s: no live workers (%s: %s); falling back to "
            "coordinator-local execution",
            q.qid, type(exc).__name__, exc,
        )
        with q.trace.span("execute-local-fallback"):
            out = self.local._run(fragment_root)
            if order_by is not None:
                from presto_tpu.exec.host_ops import apply_host_ops

                out = apply_host_ops(out, [order_by])
            return out

    def _run_join_partitioned(
        self, fragment_root, workers, q: _Query, auto: bool = False
    ):
        """Hash-partitioned intermediate JOIN stages (reference:
        FIXED_HASH_DISTRIBUTION intermediate stages — SURVEY.md §2.4
        "Join distribution choice"): BOTH join inputs run as
        partitioned producer stages that hash their output by the
        equi-join keys into ``len(workers)`` buffers, and a join stage
        (one task per partition) pulls matching partitions from every
        producer of both sides — neither side is replicated. Valid for
        every equi-join type: a key lands in the same partition on both
        sides (value-stable hash), so per-partition joins partition the
        full join.

        ``auto=False`` (session ``join_distribution_type=PARTITIONED``)
        takes every qualifying join — one whose two sides each admit a
        cut-free source-partitioned stage. ``auto=True`` (AUTOMATIC)
        additionally requires BOTH sides' estimated rows to exceed
        ``join_max_broadcast_rows``, the engine's form of the
        reference's stats-driven AddExchanges choice: when one side is
        small, replicating it (the caller's fallback path) ships less
        data than repartitioning both. Qualifying joins are taken
        best-first (largest min-side estimate — where broadcast would
        hurt most) and ITERATED: independent joins elsewhere in the
        plan each get their own partitioned stage, their result pages
        feeding the final local splice. Returns None when no join
        qualifies (caller falls through to the replicated-build path).
        """
        thresh = (
            int(self.local.session.get("join_max_broadcast_rows"))
            if auto
            else None
        )
        from presto_tpu.plan import history as plan_history

        session = self.local.session
        adaptive = bool(session.get("adaptive_enabled"))
        factor = float(session.get("adaptive_divergence_factor"))
        #: adaptive execution: node fingerprint -> OBSERVED rows of
        #: already-executed stages this query — candidate ranking for
        #: the not-yet-scheduled remainder re-runs under these
        #: overrides, so the join sequence re-orders by runtime truth
        observed_fp: Dict[str, float] = {}
        #: candidates the runtime decision point sent back to the
        #: broadcast path (never reconsidered this query)
        skip: set = set()
        root = fragment_root
        pages_map: Dict[int, object] = {}
        ran = False
        while True:
            target = self._choose_partitioned_join(
                root, thresh, skip=skip,
                observed=observed_fp if adaptive else None,
            )
            if target is None:
                break
            J, side_stages = target
            nparts = None
            if adaptive and thresh is not None:
                # runtime decision point (fail-open inside): observe
                # the build side through a summary stage BEFORE
                # committing both sides to producer stages
                obs = self._adaptive_probe_build(
                    q, J, side_stages, workers, observed_fp
                )
                if obs is not None and plan_history.diverged(
                    obs["estimate"], obs["observed"], factor
                ):
                    REGISTRY.counter(
                        "adaptive.divergence_detected"
                    ).update()
                    if obs["observed"] <= thresh:
                        # the build is actually broadcast-small: leave
                        # this join to the replicated-build path (the
                        # caller's fallback, dynamic filter included)
                        REGISTRY.counter(
                            "adaptive.strategy_switches"
                        ).update()
                        self._adaptive_note(
                            q,
                            "SWITCHED partitioned→broadcast (est "
                            f"{obs['estimate']:.0f} rows, observed "
                            f"{obs['observed']})",
                        )
                        # hand the probe's observed summary to the
                        # replicated join's dynamic-filter plane (the
                        # build subtree was JUST summarized — running
                        # the summary stage again would pay the wait
                        # twice for the same evidence)
                        try:
                            q._df_probe_reuse[
                                plan_history.node_fingerprint(J.right)
                            ] = (obs["summary"], obs["keys"])
                        except Exception:
                            pass
                        skip.add(id(J))
                        continue
                    nparts = self._adaptive_nparts(
                        obs["observed"], workers
                    )
                    if nparts != len(workers):
                        self._adaptive_note(
                            q,
                            f"RESIZED shuffle to {nparts} partition(s) "
                            f"(observed {obs['observed']} build rows)",
                        )
            page = self._run_one_partitioned_join(
                J, side_stages, workers, q, nparts=nparts
            )
            ran = True
            if J is root and not pages_map:
                return page
            remote = N.RemoteSourceNode(fragment_root=J)
            if adaptive:
                # feed the executed join's TRUE output rows back into
                # the remainder's ranking (both identities: the join
                # subtree itself and the remote splice that now stands
                # where it stood)
                try:
                    rows = float(page.num_valid)
                    observed_fp[
                        plan_history.node_fingerprint(J)
                    ] = rows
                    observed_fp[
                        plan_history.node_fingerprint(remote)
                    ] = rows
                except Exception:
                    pass
            from presto_tpu.server.scheduler import (
                _path_to,
                _replace_on_path,
            )

            path = _path_to(root, J)
            root = _replace_on_path(path[:-1], J, remote)
            pages_map[id(remote)] = page
        if not ran:
            return None
        leaves, pages = self.local.leaf_pages(root, pages_map)
        return self.local._run_with_pages(root, leaves, pages)

    def _choose_partitioned_join(
        self, root, thresh: Optional[int], skip=(), observed=None
    ):
        """Best qualifying join for a partitioned stage, or None.

        Qualifying: an equi-join whose sides BOTH admit cut-free
        source-partitioned stages. With ``thresh`` (AUTOMATIC mode) the
        min-side row estimate must exceed it, and candidates rank by
        that estimate — the join where replicating the smaller side
        would ship the most rows wins first.

        Adaptive execution: ``skip`` holds joins the runtime decision
        point sent back to the broadcast path, and ``observed`` (node
        fingerprint -> rows of already-executed stages) re-ranks the
        remainder under plan/history.with_overrides — observed
        cardinality outranks the estimate it contradicted. Both
        default empty = today's ranking, bit-exact."""
        import contextlib

        from presto_tpu.plan import history as plan_history
        from presto_tpu.plan import optimizer

        if observed:
            scope = contextlib.ExitStack()
            scope.enter_context(plan_history.with_overrides(observed))
            scope.enter_context(self.local._history_scope())
        else:
            scope = contextlib.nullcontext()
        with scope:
            return self._choose_partitioned_join_ranked(
                root, thresh, skip, optimizer
            )

    def _choose_partitioned_join_ranked(
        self, root, thresh: Optional[int], skip, optimizer
    ):
        cands = []
        for J in N.walk(root):
            if not isinstance(J, N.JoinNode) or not J.left_keys:
                continue
            if id(J) in skip:
                continue
            # a side spliced with a prior iteration's materialized
            # RemoteSourceNode cannot run as a producer stage (workers
            # have no way to resolve the remote page) — skip before any
            # stage planning
            if any(
                isinstance(n, N.RemoteSourceNode)
                for side in (J.left, J.right)
                for n in N.walk(side)
            ):
                continue
            if thresh is not None:
                # cheap stats gate BEFORE any stage-planning work: in
                # the default AUTOMATIC mode most joins are small and
                # exit here without paying plan_stage
                small = min(
                    optimizer.estimate_rows(
                        J.left, self.local.catalogs
                    ),
                    optimizer.estimate_rows(
                        J.right, self.local.catalogs
                    ),
                )
                if small <= thresh:
                    continue
                cands.append((float(small), J))
            else:
                cands.append((0.0, J))
        if thresh is not None:
            # best-first by min-side estimate; plan stages only for the
            # winner, falling back down the ranking when a candidate's
            # sides don't admit source-partitioned stages
            cands.sort(key=lambda t: -t[0])
        for _, J in cands:
            stages = []
            for side in (J.left, J.right):
                st = plan_stage(side, self.local.catalogs)
                if st is None or not isinstance(
                    st.final_root, N.RemoteSourceNode
                ):
                    stages = None
                    break
                stages.append(st)
            if stages:
                return (J, stages)
        return None

    def _run_one_partitioned_join(
        self, J, side_stages, workers, q, nparts=None
    ):
        """Run ONE join as producer stages + a partitioned join stage;
        returns the gathered join output page. ``nparts`` (adaptive
        execution) overrides the partition fan-out — clamped to the
        pool; None = one partition per worker, the legacy shape."""
        from concurrent.futures import ThreadPoolExecutor

        REGISTRY.counter("coordinator.partitioned_join_stages").update()
        nparts = (
            len(workers)
            if nparts is None
            else max(1, min(int(nparts), len(workers)))
        )
        over = max(1, int(self.local.session.get("split_queue_factor")))
        created: List[tuple] = []
        clock = threading.Lock()
        # transport selection (the scheduler owns it): both producer
        # stages and the join stage carry the same slice id — either
        # side's schema being ICI-ineligible keeps the whole exchange
        # on the HTTP wire, but a lone cross-slice worker settles its
        # own edges to HTTP at run time (per-edge selection)
        ici_slice = self._select_transport(
            workers,
            schemas=(
                dict(side_stages[0].worker_fragment.output_schema()),
                dict(side_stages[1].worker_fragment.output_schema()),
            ),
        )
        if ici_slice:
            REGISTRY.counter("exchange.ici_stages").update()

        def run_producers(stage, keys, group):
            ranges = assign_ranges(
                stage.partition_rows, max(len(workers) * over, 1)
            )
            ranges = [r for r in ranges if r[1] > r[0]] or [(0, 0)]
            pstage = self._new_stage(q, "producer")

            def make_spec(lo: int, hi: int) -> FragmentSpec:
                return self._register_task(q, pstage, FragmentSpec(
                    task_id=task_ids.mint(
                        q.qid, task_ids.PRODUCER, next(q._task_seq)
                    ),
                    query_id=q.qid,
                    fragment=stage.worker_fragment,
                    partition_scan=stage.partition_scan,
                    split_start=lo,
                    split_end=hi,
                    split_batch_rows=int(
                        self.local.session.get("page_capacity")
                    ),
                    task_concurrency=int(
                        self.local.session.get("task_concurrency")
                    ),
                    prefetch_depth=int(
                        self.local.session.get("staging_prefetch_depth")
                    ),
                    n_partitions=nparts,
                    partition_keys=tuple(keys),
                    spool=self._spooling(),
                    ici_slice=ici_slice,
                    traceparent=q.trace.traceparent(),
                ))

            def wait_producer(w, spec):
                with clock:
                    created.append((w, spec.task_id))
                self._wait_task(w, spec)
                return (w.uri, spec.task_id, group)

            # legacy (retry_policy=NONE): producer death fails the
            # query — partitioned exchanges are non-recoverable. Under
            # TASK (and QUERY, its superset) the stage recovers: the
            # sources list carries only winning attempts (barrier
            # mode), and join tasks pulling a later-dead producer
            # re-serve its committed partitions from the durable spool
            res = self._ranged_tasks(
                workers, ranges, make_spec, wait_producer,
                q=q, retry=self._retry_policy() in ("TASK", "QUERY"),
            )
            pstage.state = "FINISHED"
            return res

        try:
            # both producer stages are independent: run concurrently
            # (sequential would cost sum, not max, of the side walls)
            with q.trace.span("schedule", phase="join-producers"):
                with ThreadPoolExecutor(2) as side_pool:
                    side_futs = [
                        side_pool.submit(run_producers, stage, keys, group)
                        for (stage, keys, group) in (
                            (side_stages[0], J.left_keys, 0),
                            (side_stages[1], J.right_keys, 1),
                        )
                    ]
                    sources: List[tuple] = [
                        s for f in side_futs for s in f.result()
                    ]

            join_frag = dataclasses.replace(
                J,
                left=N.RemoteSourceNode(fragment_root=J.left),
                right=N.RemoteSourceNode(fragment_root=J.right),
            )
            jstage = self._new_stage(q, "join")
            # join tasks pull both sides' partitions and hold the only
            # merged copy: stable nodes first (preemptible-aware)
            jworkers = stable_workers(workers)

            def run_join_task(i: int):
                w = jworkers[i % len(jworkers)]
                spec = self._register_task(q, jstage, FragmentSpec(
                    task_id=task_ids.mint(
                        q.qid, task_ids.JOIN, next(q._task_seq)
                    ),
                    query_id=q.qid,
                    fragment=join_frag,
                    partition_scan=-1,
                    split_start=0,
                    split_end=0,
                    sources=tuple(sources),
                    partition=i,
                    spool=self._spooling(),
                    ici_slice=ici_slice,
                    traceparent=q.trace.traceparent(),
                ))
                with clock:
                    created.append((w, spec.task_id))
                self._rpc_json(
                    "POST", w.uri + "/v1/task", spec.to_json(),
                    traceparent=spec.traceparent,
                )
                return self._pull_task(w, spec)

            with ThreadPoolExecutor(nparts) as pool:
                futs = [
                    pool.submit(run_join_task, i) for i in range(nparts)
                ]
                payloads = [p for f in futs for p in f.result()]
            jstage.state = "FINISHED"
        finally:
            for w, tid in created:
                self._finish_task(q, w, tid)

        schema = dict(join_frag.output_schema())
        if payloads:
            merged = pages_wire.merge_payloads(payloads, schema)
        else:
            merged = {
                nm: np.empty(0, t.np_dtype) for nm, t in schema.items()
            }
        return stage_page(merged, schema)

    def _run_stage_shuffled(
        self, stage, workers, q: _Query, key_names, bucket_root,
        rest_root, worker_fragment=None, partition_scan_idx=None,
        ranges_override=None,
    ):
        """Two-stage execution with a worker<->worker data plane.

        Stage 1 (producers): the usual dynamic range queue, but each
        task hash-partitions its PARTIAL output by the final agg's group
        keys into ``len(workers)`` output buffers (value-stable hash —
        exec.streaming's). Stage 2 (mergers): one task per worker pulls
        its partition from EVERY producer and runs the FINAL merge; the
        coordinator gathers only the merged (small) results and
        concatenates — correct because the hash partitions the group
        space. Sources attach when stage 1 completes (no pipelined
        shuffle start yet — documented simplification vs the reference's
        incremental addExchangeLocations)."""
        REGISTRY.counter("coordinator.shuffled_stages").update()
        # dynamic-filter overrides from _run_stage (None = legacy)
        if worker_fragment is None:
            worker_fragment = stage.worker_fragment
        if partition_scan_idx is None:
            partition_scan_idx = stage.partition_scan
        over = max(1, int(self.local.session.get("split_queue_factor")))
        ranges = (
            ranges_override
            if ranges_override is not None
            else assign_ranges(
                stage.partition_rows, max(len(workers) * over, 1)
            )
        )
        ranges = [r for r in ranges if r[1] > r[0]] or [(0, 0)]
        nparts = len(workers)
        prod_stage = self._new_stage(q, "producer")
        merge_stage = self._new_stage(q, "merge")
        # transport selection (the scheduler owns it): co-located
        # producer/merge workers exchange partitions as device
        # collectives; "" keeps the serialized HTTP wire, and a lone
        # cross-slice worker settles its own edges at run time
        ici_slice = self._select_transport(
            workers,
            schemas=(dict(worker_fragment.output_schema()),),
        )
        if ici_slice:
            REGISTRY.counter("exchange.ici_stages").update()

        def make_spec(lo: int, hi: int) -> FragmentSpec:
            return self._register_task(q, prod_stage, FragmentSpec(
                task_id=task_ids.mint(
                    q.qid, task_ids.PRODUCER, next(q._task_seq)
                ),
                query_id=q.qid,
                fragment=worker_fragment,
                partition_scan=partition_scan_idx,
                split_start=lo,
                split_end=hi,
                split_batch_rows=int(
                    self.local.session.get("page_capacity")
                ),
                task_concurrency=int(
                    self.local.session.get("task_concurrency")
                ),
                prefetch_depth=int(
                    self.local.session.get("staging_prefetch_depth")
                ),
                n_partitions=nparts,
                partition_keys=tuple(key_names),
                spool=self._spooling(),
                ici_slice=ici_slice,
                traceparent=q.trace.traceparent(),
            ))

        from concurrent.futures import ThreadPoolExecutor

        # every task POSTed (incl. attempts on workers that later died)
        # is recorded so the finally below can DELETE it — buffered
        # shuffle partitions must not outlive the query on any worker
        created: List[tuple] = []
        clock = threading.Lock()

        # PIPELINED shuffle start (reference: merge stages run
        # concurrently with their producers; sources attach via
        # addExchangeLocations): merge tasks are created FIRST with no
        # sources, each producer is announced the moment its task is
        # POSTed (pulls overlap production), and the set is sealed when
        # every range completes. Limitation vs full recoverability: a
        # producer dying after announcement fails the query (classic
        # non-recoverable exchange; the gather path's range retry
        # remains the recoverable fallback).
        merge_specs: List[tuple] = []

        def broadcast(source_list, done: bool):
            # transient PUT drops are healed by the SEAL broadcast,
            # which always carries the FULL deduped source list; a
            # dead merge worker surfaces at the pull
            body = {
                "sources": [list(s) for s in source_list],
                "done": done,
            }
            for w, spec in merge_specs:
                try:
                    self._rpc_json(
                        "PUT",
                        f"{w.uri}/v1/task/{spec.task_id}/sources",
                        body,
                        traceparent=spec.traceparent,
                    )
                except Exception:
                    pass

        def wait_producer(w, spec):
            with clock:
                created.append((w, spec.task_id))
            broadcast([(w.uri, spec.task_id)], False)
            self._wait_task(w, spec)
            return (w, spec.task_id)

        try:
            # merge tasks first, placed on live workers (a worker that
            # died since discovery is skipped, not fatal). Preemptible-
            # aware placement: merge state is the only copy of its
            # partition's FINAL, so merges go to stable nodes when any
            # exist — preemptibles keep the spool-backed producer work
            candidates = stable_workers(workers)
            for i in range(nparts):
                posted = False
                for k in range(len(candidates)):
                    w = candidates[(i + k) % len(candidates)]
                    spec = self._register_task(q, merge_stage, FragmentSpec(
                        task_id=task_ids.mint(
                            q.qid, task_ids.MERGE, next(q._task_seq)
                        ),
                        query_id=q.qid,
                        fragment=bucket_root,
                        partition_scan=-1,
                        split_start=0,
                        split_end=0,
                        partition=i,
                        spool=self._spooling(),
                        ici_slice=ici_slice,
                        traceparent=q.trace.traceparent(),
                    ))
                    try:
                        self._rpc_json(
                            "POST", w.uri + "/v1/task", spec.to_json(),
                            traceparent=spec.traceparent,
                        )
                    except (
                        urllib.error.URLError, ConnectionError, OSError
                    ):
                        self._worker_failed(w)
                        continue
                    merge_specs.append((w, spec))
                    posted = True
                    break
                if not posted:
                    raise NoLiveWorkers(
                        "no live worker accepts merge tasks"
                    )

            # legacy (retry_policy=NONE): a producer dying after its
            # announcement fails the query (classic non-recoverable
            # exchange). With the spool (TASK, or QUERY before its
            # last-resort restart) producers are retryable: every
            # attempt spools under one logical key and merge tasks
            # consume exactly ONE committed attempt per key, so a
            # retried producer racing its announced original can
            # never double-count
            with q.trace.span("schedule", stage_id=prod_stage.stage_id):
                producers = self._ranged_tasks(
                    workers, ranges, make_spec, wait_producer,
                    q=q, retry=self._spooling(),
                )
            sources = tuple((w.uri, tid) for w, tid in producers)
            # seal with the FULL list: add_sources dedups, so this
            # also repairs any announcement a merge task missed
            broadcast(sources, True)

            def run_merge_fallback(i: int, w):
                # merge-worker death: re-run that partition's FINAL as
                # a barrier-mode merge task — the SAME logical task,
                # next attempt — on a live worker (full source list
                # known by now; dead producers' partitions re-serve
                # from the durable spool when retry_policy spools)
                spec = self._register_task(
                    q,
                    merge_stage,
                    self._retry_spec(q, merge_specs[i][1], sources=sources),
                )
                try:
                    self._rpc_json(
                        "POST", w.uri + "/v1/task", spec.to_json(),
                        traceparent=spec.traceparent,
                    )
                    return self._pull_task(w, spec)
                finally:
                    self._finish_task(
                        q, w, spec.task_id, spec.traceparent
                    )

            def run_merge(i: int):
                w, spec = merge_specs[i]
                try:
                    return self._pull_task(w, spec)
                except (
                    urllib.error.URLError, ConnectionError, OSError
                ):
                    if getattr(q, "_mem_kill", None) is None:
                        self._worker_failed(w)
                    others = stable_workers(
                        self.active_workers(exclude={w.node_id})
                    )
                    if not others:
                        raise
                    self._record_recovery(q)
                    with q.trace.span(
                        "recovery", phase="merge-task",
                        task_id=spec.task_id,
                    ):
                        return run_merge_fallback(
                            i, others[i % len(others)]
                        )

            with q.trace.span("gather", stage_id=merge_stage.stage_id):
                with ThreadPoolExecutor(nparts) as pool:
                    futs = [
                        pool.submit(run_merge, i) for i in range(nparts)
                    ]
                    payloads = [p for f in futs for p in f.result()]
        finally:
            for w, spec in merge_specs:
                self._finish_task(q, w, spec.task_id, spec.traceparent)
            for w, tid in created:
                self._finish_task(q, w, tid)
            # success only: a propagating failure leaves the stages
            # RUNNING for _finish_query_stats to close as FAILED
            if sys.exc_info()[0] is None:
                prod_stage.state = "FINISHED"
                merge_stage.state = "FINISHED"

        schema = dict(bucket_root.output_schema())
        merged = pages_wire.merge_payloads(payloads, schema)
        page = stage_page(merged, schema)
        if rest_root is None:
            return page
        rest_remote = [
            n
            for n in N.walk(rest_root)
            if isinstance(n, N.RemoteSourceNode)
        ]
        local_scans = [
            n
            for n in N.walk(rest_root)
            if isinstance(n, N.TableScanNode)
        ]
        pages = [page] + [
            self.local._load_table(s) for s in local_scans
        ]
        return self.local._run_with_pages(
            rest_root, rest_remote + local_scans, pages
        )

    def _ranged_tasks(
        self, workers, ranges, make_spec, consume,
        q: Optional[_Query] = None, retry=True, speculate=False,
    ):
        """Dynamic split placement shared by the gather and shuffle
        paths: over-partitioned ranges in a queue, each worker's thread
        pulls the next unclaimed range (work stealing by queue).
        ``consume(w, spec)`` runs after the task POST (pull pages, or
        await FINISH); its results are collected in arbitrary order.

        Fault tolerance (``retry=True``, the gather path): a DEAD
        worker's range is re-POSTed to a live worker, bounded by the
        query's ``task_retry_budget`` (generalizing the old
        retry-once); every failure feeds the worker's circuit breaker,
        and a range headed for a breaker-open worker re-routes without
        consuming budget. ``speculate=True`` additionally launches ONE
        backup attempt on another live worker when a range runs past
        the straggler threshold — ``max(speculation_min_s,
        speculation_multiplier x p50)`` of this stage's completed-range
        durations (reservoir quantiles) — first result wins, the loser
        is aborted and DELETEd. ``retry=False`` (shuffle producers)
        disables both: the pipelined shuffle must NOT re-produce a
        range whose first task was already announced to merge tasks,
        or its rows double-count. Execution errors inside a healthy
        worker are never retried — they would fail anywhere."""
        import queue as _queue
        from concurrent.futures import ThreadPoolExecutor

        session = self.local.session
        spec_on = (
            speculate
            and retry
            and bool(session.get("speculation_enabled"))
            and len(workers) > 1
        )
        spec_min = float(session.get("speculation_min_s"))
        spec_mult = float(session.get("speculation_multiplier"))
        # completed-range durations for THIS stage; the reservoir
        # quantiles set the straggler threshold
        durations = DistributionStat()

        def straggler_threshold() -> Optional[float]:
            v = durations.values()
            if v["count"] < 3:
                return None  # too few samples to call a straggler
            th = max(spec_min, spec_mult * v["p50"])
            if self.qos is not None and q is not None:
                # deadline-aware speculation (server/qos.py): the
                # threshold tightens as the query approaches its
                # group's SLO budget
                th *= self.qos.speculation_scale(q)
            return th

        def spare_worker(tried_ids):
            # exclude BEFORE the breaker check: asking for a spare
            # must not consume an already-tried worker's probe slot
            alive = self.active_workers(exclude=tried_ids)
            return alive[0] if alive else None

        def run_range(w, lo, hi):
            if not retry:
                # non-recoverable stage (shuffle producer under
                # retry_policy=NONE): no retry, no speculation — run
                # the single attempt inline instead of paying a
                # monitor thread per range. One exception: a DRAINING
                # worker answers the POST with 503 and creates NO task,
                # so re-routing the untouched spec to a spare is free
                # and safe even for pipelined exchanges.
                spec = make_spec(lo, hi)
                target, rerouted = w, set()
                while True:
                    try:
                        rpc.call_json(
                            "POST",
                            target.uri + "/v1/task",
                            spec.to_json(),
                            policy=self._rpc_policy,
                            traceparent=spec.traceparent,
                        )
                        break
                    except urllib.error.HTTPError as e:
                        if e.code != 503:
                            raise
                        rerouted.add(target.node_id)
                        alt = spare_worker(rerouted)
                        if alt is None:
                            raise
                        target = alt
                    except Exception as e:
                        # connection-level POST failure: the breaker
                        # must learn about the dead worker even though
                        # this stage cannot retry
                        if rpc.is_retryable(e):
                            self._worker_failed(target)
                        raise
                try:
                    out = consume(target, spec)
                    self._worker_ok(target)
                    return out
                except Exception as e:
                    if rpc.is_retryable(e):
                        self._worker_failed(target)
                    raise
            cond = threading.Condition()
            state = {
                "attempts": [], "active": 0, "winner": None,
                "result": None, "fatal": None, "conn_errors": [],
            }

            def attempt(worker, spec, backup):
                try:
                    rpc.call_json(
                        "POST", worker.uri + "/v1/task", spec.to_json(),
                        policy=self._rpc_policy,
                        traceparent=spec.traceparent,
                    )
                    out = consume(worker, spec)
                    self._worker_ok(worker)
                    with cond:
                        if state["winner"] is None:
                            state["winner"] = spec.task_id
                            state["result"] = out
                            if backup:
                                REGISTRY.counter(
                                    "coordinator.speculation_wins"
                                ).update()
                except Exception as e:
                    # a 404 on a task endpoint means the worker lost
                    # the task (crash + restart under the same URI);
                    # a 503 means it is DRAINING and created nothing:
                    # both recoverable, like a dead socket. Other HTTP
                    # errors (a FAILED task's 500) are execution
                    # failures — they would fail anywhere.
                    recoverable = rpc.is_task_recoverable(e)
                    if recoverable:
                        if not _is_draining_503(e) and (
                            q is None
                            or getattr(q, "_mem_kill", None) is None
                        ):
                            # a graceful drain is not a failure, and
                            # neither is a memory-pressure kill (the
                            # 404s on the victim's DELETEd tasks come
                            # from the kill, not worker health): no
                            # breaker penalty for either
                            self._worker_failed(worker)
                        with cond:
                            state["conn_errors"].append(e)
                    else:
                        with cond:
                            if state["fatal"] is None:
                                state["fatal"] = e
                finally:
                    with cond:
                        state["active"] -= 1
                        cond.notify_all()

            def launch(worker, backup=False):
                # register synchronously: the monitor loop must never
                # observe active == 0 for a launched-but-unstarted
                # attempt. Re-launches of this range keep the logical
                # task id and bump only the attempt (server.task_ids):
                # spool dedup and per-stage attempt counters key on it
                with cond:
                    prior = (
                        state["attempts"][-1][1]
                        if state["attempts"]
                        else None
                    )
                spec = (
                    make_spec(lo, hi)
                    if prior is None
                    else self._retry_spec(q, prior)
                )
                if backup and q is not None:
                    with q._stats_lock:
                        q._speculative.add(spec.task_id)
                with cond:
                    state["attempts"].append((worker, spec))
                    state["active"] += 1
                threading.Thread(
                    target=attempt, args=(worker, spec, backup),
                    daemon=True,
                ).start()

            # a range headed for a breaker-OPEN worker re-routes for
            # free (not a failure retry: the breaker already knows).
            # peek(), not allow(): this worker was already admitted by
            # active_workers() at scheduling — consuming a second
            # half-open probe slot here would strand its own probe.
            primary = w
            if retry and self._breaker(w.node_id).peek() == "OPEN":
                alt = spare_worker({w.node_id})
                if alt is not None:
                    primary = alt
            launch(primary)
            t0 = time.monotonic()
            speculated = False
            while True:
                with cond:
                    winner = state["winner"]
                    fatal = state["fatal"]
                    active = state["active"]
                    last_err = (
                        state["conn_errors"][-1]
                        if state["conn_errors"]
                        else None
                    )
                if winner is not None or fatal is not None:
                    break
                if active == 0:
                    # every attempt died on a connection failure:
                    # budget-bounded reassignment to a live worker
                    tried = {
                        wk.node_id for wk, _ in state["attempts"]
                    }
                    nxt = spare_worker(tried) if retry else None
                    # a drain rejection re-routes for FREE: the task
                    # was never created, nothing was lost — charging
                    # the retry budget would let task_retry_budget=0
                    # break the drain protocol's zero-failure promise
                    free = _is_draining_503(last_err)
                    if nxt is None or q is None or (
                        not free and not self._take_retry(q)
                    ):
                        raise last_err or NoLiveWorkers(
                            "no live worker for range "
                            f"[{lo}, {hi})"
                        )
                    if free:
                        REGISTRY.counter(
                            "coordinator.drain_reroutes"
                        ).update()
                        launch(nxt)
                        continue
                    self._record_recovery(q)
                    with q.trace.span(
                        "recovery", phase="task-retry",
                        range=f"[{lo}, {hi})",
                    ):
                        launch(nxt)
                    continue
                if spec_on and not speculated:
                    th = straggler_threshold()
                    if th is not None and time.monotonic() - t0 > th:
                        tried = {
                            wk.node_id for wk, _ in state["attempts"]
                        }
                        backup_w = spare_worker(tried)
                        if backup_w is not None:
                            speculated = True
                            REGISTRY.counter(
                                "coordinator.tasks_speculated"
                            ).update()
                            launch(backup_w, backup=True)
                # wait for progress — re-checking the predicate under
                # the lock first, so a completion that landed between
                # the read above and this wait is never slept through.
                # The periodic wakeup exists only for the straggler
                # timer; without speculation armed, sleep until the
                # attempt resolves (notify_all always fires).
                with cond:
                    if (
                        state["winner"] is None
                        and state["fatal"] is None
                        and state["active"] > 0
                    ):
                        cond.wait(
                            timeout=0.05
                            if spec_on and not speculated
                            else None
                        )
            if fatal is not None:
                # execution failure: tear down every attempt of this
                # range (an in-flight backup must not leak its task)
                if q is not None:
                    for wk, sp in state["attempts"]:
                        self._abort_task(q, wk, sp)
                raise fatal
            # first result won: abort + DELETE the losing attempts
            # (their stats fold in as provisional snapshots and are
            # closed out with the query)
            if q is not None:
                for wk, sp in state["attempts"]:
                    if sp.task_id != winner:
                        self._abort_task(q, wk, sp)
            dur = time.monotonic() - t0
            durations.add(dur)
            REGISTRY.distribution("coordinator.range_time_s").add(dur)
            return state["result"]

        range_q: "_queue.Queue" = _queue.Queue()
        for r in ranges:
            range_q.put(r)

        def drain_worker(w):
            out = []
            while True:
                # QoS preempt-and-resume: a suspended query's stage
                # threads park HERE, between ranges — claimed ranges
                # ran to completion (tasks exit clean, spool-backed
                # producers committed), unclaimed ones wait out the
                # suspension and re-run under fresh claims on resume
                self._qos_checkpoint(q)
                try:
                    lo, hi = range_q.get_nowait()
                except _queue.Empty:
                    return out
                out.append(run_range(w, lo, hi))

        with ThreadPoolExecutor(max(len(workers), 1)) as pool:
            futs = [pool.submit(drain_worker, w) for w in workers]
            return [r for f in futs for r in f.result()]

    def _wait_task(self, w, spec) -> None:
        """Poll a producer task to completion (its pages stay buffered
        for the merge stage; nothing is pulled here). Monotonic-clock
        deadline: a wall-clock jump can neither fire nor suppress the
        task timeout."""
        deadline = time.monotonic() + float(
            self.local.session.get("query_max_run_time_s")
        )
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(f"task {spec.task_id} timed out")
            st = self._rpc_json(
                "GET", f"{w.uri}/v1/task/{spec.task_id}/status",
                traceparent=spec.traceparent,
            )
            state = st.get("state")
            if state == "FINISHED":
                return
            if state == "FAILED":
                raise RuntimeError(
                    f"task on {w.node_id} failed: {st.get('error')}"
                )
            time.sleep(0.03)

    def _pull_task(self, w, spec) -> List[tuple]:
        """Token-acked page pulls until X-Complete (exchange client):
        the shared rpc.pull_pages loop, with a stall hook that polls
        task status so a FAILED task surfaces its worker-side error
        text. Monotonic-clock deadline (see _wait_task).

        ICI gather edge: when the pulled task's stage was planned on
        this coordinator's own slice (single-partition root output,
        single-program mode), the result is taken straight from the
        in-slice segment — no serialization, no HTTP page loop. The
        HTTP pull below stays the fallback either way (a worker whose
        output missed the ICI lane materializes lazily on first
        read), and the task is still DELETEd by the caller."""

        def stall():
            st = self._rpc_json(
                "GET", f"{w.uri}/v1/task/{spec.task_id}/status"
            )
            if st.get("state") == "FAILED":
                raise RuntimeError(
                    f"task on {w.node_id} failed: {st.get('error')}"
                )
            time.sleep(0.05)

        if (
            spec.ici_slice
            and spec.ici_slice == self.slice_id
            and bool(self.local.session.get("exchange_single_program"))
        ):
            from presto_tpu.server import exchange_spi

            def probe() -> bool:
                # liveness + terminality probe for the segment wait:
                # FAILED surfaces the worker error; a FINISHED task
                # returns False so the gather re-checks seal-or-never
                # instead of spinning to the deadline
                try:
                    st = self._rpc_json(
                        "GET", f"{w.uri}/v1/task/{spec.task_id}/status"
                    )
                except Exception:
                    return False
                if st.get("state") == "FAILED":
                    raise RuntimeError(
                        f"task on {w.node_id} failed: "
                        f"{st.get('error')}"
                    )
                return st.get("state") not in ("FINISHED", "ABORTED")

            got = exchange_spi.ici_gather(
                self.slice_id,
                spec,
                time.monotonic()
                + float(
                    self.local.session.get("query_max_run_time_s")
                ),
                probe,
                fold=self.local._fold_device_stat,
            )
            if got is not None:
                q = self.queries.get(spec.query_id)
                if q is not None:
                    # the gather edge is a coordinator-side consume:
                    # fold it under the delta-guard lock like the
                    # other coordinator-local stat additions
                    with q.stats._roll_lock:
                        q.stats.exchange_ici_edges += 1
                return got

        try:
            return rpc.pull_pages(
                w.uri, spec.task_id, 0,
                policy=self._rpc_policy,
                deadline_s=float(
                    self.local.session.get("query_max_run_time_s")
                ),
                traceparent=spec.traceparent,
                stall=stall,
                timeout_msg=f"task {spec.task_id} timed out",
            )
        except urllib.error.HTTPError as e:
            if e.code == 500:
                # the task FAILED: surface the worker's error text,
                # not a bare HTTP status
                st = self._rpc_json(
                    "GET", f"{w.uri}/v1/task/{spec.task_id}/status"
                )
                raise RuntimeError(
                    f"task on {w.node_id} failed: {st.get('error')}"
                ) from e
            raise

    # ------------------------------------------------------------ helpers

    def _rpc_json(
        self, method: str, url: str, body=None, traceparent: str = ""
    ) -> dict:
        """Coordinator->worker JSON RPC under the coordinator's policy
        (config-driven timeout, bounded backoff retries for idempotent
        calls, trace propagation, fault-plane hooks)."""
        return rpc.call_json(
            method, url, body,
            policy=self._rpc_policy, traceparent=traceparent,
        )

    def _store_result(self, q: _Query, res) -> None:
        q.columns = [
            {"name": c} for c in res.columns
        ]
        q.rows = [list(r) for r in res.rows()]


def _passes_through(node: N.PlanNode, col: str) -> bool:
    """Does ``col`` pass this probe-side node unchanged (so a dynamic
    filter on it may constrain the SCAN's split enumeration)? Filters
    preserve every column; a projection must map it to its own bare
    ColumnRef. Anything else (a lower join's renames, unnest, ...)
    disqualifies the column — the fused predicate still applies."""
    from presto_tpu import expr as E

    if isinstance(node, N.FilterNode):
        return True
    if isinstance(node, N.ProjectNode):
        for name, expr in node.projections:
            if name == col:
                return isinstance(expr, E.ColumnRef) and expr.name == col
        return False
    return False


def _make_handler(coord: CoordinatorServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _json(self, code: int, obj, extra_headers=None) -> None:
            # default=str: result rows may carry dates/decimals; the
            # oracle-compatible wire form is their string rendering
            body = json.dumps(obj, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(n)

        def do_POST(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "statement"]:
                from presto_tpu.server import protocol

                # a dying coordinator must not ACK a statement it
                # cannot journal (the ack promises a resumable query):
                # 503 = "nothing admitted", which the spray client
                # re-targets at a peer duplicate-free
                if coord._shutting_down:
                    return self._json(
                        503, {"error": "coordinator shutting down"}
                    )
                sql = self._read_body().decode()
                user = self.headers.get("X-Presto-User", "presto_tpu")
                # client-owned prepared statements ride per-request
                # headers (server.protocol): EXECUTE resolves against
                # this map first
                prepared = protocol.decode_prepared(
                    self.headers.get_all(
                        protocol.PREPARED_STATEMENT_HEADER
                    )
                )
                q = coord.submit(sql, user=user, prepared=prepared)
                # re-check AFTER submit: a kill that raced past the
                # gate above may have dropped the journal before the
                # frame landed — refuse the ACK (the client resubmits
                # at a peer; a frame that DID land resumes there too,
                # which is the journal's at-least-once contract)
                if coord._shutting_down:
                    return self._json(
                        503, {"error": "coordinator shutting down"}
                    )
                return self._json(
                    200,
                    {
                        "id": q.qid,
                        "nextUri": f"{coord.uri}/v1/statement/{q.qid}/0",
                    },
                )
            if len(parts) == 3 and parts[:2] == ["v1", "ingest"]:
                # streaming ingest: POST /v1/ingest/{table} with
                # {"rows": [{col: val}, ...]} or
                # {"columns": {col: [values]}}; optional
                # {"commit": true} forces a synchronous fold instead
                # of waiting for the commit loop. The batch is durable
                # (WAL-framed) once this returns; visible at commit.
                if coord.ingest is None:
                    return self._json(
                        503,
                        {
                            "error": "ingest lane not configured "
                            "(set ingest.wal-path)"
                        },
                    )
                try:
                    body = json.loads(self._read_body() or b"{}")
                    out = coord.ingest.append(
                        parts[2],
                        columns=body.get("columns"),
                        rows=body.get("rows"),
                    )
                    if body.get("commit"):
                        coord.ingest.flush()
                        out["committed"] = True
                    return self._json(200, out)
                except Exception as e:
                    return self._json(
                        400, {"error": f"{type(e).__name__}: {e}"}
                    )
            self._json(404, {"error": f"no route {self.path}"})

        def do_PUT(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "announcement"]:
                d = json.loads(self._read_body().decode())
                coord.announce(
                    d["node_id"], d["uri"], d.get("state", "ACTIVE"),
                    preemptible=bool(d.get("preemptible", False)),
                    memory=d.get("memory"),
                    slice_id=d.get("slice_id", ""),
                    device_coords=d.get("device_coords", ()),
                    backend_diag=d.get("backend_diag"),
                    role=d.get("role", ""),
                )
                # the ack names this coordinator incarnation: workers
                # track the boot nonces they have heard from so the
                # orphan reaper can tell "my coordinator restarted"
                # from "my coordinator is briefly quiet"
                return self._json(
                    200,
                    {
                        "ok": True,
                        "node_id": coord.coord_id,
                        "boot": coord._boot,
                    },
                )
            self._json(404, {"error": f"no route {self.path}"})

        def do_GET(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "cluster"]:
                return self._json(
                    200,
                    {
                        "workers": [
                            {"node_id": w.node_id, "uri": w.uri}
                            for w in coord.active_workers()
                        ]
                    },
                )
            if parts == ["v1", "metrics"]:
                body = REGISTRY.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts == ["v1", "metrics", "cluster"]:
                # cluster metrics federation: the coordinator's own
                # exposition plus every TTL-live worker's, re-emitted
                # with node="<id>" labels and a node="cluster" sum of
                # the monotone families (utils/telemetry.py)
                body = coord.cluster_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if (
                len(parts) == 4
                and parts[:2] == ["v1", "query"]
                and parts[3] == "progress"
            ):
                # live query progress, consumable MID-query: per-stage
                # splits done/total + rows/bytes/dispatches and a
                # history-derived ETA. Must be routed BEFORE the
                # len==3 QueryInfo route.
                x = coord.lookup_query(parts[2])
                if x is None:
                    return self._json(404, {"error": "no such query"})
                return self._json(200, coord.query_progress(x))
            if parts == ["v1", "query"]:
                # query listing (reference: GET /v1/query)
                with coord._lock:
                    qs = list(coord.queries.values())
                return self._json(
                    200, [coord.query_summary(x) for x in qs]
                )
            if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                # full QueryInfo incl. stage/task stats + span tree
                # (reference: GET /v1/query/{id}); works mid-flight.
                # lookup_query follows restart aliases: ids minted by
                # a dead coordinator incarnation resolve to their
                # journal-resumed runs
                x = coord.lookup_query(parts[2])
                if x is None:
                    return self._json(404, {"error": "no such query"})
                return self._json(200, coord.query_info(x))
            if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
                qid, token = parts[2], int(parts[3])
                q = coord.lookup_query(qid)
                if q is None:
                    # multi-coordinator alias lookup: a sprayed (or
                    # failed-over) client may land here holding a
                    # statement another live coordinator serves —
                    # redirect via its lease payload. Loop-free:
                    # coordinators only advertise qids they can
                    # resolve locally
                    peer = coord.locate_peer(qid)
                    if peer:
                        return self._json(
                            200,
                            {
                                "id": qid,
                                "nextUri": (
                                    f"{peer}/v1/statement/{qid}/{token}"
                                ),
                            },
                        )
                    return self._json(404, {"error": "no such query"})
                if q.state == "SUSPENDED" and not q.done.is_set():
                    # QoS preempt-and-resume: a parked query must not
                    # hold its client on the long-poll — answer NOW
                    # with empty data and a retry hint, keeping the
                    # poll loop alive (and cheap) until resume
                    return self._json(
                        200,
                        {
                            "id": qid,
                            "stats": {"state": "SUSPENDED"},
                            "data": [],
                            "nextUri": (
                                f"{coord.uri}/v1/statement/{qid}/"
                                f"{token}"
                            ),
                        },
                        extra_headers={"Retry-After": "0.5"},
                    )
                # long-poll up to 1s for progress (reference: long-poll)
                q.done.wait(timeout=1.0)
                # q.error decides failure delivery alongside the state
                # string: a rare suspension decision racing a kill can
                # leave a non-FAILED state on a done-with-error query,
                # and the client must still get the error, never an
                # empty success page
                if q.state == "FAILED" or (
                    q.done.is_set() and q.error is not None
                ):
                    q._drained = True  # error delivered: safe to evict
                    return self._json(
                        200,
                        {
                            "id": qid,
                            "error": q.error,
                            "stats": {"state": "FAILED"},
                        },
                    )
                if not q.done.is_set():
                    return self._json(
                        200,
                        {
                            "id": qid,
                            "stats": {"state": q.state},
                            "nextUri": (
                                f"{coord.uri}/v1/statement/{qid}/{token}"
                            ),
                        },
                    )
                lo = token * RESULT_PAGE_ROWS
                hi = min(lo + RESULT_PAGE_ROWS, len(q.rows))
                out = {
                    "id": qid,
                    "columns": q.columns,
                    "data": q.rows[lo:hi],
                    "stats": {"state": "FINISHED"},
                }
                if hi < len(q.rows):
                    out["nextUri"] = (
                        f"{coord.uri}/v1/statement/{qid}/{token + 1}"
                    )
                else:
                    q._drained = True  # last page served
                # prepared-statement session updates ride the result
                # response (server.protocol): the client folds them
                # into the map it replays on future requests
                extra = {}
                if q.added_prepare is not None:
                    from presto_tpu.server import protocol

                    name, text = q.added_prepare
                    # echo once: only on the FIRST result page, and
                    # only when the client's replayed map does not
                    # already carry the identical statement — a client
                    # that knows the name must not re-absorb (and
                    # re-serialize) it on every page of every request
                    if token == 0 and q.prepared.get(name) != text:
                        extra[protocol.ADDED_PREPARE_HEADER] = (
                            protocol.encode_prepared(name, text)
                        )
                if q.deallocated_prepare is not None:
                    from presto_tpu.server import protocol

                    extra[protocol.DEALLOCATED_PREPARE_HEADER] = (
                        q.deallocated_prepare
                    )
                return self._json(200, out, extra_headers=extra)
            self._json(404, {"error": f"no route {self.path}"})

    return Handler


# ------------------------------------------------- ordered MERGE exchange


def _merge_sorted_runs(payloads, schema, sort_node):
    """K-way merge of per-page sorted runs into one globally ordered
    staging payload (reference: MergeOperator consuming an ordered
    exchange — SURVEY.md §2.4 "ordered MERGE").

    Each wire page is a sorted run (workers apply the pushed-down root
    sort per batch — for TopN that truncates each run to ``limit`` rows
    BEFORE it crosses the wire, which is where the exchange saves its
    bandwidth). Dictionary columns are first remapped into one id space
    (merge_payloads), whose union dictionary is sorted — ids stay
    order-preserving, so key comparison is pure int64. The run-merge is
    expressed as a stable vectorized np.lexsort over the concatenated
    runs rather than an interpreter-level k-way heap: numpy's O(n log n)
    beats a per-row Python heap by orders of magnitude at gather sizes,
    and stability keeps ties in (run, position) order like the
    reference's MergeOperator. ``sort_node.limit`` truncates the
    output."""
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec.host_ops import orderable_np
    from presto_tpu.exec.staging import MaskedColumn

    merged = pages_wire.merge_payloads(payloads, schema)
    run_lens = [n for _, _, n in payloads]
    total = sum(run_lens)

    # least-significant-first key list for np.lexsort (mirrors
    # exec.host_ops._host_sort_perm)
    lex = []
    for k in reversed(list(sort_node.keys)):
        name = k.expr.name
        col = merged[name]
        if isinstance(col, MaskedColumn):
            data, valid = col.data, col.valid
        elif isinstance(col, DictColumn):
            data, valid = col.ids, None
        else:
            data, valid = col, None
        t = schema[name]
        img = orderable_np(np.asarray(data), t)
        if k.descending:
            img = ~img
        nf = (
            k.nulls_first if k.nulls_first is not None else k.descending
        )
        if valid is None:
            null_rank = np.zeros(total, np.int64)
        else:
            null_rank = np.where(valid, 0, -1 if nf else 1).astype(
                np.int64
            )
        lex.append(img)
        lex.append(null_rank)
    perm = np.lexsort(lex) if lex else np.arange(total)
    if sort_node.limit is not None:
        perm = perm[: sort_node.limit]

    out = {}
    for name, col in merged.items():
        if isinstance(col, MaskedColumn):
            out[name] = MaskedColumn(
                data=col.data[perm],
                valid=col.valid[perm],
                values=col.values,
            )
        elif isinstance(col, DictColumn):
            out[name] = DictColumn(ids=col.ids[perm], values=col.values)
        else:
            out[name] = col[perm]
    return out
