"""Coordinator lease plane — the ONE audited module for lease files,
expiry claims, and fencing.

Reference parity: Presto's disaggregated-coordinator direction keeps N
coordinators honest about shared state through a resource manager;
this repo's equivalent is a directory of lease files beside the
admission journals. Each coordinator owns exactly one lease file::

    <dir>/lease-<owner>.json        (atomic-rename updates)
    <dir>/claim-<owner>.json        (O_EXCL create, fencing epoch)

A lease carries the owner's id, serving URI, fencing epoch, a
wall-clock heartbeat, and an opaque ``state`` payload — the channel
peers use to share admission occupancy, memory-quota usage, QoS-lane
counts, and the set of statement ids each coordinator can serve.
Renewal is an atomic rename (write tmp, ``os.replace``), so a reader
never observes a torn lease; ``fcntl`` is deliberately NOT the
primitive — rename is atomic on every POSIX filesystem the journal
already depends on, while advisory locks die silently over NFS.

**Expiry + claims.** A lease older than its TTL is expired: the owner
stopped renewing (crash, partition, fault-plane kill). A survivor
claims the dead owner's journal by creating ``claim-<owner>.json``
with ``O_CREAT | O_EXCL`` — the filesystem picks exactly one winner —
carrying a fencing epoch strictly greater than both the dead lease's
epoch and any prior claim's. A claim whose claimant has ITSELF gone
dead is stale and may be superseded (atomic replace, epoch bumped
again): failover must survive the failover-er failing.

**Fencing.** Before (and while) a claimant writes into the claimed
journal it calls :meth:`check_fence` — the claim file must still name
it at its epoch, else :class:`FencedError`. A claimant that stalled
past its own TTL and was superseded gets its writes REJECTED, never
interleaved: split-brain double-resume is structurally impossible.

Construction, claims, fencing, and the ``lease-``/``claim-`` file-name
prefixes are confined to this module (``tools/analyze.py`` rule
``lease-plane``); the coordinator is the one audited consumer.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
import uuid
from typing import Dict, List, Optional

from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.lease")

#: default lease TTL (``lease.ttl-s``): a lease not renewed for this
#: long is expired and its journal claimable. Renewal runs at TTL/3,
#: so two missed heartbeats never expire a healthy owner.
DEFAULT_TTL_S = 10.0

_LEASE_PREFIX = "lease-"
_CLAIM_PREFIX = "claim-"
_SUFFIX = ".json"


class FencedError(RuntimeError):
    """A claimant's fencing epoch was superseded: its claim file no
    longer names it. Every write it intended against the claimed
    journal must be abandoned."""


@dataclasses.dataclass
class Lease:
    """One parsed lease (or claim) file."""

    owner: str
    uri: str = ""
    epoch: int = 0
    ts: float = 0.0
    state: dict = dataclasses.field(default_factory=dict)
    #: claim files only: who claimed this owner's journal
    claimant: str = ""

    def age(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.ts


class LeasePlane:
    """One coordinator's handle on the shared lease directory."""

    def __init__(
        self,
        path: str,
        owner: str,
        uri: str = "",
        ttl_s: float = DEFAULT_TTL_S,
    ):
        self.path = path
        self.owner = owner
        self.uri = uri
        self.ttl_s = max(float(ttl_s), 0.1)
        os.makedirs(path, exist_ok=True)
        # fencing epoch: strictly greater than anything this owner
        # name has carried before (a restarted coordinator rejoins
        # ABOVE the epoch a claimant may have fenced it at)
        prev = self._read(self._lease_path(owner))
        claim = self._read(self._claim_path(owner))
        self.epoch = (
            max(
                prev.epoch if prev else 0,
                claim.epoch if claim else 0,
            )
            + 1
        )

    # ------------------------------------------------------------ paths

    def _lease_path(self, owner: str) -> str:
        return os.path.join(self.path, f"{_LEASE_PREFIX}{owner}{_SUFFIX}")

    def _claim_path(self, owner: str) -> str:
        return os.path.join(self.path, f"{_CLAIM_PREFIX}{owner}{_SUFFIX}")

    # ------------------------------------------------------------- file

    @staticmethod
    def _read(path: str) -> Optional[Lease]:
        try:
            with open(path, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(d, dict) or not d.get("owner"):
            return None
        return Lease(
            owner=str(d["owner"]),
            uri=str(d.get("uri", "")),
            epoch=int(d.get("epoch", 0)),
            ts=float(d.get("ts", 0.0)),
            state=dict(d.get("state") or {}),
            claimant=str(d.get("claimant", "")),
        )

    def _write_atomic(self, path: str, payload: dict) -> None:
        """Torn-read-proof write: tmp file + atomic rename. The tmp
        name carries a nonce so two processes racing one target never
        collide on the intermediate."""
        tmp = f"{path}.{uuid.uuid4().hex[:8]}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
            f.flush()
        os.replace(tmp, path)

    # ------------------------------------------------------------ renew

    def renew(self, state: Optional[dict] = None) -> None:
        """Heartbeat: re-publish this owner's lease with a fresh
        timestamp and the current shared-state payload. Atomic — peers
        read either the previous lease or this one, never a tear (the
        single writer is the owner's lease loop; no lock needed, the
        rename IS the publish)."""
        self._write_atomic(
            self._lease_path(self.owner),
            {
                "owner": self.owner,
                "uri": self.uri,
                "epoch": self.epoch,
                "ts": time.time(),
                "state": state or {},
            },
        )
        REGISTRY.counter("lease.renewals").update()

    # ------------------------------------------------------------- read

    def peers(self, live_only: bool = False) -> List[Lease]:
        """Every OTHER owner's lease; ``live_only`` filters to leases
        inside the TTL."""
        out: List[Lease] = []
        now = time.time()
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return out
        for name in names:
            if not (
                name.startswith(_LEASE_PREFIX) and name.endswith(_SUFFIX)
            ):
                continue
            lease = self._read(os.path.join(self.path, name))
            if lease is None or lease.owner == self.owner:
                continue
            if live_only and lease.age(now) > self.ttl_s:
                continue
            out.append(lease)
        return out

    def read_lease(self, owner: str) -> Optional[Lease]:
        return self._read(self._lease_path(owner))

    def is_expired(self, lease: Lease) -> bool:
        return lease.age() > self.ttl_s

    # ------------------------------------------------------------ claim

    def claim_expired(self, owner: str) -> Optional[Lease]:
        """Claim a dead owner's journal. Returns the claim (fencing
        epoch included) when THIS plane won, None when the owner is
        still live, already retired, or another claimant holds a live
        claim. Exactly-one-winner rides ``O_CREAT | O_EXCL``; a STALE
        claim (its claimant's own lease expired) is superseded by
        atomic replace at a strictly higher epoch."""
        lease = self.read_lease(owner)
        if lease is None or not self.is_expired(lease):
            return None
        cpath = self._claim_path(owner)
        prior = self._read(cpath)
        if prior is not None:
            claimant = self.read_lease(prior.claimant)
            if claimant is not None and not self.is_expired(claimant):
                return None  # live claimant: the claim stands
            # stale claim: supersede it ABOVE both epochs so the old
            # claimant's fence check can never pass again
            claim = Lease(
                owner=owner,
                claimant=self.owner,
                epoch=max(lease.epoch, prior.epoch) + 1,
                ts=time.time(),
            )
            self._write_atomic(
                cpath,
                {
                    "owner": owner,
                    "claimant": self.owner,
                    "epoch": claim.epoch,
                    "ts": claim.ts,
                },
            )
            REGISTRY.counter("lease.claims").update()
            return claim
        claim = Lease(
            owner=owner,
            claimant=self.owner,
            epoch=lease.epoch + 1,
            ts=time.time(),
        )
        try:
            fd = os.open(cpath, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return None  # lost the race: exactly one winner
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "owner": owner,
                        "claimant": self.owner,
                        "epoch": claim.epoch,
                        "ts": claim.ts,
                    },
                    f,
                )
                f.flush()
        except OSError:
            return None
        REGISTRY.counter("lease.claims").update()
        return claim

    def check_fence(self, claim: Lease) -> None:
        """Raise :class:`FencedError` unless ``claim`` is still the
        current claim on its owner's journal — called before every
        write a claimant makes into claimed state."""
        cur = self._read(self._claim_path(claim.owner))
        if (
            cur is None
            or cur.claimant != self.owner
            or cur.epoch != claim.epoch
        ):
            REGISTRY.counter("lease.fenced_writes").update()
            raise FencedError(
                f"claim on {claim.owner} (epoch {claim.epoch}) "
                "superseded"
            )

    def retire(self, owner: str) -> None:
        """Drop a fully failed-over owner's lease + claim files: its
        journal was replayed and closed out, there is nothing left to
        claim. Idempotent."""
        for p in (self._lease_path(owner), self._claim_path(owner)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def stop(self) -> None:
        """Withdraw this owner's lease (clean shutdown): peers see an
        absent lease, not an expiring one, so nothing claims a journal
        the owner closed out itself."""
        try:
            os.unlink(self._lease_path(self.owner))
        except OSError:
            pass
