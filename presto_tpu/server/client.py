"""Client: drives the paged ``/v1/statement`` protocol.

Reference parity: the ``StatementClient`` inside ``presto-client/``
(SURVEY.md §1 L0) — submit SQL with one POST, then follow ``nextUri``
pages until the response carries no continuation, accumulating data
rows; surface server-side failures as exceptions.

Multi-coordinator HA: constructed with a LIST of coordinator URIs the
client SPRAYS statements round-robin, and on a connection-level
failure re-targets the SAME statement token at a peer — a coordinator
that failed over the query serves it by alias, any other live
coordinator redirects through its lease-payload lookup. A 404 from
EVERY coordinator means the alias chain is exhausted (nothing can
resume the statement) and fails the query immediately instead of
spinning the full reconnect budget. One URI keeps the legacy
single-coordinator behavior bit-exact.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import urllib.error
from typing import Dict, List

from presto_tpu.server import protocol, rpc
from presto_tpu.utils.metrics import REGISTRY


class QueryFailed(RuntimeError):
    """The server reported the query FAILED."""


@dataclasses.dataclass
class ClientResult:
    """Materialized result of one statement."""

    query_id: str
    columns: List[str]
    data: List[list]

    def rows(self) -> List[tuple]:
        return [tuple(r) for r in self.data]


class PrestoTpuClient:
    """Minimal blocking client for one coordinator (or a spray list
    of peers — see the module docstring)."""

    def __init__(
        self,
        coordinator_uri,
        timeout_s: float = 120.0,
        user: str = "presto_tpu",
        rpc_policy: rpc.RpcPolicy = rpc.DEFAULT_POLICY,
        reconnect_attempts: int = 8,
    ):
        # one URI, a comma-separated string, or a sequence of URIs
        if isinstance(coordinator_uri, str):
            uris = [
                u.strip()
                for u in coordinator_uri.split(",")
                if u.strip()
            ]
        else:
            uris = [str(u).strip() for u in coordinator_uri]
        if not uris:
            raise ValueError("at least one coordinator URI required")
        #: spray set: statements round-robin across these; nextUri
        #: polls re-target across them on connection failure
        self.uris = [u.rstrip("/") for u in uris]
        #: first coordinator — the single-target compatibility handle
        #: (observability GETs and existing callers read it)
        self.uri = self.uris[0]
        self._rr = itertools.count(0)
        self.timeout_s = timeout_s
        self.user = user  # sent as X-Presto-User (resource-group routing)
        #: per-request policy: nextUri GETs are idempotent and retry
        #: with backoff; the statement POST never retries (resubmitting
        #: would start a second query)
        self.rpc_policy = rpc_policy
        #: transparent-reconnect budget across a coordinator BOUNCE:
        #: connection-level failures on nextUri GETs retry this many
        #: times with jittered backoff (on top of the rpc policy's own
        #: short retries) before surfacing — a restarted coordinator
        #: resumes journaled queries under the same statement URIs, so
        #: mid-pagination clients ride out the restart instead of dying
        #: on the first connection reset
        self.reconnect_attempts = max(int(reconnect_attempts), 0)
        #: prepared statements this client session owns (reference: the
        #: client protocol's prepared-statement session headers). The
        #: map replays on every request as X-Presto-Prepared-Statement
        #: headers and updates from the server's added/deallocated
        #: response headers — the coordinator stays stateless, and
        #: EXECUTE reaches its zero-recompile plan-cache fast lane.
        self.prepared: Dict[str, str] = {}
        #: memoized wire form of ``prepared`` (the header value every
        #: request replays): rebuilt only when the map MUTATES — a
        #: serving loop EXECUTEing one hot statement re-encodes
        #: nothing per request. None = dirty.
        self._prepared_header: Optional[str] = None

    def execute(self, sql: str) -> ClientResult:
        first = self._post_statement(sql.encode())
        qid = first["id"]
        columns: List[str] = []
        data: List[list] = []
        cur = first
        deadline = time.monotonic() + self.timeout_s
        while True:
            if "error" in cur:
                raise QueryFailed(cur["error"])
            if cur.get("columns"):
                columns = [c["name"] for c in cur["columns"]]
            data.extend(cur.get("data") or [])
            nxt = cur.get("nextUri")
            if not nxt:
                return ClientResult(query_id=qid, columns=columns, data=data)
            if time.monotonic() > deadline:
                raise TimeoutError(f"query {qid} did not finish in time")
            resp = self._get_with_reconnect(nxt, deadline)
            self._absorb_prepared_headers(resp.headers)
            cur = resp.json()
            # a SUSPENDED (QoS-parked) query answers polls immediately
            # with empty data + a Retry-After hint: honor it so the
            # poll loop idles gently instead of hammering the
            # coordinator until resume
            retry_after = resp.headers.get("Retry-After")
            if retry_after and not cur.get("data") and cur.get(
                "nextUri"
            ):
                try:
                    time.sleep(
                        min(
                            float(retry_after),
                            max(deadline - time.monotonic(), 0.0),
                            2.0,
                        )
                    )
                except ValueError:
                    pass

    def _post_statement(self, body: bytes) -> dict:
        """Submit one statement, spraying the coordinator list
        round-robin. A connection-level failure moves to the next peer
        (the POST was never delivered, so re-targeting starts no
        duplicate query); a 503 moves on too — the coordinator is
        shutting down and explicitly admitted NOTHING. Any other HTTP
        error response surfaces — the server answered, resubmitting
        elsewhere WOULD double-run."""
        start = next(self._rr) % len(self.uris)
        order = self.uris[start:] + self.uris[:start]
        for i, base in enumerate(order):
            try:
                return self._post_json(base + "/v1/statement", body)
            except Exception as e:
                refused = (
                    isinstance(e, urllib.error.HTTPError)
                    and e.code == 503
                )
                if (
                    not (refused or rpc.is_retryable(e))
                    or i + 1 >= len(order)
                ):
                    raise
                REGISTRY.counter("client.spray_retargets").update()
        raise AssertionError("unreachable")  # pragma: no cover

    def _spray_targets(self, url: str) -> List[str]:
        """The URL plus its rebase onto every other coordinator in the
        spray set (origin first — the server that minted it is the
        likeliest to answer). Single-coordinator: just the URL."""
        if len(self.uris) == 1:
            return [url]
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        path = parts.path + (
            f"?{parts.query}" if parts.query else ""
        )
        origin = f"{parts.scheme}://{parts.netloc}"
        return [origin + path] + [
            b + path for b in self.uris if b != origin
        ]

    def _get_with_reconnect(self, url: str, deadline: float):
        """One nextUri GET with transparent reconnect: a coordinator
        bounce mid-pagination presents as connection resets/refusals,
        and the restarted coordinator serves the SAME statement URIs
        for journal-resumed queries — so connection-level failures
        retry with full-jitter backoff up to the reconnect budget. An
        HTTP error response (the server answered) and the query's own
        ``error`` payload surface immediately, as before.

        With a spray set, each attempt SWEEPS every coordinator: a
        peer that claimed the dead coordinator's journal serves the
        statement by alias, and any other live peer redirects to it.
        Two terminal verdicts are distinguished: "coordinator gone"
        (connection failure — re-target and, across sweeps, spend the
        reconnect budget) versus "statement gone" (404 from EVERY
        coordinator — the alias chain is exhausted, nothing can resume
        the query: fail NOW, not after the full backoff schedule)."""
        attempt = 0
        last_exc: Exception = None
        while True:
            targets = self._spray_targets(url)
            gone = 0
            for target in targets:
                try:
                    resp = rpc.call(
                        "GET", target, policy=self.rpc_policy
                    )
                    if target != url:
                        REGISTRY.counter("client.retargets").update()
                    return resp
                except urllib.error.HTTPError as e:
                    # the server ANSWERED. Only a 404 with peers left
                    # to consult means "ask another coordinator" —
                    # anything else is final, exactly as before
                    if e.code == 404 and len(targets) > 1:
                        gone += 1
                        last_exc = e
                        continue
                    raise
                except Exception as e:
                    if not rpc.is_retryable(e):
                        raise
                    last_exc = e
            if gone == len(targets):
                raise QueryFailed(
                    "statement gone on every coordinator "
                    f"(alias chain exhausted): {url}"
                )
            attempt += 1
            if (
                attempt > self.reconnect_attempts
                or time.monotonic() > deadline
            ):
                raise last_exc
            REGISTRY.counter("client.reconnects").update()
            time.sleep(
                rpc.compute_backoff(attempt - 1, self.rpc_policy)
            )

    def _absorb_prepared_headers(self, headers) -> None:
        added = headers.get_all(protocol.ADDED_PREPARE_HEADER)
        if added:
            # absorb once per (client, name): an echo of a statement
            # the map already carries verbatim must not dirty the
            # memoized request header (the common case — the server
            # echoes at most the first page, but a retried page read
            # can replay it)
            fresh = {
                n: s
                for n, s in protocol.decode_prepared(added).items()
                if self.prepared.get(n) != s
            }
            if fresh:
                self.prepared.update(fresh)
                self._prepared_header = None
        dropped = headers.get(protocol.DEALLOCATED_PREPARE_HEADER)
        if dropped and self.prepared.pop(dropped, None) is not None:
            self._prepared_header = None

    # ----------------------------------------------------- observability

    def query_info(self, query_id: str) -> dict:
        """Full QueryInfo for one query — the stats rollup (per-stage
        task timings) and the span tree (``GET /v1/query/{id}``)."""
        return self._get_json(f"{self.uri}/v1/query/{query_id}")

    def query_progress(self, query_id: str) -> dict:
        """Live progress for one query — per-stage splits done/total,
        rows/bytes/dispatch counters, and an ETA — consumable while
        the query is still RUNNING
        (``GET /v1/query/{id}/progress``)."""
        return self._get_json(
            f"{self.uri}/v1/query/{query_id}/progress"
        )

    def list_queries(self) -> List[dict]:
        """Summaries of every query the coordinator remembers
        (``GET /v1/query``)."""
        return self._get_json(f"{self.uri}/v1/query")

    # ------------------------------------------------------------ http

    def _post_json(self, url: str, body: bytes) -> dict:
        headers = {
            "Content-Type": "text/plain",
            "X-Presto-User": self.user,
        }
        if self.prepared:
            hdr = self._prepared_header
            if hdr is None:
                hdr = self._prepared_header = ",".join(
                    protocol.encode_prepared(n, s)
                    for n, s in self.prepared.items()
                )
            headers[protocol.PREPARED_STATEMENT_HEADER] = hdr
        return rpc.call(
            "POST", url, body,
            policy=self.rpc_policy,
            headers=headers,
        ).json()

    def _get_json(self, url: str) -> dict:
        return rpc.call("GET", url, policy=self.rpc_policy).json()
