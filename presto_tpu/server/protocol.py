"""Wire protocol: plan/expression trees and task specs as JSON.

Reference parity: the coordinator->worker task protocol — a
``PlanFragment`` serialized as JSON plus split batches, exactly the
boundary where the reference swaps execution backends (SURVEY.md
preamble, §2.3 "presto_protocol" codegen'd structs, §3.2).

Implementation: a generic tagged codec over the engine's frozen
dataclasses (plan nodes, expressions, types, agg/sort/window calls,
table handles, splits). Every object encodes as
``{"@": "ClassName", ...fields}``; tuples encode as lists and are
restored per-field from dataclass annotations at decode time — the
registry below is the single source of which classes may appear on the
wire (arbitrary class instantiation from JSON is not possible).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List

from presto_tpu import expr as E
from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    ConnectorSplit,
    RangeSet,
    TableHandle,
)
from presto_tpu.ops.aggregation import AggCall
from presto_tpu.ops.sort import SortKey
from presto_tpu.ops.window import WindowCall
from presto_tpu.plan import nodes as N


def _registry() -> Dict[str, type]:
    classes: List[type] = [TableHandle, ConnectorSplit, RangeSet,
                           AggCall, SortKey, WindowCall]
    for mod in (E, T, N):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                classes.append(obj)
    return {c.__name__: c for c in classes}


_REGISTRY = _registry()

#: singleton DataType instances by type name (decimal carries params)
_TYPE_SINGLETONS = {
    t.name: t
    for t in [
        T.BIGINT, T.INTEGER, T.DOUBLE, T.REAL, T.BOOLEAN, T.VARCHAR,
        T.DATE, T.TIMESTAMP,
    ]
}


def encode(obj: Any) -> Any:
    """Engine object -> JSON-able structure."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, T.DataType):
        if obj.is_array:
            return {"@": "array", "element": encode(obj.element)}
        if obj.is_decimal:
            return {"@": "decimal", "p": obj.precision, "s": obj.scale}
        if isinstance(obj, T.VarcharType) and obj.length is not None:
            # parameterized varchar(n)/char(n): name not in singletons
            return {"@": "varchar", "len": obj.length}
        return {"@": "type", "name": obj.name}
    if isinstance(obj, (tuple, list)):
        return [encode(x) for x in obj]
    if dataclasses.is_dataclass(obj):
        cls = type(obj)
        if cls.__name__ not in _REGISTRY:
            raise TypeError(f"{cls.__name__} is not wire-registered")
        out = {"@": cls.__name__}
        for f in dataclasses.fields(obj):
            if f.name == "fn" and isinstance(
                obj, (E.DictTransform, E.DictPredicate, E.DictIntFunc,
                      E.DictCombine, E.IntToDict)
            ):
                # host callables don't cross the wire: fn_key is the
                # canonical identity, rebuilt at decode time
                continue
            out[f.name] = encode(getattr(obj, f.name))
        return out
    raise TypeError(f"cannot encode {type(obj).__name__}")


def decode(data: Any) -> Any:
    """JSON structure -> engine object."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return tuple(decode(x) for x in data)
    tag = data.get("@")
    if tag == "array":
        return T.array(decode(data["element"]))
    if tag == "decimal":
        return T.decimal(data["p"], data["s"])
    if tag == "varchar":
        return T.varchar(data["len"])
    if tag == "type":
        return _TYPE_SINGLETONS[data["name"]]
    cls = _REGISTRY.get(tag)
    if cls is None:
        raise TypeError(f"unknown wire tag {tag!r}")
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(decode(data[f.name]), f.type, cls)
    if (
        cls in (E.DictTransform, E.DictPredicate, E.DictIntFunc,
            E.DictCombine, E.IntToDict)
        and "fn" not in kwargs
    ):
        kwargs["fn"] = E.dict_transform_fn(kwargs["fn_key"])
    return cls(**kwargs)


def _coerce(value: Any, annot: Any, cls: type) -> Any:
    """Tuples come back as tuples already; lists in annotations stay
    tuples (engine convention: all plan/expr collections are tuples)."""
    return value


# ----------------------------------------- prepared-statement headers
#
# Reference parity: the client protocol's prepared-statement session
# headers — the CLIENT owns the prepared map and replays it on every
# request (the coordinator is stateless across requests):
#
#   request:  X-Presto-Prepared-Statement: name=<urlencoded sql>[, ...]
#   response: X-Presto-Added-Prepare: name=<urlencoded sql>  (PREPARE)
#             X-Presto-Deallocated-Prepare: name           (DEALLOCATE)
#
# EXECUTE then reaches the coordinator's plan-cache fast lane with the
# statement text supplied by the header — zero server-side session
# state, warm shapes skip planning and compilation entirely.

PREPARED_STATEMENT_HEADER = "X-Presto-Prepared-Statement"
ADDED_PREPARE_HEADER = "X-Presto-Added-Prepare"
DEALLOCATED_PREPARE_HEADER = "X-Presto-Deallocated-Prepare"


def encode_prepared(name: str, sql: str) -> str:
    import urllib.parse

    return f"{name}={urllib.parse.quote(sql, safe='')}"


def decode_prepared(header_values) -> Dict[str, str]:
    """Parse one or more ``name=<urlencoded sql>`` header values
    (comma-separated within a value; quoting escapes commas)."""
    import urllib.parse

    out: Dict[str, str] = {}
    for value in header_values or ():
        for part in value.split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            name, enc = part.split("=", 1)
            out[name.strip()] = urllib.parse.unquote(enc.strip())
    return out


# ------------------------------------------------------------ task spec


@dataclasses.dataclass(frozen=True)
class FragmentSpec:
    """One task: a plan fragment + the splits this worker owns.

    ``partition_scan`` names the scan (by walk index) whose splits are
    sharded across workers; every other scan is replicated (scanned in
    full by each worker) — the reference's source-partitioned stage vs
    replicated build sides (SURVEY.md §2.4).
    """

    task_id: str
    query_id: str
    fragment: N.PlanNode
    partition_scan: int  # walk index of the partitioned TableScanNode
    split_start: int  # row range of the partitioned scan owned here
    split_end: int
    #: rows per split batch streamed through the compiled fragment
    #: (session ``page_capacity``; 0 = the whole range in one batch).
    #: Safe because the coordinator's FINAL step merges partial states,
    #: so per-batch partials concatenate like per-worker partials.
    split_batch_rows: int = 0
    #: concurrent split-batch drivers per task (session
    #: ``task_concurrency``; reference: task.concurrency driver count)
    task_concurrency: int = 1
    #: split batches prefetch-staged ahead of device execution
    #: (session ``staging_prefetch_depth``; -1 = unset — the worker
    #: falls back to its own session/config default)
    prefetch_depth: int = -1
    #: partitioned output (reference: PartitionedOutputOperator +
    #: PartitionedOutputBuffer): producers hash-partition output rows by
    #: ``partition_keys`` into ``n_partitions`` buffers; downstream
    #: merge tasks pull only their buffer — worker<->worker shuffle,
    #: pages never touch the coordinator
    n_partitions: int = 1
    partition_keys: tuple = ()
    #: merge task (reference: an intermediate stage's ExchangeClient):
    #: ``sources`` = [(uri, task_id), ...] of the producing stage;
    #: ``partition`` = which output buffer this merge task owns. When
    #: sources is non-empty the fragment's leaf is a RemoteSourceNode
    #: fed by the pulled pages instead of a table scan.
    sources: tuple = ()
    partition: int = 0
    #: dynamic-filter SUMMARY task (exec/dynfilter.py): instead of
    #: emitting result pages, the worker summarizes the named output
    #: columns (the join's build keys) of every batch — min/max +
    #: small distinct sets, NDV-capped at ``dynfilter_ndv`` — merges
    #: them, and reports the summary on the task-status response
    dynfilter_keys: tuple = ()
    dynfilter_ndv: int = 0
    #: fault-tolerant execution (session ``retry_policy`` TASK/QUERY
    #: with ``exchange.spool-path`` configured): the worker tees this
    #: task's partitioned output-buffer pages into the durable exchange
    #: spool (committed on FINISH), and a merge/join task whose
    #: upstream peer died re-serves that source's partition from the
    #: spool instead of failing (server.spool)
    spool: bool = False
    #: in-slice collective shuffle (server/exchange_spi.py): the slice
    #: id the SCHEDULER selected for this stage's exchange edges —
    #: producers whose own slice matches keep partitioned output
    #: device-resident in the ICI segment, and merge/join consumers
    #: gather their partition device-to-device instead of pulling
    #: serialized pages over HTTP. Empty = the HTTP wire (bit-exact
    #: legacy). A worker whose slice does NOT match (a retry landed
    #: cross-slice) silently uses HTTP; recovery and drain degrade the
    #: same way.
    ici_slice: str = ""
    #: trace context (utils.tracing traceparent header value): the
    #: coordinator stamps every task with the query's trace so
    #: worker-side spans join the query's span tree; also sent as the
    #: ``traceparent`` HTTP header on every coordinator->worker call
    traceparent: str = ""

    def to_json(self) -> dict:
        return {
            "task_id": self.task_id,
            "query_id": self.query_id,
            "fragment": encode(self.fragment),
            "partition_scan": self.partition_scan,
            "split_start": self.split_start,
            "split_end": self.split_end,
            "split_batch_rows": self.split_batch_rows,
            "task_concurrency": self.task_concurrency,
            "prefetch_depth": self.prefetch_depth,
            "n_partitions": self.n_partitions,
            "partition_keys": list(self.partition_keys),
            "sources": [list(s) for s in self.sources],
            "partition": self.partition,
            "dynfilter_keys": list(self.dynfilter_keys),
            "dynfilter_ndv": self.dynfilter_ndv,
            "spool": self.spool,
            "ici_slice": self.ici_slice,
            "traceparent": self.traceparent,
        }

    @staticmethod
    def from_json(d: dict) -> "FragmentSpec":
        return FragmentSpec(
            task_id=d["task_id"],
            query_id=d["query_id"],
            fragment=decode(d["fragment"]),
            partition_scan=d["partition_scan"],
            split_start=d["split_start"],
            split_end=d["split_end"],
            split_batch_rows=d.get("split_batch_rows", 0),
            task_concurrency=d.get("task_concurrency", 1),
            prefetch_depth=d.get("prefetch_depth", -1),
            n_partitions=d.get("n_partitions", 1),
            partition_keys=tuple(d.get("partition_keys", ())),
            sources=tuple(
                tuple(s) for s in d.get("sources", ())
            ),
            partition=d.get("partition", 0),
            dynfilter_keys=tuple(d.get("dynfilter_keys", ())),
            dynfilter_ndv=d.get("dynfilter_ndv", 0),
            spool=bool(d.get("spool", False)),
            ici_slice=d.get("ici_slice", ""),
            traceparent=d.get("traceparent", ""),
        )
