"""SQL type system.

Reference parity: ``presto-common`` ``Type`` / ``TypeSignature`` hierarchy
(BigintType ... DecimalType, VarcharType, ArrayType/MapType/RowType) —
SURVEY.md §2.1 "Type system".

TPU-first design decisions (SURVEY.md §7 step 1):

- Every SQL type maps to a fixed-width device representation so that all
  pages are static-shape JAX arrays:

    BIGINT            -> int64
    INTEGER           -> int32
    SMALLINT/TINYINT  -> int32 (widened on device; narrowing on output)
    DOUBLE / REAL     -> float64 / float32
    BOOLEAN           -> bool
    DATE              -> int32  (days since 1970-01-01, like the reference)
    TIMESTAMP         -> int64  (microseconds since epoch)
    DECIMAL(p<=18, s) -> int64  (unscaled value; exact arithmetic)
    DECIMAL(p>18, s)  -> int64 pair (hi, lo) — emulated int128 (future);
                         round 1 gates p<=18 which covers all of TPC-H
    VARCHAR / CHAR    -> int32 dictionary ids + host-side order-preserving
                         dictionary (see presto_tpu.page.Dictionary)

- Types are immutable, interned value objects; they are *static* metadata
  (never traced), safe to hash into jit cache keys.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    """Base SQL type. Frozen/hashable: types are static jit-cache metadata."""

    name: str

    @property
    def jnp_dtype(self):
        raise NotImplementedError

    @property
    def np_dtype(self):
        return np.dtype(self.jnp_dtype)

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_integer(self) -> bool:
        return False

    @property
    def is_string(self) -> bool:
        return False

    @property
    def is_decimal(self) -> bool:
        return False

    @property
    def is_long_decimal(self) -> bool:
        return False

    @property
    def is_array(self) -> bool:
        return False

    @property
    def is_map(self) -> bool:
        return False

    @property
    def is_row(self) -> bool:
        return False

    @property
    def is_nested(self) -> bool:
        """array/map/row — types whose blocks carry offsets/children."""
        return self.is_array or self.is_map or self.is_row

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class BigintType(DataType):
    name: str = "bigint"

    @property
    def jnp_dtype(self):
        return jnp.int64

    @property
    def is_numeric(self):
        return True

    @property
    def is_integer(self):
        return True


@dataclasses.dataclass(frozen=True)
class IntegerType(DataType):
    name: str = "integer"

    @property
    def jnp_dtype(self):
        return jnp.int32

    @property
    def is_numeric(self):
        return True

    @property
    def is_integer(self):
        return True


@dataclasses.dataclass(frozen=True)
class DoubleType(DataType):
    name: str = "double"

    @property
    def jnp_dtype(self):
        return jnp.float64

    @property
    def is_numeric(self):
        return True


@dataclasses.dataclass(frozen=True)
class RealType(DataType):
    name: str = "real"

    @property
    def jnp_dtype(self):
        return jnp.float32

    @property
    def is_numeric(self):
        return True


@dataclasses.dataclass(frozen=True)
class BooleanType(DataType):
    name: str = "boolean"

    @property
    def jnp_dtype(self):
        return jnp.bool_


@dataclasses.dataclass(frozen=True)
class DateType(DataType):
    """Days since 1970-01-01 (matches the reference's DateType encoding)."""

    name: str = "date"

    @property
    def jnp_dtype(self):
        return jnp.int32

    @property
    def is_numeric(self):
        return False


@dataclasses.dataclass(frozen=True)
class TimestampType(DataType):
    """Microseconds since epoch."""

    name: str = "timestamp"

    @property
    def jnp_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class DecimalType(DataType):
    """Exact decimal as an unscaled int64 (short decimal path).

    Reference parity: presto-common DecimalType; short decimals (p<=18) are
    long-backed there too, long decimals (p<=38) are int128-backed (future
    round: int64-pair emulation; TPC-H needs only p<=15).
    """

    precision: int = 38
    scale: int = 0
    name: str = "decimal"

    def __post_init__(self):
        object.__setattr__(
            self, "name", f"decimal({self.precision},{self.scale})"
        )
        if self.precision > 18:
            raise ValueError(
                "DecimalType is the short-decimal (p<=18) path; use "
                "T.decimal(), which routes p>18 to LongDecimalType"
            )

    @property
    def jnp_dtype(self):
        return jnp.int64

    @property
    def is_numeric(self):
        return True

    @property
    def is_decimal(self):
        return True


@dataclasses.dataclass(frozen=True)
class LongDecimalType(DataType):
    """decimal(19..38, s): emulated int128 (reference parity:
    presto-common long DecimalType, Int128-backed).

    Physical layout: the block's data array has shape ``(capacity, 2)``
    int64 — column 0 the signed high limb, column 1 the low 64 bits
    (unsigned, stored in an int64 bit pattern); value = hi*2^64 + lo.
    All limb arithmetic lives in ``presto_tpu.int128`` and runs inside
    jit (static shapes, pure int64/uint64 ops — nothing the MXU/VPU
    can't chew).

    Supported surface this round: scans (parquet/ORC/memory/pylist),
    comparisons, +/-/negate, casts (short<->long incl. half-up
    downscale via int128 division, ->double, ->bigint), projection and
    exact host materialization (``decimal.Decimal``).
    Documented deviation: long decimals as GROUP BY / join / sort keys
    and as aggregate inputs raise PlanningError — cast to
    decimal(18,s) or double to aggregate (no benchmark config needs a
    >18-digit key; see COMPONENTS.md type-system row).
    """

    precision: int = 38
    scale: int = 0
    name: str = "decimal"

    def __post_init__(self):
        object.__setattr__(
            self, "name", f"decimal({self.precision},{self.scale})"
        )
        if not (18 < self.precision <= 38):
            raise ValueError(
                f"LongDecimalType precision must be in 19..38, got "
                f"{self.precision}"
            )

    @property
    def jnp_dtype(self):
        return jnp.int64

    @property
    def is_numeric(self):
        return True

    @property
    def is_decimal(self):
        return True

    @property
    def is_long_decimal(self):
        return True


@dataclasses.dataclass(frozen=True)
class VarcharType(DataType):
    """Dictionary-encoded string: device arrays hold int32 dictionary ids.

    The dictionary itself (presto_tpu.page.Dictionary) lives host-side and
    is order-preserving (ids sorted by string value), so <, <=, =, >=, >
    on ids agree with string comparison within one dictionary.
    """

    length: Optional[int] = None  # None = unbounded
    name: str = "varchar"

    def __post_init__(self):
        if self.length is not None:
            object.__setattr__(self, "name", f"varchar({self.length})")

    @property
    def jnp_dtype(self):
        return jnp.int32

    @property
    def is_string(self):
        return True


# Interned singletons — reference parity with presto-common's static
# instances (BigintType.BIGINT etc.).
BIGINT = BigintType()
INTEGER = IntegerType()
DOUBLE = DoubleType()
REAL = RealType()
BOOLEAN = BooleanType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARCHAR = VarcharType()


def decimal(precision: int, scale: int) -> DataType:
    """decimal(p,s) — int64-backed for p<=18, int128 limb pair beyond."""
    if precision > 18:
        return LongDecimalType(precision=precision, scale=scale)
    return DecimalType(precision=precision, scale=scale)


def long_decimal(precision: int, scale: int) -> LongDecimalType:
    return LongDecimalType(precision=precision, scale=scale)


def int128_limbs(unscaled) -> np.ndarray:
    """Python ints -> (n, 2) int64 limb array [hi, lo] (lo = low 64
    bits as an int64 bit pattern)."""
    vals = [int(v) for v in unscaled]
    lo = np.asarray(
        [(v & 0xFFFFFFFFFFFFFFFF) - (1 << 64) if (v & (1 << 63)) else
         (v & 0xFFFFFFFFFFFFFFFF) for v in vals],
        dtype=np.int64,
    )
    hi = np.asarray([v >> 64 for v in vals], dtype=np.int64)
    return np.stack([hi, lo], axis=1)


def int128_value(hi: int, lo: int) -> int:
    """Limb pair -> python int (lo re-read as unsigned)."""
    return (int(hi) << 64) + (int(lo) & 0xFFFFFFFFFFFFFFFF)


def varchar(length: Optional[int] = None) -> VarcharType:
    return VarcharType(length=length)


_BY_NAME = {
    "bigint": BIGINT,
    "integer": INTEGER,
    "int": INTEGER,
    "double": DOUBLE,
    "real": REAL,
    "boolean": BOOLEAN,
    "date": DATE,
    "timestamp": TIMESTAMP,
    "varchar": VARCHAR,
}


@dataclasses.dataclass(frozen=True)
class ArrayType(DataType):
    """array(T) — physical array columns (reference: ArrayType).

    Device representation (SURVEY.md §2.1 "Block/Page data model"): an
    offsets int32 array (capacity+1) over a flat child values array
    (``Block.offsets``/``Block.data``); per-row validity as usual.
    """

    element: DataType = None  # type: ignore[assignment]
    name: str = "array"

    @property
    def jnp_dtype(self):
        # the VALUES child array's dtype (offsets are always int32)
        return self.element.jnp_dtype

    @property
    def is_array(self) -> bool:
        return True

    def __str__(self) -> str:
        return f"array({self.element})"


def array(element: DataType) -> ArrayType:
    return ArrayType(element=element)


@dataclasses.dataclass(frozen=True)
class MapType(DataType):
    """map(K, V) — physical map columns (reference: MapType /
    MapBlock, SURVEY.md §2.1 "Type system").

    Device representation: an int32 offsets array (capacity+1) shared
    by TWO flat child blocks — keys and values (``Block.children``);
    row i's entries are ``keys[offsets[i]:offsets[i+1]]`` zipped with
    the same span of values. Per-row validity as usual. Key lookup is
    a flat segment-max scan (expr.MapSubscript) — branch-free, one
    pass over the values axis."""

    key: DataType = None  # type: ignore[assignment]
    value: DataType = None  # type: ignore[assignment]
    name: str = "map"

    @property
    def jnp_dtype(self):
        raise TypeError("map columns have no single dtype (children)")

    @property
    def is_map(self) -> bool:
        return True

    def __str__(self) -> str:
        return f"map({self.key},{self.value})"


@dataclasses.dataclass(frozen=True)
class RowType(DataType):
    """row(f1 T1, ..., fk Tk) — physical struct columns (reference:
    RowType / RowBlock, SURVEY.md §2.1 "Type system").

    Device representation: one child block per field, all at the row
    capacity (``Block.children``, shredded layout — the columnar form
    parquet/ORC use for structs); per-row validity on the parent.
    Field access (expr.RowFieldAccess) is a zero-copy child select."""

    fields: tuple = ()  # ((name, DataType), ...)
    name: str = "row"

    @property
    def jnp_dtype(self):
        raise TypeError("row columns have no single dtype (children)")

    @property
    def is_row(self) -> bool:
        return True

    def field_index(self, name: str) -> int:
        for i, (n, _) in enumerate(self.fields):
            if n.lower() == name.lower():
                return i
        raise KeyError(name)

    def __str__(self) -> str:
        inner = ", ".join(f"{n} {t}" for n, t in self.fields)
        return f"row({inner})"


def map_(key: DataType, value: DataType) -> MapType:
    return MapType(key=key, value=value)


def row(*fields) -> RowType:
    """row(("a", BIGINT), ("b", VARCHAR)) or row(a=BIGINT, ...) via
    tuple pairs."""
    return RowType(fields=tuple((n, t) for n, t in fields))


def parse_type(text: str) -> DataType:
    """Parse a SQL type string, e.g. ``decimal(12,2)`` or ``varchar(25)``."""
    t = text.strip().lower()
    if t in _BY_NAME:
        return _BY_NAME[t]
    if t in ("decimal", "char"):  # bare forms: SQL defaults
        return decimal(18, 0) if t == "decimal" else varchar(1)
    if t.startswith("decimal(") and t.endswith(")"):
        inner = t[len("decimal(") : -1]
        p, s = (int(x) for x in inner.split(","))
        return decimal(p, s)
    if (t.startswith("varchar(") or t.startswith("char(")) and t.endswith(")"):
        inner = t[t.index("(") + 1 : -1]
        return varchar(int(inner))
    if t.startswith("array(") and t.endswith(")"):
        return array(parse_type(t[len("array(") : -1]))
    if t.startswith("map(") and t.endswith(")"):
        parts = _split_top(t[len("map(") : -1])
        if len(parts) != 2:
            raise ValueError(f"map type needs key,value: {text}")
        return map_(parse_type(parts[0]), parse_type(parts[1]))
    if t.startswith("row(") and t.endswith(")"):
        fields = []
        for p in _split_top(t[len("row(") : -1]):
            p = p.strip()
            sp = p.find(" ")
            if sp < 0:
                raise ValueError(f"row field needs 'name type': {p}")
            fields.append((p[:sp], parse_type(p[sp + 1 :])))
        return RowType(fields=tuple(fields))
    raise ValueError(f"unknown type: {text}")


def _split_top(s: str) -> list:
    """Split on commas at paren depth 0."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur or parts:
        parts.append("".join(cur).strip())
    return parts


# --- coercion lattice (reference: presto-common TypeCoercion) -------------

_NUMERIC_ORDER = ["integer", "bigint", "real", "double"]


def common_super_type(a: DataType, b: DataType) -> DataType:
    """Least common type two operands coerce to (simplified lattice)."""
    if a == b:
        return a
    if a.is_decimal and b.is_decimal:
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        # p>18 routes to LongDecimalType via decimal(); cap at the
        # int128 ceiling like the reference caps at 38
        return decimal(min(intd + scale, 38), scale)
    if a.is_decimal and b.is_integer:
        # widen integer digits to the representation ceiling; precision
        # is capacity-advisory (arithmetic runs on int64 / int128 limbs)
        return decimal(38 if a.is_long_decimal else 18, a.scale)
    if b.is_decimal and a.is_integer:
        return decimal(38 if b.is_long_decimal else 18, b.scale)
    if a.is_decimal and b.name == "double":
        return DOUBLE
    if b.is_decimal and a.name == "double":
        return DOUBLE
    if a.is_numeric and b.is_numeric:
        ia = _NUMERIC_ORDER.index(a.name)
        ib = _NUMERIC_ORDER.index(b.name)
        return _BY_NAME[_NUMERIC_ORDER[max(ia, ib)]]
    if a.is_string and b.is_string:
        return VARCHAR
    if {a.name, b.name} == {"date", "timestamp"}:
        return TIMESTAMP
    raise TypeError(f"no common type for {a} and {b}")
