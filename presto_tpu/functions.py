"""Scalar function registry.

Reference parity: ``FunctionAndTypeManager`` and the annotation-driven
builtin registry (``@ScalarFunction`` over hundreds of builtins —
SURVEY.md §2.1 "Function registry"). The reference registers a function
once and every layer (analyzer, planner, interpreter, codegen) resolves
it through the manager; here the analogous seam is a declarative table
``name -> ScalarFunction`` whose ``build`` lowers a call directly to the
engine's Expr IR (XLA is the codegen, so "registering" a function means
providing its typed Expr construction — no interpreter entry needed).

Adding a builtin touches ONLY this module: the planner resolves every
non-aggregate, non-window FuncCall here (plan/planner.py FuncCall
branch), and the fuzzer draws generatable functions from the same table
(``fuzz`` argument classes).

String functions follow the dictionary-LUT design (SURVEY.md §7
"Strings on TPU"): host-side evaluation over the (small) dictionary,
device-side int32/int64/bool LUT gathers — so string builtins require a
dictionary-backed argument and literal parameters, enforced here at
plan time.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu import expr as E
from presto_tpu import types as T


class FunctionError(ValueError):
    """Raised for bad calls; the planner re-raises as PlanningError."""


@dataclasses.dataclass(frozen=True)
class ScalarFunction:
    """One registered scalar builtin."""

    name: str
    min_args: int
    max_args: int  # -1 = variadic
    build: Callable[[List[E.Expr]], E.Expr]
    description: str = ""
    #: fuzzer argument classes, each in {"num", "str", "date", "any",
    #: "bool"}; None = not fuzz-generatable (needs literal params etc.)
    fuzz: Optional[Tuple[str, ...]] = None


SCALAR: Dict[str, ScalarFunction] = {}


def _register(
    name: str,
    min_args: int,
    max_args: Optional[int] = None,
    description: str = "",
    fuzz: Optional[Tuple[str, ...]] = None,
):
    def deco(fn):
        SCALAR[name] = ScalarFunction(
            name=name,
            min_args=min_args,
            max_args=min_args if max_args is None else max_args,
            build=fn,
            description=description,
            fuzz=fuzz,
        )
        return fn

    return deco


def lower_scalar(name: str, args: List[E.Expr]) -> E.Expr:
    """Resolve + build a scalar call; FunctionError on unknown name or
    arity/type mismatch. The planner's single entry point."""
    fn = SCALAR.get(name)
    if fn is None:
        raise FunctionError(f"unknown function: {name}")
    n = len(args)
    if n < fn.min_args or (fn.max_args >= 0 and n > fn.max_args):
        want = (
            str(fn.min_args)
            if fn.min_args == fn.max_args
            else f"{fn.min_args}..{'*' if fn.max_args < 0 else fn.max_args}"
        )
        raise FunctionError(f"{name}() takes {want} arguments, got {n}")
    return fn.build(args)


# ------------------------------------------------------------- helpers


def _lit_str(e: E.Expr, what: str) -> str:
    if not isinstance(e, E.Literal) or not isinstance(e.value, str):
        raise FunctionError(f"{what} must be a string literal")
    return e.value


def _lit_int(e: E.Expr, what: str) -> int:
    if not isinstance(e, E.Literal) or e.value is None:
        raise FunctionError(f"{what} must be an integer literal")
    try:
        return int(e.value)
    except (TypeError, ValueError):
        raise FunctionError(
            f"{what} must be an integer literal, got {e.value!r}"
        ) from None


def _string_arg(e: E.Expr, fname: str) -> E.Expr:
    if not e.dtype.is_string:
        raise FunctionError(
            f"{fname}() requires a varchar argument, got {e.dtype}"
        )
    return e


def _numeric_arg(e: E.Expr, fname: str) -> E.Expr:
    t = e.dtype
    if not (t.is_integer or t.is_decimal or t.name in ("double", "real")):
        raise FunctionError(
            f"{fname}() requires a numeric argument, got {t}"
        )
    return e


def _date_arg(e: E.Expr, fname: str) -> E.Expr:
    if e.dtype.name not in ("date", "timestamp"):
        raise FunctionError(
            f"{fname}() requires a date/timestamp argument, got {e.dtype}"
        )
    return e


def _common_type(args: List[E.Expr]) -> T.DataType:
    ct = args[0].dtype
    for a in args[1:]:
        ct = T.common_super_type(ct, a.dtype)
    return ct


def _transform(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):  # constant fold
        v = None if arg.value is None else str(fn(str(arg.value)))
        return E.Literal(v, T.VARCHAR)
    return E.DictTransform(arg, key, fn)


def _int_func(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):
        v = None if arg.value is None else int(fn(str(arg.value)))
        return E.Literal(v, T.BIGINT)
    return E.DictIntFunc(arg, key, fn)


def _predicate(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):
        v = None if arg.value is None else bool(fn(str(arg.value)))
        return E.Literal(v, T.BOOLEAN)
    return E.DictPredicate(arg, key, fn)


def _math1(func: str):
    def build(args, _f=func):
        return E.MathFunc(_f, _numeric_arg(args[0], _f))

    return build


# ---------------------------------------------------------------- math

for _f in (
    "sqrt", "ln", "exp", "abs", "sign", "cbrt",
    "log2", "log10", "sin", "cos", "tan", "asin", "acos", "atan",
    "degrees", "radians", "sinh", "cosh", "tanh",
):
    _register(_f, 1, description=f"{_f}(x)", fuzz=("num",))(_math1(_f))


@_register(
    "width_bucket", 4,
    description="width_bucket(x, lo, hi, n) -> bucket in [0, n+1]; "
    "descending bounds (lo > hi) reverse the buckets like the "
    "reference; equal bounds -> NULL (deviation: the reference raises)",
)
def _width_bucket(args):
    x = _numeric_arg(args[0], "width_bucket")
    lo = _numeric_arg(args[1], "width_bucket")
    hi = _numeric_arg(args[2], "width_bucket")
    n_count = _lit_int(args[3], "width_bucket bucket count")
    if n_count < 1:
        raise FunctionError(
            f"width_bucket bucket count must be >= 1, got {n_count}"
        )
    xf = E.Cast(x, T.DOUBLE) if x.dtype != T.DOUBLE else x
    lof = E.Cast(lo, T.DOUBLE) if lo.dtype != T.DOUBLE else lo
    hif = E.Cast(hi, T.DOUBLE) if hi.dtype != T.DOUBLE else hi
    nf = _flit(n_count)
    over = E.Literal(n_count + 1, T.BIGINT)

    def bucket(span_from, span_to):
        # floor((x - from) / (to - from) * n) + 1
        span = _fsub(span_to, span_from)
        frac = _fdiv(_fsub(xf, span_from), span)
        return E.Arithmetic(
            "+",
            E.MathFunc("floor", _fmul(frac, nf)),
            E.Literal(1, T.BIGINT),
            T.BIGINT,
        )

    asc = E.Case(
        whens=(
            (E.Compare("<", xf, lof), E.Literal(0, T.BIGINT)),
            (E.Compare(">=", xf, hif), over),
        ),
        default=bucket(lof, hif),
        _dtype=T.BIGINT,
    )
    # descending bounds: buckets decrease from lo to hi, (hi, lo]-open
    desc = E.Case(
        whens=(
            (E.Compare(">", xf, lof), E.Literal(0, T.BIGINT)),
            (E.Compare("<=", xf, hif), over),
        ),
        default=bucket(lof, hif),
        _dtype=T.BIGINT,
    )
    return E.Case(
        whens=(
            (E.Compare("<", lof, hif), asc),
            (E.Compare(">", lof, hif), desc),
        ),
        default=E.Literal(None, T.BIGINT),
        _dtype=T.BIGINT,
    )


@_register("floor", 1, description="floor(x) -> bigint", fuzz=("num",))
def _floor(args):
    return E.MathFunc("floor", _numeric_arg(args[0], "floor"))


@_register("ceil", 1, description="ceil(x) -> bigint", fuzz=("num",))
@_register("ceiling", 1, description="alias of ceil")
def _ceil(args):
    return E.MathFunc("ceil", _numeric_arg(args[0], "ceil"))


@_register("round", 1, 2, description="round(x[, digits])", fuzz=("num",))
def _round(args):
    x = _numeric_arg(args[0], "round")
    if len(args) == 1:
        return E.MathFunc("round", x)
    return E.MathFunc2("round", x, _numeric_arg(args[1], "round"))


@_register("truncate", 1, 2, description="truncate(x[, digits])",
           fuzz=("num",))
def _truncate(args):
    x = _numeric_arg(args[0], "truncate")
    if len(args) == 1:
        return E.MathFunc("truncate", x)
    return E.MathFunc2("truncate", x, _numeric_arg(args[1], "truncate"))


@_register("power", 2, description="power(x, y)", fuzz=("num", "num"))
@_register("pow", 2, description="alias of power")
def _power(args):
    return E.MathFunc2(
        "power",
        _numeric_arg(args[0], "power"),
        _numeric_arg(args[1], "power"),
    )


@_register("atan2", 2, description="atan2(y, x)", fuzz=("num", "num"))
def _atan2(args):
    return E.MathFunc2(
        "atan2",
        _numeric_arg(args[0], "atan2"),
        _numeric_arg(args[1], "atan2"),
    )


@_register("log", 2, description="log(base, x)")
def _log(args):
    return E.MathFunc2(
        "log", _numeric_arg(args[0], "log"), _numeric_arg(args[1], "log")
    )


@_register("mod", 2, description="mod(x, y)", fuzz=("num", "num"))
def _mod(args):
    return E.arith(
        "%", _numeric_arg(args[0], "mod"), _numeric_arg(args[1], "mod")
    )


@_register("pi", 0, description="pi()")
def _pi(args):
    import math

    return E.Literal(math.pi, T.DOUBLE)


@_register("e", 0, description="e()")
def _e(args):
    import math

    return E.Literal(math.e, T.DOUBLE)


def _bound(op: str, args: List[E.Expr], fname: str) -> E.Expr:
    """greatest/least as a CASE fold; NULL if any argument is NULL
    (Presto semantics)."""
    ct = _common_type(args)
    args = [a if a.dtype == ct else E.Cast(a, ct) for a in args]
    out = args[0]
    for a in args[1:]:
        out = E.Case(
            whens=(
                (E.IsNull(out), E.Literal(None, ct)),
                (E.IsNull(a), E.Literal(None, ct)),
                (E.Compare(op, out, a), out),
            ),
            default=a,
            _dtype=ct,
        )
    return out


@_register("greatest", 1, -1, description="greatest(x, ...)",
           fuzz=("num", "num"))
def _greatest(args):
    return _bound(">=", list(args), "greatest")


@_register("least", 1, -1, description="least(x, ...)",
           fuzz=("num", "num"))
def _least(args):
    return _bound("<=", list(args), "least")


# --------------------------------------------------------- conditional


@_register("coalesce", 1, -1, description="coalesce(x, ...)")
def _coalesce(args):
    ct = _common_type(list(args))
    return E.Coalesce(tuple(args), ct)


@_register("if", 2, 3, description="if(cond, then[, else])")
def _if(args):
    cond = args[0]
    if cond.dtype.name != "boolean":
        raise FunctionError("if() condition must be boolean")
    then = args[1]
    default = args[2] if len(args) > 2 else E.Literal(None, then.dtype)
    ct = T.common_super_type(then.dtype, default.dtype)
    return E.Case(whens=((cond, then),), default=default, _dtype=ct)


@_register("nullif", 2, description="nullif(a, b)")
def _nullif(args):
    a, b = args
    return E.Case(
        whens=((E.Compare("=", a, b), E.Literal(None, a.dtype)),),
        default=a,
        _dtype=a.dtype,
    )


# -------------------------------------------------------------- string


@_register("lower", 1, description="lower(s)", fuzz=("str",))
def _lower_fn(args):
    return _transform(_string_arg(args[0], "lower"), "lower")


@_register("upper", 1, description="upper(s)", fuzz=("str",))
def _upper_fn(args):
    return _transform(_string_arg(args[0], "upper"), "upper")


@_register("trim", 1, description="trim(s)", fuzz=("str",))
def _trim(args):
    return _transform(_string_arg(args[0], "trim"), "trim")


@_register("ltrim", 1, description="ltrim(s)", fuzz=("str",))
def _ltrim(args):
    return _transform(_string_arg(args[0], "ltrim"), "ltrim")


@_register("rtrim", 1, description="rtrim(s)", fuzz=("str",))
def _rtrim(args):
    return _transform(_string_arg(args[0], "rtrim"), "rtrim")


@_register("reverse", 1, description="reverse(s)", fuzz=("str",))
def _reverse(args):
    return _transform(_string_arg(args[0], "reverse"), "reverse")


@_register("length", 1, description="length(s) -> bigint", fuzz=("str",))
def _length(args):
    return _int_func(_string_arg(args[0], "length"), "length")


@_register("substring", 2, 3, description="substring(s, start[, len])")
@_register("substr", 2, 3, description="alias of substring")
def _substring(args):
    s = _string_arg(args[0], "substring")
    start = _lit_int(args[1], "substring start")
    length = _lit_int(args[2], "substring length") if len(args) > 2 else None
    return _transform(s, f"substring:{start}:{length}")


@_register("replace", 3, description="replace(s, search, repl)")
def _replace(args):
    s = _string_arg(args[0], "replace")
    old = _lit_str(args[1], "replace search")
    new = _lit_str(args[2], "replace replacement")
    return _transform(s, f"replace:{json.dumps([old, new])}")


@_register(
    "concat", 1, -1,
    description="concat(s, ...): at most one dictionary column, any "
    "number of string literals (host-LUT design)",
)
def _concat(args):
    cols = [a for a in args if not isinstance(a, E.Literal)]
    if len(cols) == 2:
        # two dictionary columns: cross-product combined dictionary
        # (E.DictCombine). Literals between/around the columns fold
        # into the combine function; deeper chains nest (|| is
        # left-associative, so a || b || c combines pairwise)
        for col in cols:
            _string_arg(col, "concat")
        i0 = next(i for i, a in enumerate(args) if a is cols[0])
        i1 = next(
            i
            for i, a in enumerate(args)
            if a is cols[1] and i > i0
        )
        pre = "".join(
            _lit_str(a, "concat argument") for a in args[:i0]
        )
        mid = "".join(
            _lit_str(a, "concat argument")
            for a in args[i0 + 1: i1]
        )
        suf = "".join(
            _lit_str(a, "concat argument") for a in args[i1 + 1:]
        )
        key = f"concat2:{json.dumps([pre, mid, suf])}"
        return E.DictCombine(
            cols[0], cols[1], key, E.dict_transform_fn(key)
        )
    if len(cols) > 2:
        raise FunctionError(
            "concat() supports at most two non-literal arguments "
            "(chain || pairwise for more)"
        )
    if not cols:
        return E.Literal(
            "".join(_lit_str(a, "concat argument") for a in args),
            T.VARCHAR,
        )
    col = cols[0]
    _string_arg(col, "concat")
    idx = next(i for i, a in enumerate(args) if a is col)
    prefix = "".join(
        _lit_str(a, "concat argument") for a in args[:idx]
    )
    suffix = "".join(
        _lit_str(a, "concat argument") for a in args[idx + 1:]
    )
    return _transform(col, f"concat:{json.dumps([prefix, suffix])}")


#: MySQL date_format directives -> strftime (the supported subset;
#: unknown directives fail at plan time, not silently)
_MYSQL_FMT = {
    "%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%-m", "%d": "%d",
    "%e": "%-d", "%j": "%j", "%W": "%A", "%a": "%a", "%M": "%B",
    "%b": "%b", "%u": "%W", "%%": "%%",
}

#: JodaTime format_datetime tokens -> strftime (longest-match subset)
_JODA_FMT = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MMMM", "%B"), ("MMM", "%b"),
    ("MM", "%m"), ("M", "%-m"), ("dd", "%d"), ("d", "%-d"),
    ("EEEE", "%A"), ("EEE", "%a"), ("DDD", "%j"),
]

#: date-domain LUT bounds: 1900-01-01 .. 2071-06-06 (epoch days)
_DATE_LO, _DATE_HI = -25567, 37040


def _date_arg(e: E.Expr, fname: str) -> E.Expr:
    if e.dtype.name != "date":
        raise FunctionError(f"{fname}() requires a DATE argument")
    return e


@_register(
    "date_format", 2, description="date_format(d, '%Y-%m-%d') (MySQL "
    "directives, date args)",
)
def _date_format(args):
    arg = _date_arg(args[0], "date_format")
    fmt = _lit_str(args[1], "date_format pattern")
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "%":
            tok = fmt[i:i + 2]
            if tok not in _MYSQL_FMT:
                raise FunctionError(
                    f"date_format directive {tok!r} is not supported"
                )
            out.append(_MYSQL_FMT[tok])
            i += 2
        else:
            ch = fmt[i]
            out.append("%%" if ch == "%" else ch)
            i += 1
    key = f"date_format:{json.dumps([''.join(out)])}"
    return E.IntToDict(
        arg, key, _DATE_LO, _DATE_HI, E.dict_transform_fn(key)
    )


@_register(
    "format_datetime", 2,
    description="format_datetime(d, 'yyyy-MM-dd') (Joda tokens, "
    "date args)",
)
def _format_datetime(args):
    arg = _date_arg(args[0], "format_datetime")
    fmt = _lit_str(args[1], "format_datetime pattern")
    out = []
    i = 0
    while i < len(fmt):
        for tok, st in _JODA_FMT:
            if fmt.startswith(tok, i):
                out.append(st)
                i += len(tok)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                raise FunctionError(
                    f"format_datetime token {ch!r} at {i} is not "
                    "supported"
                )
            out.append("%%" if ch == "%" else ch)
            i += 1
    key = f"date_format:{json.dumps([''.join(out)])}"
    return E.IntToDict(
        arg, key, _DATE_LO, _DATE_HI, E.dict_transform_fn(key)
    )


@_register("initcap", 1, description="initcap(s)")
def _initcap(args):
    return _transform(_string_arg(args[0], "initcap"), "initcap")


@_register("md5", 1, description="md5(s) -> hex digest")
def _md5(args):
    return _transform(_string_arg(args[0], "md5"), "md5")


@_register("sha256", 1, description="sha256(s) -> hex digest")
def _sha256(args):
    return _transform(_string_arg(args[0], "sha256"), "sha256")


@_register("crc32", 1, description="crc32(s) -> bigint")
def _crc32(args):
    return _int_func(_string_arg(args[0], "crc32"), "crc32")


@_register("codepoint", 1, description="codepoint(s) -> first char")
def _codepoint(args):
    return _int_func(_string_arg(args[0], "codepoint"), "codepoint")


@_register("repeat", 2, description="repeat(s, n)")
def _repeat(args):
    if not isinstance(args[1], E.Literal) or args[1].value is None:
        raise FunctionError("repeat count must be a constant")
    n = int(args[1].value)
    if n < 0 or n > 100:
        raise FunctionError("repeat count out of range [0, 100]")
    return _transform(
        _string_arg(args[0], "repeat"), f"repeat:{json.dumps([n])}"
    )


@_register("translate", 3, description="translate(s, from, to)")
def _translate(args):
    src = _lit_str(args[1], "translate from")
    dst = _lit_str(args[2], "translate to")
    if len(src) != len(dst):
        raise FunctionError(
            "translate from/to must have equal length"
        )
    return _transform(
        _string_arg(args[0], "translate"),
        f"translate:{json.dumps([src, dst])}",
    )


@_register(
    "levenshtein_distance", 2,
    description="levenshtein_distance(s, literal)",
)
def _levenshtein(args):
    other = _lit_str(args[1], "levenshtein_distance reference")
    return _int_func(
        _string_arg(args[0], "levenshtein_distance"),
        f"levenshtein:{json.dumps([other])}",
    )


@_register("char_length", 1, description="char_length(s)")
def _char_length(args):
    return _int_func(_string_arg(args[0], "char_length"), "length")


@_register("strpos", 2, description="strpos(s, sub) -> 1-based, 0=absent")
def _strpos(args):
    s = _string_arg(args[0], "strpos")
    sub = _lit_str(args[1], "strpos substring")
    return _int_func(s, f"strpos:{json.dumps([sub])}")


@_register("position", 2, description="position(sub IN s)")
def _position(args):
    # the parser's position(x IN y) special form produces
    # position(x, y): arg order is (substring, string) — flipped vs
    # strpos
    sub = _lit_str(args[0], "position substring")
    s = _string_arg(args[1], "position")
    return _int_func(s, f"strpos:{json.dumps([sub])}")


@_register("lpad", 3, description="lpad(s, size, pad)")
def _lpad(args):
    s = _string_arg(args[0], "lpad")
    size = _lit_int(args[1], "lpad size")
    pad = _lit_str(args[2], "lpad padstring")
    return _transform(s, f"lpad:{json.dumps([size, pad])}")


@_register("rpad", 3, description="rpad(s, size, pad)")
def _rpad(args):
    s = _string_arg(args[0], "rpad")
    size = _lit_int(args[1], "rpad size")
    pad = _lit_str(args[2], "rpad padstring")
    return _transform(s, f"rpad:{json.dumps([size, pad])}")


@_register(
    "split_part", 3,
    description="split_part(s, delim, index); out-of-range -> '' "
    "(deviation: the reference returns NULL)",
)
def _split_part(args):
    s = _string_arg(args[0], "split_part")
    delim = _lit_str(args[1], "split_part delimiter")
    index = _lit_int(args[2], "split_part index")
    if index < 1:
        raise FunctionError("split_part index must be >= 1")
    return _transform(s, f"split_part:{json.dumps([delim, index])}")


@_register("regexp_like", 2, description="regexp_like(s, pattern)")
def _regexp_like(args):
    s = _string_arg(args[0], "regexp_like")
    pat = _lit_str(args[1], "regexp_like pattern")
    return _predicate(s, f"regexp_like:{json.dumps([pat])}")


@_register("starts_with", 2, description="starts_with(s, prefix)")
def _starts_with(args):
    s = _string_arg(args[0], "starts_with")
    prefix = _lit_str(args[1], "starts_with prefix")
    return _predicate(s, f"starts_with:{json.dumps([prefix])}")


@_register("ends_with", 2, description="ends_with(s, suffix)")
def _ends_with(args):
    s = _string_arg(args[0], "ends_with")
    suffix = _lit_str(args[1], "ends_with suffix")
    return _predicate(s, f"ends_with:{json.dumps([suffix])}")


# ---------------------------------------------------------------- date

_DATE_UNITS = ("year", "quarter", "month", "week", "day")
_TIME_UNITS = ("hour", "minute", "second")


@_register("date_trunc", 2, description="date_trunc(unit, x)",
           fuzz=None)
def _date_trunc(args):
    unit = _lit_str(args[0], "date_trunc unit").lower()
    x = _date_arg(args[1], "date_trunc")
    if unit not in _DATE_UNITS + _TIME_UNITS:
        raise FunctionError(f"date_trunc: unknown unit {unit!r}")
    if unit in _TIME_UNITS and x.dtype.name != "timestamp":
        raise FunctionError(
            f"date_trunc({unit!r}) requires a timestamp argument"
        )
    return E.DateTrunc(unit, x)


@_register("date_add", 3, description="date_add(unit, n, x)")
def _date_add(args):
    unit = _lit_str(args[0], "date_add unit").lower()
    if unit not in _DATE_UNITS or unit == "quarter":
        raise FunctionError(f"date_add: unsupported unit {unit!r}")
    n = _numeric_arg(args[1], "date_add")
    if not n.dtype.is_integer:
        raise FunctionError("date_add count must be an integer")
    x = _date_arg(args[2], "date_add")
    return E.DateAdd(unit, n, x)


@_register(
    "date_diff", 3,
    description="date_diff('day'|'week', a, b) -> b - a in units",
)
def _date_diff(args):
    unit = _lit_str(args[0], "date_diff unit").lower()
    a = _date_arg(args[1], "date_diff")
    b = _date_arg(args[2], "date_diff")
    if unit not in ("day", "week"):
        raise FunctionError(
            f"date_diff: unsupported unit {unit!r} (day/week only; "
            "month/year boundaries need per-row civil division)"
        )
    if a.dtype.name == "timestamp" or b.dtype.name == "timestamp":
        raise FunctionError("date_diff over timestamps: cast to date")
    diff = E.Arithmetic("-", b, a, T.BIGINT)
    if unit == "week":
        return E.arith("/", diff, E.Literal(7, T.BIGINT))
    return diff


def _extract_fn(field: str):
    def build(args, _f=field):
        return E.Extract(_f, _date_arg(args[0], _f))

    return build


for _f in (
    "year", "month", "day", "quarter", "week",
    "day_of_week", "day_of_year",
):
    _register(_f, 1, description=f"{_f}(x)", fuzz=("date",))(
        _extract_fn(_f)
    )


# --------------------------------------------------- aggregate registry

@dataclasses.dataclass(frozen=True)
class KernelAgg:
    """Aggregate lowering onto a native kernel accumulator: ``func`` is
    an ops/aggregation.py kernel name (count/count_star/sum/min/max/
    array_agg/approx_percentile/min_by/max_by), ``arg2`` the ordering
    argument of min_by/max_by, ``param`` approx_percentile's quantile."""

    func: str
    arg: Optional[E.Expr]
    arg2: Optional[E.Expr] = None
    param: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class ComposedAgg:
    """Aggregate lowering as primitive mergeable states + an Expr
    finisher — the engine's form of the reference's accumulator quartet
    (@InputFunction/@CombineFunction/@OutputFunction — SURVEY.md §2.1
    "Function registry"): each state is a (suffix, primitive, expr)
    where primitive ∈ {sum, count, min, max} merges with itself (sum/
    count by summing, min/max by re-reducing), and ``finish`` maps
    ColumnRefs of the state columns to the output expression. Because
    states are self-mergeable primitives, the partial/final distributed
    split (parallel/agg_split.py) handles every composed aggregate with
    NO per-function code."""

    states: Tuple[Tuple[str, str, E.Expr], ...]
    finish: Callable[[Dict[str, E.Expr]], E.Expr]
    dtype: T.DataType


@dataclasses.dataclass(frozen=True)
class AggregateFunction:
    """One registered aggregate builtin. ``build`` validates the
    lowered argument exprs and returns a KernelAgg or ComposedAgg;
    ``distinct_rewrite`` marks approx_distinct-style functions the
    planner rewrites into the two-level count(DISTINCT) tree before
    lowering ever happens."""

    name: str
    min_args: int
    max_args: int  # -1 = variadic
    build: Optional[Callable[[List[E.Expr]], object]]
    description: str = ""
    #: fuzzer argument classes (see ScalarFunction.fuzz); aggregates
    #: with no sqlite oracle equivalent set None
    fuzz: Optional[Tuple[str, ...]] = None
    distinct_rewrite: bool = False


AGGREGATE: Dict[str, AggregateFunction] = {}


def _register_agg(
    name: str,
    min_args: int,
    max_args: Optional[int] = None,
    description: str = "",
    fuzz: Optional[Tuple[str, ...]] = None,
    distinct_rewrite: bool = False,
):
    def deco(fn):
        AGGREGATE[name] = AggregateFunction(
            name=name,
            min_args=min_args,
            max_args=min_args if max_args is None else max_args,
            build=fn,
            description=description,
            fuzz=fuzz,
            distinct_rewrite=distinct_rewrite,
        )
        return fn

    return deco


def is_aggregate(name: str) -> bool:
    return name in AGGREGATE


def lower_aggregate(name: str, args: List[E.Expr]):
    """Resolve + build an aggregate call -> KernelAgg | ComposedAgg;
    FunctionError on unknown name or arity/type mismatch. The planner's
    single entry point (plan/planner.py::_plain_agg_node)."""
    fn = AGGREGATE.get(name)
    if fn is None:
        raise FunctionError(f"unknown aggregate function: {name}")
    n = len(args)
    if n < fn.min_args or (fn.max_args >= 0 and n > fn.max_args):
        want = (
            str(fn.min_args)
            if fn.min_args == fn.max_args
            else f"{fn.min_args}..{'*' if fn.max_args < 0 else fn.max_args}"
        )
        raise FunctionError(f"{name}() takes {want} arguments, got {n}")
    return fn.build(args)


def agg_state_type(prim: str, expr: Optional[E.Expr]) -> T.DataType:
    """Result type of one primitive state column (mirrors the kernel's
    AggCall.result_type for the primitive subset)."""
    if prim in ("count", "count_star"):
        return T.BIGINT
    t = expr.dtype
    if prim == "sum":
        if t.is_decimal:
            return T.decimal(18, t.scale)
        if t.is_integer:
            return T.BIGINT
        return T.DOUBLE
    if prim in ("min", "max"):
        return t
    raise FunctionError(f"unknown aggregate state primitive {prim}")


# --- Expr algebra helpers for finishers (all in DOUBLE) ---------------


def _f64(e: E.Expr) -> E.Expr:
    return e if e.dtype == T.DOUBLE else E.Cast(e, T.DOUBLE)


def _flit(v: float) -> E.Expr:
    return E.Literal(float(v), T.DOUBLE)


def _fmul(a: E.Expr, b: E.Expr) -> E.Expr:
    return E.Arithmetic("*", a, b, T.DOUBLE)


def _fdiv(a: E.Expr, b: E.Expr) -> E.Expr:
    return E.Arithmetic("/", a, b, T.DOUBLE)


def _fadd(a: E.Expr, b: E.Expr) -> E.Expr:
    return E.Arithmetic("+", a, b, T.DOUBLE)


def _fsub(a: E.Expr, b: E.Expr) -> E.Expr:
    return E.Arithmetic("-", a, b, T.DOUBLE)


def _null_unless(cond: E.Expr, body: E.Expr, dtype: T.DataType) -> E.Expr:
    """body where cond, SQL NULL otherwise."""
    return E.Case(
        whens=((E.Not(cond), E.Literal(None, dtype)),),
        default=body,
        _dtype=dtype,
    )


def _cnt_ge(cnt: E.Expr, n: int) -> E.Expr:
    return E.Compare(">=", cnt, E.Literal(n, T.BIGINT))


def _pair_masked(x: E.Expr, y: E.Expr, e: E.Expr) -> E.Expr:
    """e where BOTH x and y are non-null, else NULL — two-argument
    aggregates (corr/covar/regr) skip a row when either input is NULL."""
    both = E.And((E.Not(E.IsNull(x)), E.Not(E.IsNull(y))))
    return E.Case(
        whens=((both, e),),
        default=E.Literal(None, e.dtype),
        _dtype=e.dtype,
    )


def _orderable_arg(e: E.Expr, fname: str) -> E.Expr:
    t = e.dtype
    ok = (
        t.is_integer or t.is_decimal or t.is_string
        or t.name in ("double", "real", "date", "timestamp", "boolean")
    )
    if not ok:
        raise FunctionError(f"{fname}() cannot order type {t}")
    return e


# --- entries ----------------------------------------------------------


@_register_agg("count", 0, 1, description="count(*) | count(x)",
               fuzz=("any",))
def _agg_count(args):
    if not args:
        return KernelAgg("count_star", None)
    return KernelAgg("count", args[0])


@_register_agg("sum", 1, description="sum(x)", fuzz=("num",))
def _agg_sum(args):
    return KernelAgg("sum", _numeric_arg(args[0], "sum"))


@_register_agg("min", 1, description="min(x)", fuzz=("any",))
def _agg_min(args):
    return KernelAgg("min", _orderable_arg(args[0], "min"))


@_register_agg("max", 1, description="max(x)", fuzz=("any",))
def _agg_max(args):
    return KernelAgg("max", _orderable_arg(args[0], "max"))


@_register_agg("arbitrary", 1, description="any value of the group")
@_register_agg("any_value", 1, description="alias of arbitrary")
def _agg_arbitrary(args):
    return KernelAgg("min", _orderable_arg(args[0], "arbitrary"))


def _bool_arg(e: E.Expr, fname: str) -> E.Expr:
    if e.dtype.name != "boolean":
        raise FunctionError(f"{fname}() requires a boolean argument")
    return e


@_register_agg("bool_and", 1, description="true iff every value true")
@_register_agg("every", 1, description="alias of bool_and")
def _agg_bool_and(args):
    return KernelAgg("min", _bool_arg(args[0], "bool_and"))


@_register_agg("bool_or", 1, description="true iff any value true")
def _agg_bool_or(args):
    return KernelAgg("max", _bool_arg(args[0], "bool_or"))


@_register_agg("array_agg", 1, description="array_agg(x)")
def _agg_array_agg(args):
    return KernelAgg("array_agg", args[0])


@_register_agg(
    "approx_distinct", 1,
    description="plans as exact count(DISTINCT x) — error 0 <= any "
    "HLL standard error",
    distinct_rewrite=True,
)
def _agg_approx_distinct(args):
    raise FunctionError(
        "approx_distinct is rewritten by the planner before lowering"
    )


@_register_agg("avg", 1, description="avg(x) = sum/count", fuzz=("num",))
def _agg_avg(args):
    x = _numeric_arg(args[0], "avg")

    def finish(s):
        return _null_unless(
            _cnt_ge(s["cnt"], 1),
            _fdiv(_f64(s["sum"]), _f64(s["cnt"])),
            T.DOUBLE,
        )

    return ComposedAgg(
        states=(("sum", "sum", x), ("cnt", "count", x)),
        finish=finish,
        dtype=T.DOUBLE,
    )


def _variance_entry(func: str):
    """stddev/variance family from (Σx, Σx², n) — the same mergeable
    decomposition the single-node kernel used to hardcode."""

    def build(args, _func=func):
        x = _f64(_numeric_arg(args[0], _func))
        sq = _fmul(x, x)
        samp = _func.endswith("_samp")

        def finish(s, _samp=samp, _f=_func):
            nf = _f64(s["cnt"])
            mean = _fdiv(s["s1"], nf)
            var = _fsub(_fdiv(s["s2"], nf), _fmul(mean, mean))
            if _samp:
                var = _fdiv(
                    _fmul(var, nf), _fsub(nf, _flit(1.0))
                )
            # clamp fp cancellation residue: tiny negative -> 0
            var = E.Case(
                whens=(
                    (E.Compare("<", var, _flit(0.0)), _flit(0.0)),
                ),
                default=var,
                _dtype=T.DOUBLE,
            )
            if _f.startswith("stddev"):
                var = E.MathFunc("sqrt", var)
            return _null_unless(
                _cnt_ge(s["cnt"], 2 if _samp else 1), var, T.DOUBLE
            )

        return ComposedAgg(
            states=(
                ("s1", "sum", x),
                ("s2", "sum", sq),
                ("cnt", "count", x),
            ),
            finish=finish,
            dtype=T.DOUBLE,
        )

    return build


for _f, _target in (
    ("stddev", "stddev_samp"), ("stddev_samp", "stddev_samp"),
    ("stddev_pop", "stddev_pop"), ("variance", "var_samp"),
    ("var_samp", "var_samp"), ("var_pop", "var_pop"),
):
    _register_agg(_f, 1, description=f"{_f}(x)", fuzz=None)(
        _variance_entry(_target)
    )


@_register_agg("geometric_mean", 1,
               description="exp(avg(ln(x))); non-positive values are "
               "skipped as NULL ln (deviation: the reference raises)")
def _agg_geometric_mean(args):
    x = _f64(_numeric_arg(args[0], "geometric_mean"))
    lx = E.MathFunc("ln", x)

    def finish(s):
        return _null_unless(
            _cnt_ge(s["cnt"], 1),
            E.MathFunc("exp", _fdiv(s["s"], _f64(s["cnt"]))),
            T.DOUBLE,
        )

    return ComposedAgg(
        states=(("s", "sum", lx), ("cnt", "count", lx)),
        finish=finish,
        dtype=T.DOUBLE,
    )


@_register_agg("count_if", 1, description="count_if(b) = rows where true")
def _agg_count_if(args):
    b = _bool_arg(args[0], "count_if")
    one_if = E.Case(
        whens=((b, E.Literal(1, T.BIGINT)),),
        default=E.Literal(None, T.BIGINT),
        _dtype=T.BIGINT,
    )

    def finish(s):
        return s["c"]

    return ComposedAgg(
        states=(("c", "count", one_if),), finish=finish, dtype=T.BIGINT
    )


@_register_agg(
    "checksum", 1,
    description="order/partitioning-insensitive BIGINT digest: sum of "
    "per-value 32-bit hashes (deviation: the reference emits varbinary)",
)
def _agg_checksum(args):
    h = E.ValueHash(args[0])

    def finish(s):
        return s["s"]

    return ComposedAgg(
        states=(("s", "sum", h),), finish=finish, dtype=T.BIGINT
    )


def _covar_states(y: E.Expr, x: E.Expr):
    """Pairwise-masked (Σx, Σy, Σxy, n) over rows where BOTH non-null."""
    xf, yf = _f64(x), _f64(y)
    return (
        ("sx", "sum", _pair_masked(x, y, xf)),
        ("sy", "sum", _pair_masked(x, y, yf)),
        ("sxy", "sum", _pair_masked(x, y, _fmul(xf, yf))),
        ("cnt", "count", _pair_masked(x, y, xf)),
    )


def _covar_entry(pop: bool):
    def build(args, _pop=pop):
        y = _numeric_arg(args[0], "covar")
        x = _numeric_arg(args[1], "covar")

        def finish(s, _p=_pop):
            nf = _f64(s["cnt"])
            num = _fsub(
                s["sxy"], _fdiv(_fmul(s["sx"], s["sy"]), nf)
            )
            if _p:
                out = _fdiv(num, nf)
                min_n = 1
            else:
                out = _fdiv(num, _fsub(nf, _flit(1.0)))
                min_n = 2
            return _null_unless(
                _cnt_ge(s["cnt"], min_n), out, T.DOUBLE
            )

        return ComposedAgg(
            states=_covar_states(y, x), finish=finish, dtype=T.DOUBLE
        )

    return build


_register_agg("covar_samp", 2, description="sample covariance(y, x)")(
    _covar_entry(False)
)
_register_agg("covar_pop", 2, description="population covariance(y, x)")(
    _covar_entry(True)
)


@_register_agg("corr", 2, description="Pearson correlation of (y, x)")
def _agg_corr(args):
    y = _numeric_arg(args[0], "corr")
    x = _numeric_arg(args[1], "corr")
    xf, yf = _f64(x), _f64(y)
    states = _covar_states(y, x) + (
        ("sx2", "sum", _pair_masked(x, y, _fmul(xf, xf))),
        ("sy2", "sum", _pair_masked(x, y, _fmul(yf, yf))),
    )

    def finish(s):
        nf = _f64(s["cnt"])
        num = _fsub(_fmul(nf, s["sxy"]), _fmul(s["sx"], s["sy"]))
        dx = _fsub(_fmul(nf, s["sx2"]), _fmul(s["sx"], s["sx"]))
        dy = _fsub(_fmul(nf, s["sy2"]), _fmul(s["sy"], s["sy"]))
        den = E.MathFunc("sqrt", _fmul(dx, dy))
        # sqrt() NULLs on negative domain; also NULL a zero denominator
        out = _null_unless(
            E.Compare(">", den, _flit(0.0)), _fdiv(num, den), T.DOUBLE
        )
        return _null_unless(_cnt_ge(s["cnt"], 2), out, T.DOUBLE)

    return ComposedAgg(states=states, finish=finish, dtype=T.DOUBLE)


@_register_agg("regr_slope", 2,
               description="regr_slope(y, x) = covar_pop(y,x)/var_pop(x)")
def _agg_regr_slope(args):
    y = _numeric_arg(args[0], "regr_slope")
    x = _numeric_arg(args[1], "regr_slope")
    xf = _f64(x)
    states = _covar_states(y, x) + (
        ("sx2", "sum", _pair_masked(x, y, _fmul(xf, xf))),
    )

    def finish(s):
        nf = _f64(s["cnt"])
        num = _fsub(_fmul(nf, s["sxy"]), _fmul(s["sx"], s["sy"]))
        den = _fsub(_fmul(nf, s["sx2"]), _fmul(s["sx"], s["sx"]))
        out = _null_unless(
            E.Compare("!=", den, _flit(0.0)), _fdiv(num, den), T.DOUBLE
        )
        return _null_unless(_cnt_ge(s["cnt"], 1), out, T.DOUBLE)

    return ComposedAgg(states=states, finish=finish, dtype=T.DOUBLE)


@_register_agg("regr_intercept", 2,
               description="regr_intercept(y, x) = avg(y) - slope*avg(x)")
def _agg_regr_intercept(args):
    y = _numeric_arg(args[0], "regr_intercept")
    x = _numeric_arg(args[1], "regr_intercept")
    xf = _f64(x)
    states = _covar_states(y, x) + (
        ("sx2", "sum", _pair_masked(x, y, _fmul(xf, xf))),
    )

    def finish(s):
        nf = _f64(s["cnt"])
        num = _fsub(_fmul(nf, s["sxy"]), _fmul(s["sx"], s["sy"]))
        den = _fsub(_fmul(nf, s["sx2"]), _fmul(s["sx"], s["sx"]))
        slope = _fdiv(num, den)
        icept = _fdiv(
            _fsub(s["sy"], _fmul(slope, s["sx"])), nf
        )
        out = _null_unless(
            E.Compare("!=", den, _flit(0.0)), icept, T.DOUBLE
        )
        return _null_unless(_cnt_ge(s["cnt"], 1), out, T.DOUBLE)

    return ComposedAgg(states=states, finish=finish, dtype=T.DOUBLE)


def _moment_states(x: E.Expr, upto: int):
    xf = _f64(x)
    states = [("s1", "sum", xf), ("cnt", "count", xf)]
    p = xf
    for k in range(2, upto + 1):
        p = _fmul(p, xf)
        states.append((f"s{k}", "sum", p))
    return tuple(states)


@_register_agg("skewness", 1,
               description="sqrt(n) * m3 / m2^1.5 over central moment "
               "sums (the reference's AggregationUtils formula)")
def _agg_skewness(args):
    x = _numeric_arg(args[0], "skewness")

    def finish(s):
        nf = _f64(s["cnt"])
        mean = _fdiv(s["s1"], nf)
        # central moment SUMS from raw moment sums
        m2 = _fsub(s["s2"], _fdiv(_fmul(s["s1"], s["s1"]), nf))
        m3 = _fadd(
            _fsub(
                s["s3"],
                _fmul(_flit(3.0), _fmul(mean, s["s2"])),
            ),
            _fmul(_flit(2.0), _fmul(_fmul(mean, mean), s["s1"])),
        )
        den = E.MathFunc2("power", m2, _flit(1.5))
        out = _fdiv(_fmul(E.MathFunc("sqrt", nf), m3), den)
        out = _null_unless(E.Compare(">", m2, _flit(0.0)), out, T.DOUBLE)
        return _null_unless(_cnt_ge(s["cnt"], 3), out, T.DOUBLE)

    return ComposedAgg(
        states=_moment_states(x, 3), finish=finish, dtype=T.DOUBLE
    )


@_register_agg("kurtosis", 1,
               description="sample excess kurtosis (the reference's "
               "AggregationUtils formula)")
def _agg_kurtosis(args):
    x = _numeric_arg(args[0], "kurtosis")

    def finish(s):
        nf = _f64(s["cnt"])
        mean = _fdiv(s["s1"], nf)
        m2 = _fsub(s["s2"], _fdiv(_fmul(s["s1"], s["s1"]), nf))
        m3 = _fadd(
            _fsub(
                s["s3"], _fmul(_flit(3.0), _fmul(mean, s["s2"]))
            ),
            _fmul(_flit(2.0), _fmul(_fmul(mean, mean), s["s1"])),
        )
        mean2 = _fmul(mean, mean)
        m4 = _fadd(
            _fsub(
                _fadd(
                    s["s4"],
                    _fmul(
                        _flit(6.0), _fmul(mean2, s["s2"])
                    ),
                ),
                _fmul(_flit(4.0), _fmul(mean, s["s3"])),
            ),
            _fmul(_flit(-3.0), _fmul(mean2, _fmul(mean2, nf))),
        )
        _ = m3  # m3 not used by kurtosis; kept for clarity of family
        n1 = _fsub(nf, _flit(1.0))
        n2 = _fsub(nf, _flit(2.0))
        n3 = _fsub(nf, _flit(3.0))
        term = _fdiv(_fmul(nf, _fadd(nf, _flit(1.0))), _fmul(n1, _fmul(n2, n3)))
        # Σd⁴ / s⁴ with s² = m2/(n-1):  m4 · (n-1)² / m2²
        core = _fdiv(_fmul(_fmul(n1, n1), m4), _fmul(m2, m2))
        adj = _fdiv(
            _fmul(_flit(3.0), _fmul(n1, n1)), _fmul(n2, n3)
        )
        out = _fsub(_fmul(term, core), adj)
        out = _null_unless(E.Compare(">", m2, _flit(0.0)), out, T.DOUBLE)
        return _null_unless(_cnt_ge(s["cnt"], 4), out, T.DOUBLE)

    return ComposedAgg(
        states=_moment_states(x, 4), finish=finish, dtype=T.DOUBLE
    )


@_register_agg(
    "approx_percentile", 2,
    description="approx_percentile(x, p): exact nearest-rank percentile "
    "(error 0 <= any qdigest bound); p must be a literal in [0, 1]",
)
def _agg_approx_percentile(args):
    x = _numeric_arg(args[0], "approx_percentile")
    p = args[1]
    if not isinstance(p, E.Literal) or p.value is None:
        raise FunctionError(
            "approx_percentile percentile must be a numeric literal"
        )
    pv = float(p.value)
    if isinstance(p.value, int) and p.dtype.is_decimal:
        pv = pv / (10 ** p.dtype.scale)
    if not (0.0 <= pv <= 1.0):
        raise FunctionError(
            f"approx_percentile percentile must be in [0, 1], got {pv}"
        )
    return KernelAgg("approx_percentile", x, param=pv)


@_register_agg("min_by", 2, description="min_by(x, y): x at minimal y")
def _agg_min_by(args):
    return KernelAgg(
        "min_by", args[0], arg2=_orderable_arg(args[1], "min_by")
    )


@_register_agg("max_by", 2, description="max_by(x, y): x at maximal y")
def _agg_max_by(args):
    return KernelAgg(
        "max_by", args[0], arg2=_orderable_arg(args[1], "max_by")
    )


# ------------------------------------------------------ window registry


@dataclasses.dataclass(frozen=True)
class WindowFunction:
    """One registered window builtin. ``kind`` selects the planner's
    argument protocol (plan/planner.py::_plan_windows):

    - "rank":  no arguments (row_number/rank/dense_rank/percent_rank/
               cume_dist) — pure position arithmetic in the kernel
    - "ntile": one constant bucket-count argument
    - "nav":   lag/lead — value, optional constant offset + default
    - "value": first_value/last_value (one value argument) and
               nth_value (value + constant n)
    - "agg":   aggregate-over-frame (sum/count/avg/min/max)
    """

    name: str
    kind: str
    description: str = ""


WINDOW: Dict[str, WindowFunction] = {}

for _n, _k, _d in (
    ("row_number", "rank", "1-based row position in partition"),
    ("rank", "rank", "rank with gaps over the peer groups"),
    ("dense_rank", "rank", "rank without gaps"),
    ("percent_rank", "rank", "(rank-1)/(rows-1); 0 for 1-row partitions"),
    ("cume_dist", "rank", "peers-through-current / partition rows"),
    ("ntile", "ntile", "ntile(n): n near-equal buckets"),
    ("lag", "nav", "lag(x[, offset[, default]])"),
    ("lead", "nav", "lead(x[, offset[, default]])"),
    ("first_value", "value", "first frame value"),
    ("last_value", "value", "last frame value"),
    ("nth_value", "value", "nth_value(x, n): n-th frame row's value"),
    ("sum", "agg", "running/frame sum"),
    ("count", "agg", "running/frame count"),
    ("avg", "agg", "running/frame average"),
    ("min", "agg", "running/frame minimum"),
    ("max", "agg", "running/frame maximum"),
):
    WINDOW[_n] = WindowFunction(name=_n, kind=_k, description=_d)


def is_window(name: str) -> bool:
    return name in WINDOW
