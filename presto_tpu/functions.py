"""Scalar function registry.

Reference parity: ``FunctionAndTypeManager`` and the annotation-driven
builtin registry (``@ScalarFunction`` over hundreds of builtins —
SURVEY.md §2.1 "Function registry"). The reference registers a function
once and every layer (analyzer, planner, interpreter, codegen) resolves
it through the manager; here the analogous seam is a declarative table
``name -> ScalarFunction`` whose ``build`` lowers a call directly to the
engine's Expr IR (XLA is the codegen, so "registering" a function means
providing its typed Expr construction — no interpreter entry needed).

Adding a builtin touches ONLY this module: the planner resolves every
non-aggregate, non-window FuncCall here (plan/planner.py FuncCall
branch), and the fuzzer draws generatable functions from the same table
(``fuzz`` argument classes).

String functions follow the dictionary-LUT design (SURVEY.md §7
"Strings on TPU"): host-side evaluation over the (small) dictionary,
device-side int32/int64/bool LUT gathers — so string builtins require a
dictionary-backed argument and literal parameters, enforced here at
plan time.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu import expr as E
from presto_tpu import types as T


class FunctionError(ValueError):
    """Raised for bad calls; the planner re-raises as PlanningError."""


@dataclasses.dataclass(frozen=True)
class ScalarFunction:
    """One registered scalar builtin."""

    name: str
    min_args: int
    max_args: int  # -1 = variadic
    build: Callable[[List[E.Expr]], E.Expr]
    description: str = ""
    #: fuzzer argument classes, each in {"num", "str", "date", "any",
    #: "bool"}; None = not fuzz-generatable (needs literal params etc.)
    fuzz: Optional[Tuple[str, ...]] = None


SCALAR: Dict[str, ScalarFunction] = {}


def _register(
    name: str,
    min_args: int,
    max_args: Optional[int] = None,
    description: str = "",
    fuzz: Optional[Tuple[str, ...]] = None,
):
    def deco(fn):
        SCALAR[name] = ScalarFunction(
            name=name,
            min_args=min_args,
            max_args=min_args if max_args is None else max_args,
            build=fn,
            description=description,
            fuzz=fuzz,
        )
        return fn

    return deco


def lower_scalar(name: str, args: List[E.Expr]) -> E.Expr:
    """Resolve + build a scalar call; FunctionError on unknown name or
    arity/type mismatch. The planner's single entry point."""
    fn = SCALAR.get(name)
    if fn is None:
        raise FunctionError(f"unknown function: {name}")
    n = len(args)
    if n < fn.min_args or (fn.max_args >= 0 and n > fn.max_args):
        want = (
            str(fn.min_args)
            if fn.min_args == fn.max_args
            else f"{fn.min_args}..{'*' if fn.max_args < 0 else fn.max_args}"
        )
        raise FunctionError(f"{name}() takes {want} arguments, got {n}")
    return fn.build(args)


# ------------------------------------------------------------- helpers


def _lit_str(e: E.Expr, what: str) -> str:
    if not isinstance(e, E.Literal) or not isinstance(e.value, str):
        raise FunctionError(f"{what} must be a string literal")
    return e.value


def _lit_int(e: E.Expr, what: str) -> int:
    if not isinstance(e, E.Literal) or e.value is None:
        raise FunctionError(f"{what} must be an integer literal")
    try:
        return int(e.value)
    except (TypeError, ValueError):
        raise FunctionError(
            f"{what} must be an integer literal, got {e.value!r}"
        ) from None


def _string_arg(e: E.Expr, fname: str) -> E.Expr:
    if not e.dtype.is_string:
        raise FunctionError(
            f"{fname}() requires a varchar argument, got {e.dtype}"
        )
    return e


def _numeric_arg(e: E.Expr, fname: str) -> E.Expr:
    t = e.dtype
    if not (t.is_integer or t.is_decimal or t.name in ("double", "real")):
        raise FunctionError(
            f"{fname}() requires a numeric argument, got {t}"
        )
    return e


def _date_arg(e: E.Expr, fname: str) -> E.Expr:
    if e.dtype.name not in ("date", "timestamp"):
        raise FunctionError(
            f"{fname}() requires a date/timestamp argument, got {e.dtype}"
        )
    return e


def _common_type(args: List[E.Expr]) -> T.DataType:
    ct = args[0].dtype
    for a in args[1:]:
        ct = T.common_super_type(ct, a.dtype)
    return ct


def _transform(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):  # constant fold
        v = None if arg.value is None else str(fn(str(arg.value)))
        return E.Literal(v, T.VARCHAR)
    return E.DictTransform(arg, key, fn)


def _int_func(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):
        v = None if arg.value is None else int(fn(str(arg.value)))
        return E.Literal(v, T.BIGINT)
    return E.DictIntFunc(arg, key, fn)


def _predicate(arg: E.Expr, key: str) -> E.Expr:
    fn = E.dict_transform_fn(key)
    if isinstance(arg, E.Literal):
        v = None if arg.value is None else bool(fn(str(arg.value)))
        return E.Literal(v, T.BOOLEAN)
    return E.DictPredicate(arg, key, fn)


def _math1(func: str):
    def build(args, _f=func):
        return E.MathFunc(_f, _numeric_arg(args[0], _f))

    return build


# ---------------------------------------------------------------- math

for _f in (
    "sqrt", "ln", "exp", "abs", "sign", "cbrt",
    "log2", "log10", "sin", "cos", "tan", "asin", "acos", "atan",
    "degrees", "radians",
):
    _register(_f, 1, description=f"{_f}(x)", fuzz=("num",))(_math1(_f))


@_register("floor", 1, description="floor(x) -> bigint", fuzz=("num",))
def _floor(args):
    return E.MathFunc("floor", _numeric_arg(args[0], "floor"))


@_register("ceil", 1, description="ceil(x) -> bigint", fuzz=("num",))
@_register("ceiling", 1, description="alias of ceil")
def _ceil(args):
    return E.MathFunc("ceil", _numeric_arg(args[0], "ceil"))


@_register("round", 1, 2, description="round(x[, digits])", fuzz=("num",))
def _round(args):
    x = _numeric_arg(args[0], "round")
    if len(args) == 1:
        return E.MathFunc("round", x)
    return E.MathFunc2("round", x, _numeric_arg(args[1], "round"))


@_register("truncate", 1, 2, description="truncate(x[, digits])",
           fuzz=("num",))
def _truncate(args):
    x = _numeric_arg(args[0], "truncate")
    if len(args) == 1:
        return E.MathFunc("truncate", x)
    return E.MathFunc2("truncate", x, _numeric_arg(args[1], "truncate"))


@_register("power", 2, description="power(x, y)", fuzz=("num", "num"))
@_register("pow", 2, description="alias of power")
def _power(args):
    return E.MathFunc2(
        "power",
        _numeric_arg(args[0], "power"),
        _numeric_arg(args[1], "power"),
    )


@_register("atan2", 2, description="atan2(y, x)", fuzz=("num", "num"))
def _atan2(args):
    return E.MathFunc2(
        "atan2",
        _numeric_arg(args[0], "atan2"),
        _numeric_arg(args[1], "atan2"),
    )


@_register("log", 2, description="log(base, x)")
def _log(args):
    return E.MathFunc2(
        "log", _numeric_arg(args[0], "log"), _numeric_arg(args[1], "log")
    )


@_register("mod", 2, description="mod(x, y)", fuzz=("num", "num"))
def _mod(args):
    return E.arith(
        "%", _numeric_arg(args[0], "mod"), _numeric_arg(args[1], "mod")
    )


@_register("pi", 0, description="pi()")
def _pi(args):
    import math

    return E.Literal(math.pi, T.DOUBLE)


@_register("e", 0, description="e()")
def _e(args):
    import math

    return E.Literal(math.e, T.DOUBLE)


def _bound(op: str, args: List[E.Expr], fname: str) -> E.Expr:
    """greatest/least as a CASE fold; NULL if any argument is NULL
    (Presto semantics)."""
    ct = _common_type(args)
    args = [a if a.dtype == ct else E.Cast(a, ct) for a in args]
    out = args[0]
    for a in args[1:]:
        out = E.Case(
            whens=(
                (E.IsNull(out), E.Literal(None, ct)),
                (E.IsNull(a), E.Literal(None, ct)),
                (E.Compare(op, out, a), out),
            ),
            default=a,
            _dtype=ct,
        )
    return out


@_register("greatest", 1, -1, description="greatest(x, ...)",
           fuzz=("num", "num"))
def _greatest(args):
    return _bound(">=", list(args), "greatest")


@_register("least", 1, -1, description="least(x, ...)",
           fuzz=("num", "num"))
def _least(args):
    return _bound("<=", list(args), "least")


# --------------------------------------------------------- conditional


@_register("coalesce", 1, -1, description="coalesce(x, ...)")
def _coalesce(args):
    ct = _common_type(list(args))
    return E.Coalesce(tuple(args), ct)


@_register("if", 2, 3, description="if(cond, then[, else])")
def _if(args):
    cond = args[0]
    if cond.dtype.name != "boolean":
        raise FunctionError("if() condition must be boolean")
    then = args[1]
    default = args[2] if len(args) > 2 else E.Literal(None, then.dtype)
    ct = T.common_super_type(then.dtype, default.dtype)
    return E.Case(whens=((cond, then),), default=default, _dtype=ct)


@_register("nullif", 2, description="nullif(a, b)")
def _nullif(args):
    a, b = args
    return E.Case(
        whens=((E.Compare("=", a, b), E.Literal(None, a.dtype)),),
        default=a,
        _dtype=a.dtype,
    )


# -------------------------------------------------------------- string


@_register("lower", 1, description="lower(s)", fuzz=("str",))
def _lower_fn(args):
    return _transform(_string_arg(args[0], "lower"), "lower")


@_register("upper", 1, description="upper(s)", fuzz=("str",))
def _upper_fn(args):
    return _transform(_string_arg(args[0], "upper"), "upper")


@_register("trim", 1, description="trim(s)", fuzz=("str",))
def _trim(args):
    return _transform(_string_arg(args[0], "trim"), "trim")


@_register("ltrim", 1, description="ltrim(s)", fuzz=("str",))
def _ltrim(args):
    return _transform(_string_arg(args[0], "ltrim"), "ltrim")


@_register("rtrim", 1, description="rtrim(s)", fuzz=("str",))
def _rtrim(args):
    return _transform(_string_arg(args[0], "rtrim"), "rtrim")


@_register("reverse", 1, description="reverse(s)", fuzz=("str",))
def _reverse(args):
    return _transform(_string_arg(args[0], "reverse"), "reverse")


@_register("length", 1, description="length(s) -> bigint", fuzz=("str",))
def _length(args):
    return _int_func(_string_arg(args[0], "length"), "length")


@_register("substring", 2, 3, description="substring(s, start[, len])")
@_register("substr", 2, 3, description="alias of substring")
def _substring(args):
    s = _string_arg(args[0], "substring")
    start = _lit_int(args[1], "substring start")
    length = _lit_int(args[2], "substring length") if len(args) > 2 else None
    return _transform(s, f"substring:{start}:{length}")


@_register("replace", 3, description="replace(s, search, repl)")
def _replace(args):
    s = _string_arg(args[0], "replace")
    old = _lit_str(args[1], "replace search")
    new = _lit_str(args[2], "replace replacement")
    return _transform(s, f"replace:{json.dumps([old, new])}")


@_register(
    "concat", 1, -1,
    description="concat(s, ...): at most one dictionary column, any "
    "number of string literals (host-LUT design)",
)
def _concat(args):
    cols = [a for a in args if not isinstance(a, E.Literal)]
    if len(cols) > 1:
        raise FunctionError(
            "concat() supports one non-literal argument (dictionary "
            "LUT design); concatenating two columns requires a "
            "cross-dictionary rebuild"
        )
    if not cols:
        return E.Literal(
            "".join(_lit_str(a, "concat argument") for a in args),
            T.VARCHAR,
        )
    col = cols[0]
    _string_arg(col, "concat")
    idx = next(i for i, a in enumerate(args) if a is col)
    prefix = "".join(
        _lit_str(a, "concat argument") for a in args[:idx]
    )
    suffix = "".join(
        _lit_str(a, "concat argument") for a in args[idx + 1:]
    )
    return _transform(col, f"concat:{json.dumps([prefix, suffix])}")


@_register("strpos", 2, description="strpos(s, sub) -> 1-based, 0=absent")
def _strpos(args):
    s = _string_arg(args[0], "strpos")
    sub = _lit_str(args[1], "strpos substring")
    return _int_func(s, f"strpos:{json.dumps([sub])}")


@_register("position", 2, description="position(sub IN s)")
def _position(args):
    # the parser's position(x IN y) special form produces
    # position(x, y): arg order is (substring, string) — flipped vs
    # strpos
    sub = _lit_str(args[0], "position substring")
    s = _string_arg(args[1], "position")
    return _int_func(s, f"strpos:{json.dumps([sub])}")


@_register("lpad", 3, description="lpad(s, size, pad)")
def _lpad(args):
    s = _string_arg(args[0], "lpad")
    size = _lit_int(args[1], "lpad size")
    pad = _lit_str(args[2], "lpad padstring")
    return _transform(s, f"lpad:{json.dumps([size, pad])}")


@_register("rpad", 3, description="rpad(s, size, pad)")
def _rpad(args):
    s = _string_arg(args[0], "rpad")
    size = _lit_int(args[1], "rpad size")
    pad = _lit_str(args[2], "rpad padstring")
    return _transform(s, f"rpad:{json.dumps([size, pad])}")


@_register(
    "split_part", 3,
    description="split_part(s, delim, index); out-of-range -> '' "
    "(deviation: the reference returns NULL)",
)
def _split_part(args):
    s = _string_arg(args[0], "split_part")
    delim = _lit_str(args[1], "split_part delimiter")
    index = _lit_int(args[2], "split_part index")
    if index < 1:
        raise FunctionError("split_part index must be >= 1")
    return _transform(s, f"split_part:{json.dumps([delim, index])}")


@_register("regexp_like", 2, description="regexp_like(s, pattern)")
def _regexp_like(args):
    s = _string_arg(args[0], "regexp_like")
    pat = _lit_str(args[1], "regexp_like pattern")
    return _predicate(s, f"regexp_like:{json.dumps([pat])}")


@_register("starts_with", 2, description="starts_with(s, prefix)")
def _starts_with(args):
    s = _string_arg(args[0], "starts_with")
    prefix = _lit_str(args[1], "starts_with prefix")
    return _predicate(s, f"starts_with:{json.dumps([prefix])}")


@_register("ends_with", 2, description="ends_with(s, suffix)")
def _ends_with(args):
    s = _string_arg(args[0], "ends_with")
    suffix = _lit_str(args[1], "ends_with suffix")
    return _predicate(s, f"ends_with:{json.dumps([suffix])}")


# ---------------------------------------------------------------- date

_DATE_UNITS = ("year", "quarter", "month", "week", "day")
_TIME_UNITS = ("hour", "minute", "second")


@_register("date_trunc", 2, description="date_trunc(unit, x)",
           fuzz=None)
def _date_trunc(args):
    unit = _lit_str(args[0], "date_trunc unit").lower()
    x = _date_arg(args[1], "date_trunc")
    if unit not in _DATE_UNITS + _TIME_UNITS:
        raise FunctionError(f"date_trunc: unknown unit {unit!r}")
    if unit in _TIME_UNITS and x.dtype.name != "timestamp":
        raise FunctionError(
            f"date_trunc({unit!r}) requires a timestamp argument"
        )
    return E.DateTrunc(unit, x)


@_register("date_add", 3, description="date_add(unit, n, x)")
def _date_add(args):
    unit = _lit_str(args[0], "date_add unit").lower()
    if unit not in _DATE_UNITS or unit == "quarter":
        raise FunctionError(f"date_add: unsupported unit {unit!r}")
    n = _numeric_arg(args[1], "date_add")
    if not n.dtype.is_integer:
        raise FunctionError("date_add count must be an integer")
    x = _date_arg(args[2], "date_add")
    return E.DateAdd(unit, n, x)


@_register(
    "date_diff", 3,
    description="date_diff('day'|'week', a, b) -> b - a in units",
)
def _date_diff(args):
    unit = _lit_str(args[0], "date_diff unit").lower()
    a = _date_arg(args[1], "date_diff")
    b = _date_arg(args[2], "date_diff")
    if unit not in ("day", "week"):
        raise FunctionError(
            f"date_diff: unsupported unit {unit!r} (day/week only; "
            "month/year boundaries need per-row civil division)"
        )
    if a.dtype.name == "timestamp" or b.dtype.name == "timestamp":
        raise FunctionError("date_diff over timestamps: cast to date")
    diff = E.Arithmetic("-", b, a, T.BIGINT)
    if unit == "week":
        return E.arith("/", diff, E.Literal(7, T.BIGINT))
    return diff


def _extract_fn(field: str):
    def build(args, _f=field):
        return E.Extract(_f, _date_arg(args[0], _f))

    return build


for _f in (
    "year", "month", "day", "quarter", "week",
    "day_of_week", "day_of_year",
):
    _register(_f, 1, description=f"{_f}(x)", fuzz=("date",))(
        _extract_fn(_f)
    )


# ------------------------------------------------------- aggregate aliases

#: aggregate-name aliases resolved in the planner's aggregation path
#: (these are AGGREGATES, not scalars — listed here so the registry is
#: the one catalog of builtin names): approx_distinct(x) plans as the
#: exact count(DISTINCT x) two-level rewrite (error 0 <= any HLL
#: standard error); arbitrary/any_value take min (any value is valid);
#: bool_and/bool_or/every are min/max over booleans.
AGGREGATE_ALIASES: Dict[str, str] = {
    "arbitrary": "min",
    "any_value": "min",
    "bool_and": "min",
    "every": "min",
    "bool_or": "max",
}
