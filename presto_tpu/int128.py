"""int128 limb arithmetic for long decimals, as jit-safe jnp ops.

Reference parity: presto-common's ``Int128Math`` (the long-decimal
accumulator/arithmetic kernel, used by DecimalType p>18). TPU-first
shape: a value is an (..., 2) int64 array — [..., 0] the signed high
limb, [..., 1] the low 64 bits (unsigned, stored as an int64 bit
pattern). Everything here is elementwise int64/uint64 VPU work with
static shapes; no loops, no host.

Requires ``jax_enable_x64`` (the engine enables it at import for SQL
bigint semantics).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64
_MASK32 = np.uint64(0xFFFFFFFF)


def _u(x):
    return x.astype(_U64)


def from_i64(x):
    """Sign-extend int64 -> (hi, lo) limbs."""
    return jnp.where(x < 0, jnp.int64(-1), jnp.int64(0)), x


def add(ah, al, bh, bl):
    """(ah, al) + (bh, bl) with carry out of the low limb."""
    lo = al + bl  # wraps (two's complement)
    carry = (_u(lo) < _u(al)).astype(jnp.int64)
    return ah + bh + carry, lo


def neg(h, l):
    """Two's-complement negate: ~x + 1 across limbs."""
    lo = -l  # wraps
    borrow = (l != 0).astype(jnp.int64)
    return -h - borrow, lo


def sub(ah, al, bh, bl):
    nh, nl = neg(bh, bl)
    return add(ah, al, nh, nl)


def eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def lt(ah, al, bh, bl):
    """Signed 128-bit less-than: high limb signed, low limb unsigned."""
    return (ah < bh) | ((ah == bh) & (_u(al) < _u(bl)))


def mul_u32(h, l, c: int):
    """Multiply by a non-negative python int < 2**31 (schoolbook on
    32-bit halves of the low limb; the high limb wraps like the
    reference's overflow-unchecked fast path)."""
    assert 0 <= c < (1 << 31), c
    cu = np.uint64(c)
    lu = _u(l)
    lo32 = lu & _MASK32
    hi32 = lu >> np.uint64(32)
    p_lo = lo32 * cu  # < 2^63
    p_hi = hi32 * cu  # < 2^63
    low = p_lo + ((p_hi & _MASK32) << np.uint64(32))  # may wrap once
    carry = (low < p_lo).astype(jnp.int64)
    new_l = low.astype(jnp.int64)
    new_h = h * jnp.int64(c) + (p_hi >> np.uint64(32)).astype(
        jnp.int64
    ) + carry
    return new_h, new_l


def mul_pow10(h, l, k: int):
    """Multiply by 10**k (k >= 0) in <=2^31 steps — the decimal rescale
    primitive."""
    while k > 0:
        step = min(k, 9)  # 10^9 < 2^31
        h, l = mul_u32(h, l, 10 ** step)
        k -= step
    return h, l


def to_f64(h, l):
    """Approximate float64 value (for casts to DOUBLE)."""
    return h.astype(jnp.float64) * jnp.float64(2.0 ** 64) + _u(l).astype(
        jnp.float64
    )


def _divmod_u128_small(uh, ul, d: int):
    """Unsigned (uh, ul as uint64) // d for python 0 < d < 2**31.
    Schoolbook long division in 32-bit chunks: every partial dividend
    (rem << 32 | chunk) < 2^63, so plain uint64 ops suffice."""
    du = np.uint64(d)
    q3 = uh >> np.uint64(32)
    r = q3 % du
    q3 = q3 // du
    t = (r << np.uint64(32)) | (uh & _MASK32)
    q2 = t // du
    r = t % du
    t = (r << np.uint64(32)) | (ul >> np.uint64(32))
    q1 = t // du
    r = t % du
    t = (r << np.uint64(32)) | (ul & _MASK32)
    q0 = t // du
    r = t % du
    out_h = (q3 << np.uint64(32)) | q2
    out_l = (q1 << np.uint64(32)) | q0
    return out_h, out_l, r


def div_pow10_half_up(h, l, k: int):
    """(h, l) / 10**k with SQL half-up rounding away from zero — the
    long-decimal downscale primitive (reference: Int128Math
    rescaleTruncate/round pair). k <= 18 (two 10^9 chunks; larger
    downscales do not occur in decimal(38) practice)."""
    if k == 0:
        return h, l
    if k > 18:
        raise NotImplementedError(
            f"long-decimal downscale by 10^{k} (>18 digits)"
        )
    is_neg = h < 0
    nh, nl = neg(h, l)
    uh = jnp.where(is_neg, nh, h).astype(_U64)
    ul = jnp.where(is_neg, nl, l).astype(_U64)
    d1 = 10 ** min(k, 9)
    qh, ql, r1 = _divmod_u128_small(uh, ul, d1)
    rem = r1
    dd = np.uint64(d1)
    if k > 9:
        d2 = 10 ** (k - 9)
        qh, ql, r2 = _divmod_u128_small(qh, ql, d2)
        # total remainder = r2*d1 + r1 < 10^18, fits uint64
        rem = r2 * np.uint64(d1) + r1
        dd = np.uint64(d1) * np.uint64(d2)
    half = dd // np.uint64(2) + dd % np.uint64(2)  # ceil(d/2): half-up
    carry = (rem >= half).astype(jnp.int64)
    qh = qh.astype(jnp.int64)
    ql = ql.astype(jnp.int64)
    qh, ql = add(qh, ql, jnp.zeros_like(qh), carry)
    back_h, back_l = neg(qh, ql)
    return (
        jnp.where(is_neg, back_h, qh),
        jnp.where(is_neg, back_l, ql),
    )
