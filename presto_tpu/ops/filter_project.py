"""Fused filter + project over a Page.

Reference parity: ``ScanFilterAndProjectOperator`` / ``FilterAndProject-
Operator`` driven by the bytecode-compiled ``PageProcessor`` (selected
positions + projected blocks) — SURVEY.md §2.1, §3.3.

TPU-first shape: the predicate lowers to a boolean mask, survivors are
*compacted to the front* with a static-size ``jnp.nonzero`` so the output
page has the same capacity (XLA static shapes) and a traced ``num_valid``.
Projections are evaluated over the full page and gathered through the
selection — XLA fuses mask, select and projection into one kernel, which
is exactly what the reference's JIT'd PageProcessor does on CPU.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import Expr, ExprLowerer, eval_predicate
from presto_tpu.page import Block, Page


def project(
    page: Page, projections: Sequence[Tuple[str, Expr]]
) -> Page:
    """Pure projection (no selection)."""
    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, (page.capacity,))
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks), num_valid=page.num_valid, names=tuple(names)
    )


def filter_project(
    page: Page,
    predicate: Optional[Expr],
    projections: Sequence[Tuple[str, Expr]],
    out_capacity: Optional[int] = None,
) -> Page:
    """Filter by ``predicate`` (None = keep all live rows), then project.

    Output capacity defaults to input capacity; pass a smaller
    ``out_capacity`` when the planner knows a tighter bound (static shape
    step-down without a host round-trip)."""
    if predicate is None:
        out = project(page, projections)
        if out_capacity is not None and out_capacity != page.capacity:
            from presto_tpu.page import pad_capacity

            out = pad_capacity(out, out_capacity)
        return out

    cap = out_capacity if out_capacity is not None else page.capacity
    mask = eval_predicate(predicate, page)
    count = jnp.sum(mask).astype(jnp.int32)
    (sel,) = jnp.nonzero(mask, size=cap, fill_value=0)

    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, (page.capacity,))[sel]
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))[sel]
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(count, cap),
        names=tuple(names),
    )
