"""Fused filter + project over a Page.

Reference parity: ``ScanFilterAndProjectOperator`` / ``FilterAndProject-
Operator`` driven by the bytecode-compiled ``PageProcessor`` (selected
positions + projected blocks) — SURVEY.md §2.1, §3.3.

TPU-first shape: the predicate lowers to a boolean mask. By default the
filter is LAZY — survivors stay in place and the output page carries the
selection mask (``Page.live``), because on TPU the nonzero+gather
compaction costs orders of magnitude more than the masked reads
downstream kernels (agg/join/sort/window all take ``row_mask()``) do
anyway. ``lazy=False`` forces the eager compact-to-front form for
consumers that need a dense prefix. Projections are evaluated over the
full page — XLA fuses mask, select and projection into one kernel, which
is exactly what the reference's JIT'd PageProcessor does on CPU.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import ColumnRef, Expr, ExprLowerer, eval_predicate
from presto_tpu.page import Block, Page


def project(
    page: Page, projections: Sequence[Tuple[str, Expr]]
) -> Page:
    """Pure projection (no selection)."""
    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        if isinstance(expr, ColumnRef) and expr.dtype.is_nested:
            # array/map/row columns pass through whole (offsets +
            # flat/child blocks); non-identity nested expressions have
            # no lane form
            blocks.append(page.block(expr.name))
            names.append(name)
            continue
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, _col_shape(page, expr))
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks),
        num_valid=page.num_valid,
        names=tuple(names),
        live=page.live,
    )


def _col_shape(page: Page, expr: Expr):
    """Column data shape: long decimals carry (capacity, 2) limb pairs."""
    if expr.dtype.is_long_decimal:
        return (page.capacity, 2)
    return (page.capacity,)


def unnest(
    page: Page,
    elements: Sequence[Expr],
    out_name: str,
    out_type,
    ordinality_name: Optional[str] = None,
) -> Page:
    """CROSS JOIN UNNEST(ARRAY[e1..ek]) — static-width row expansion
    (reference: UnnestOperator; see plan.nodes.UnnestNode).

    Every input row yields exactly k = len(elements) output rows, so the
    output capacity is a static ``capacity * k`` and the whole expansion
    is repeat/stack/reshape — no dynamic shapes for XLA. Row i expands
    to positions [i*k, (i+1)*k): parent columns repeat, the unnest
    column interleaves the k element expressions, ordinality tiles
    1..k. Liveness: an output row is live iff its parent row is
    (Presto emits NULL elements as rows; arrays here are never NULL)."""
    import numpy as np

    from presto_tpu.page import Dictionary

    from presto_tpu.expr import Literal

    k = len(elements)
    cap = page.capacity
    lowerer = ExprLowerer(page)
    datas, valids, dicts = [], [], []
    for el in elements:
        if out_type.is_string and isinstance(el, Literal):
            # bare string literal: no dictionary context exists in the
            # page, so synthesize a one-entry dictionary (or all-NULL)
            if el.value is None:
                datas.append(jnp.zeros((cap,), jnp.int32))
                valids.append(jnp.zeros((cap,), bool))
                dicts.append(None)
            else:
                datas.append(jnp.zeros((cap,), jnp.int32))
                valids.append(None)
                dicts.append(
                    Dictionary(np.asarray([el.value], dtype=object))
                )
            continue
        d, v = lowerer.eval(el)
        datas.append(
            jnp.broadcast_to(
                d, (cap, 2) if out_type.is_long_decimal else (cap,)
            )
        )
        valids.append(
            None if v is None else jnp.broadcast_to(v, (cap,))
        )
        dicts.append(
            lowerer.dictionary_of(el) if out_type.is_string else None
        )

    out_dict = None
    if out_type.is_string:
        # union the per-element dictionaries host-side (static pytree
        # metadata) and remap each element's ids through a device LUT
        values = np.unique(
            np.concatenate(
                [
                    np.asarray(d.values, dtype=object)
                    if d is not None and len(d.values)
                    else np.empty(0, dtype=object)
                    for d in dicts
                ]
            ).astype(str)
        )
        out_dict = Dictionary(values.astype(object))
        remapped = []
        for d, ids in zip(dicts, datas):
            if d is None or len(d.values) == 0:
                remapped.append(jnp.zeros((cap,), ids.dtype))
                continue
            lut = jnp.asarray(
                np.searchsorted(
                    values, np.asarray(d.values).astype(str)
                ).astype(np.int32)
            )
            remapped.append(lut[jnp.clip(ids, 0, len(d.values) - 1)])
        datas = remapped

    def expand(x):
        # axis=0: repeat ROWS (long-decimal blocks are (cap, 2) limb
        # pairs; default axis=None would flatten and interleave limbs)
        return jnp.repeat(x, k, axis=0, total_repeat_length=cap * k)

    blocks = []
    names = []
    for name, blk in zip(page.names, page.blocks):
        blocks.append(
            Block(
                data=expand(blk.data),
                valid=None if blk.valid is None else expand(blk.valid),
                dtype=blk.dtype,
                dictionary=blk.dictionary,
            )
        )
        names.append(name)
    # interleave the k element columns: stack -> (cap, k, ...) ->
    # (cap*k, ...) — trailing dims carry long-decimal limb pairs
    tail = datas[0].shape[1:]
    el_data = jnp.stack(datas, axis=1).reshape((cap * k,) + tail)
    if any(v is not None for v in valids):
        el_valid = jnp.stack(
            [
                jnp.ones((cap,), bool) if v is None else v
                for v in valids
            ],
            axis=1,
        ).reshape(cap * k)
    else:
        el_valid = None
    blocks.append(
        Block(
            data=el_data, valid=el_valid, dtype=out_type,
            dictionary=out_dict,
        )
    )
    names.append(out_name)
    if ordinality_name is not None:
        blocks.append(
            Block(
                data=jnp.tile(
                    jnp.arange(1, k + 1, dtype=jnp.int64), cap
                ),
                valid=None,
                dtype=T.BIGINT,
            )
        )
        names.append(ordinality_name)
    return Page(
        blocks=tuple(blocks),
        num_valid=(page.num_valid * k).astype(jnp.int32),
        names=tuple(names),
        live=expand(page.row_mask()),
    )


def unnest_column(
    page: Page,
    array_column: str,
    out_name: str,
    out_type,
    ordinality_name: Optional[str],
    out_capacity: int,
):
    """UNNEST of a physical array column (reference: UnnestOperator
    over ArrayBlock): per-row length expansion via the engine's
    prefix-sum + inverse-searchsorted trick, under the capacity-bucket
    protocol. Returns (page, overflow). NULL / dead rows contribute 0
    output rows (Presto: NULL arrays emit nothing)."""
    blk = page.block(array_column)
    off = blk.offsets
    lengths = (off[1:] - off[:-1]).astype(jnp.int64)
    live = page.row_mask()
    if blk.valid is not None:
        live = live & blk.valid
    m = jnp.where(live, lengths, 0)
    total = jnp.cumsum(m)
    out_count = total[-1] if page.capacity else jnp.asarray(0, jnp.int64)
    overflow = out_count > out_capacity

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    p_idx = jnp.searchsorted(total, j, side="right")
    p_idx = jnp.minimum(p_idx, page.capacity - 1)
    prev = jnp.where(p_idx > 0, total[jnp.maximum(p_idx - 1, 0)], 0)
    offset = j - prev  # position within the parent row's array

    vcap = max(blk.data.shape[0], 1)
    src = jnp.clip(
        off[p_idx].astype(jnp.int64) + offset, 0, vcap - 1
    )

    blocks, names = [], []
    for name, b in zip(page.names, page.blocks):
        if b.offsets is not None or b.children is not None:
            # nested columns do not ride through the expansion (flat
            # repeats could exceed value capacity; row children would
            # need their own gather); UnnestNode.output_schema drops
            # them identically, so a post-unnest reference fails at
            # PLAN time, not here
            continue
        blocks.append(
            dataclasses.replace(
                b,
                data=b.data[p_idx],
                valid=None if b.valid is None else b.valid[p_idx],
            )
        )
        names.append(name)
    blocks.append(
        Block(
            data=blk.data[src],
            valid=None,
            dtype=out_type,
            dictionary=blk.dictionary,
        )
    )
    names.append(out_name)
    if ordinality_name is not None:
        blocks.append(
            Block(
                data=offset + 1, valid=None, dtype=T.BIGINT
            )
        )
        names.append(ordinality_name)
    return (
        Page(
            blocks=tuple(blocks),
            num_valid=jnp.minimum(out_count, out_capacity).astype(
                jnp.int32
            ),
            names=tuple(names),
        ),
        overflow,
    )


def union_all(pages: Sequence[Page]) -> Page:
    """UNION ALL: concatenate pages (reference: UnionNode). Inputs are
    schema-aligned by the planner (same names/types per position);
    liveness concatenates as masks (no compaction), capacities add.
    String columns re-encode through a trace-time union dictionary
    (per-input dictionaries are static metadata, so the remap LUTs are
    constants)."""
    import numpy as np

    from presto_tpu.page import Dictionary

    first = pages[0]
    blocks: List[Block] = []
    for ci, name in enumerate(first.names):
        blks = [p.blocks[ci] for p in pages]
        if any(
            b.offsets is not None or b.children is not None
            for b in blks
        ):
            raise NotImplementedError(
                f"nested column {name} through UNION is not supported"
            )
        dictionary = None
        if first.blocks[ci].dtype.is_string:
            dicts = [b.dictionary for b in blks]
            values = np.unique(
                np.concatenate(
                    [
                        np.asarray(d.values, object)
                        if d is not None and len(d.values)
                        else np.empty(0, object)
                        for d in dicts
                    ]
                ).astype(str)
            )
            dictionary = Dictionary(values.astype(object))
            datas = []
            for b, d in zip(blks, dicts):
                if d is None or len(d.values) == 0:
                    datas.append(jnp.zeros_like(b.data))
                    continue
                lut = jnp.asarray(
                    np.searchsorted(
                        values, np.asarray(d.values).astype(str)
                    ).astype(np.int32)
                )
                datas.append(
                    lut[jnp.clip(b.data, 0, len(d.values) - 1)]
                )
        else:
            datas = [b.data for b in blks]
        data = jnp.concatenate(datas, axis=0)
        if any(b.valid is not None for b in blks):
            valid = jnp.concatenate(
                [
                    b.valid
                    if b.valid is not None
                    else jnp.ones((b.capacity,), jnp.bool_)
                    for b in blks
                ]
            )
        else:
            valid = None
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=first.blocks[ci].dtype,
                dictionary=dictionary,
            )
        )
    live = jnp.concatenate([p.row_mask() for p in pages])
    num = sum(
        (p.num_valid for p in pages), jnp.asarray(0, jnp.int32)
    ).astype(jnp.int32)
    return Page(
        blocks=tuple(blocks),
        num_valid=num,
        names=first.names,
        live=live,
    )


def filter_project(
    page: Page,
    predicate: Optional[Expr],
    projections: Sequence[Tuple[str, Expr]],
    out_capacity: Optional[int] = None,
    lazy: bool = True,
) -> Page:
    """Filter by ``predicate`` (None = keep all live rows), then project.

    ``lazy=True`` (default) returns the masked form (rows in place,
    ``Page.live`` selection mask) — no gather. ``lazy=False`` compacts
    survivors to the front. Output capacity defaults to input capacity;
    pass a smaller ``out_capacity`` when the planner knows a tighter
    bound (static shape step-down without a host round-trip; implies
    eager compaction)."""
    if predicate is None:
        out = project(page, projections)
        if out_capacity is not None and out_capacity != page.capacity:
            from presto_tpu.page import compact_page

            out = compact_page(out, out_capacity)
        return out

    # eval_predicate already ANDs row_mask(), which honors Page.live
    mask = eval_predicate(predicate, page)
    count = jnp.sum(mask).astype(jnp.int32)

    if lazy and out_capacity is None:
        out = project(page, projections)
        return dataclasses.replace(out, num_valid=count, live=mask)

    cap = out_capacity if out_capacity is not None else page.capacity
    (sel,) = jnp.nonzero(mask, size=cap, fill_value=0)

    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        if isinstance(expr, ColumnRef) and expr.dtype.is_nested:
            from presto_tpu.page import (
                _gather_array_block,
                _gather_row_block,
            )

            blk = page.block(expr.name)
            blocks.append(
                _gather_row_block(blk, sel, count)
                if expr.dtype.is_row
                else _gather_array_block(blk, sel, count)
            )
            names.append(name)
            continue
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, _col_shape(page, expr))[sel]
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))[sel]
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(count, cap),
        names=tuple(names),
    )
