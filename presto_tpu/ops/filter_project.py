"""Fused filter + project over a Page.

Reference parity: ``ScanFilterAndProjectOperator`` / ``FilterAndProject-
Operator`` driven by the bytecode-compiled ``PageProcessor`` (selected
positions + projected blocks) — SURVEY.md §2.1, §3.3.

TPU-first shape: the predicate lowers to a boolean mask. By default the
filter is LAZY — survivors stay in place and the output page carries the
selection mask (``Page.live``), because on TPU the nonzero+gather
compaction costs orders of magnitude more than the masked reads
downstream kernels (agg/join/sort/window all take ``row_mask()``) do
anyway. ``lazy=False`` forces the eager compact-to-front form for
consumers that need a dense prefix. Projections are evaluated over the
full page — XLA fuses mask, select and projection into one kernel, which
is exactly what the reference's JIT'd PageProcessor does on CPU.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import Expr, ExprLowerer, eval_predicate
from presto_tpu.page import Block, Page


def project(
    page: Page, projections: Sequence[Tuple[str, Expr]]
) -> Page:
    """Pure projection (no selection)."""
    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, (page.capacity,))
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks),
        num_valid=page.num_valid,
        names=tuple(names),
        live=page.live,
    )


def filter_project(
    page: Page,
    predicate: Optional[Expr],
    projections: Sequence[Tuple[str, Expr]],
    out_capacity: Optional[int] = None,
    lazy: bool = True,
) -> Page:
    """Filter by ``predicate`` (None = keep all live rows), then project.

    ``lazy=True`` (default) returns the masked form (rows in place,
    ``Page.live`` selection mask) — no gather. ``lazy=False`` compacts
    survivors to the front. Output capacity defaults to input capacity;
    pass a smaller ``out_capacity`` when the planner knows a tighter
    bound (static shape step-down without a host round-trip; implies
    eager compaction)."""
    if predicate is None:
        out = project(page, projections)
        if out_capacity is not None and out_capacity != page.capacity:
            from presto_tpu.page import compact_page

            out = compact_page(out, out_capacity)
        return out

    # eval_predicate already ANDs row_mask(), which honors Page.live
    mask = eval_predicate(predicate, page)
    count = jnp.sum(mask).astype(jnp.int32)

    if lazy and out_capacity is None:
        out = project(page, projections)
        return dataclasses.replace(out, num_valid=count, live=mask)

    cap = out_capacity if out_capacity is not None else page.capacity
    (sel,) = jnp.nonzero(mask, size=cap, fill_value=0)

    lowerer = ExprLowerer(page)
    names, blocks = [], []
    for name, expr in projections:
        data, valid = lowerer.eval(expr)
        data = jnp.broadcast_to(data, (page.capacity,))[sel]
        if valid is not None:
            valid = jnp.broadcast_to(valid, (page.capacity,))[sel]
        blocks.append(
            Block(
                data=data,
                valid=valid,
                dtype=expr.dtype,
                dictionary=(
                    lowerer.dictionary_of(expr)
                    if expr.dtype.is_string
                    else None
                ),
            )
        )
        names.append(name)
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(count, cap),
        names=tuple(names),
    )
