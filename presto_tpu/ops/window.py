"""Window function kernel.

Reference parity: ``WindowOperator`` + window function registry
(row_number, rank, dense_rank, aggregate windows) — SURVEY.md §2.1,
BASELINE.json config "Window functions (rank/row_number OVER PARTITION
BY)".

TPU-first: one stable sort by (partition keys, order keys), then every
window function is a *segmented scan* — partition starts and peer-group
starts fall out of neighbour-compares, ranks are index arithmetic against
segment-start gathers, and running aggregates are cumulative sums with
the partition prefix subtracted (all O(n) vectorized, no per-partition
loops; SURVEY.md §7 step 3 "window (segmented scans)").

Default SQL frame semantics: with ORDER BY, aggregates run over RANGE
UNBOUNDED PRECEDING..CURRENT ROW (peers share the value of their last
peer row); without ORDER BY, over the whole partition. Output rows are
emitted in (partition, order) sorted order — row order between operators
is unspecified in SQL, the final ORDER BY governs.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import Expr, ExprLowerer
from presto_tpu.ops.common import boundaries, sort_order
from presto_tpu.ops.sort import SortKey
from presto_tpu.page import Block, Page


@dataclasses.dataclass(frozen=True)
class WindowCall:
    """func in {row_number, rank, dense_rank, ntile, lag, lead,
    first_value, last_value, sum, count, avg, min, max}.

    ``offset`` is lag/lead's constant distance (ntile reuses it as the
    bucket count); ``default`` is lag/lead's constant fill for
    out-of-partition positions as a Literal/Cast Expr (None = SQL
    NULL)."""

    func: str
    arg: Optional[Expr]  # None for row_number/rank/dense_rank/count(*)
    out_name: str
    offset: int = 1
    default: Optional[Expr] = None
    #: aggregate frame: "range" = default RANGE UNBOUNDED..CURRENT ROW
    #: (peers share the last peer row's value); "rows" = ROWS
    #: UNBOUNDED..CURRENT ROW (each row sees its own prefix)
    frame: str = "range"

    def result_type(self) -> T.DataType:
        if self.func in ("row_number", "rank", "dense_rank", "count",
                         "ntile"):
            return T.BIGINT
        if self.func in ("percent_rank", "cume_dist"):
            return T.DOUBLE
        t = self.arg.dtype
        if self.func in ("lag", "lead", "first_value", "last_value",
                         "nth_value"):
            return t
        if self.func == "sum":
            if t.is_decimal:
                return T.decimal(18, t.scale)
            if t.is_integer:
                return T.BIGINT
            return T.DOUBLE
        if self.func == "avg":
            return T.DOUBLE
        if self.func in ("min", "max"):
            return t
        raise NotImplementedError(f"window function {self.func}")


def window(
    page: Page,
    partition_by: Sequence[Expr],
    order_by: Sequence[SortKey],
    calls: Sequence[WindowCall],
) -> Page:
    """Append window-function columns to ``page`` (sorted order output)."""
    cap = page.capacity
    live = page.row_mask()
    lowerer = ExprLowerer(page)
    part_eval = [(*lowerer.eval(e), e.dtype) for e in partition_by]
    order_eval = [
        (*lowerer.eval(k.expr), k.expr.dtype) for k in order_by
    ]

    perm = sort_order(
        part_eval + order_eval,
        live,
        descending=[False] * len(part_eval)
        + [k.descending for k in order_by],
        nulls_first=[False] * len(part_eval)
        + [
            k.nulls_first if k.nulls_first is not None else k.descending
            for k in order_by
        ],
    )
    live_s = live[perm]
    part_s = [(d[perm], None if v is None else v[perm]) for d, v, _ in part_eval]
    order_s = [(d[perm], None if v is None else v[perm]) for d, v, _ in order_eval]

    part_bnd = (
        boundaries(part_s, live_s)
        if part_s
        else (jnp.zeros((cap,), jnp.bool_).at[0].set(True) & live_s)
    )
    peer_bnd = boundaries(part_s + order_s, live_s) if order_s else part_bnd

    pos = jnp.arange(cap, dtype=jnp.int64)
    pid = jnp.cumsum(part_bnd.astype(jnp.int32)) - 1
    pid = jnp.where(live_s, pid, cap)  # dead rows -> dropped segment
    peer_gid = jnp.cumsum(peer_bnd.astype(jnp.int32)) - 1
    peer_gid = jnp.where(live_s, peer_gid, cap)

    nseg = cap + 1
    part_start = jax.ops.segment_min(pos, pid, num_segments=nseg)
    peer_start = jax.ops.segment_min(pos, peer_gid, num_segments=nseg)
    # last row position of each peer group (for RANGE frame value sharing)
    peer_end = jax.ops.segment_max(pos, peer_gid, num_segments=nseg)

    safe_pid = jnp.minimum(pid, cap)
    safe_peer = jnp.minimum(peer_gid, cap)

    names = list(page.names)
    for name, blk in zip(names, page.blocks):
        if blk.offsets is not None or blk.children is not None:
            # flat-values gather with stale offsets (arrays/maps) or a
            # permuted placeholder with unpermuted children (rows)
            # would silently corrupt nested columns
            raise NotImplementedError(
                f"nested column {name} ({blk.dtype}) cannot ride "
                "through a window operator; select it separately"
            )
    blocks = [
        dataclasses.replace(
            blk,
            data=blk.data[perm],
            valid=None if blk.valid is None else blk.valid[perm],
        )
        for blk in page.blocks
    ]

    # last live row position of each partition (lead bound, ntile size)
    part_end = jax.ops.segment_max(
        jnp.where(live_s, pos, -1), pid, num_segments=nseg
    )
    part_cnt = jax.ops.segment_sum(
        live_s.astype(jnp.int64), pid, num_segments=nseg
    )

    for call in calls:
        rt = call.result_type()
        if call.func == "row_number":
            # int32 lanes: ranks are bounded by the page capacity, so
            # the BIGINT-typed block carries int32 data — half the HBM
            # and half the result-transfer bytes on rank-heavy outputs
            data = (pos - part_start[safe_pid] + 1).astype(jnp.int32)
            blocks.append(Block(data=data, valid=None, dtype=T.BIGINT))
        elif call.func == "ntile":
            # SQL ntile: sizes differ by at most 1 and the FIRST
            # (m mod n) buckets take the extra row
            n_tiles = jnp.int64(max(int(call.offset), 1))
            rn0 = pos - part_start[safe_pid]
            m = jnp.maximum(part_cnt[safe_pid], 1)
            q = m // n_tiles
            r = m % n_tiles
            big = r * (q + 1)  # rows covered by the (q+1)-sized buckets
            data = jnp.where(
                rn0 < big,
                rn0 // jnp.maximum(q + 1, 1),
                r + (rn0 - big) // jnp.maximum(q, 1),
            ) + 1
            blocks.append(Block(data=data, valid=None, dtype=T.BIGINT))
        elif call.func in ("lag", "lead", "first_value", "last_value",
                           "nth_value"):
            blocks.append(
                _window_nav(
                    call, page, perm, live_s, safe_pid, part_start,
                    part_end, peer_end, safe_peer, pos, lowerer,
                )
            )
        elif call.func == "rank":
            data = (
                peer_start[safe_peer] - part_start[safe_pid] + 1
            ).astype(jnp.int32)
            blocks.append(Block(data=data, valid=None, dtype=T.BIGINT))
        elif call.func == "percent_rank":
            # (rank - 1) / (partition rows - 1); 0 for 1-row partitions
            rank0 = (
                peer_start[safe_peer] - part_start[safe_pid]
            ).astype(jnp.float64)
            denom = (part_cnt[safe_pid] - 1).astype(jnp.float64)
            data = jnp.where(denom > 0, rank0 / jnp.maximum(denom, 1.0), 0.0)
            blocks.append(Block(data=data, valid=None, dtype=T.DOUBLE))
        elif call.func == "cume_dist":
            # rows with position <= last peer row, over partition rows
            thru = (
                peer_end[safe_peer] - part_start[safe_pid] + 1
            ).astype(jnp.float64)
            data = thru / jnp.maximum(
                part_cnt[safe_pid].astype(jnp.float64), 1.0
            )
            blocks.append(Block(data=data, valid=None, dtype=T.DOUBLE))
        elif call.func == "dense_rank":
            first_peer_of_part = jax.ops.segment_min(
                peer_gid, pid, num_segments=nseg
            )
            data = peer_gid - first_peer_of_part[safe_pid] + 1
            blocks.append(
                Block(data=data.astype(jnp.int32), valid=None, dtype=T.BIGINT)
            )
        elif call.func in ("sum", "count", "avg", "min", "max"):
            blocks.append(
                _window_agg(
                    call,
                    page,
                    perm,
                    live_s,
                    pid,
                    safe_pid,
                    peer_gid,
                    safe_peer,
                    part_start,
                    peer_end,
                    pos,
                    running=bool(order_by),
                    nseg=nseg,
                    lowerer=lowerer,
                )
            )
        else:
            raise NotImplementedError(call.func)
        names.append(call.out_name)

    return Page(
        blocks=tuple(blocks), num_valid=page.num_valid, names=tuple(names)
    )


def _window_nav(
    call: WindowCall,
    page: Page,
    perm,
    live_s,
    safe_pid,
    part_start,
    part_end,
    peer_end,
    safe_peer,
    pos,
    lowerer: ExprLowerer,
) -> Block:
    """Navigation functions over the sorted layout: lag/lead by row
    offset within the partition; first_value at the partition start;
    last_value at the current frame end (default RANGE frame: the last
    peer row)."""
    cap = page.capacity
    at = call.arg.dtype
    d, v = lowerer.eval(call.arg)
    d = jnp.broadcast_to(d, (cap,))[perm]
    v_s = None if v is None else jnp.broadcast_to(v, (cap,))[perm]

    if call.func == "lag":
        src = pos - jnp.int64(call.offset)
        in_part = src >= part_start[safe_pid]
    elif call.func == "lead":
        src = pos + jnp.int64(call.offset)
        in_part = src <= part_end[safe_pid]
    elif call.func == "first_value":
        src = part_start[safe_pid].astype(jnp.int64)
        in_part = jnp.ones((cap,), jnp.bool_)
    elif call.func == "nth_value":
        # n-th row of the frame (default RANGE frame ends at the last
        # peer row): NULL until the frame has grown past n rows
        src = part_start[safe_pid].astype(jnp.int64) + jnp.int64(
            call.offset - 1
        )
        in_part = src <= peer_end[safe_peer]
    else:  # last_value: frame ends at the last peer row
        src = peer_end[safe_peer].astype(jnp.int64)
        in_part = jnp.ones((cap,), jnp.bool_)

    src_c = jnp.clip(src, 0, cap - 1).astype(jnp.int32)
    data = d[src_c]
    src_valid = in_part if v_s is None else (in_part & v_s[src_c])
    if call.default is not None and call.func in ("lag", "lead"):
        fd, _ = lowerer.eval(call.default)
        data = jnp.where(in_part, data, jnp.broadcast_to(fd, data.shape))
        src_valid = (
            jnp.ones((cap,), jnp.bool_)
            if v_s is None
            else jnp.where(in_part, src_valid, True)
        )
    valid = live_s & src_valid
    dictionary = None
    if at.is_string:
        dictionary = lowerer.dictionary_of(call.arg)
    return Block(
        data=data.astype(at.jnp_dtype), valid=valid, dtype=at,
        dictionary=dictionary,
    )


def _window_agg(
    call: WindowCall,
    page: Page,
    perm,
    live_s,
    pid,
    safe_pid,
    peer_gid,
    safe_peer,
    part_start,
    peer_end,
    pos,
    running: bool,
    nseg: int,
    lowerer: ExprLowerer = None,
):
    rt = call.result_type()
    if call.arg is not None:
        d, v = lowerer.eval(call.arg)
        d = jnp.broadcast_to(d, (page.capacity,))[perm]
        valid = live_s if v is None else (
            live_s & jnp.broadcast_to(v, (page.capacity,))[perm]
        )
    else:  # count(*)
        d = jnp.ones((page.capacity,), jnp.int64)
        valid = live_s

    at = call.arg.dtype if call.arg is not None else T.BIGINT
    is_float = (
        call.func == "avg" or at.name in ("double", "real")
    ) and call.func not in ("min", "max", "count")

    if is_float:
        x = d.astype(jnp.float64)
        if at.is_decimal:
            x = x / (10 ** at.scale)
        x = jnp.where(valid, x, 0.0)
    elif call.func == "count":
        x = valid.astype(jnp.int64)
    else:
        x = jnp.where(valid, d.astype(jnp.int64), 0)

    run_cnt = None
    if running:
        # running non-null count up to the frame end (shared by every
        # running aggregate's validity and by avg's divisor)
        cnt_cs = jnp.cumsum(valid.astype(jnp.int64))
        cnt_before = jnp.where(
            part_start[safe_pid] > 0,
            cnt_cs[jnp.maximum(part_start[safe_pid] - 1, 0)],
            jnp.zeros((), jnp.int64),
        )
        run_within = cnt_cs - cnt_before
        run_cnt = (
            run_within
            if call.frame == "rows"
            else run_within[peer_end[safe_peer]]
        )

    if call.func in ("min", "max"):
        if at.name in ("double", "real"):
            fill = jnp.inf if call.func == "min" else -jnp.inf
            xv = jnp.where(valid, d.astype(jnp.float64), fill)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if call.func == "min" else info.min
            xv = jnp.where(valid, d.astype(jnp.int64), fill)
        if running:
            op = jnp.minimum if call.func == "min" else jnp.maximum

            # segmented cumulative min/max in O(log n) parallel depth
            def combine(a, b):
                ap, av = a
                bp, bv = b
                return bp, jnp.where(ap == bp, op(av, bv), bv)

            _, out = jax.lax.associative_scan(combine, (pid, xv))
            # RANGE: peers share the last peer row's value; ROWS: own
            data = (
                out
                if call.frame == "rows"
                else out[peer_end[safe_peer]]
            )
            has = run_cnt > 0
        else:
            seg = (
                jax.ops.segment_min if call.func == "min" else jax.ops.segment_max
            )(xv, pid, num_segments=nseg)
            data = seg[safe_pid]
            cnt_seg = jax.ops.segment_sum(
                valid.astype(jnp.int64), pid, num_segments=nseg
            )
            has = cnt_seg[safe_pid] > 0
        dictionary = None
        if at.is_string:
            dictionary = lowerer.dictionary_of(call.arg)
        return Block(
            data=data.astype(at.jnp_dtype),
            valid=has,
            dtype=at,
            dictionary=dictionary,
        )

    if running:
        cs = jnp.cumsum(x)
        before_part = jnp.where(
            part_start[safe_pid] > 0,
            cs[jnp.maximum(part_start[safe_pid] - 1, 0)],
            jnp.zeros((), cs.dtype),
        )
        within = cs - before_part
        # RANGE: peers share the last peer row's value; ROWS: own
        data = (
            within
            if call.frame == "rows"
            else within[peer_end[safe_peer]]
        )
        if call.func == "count":
            return Block(data=data.astype(jnp.int64), valid=None, dtype=T.BIGINT)
        if call.func == "avg":
            return Block(
                data=data / jnp.maximum(run_cnt, 1),
                valid=run_cnt > 0,
                dtype=T.DOUBLE,
            )
        # sum
        if is_float:
            return Block(data=data, valid=run_cnt > 0, dtype=T.DOUBLE)
        return Block(data=data.astype(jnp.int64), valid=run_cnt > 0, dtype=rt)

    # whole-partition aggregate
    seg = jax.ops.segment_sum(x, pid, num_segments=nseg)
    cnt_seg = jax.ops.segment_sum(
        valid.astype(jnp.int64), pid, num_segments=nseg
    )
    if call.func == "count":
        return Block(
            data=seg[safe_pid].astype(jnp.int64), valid=None, dtype=T.BIGINT
        )
    has = cnt_seg[safe_pid] > 0
    if call.func == "avg":
        return Block(
            data=seg[safe_pid] / jnp.maximum(cnt_seg[safe_pid], 1),
            valid=has,
            dtype=T.DOUBLE,
        )
    if is_float:
        return Block(data=seg[safe_pid], valid=has, dtype=T.DOUBLE)
    return Block(data=seg[safe_pid].astype(jnp.int64), valid=has, dtype=rt)
