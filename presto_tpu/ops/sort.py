"""Ordering operators: ORDER BY, TopN, LIMIT, DISTINCT.

Reference parity: ``OrderByOperator``, ``TopNOperator``, ``LimitOperator``,
``DistinctLimitOperator``, ``MarkDistinctOperator`` (SURVEY.md §2.1).

TPU-first: all orderings are stable multi-key int64 sorts (ops.common);
TopN slices the sorted permutation (XLA sorts are O(n log n) bitonic-ish
and bandwidth-bound — for the small-N case the planner can step the
output capacity down to N so downstream fragments compile at the small
shape). DISTINCT reuses the group-by machinery with zero aggregates.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import dataclasses

import jax.numpy as jnp

from presto_tpu.expr import Expr, eval_expr
from presto_tpu.ops.common import sort_order
from presto_tpu.page import Block, Page


@dataclasses.dataclass(frozen=True)
class SortKey:
    expr: Expr
    descending: bool = False
    nulls_first: Optional[bool] = None  # SQL default: last in ASC, first in DESC


def order_by(
    page: Page, keys: Sequence[SortKey], limit: Optional[int] = None
) -> Page:
    """Sort live rows; optionally keep only the first ``limit`` (TopN).

    Output capacity = input capacity unless ``limit`` is given, in which
    case the output page is sliced to capacity ``limit`` (static shape
    step-down inside the fragment — the TopN fast path)."""
    evaluated = [
        (*eval_expr(k.expr, page), k.expr.dtype) for k in keys
    ]
    order = sort_order(
        [(d, v, t) for d, v, t in evaluated],
        page.row_mask(),
        descending=[k.descending for k in keys],
        nulls_first=[
            k.nulls_first if k.nulls_first is not None else k.descending
            for k in keys
        ],
    )
    if limit is not None:
        order = order[:limit]
    blocks = []
    for blk in page.blocks:
        if blk.offsets is not None:
            from presto_tpu.page import _gather_array_block

            blocks.append(
                _gather_array_block(blk, order, page.num_valid)
            )
            continue
        blocks.append(
            dataclasses.replace(
                blk,
                data=blk.data[order],
                valid=None if blk.valid is None else blk.valid[order],
            )
        )
    num = page.num_valid if limit is None else jnp.minimum(
        page.num_valid, limit
    )
    return Page(blocks=tuple(blocks), num_valid=num, names=page.names)


def limit(page: Page, n: int) -> Page:
    """LIMIT n: clamp the live-row count (no data movement for
    prefix-form pages). Masked form compacts first — but only into an
    n-sized bucket: LIMIT without ORDER BY may return ANY n rows, so
    gathering just the first n live rows (not the full capacity) keeps
    the compaction cost O(n) per column instead of O(capacity)."""
    from presto_tpu.exec.staging import bucket_capacity
    from presto_tpu.page import compact_page

    if page.live is not None:
        page = compact_page(page, bucket_capacity(n))
    return dataclasses.replace(
        page, num_valid=jnp.minimum(page.num_valid, n).astype(jnp.int32)
    )


def distinct(page: Page, max_groups: Optional[int] = None):
    """SELECT DISTINCT over all columns of ``page``.

    Returns (page, overflow) like hash_aggregate."""
    from presto_tpu.expr import ColumnRef
    from presto_tpu.ops.aggregation import hash_aggregate

    schema = page.schema()
    keys = [(n, ColumnRef(n, schema[n])) for n in page.names]
    return hash_aggregate(
        page, keys, [], max_groups or page.capacity
    )
