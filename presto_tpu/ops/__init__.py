"""Device-side operator kernels.

Reference parity: presto-main ``…/operator/`` (SURVEY.md §2.1 "Operators").
TPU-first redesign: operators here are *trace-time kernel compositions* —
pure functions over Page pytrees called inside a fragment's ``jax.jit`` —
not runtime objects pumping pages through a Driver loop. XLA fuses
adjacent operators; the fragment is the compilation unit (SURVEY.md §7
"Design stance").
"""

from presto_tpu.ops.filter_project import (  # noqa: F401
    filter_project,
    project,
    union_all,
    unnest,
    unnest_column,
)
from presto_tpu.ops.aggregation import AggCall, hash_aggregate  # noqa: F401
from presto_tpu.ops.join import hash_join, pack_keys  # noqa: F401
from presto_tpu.ops.sort import SortKey, distinct, limit, order_by  # noqa: F401
from presto_tpu.ops.window import WindowCall, window  # noqa: F401
