"""Grouped aggregation kernel.

Reference parity: ``HashAggregationOperator`` + ``GroupByHash`` +
``InMemoryHashAggregationBuilder`` and the annotation-generated
accumulators (SURVEY.md §2.1 "Operators", "Function registry").

TPU-first redesign (SURVEY.md §7 step 3), informed by v5e microbenchmarks
(scatter-adds — XLA's lowering of ``jax.ops.segment_*`` — run ~0.6s per
call over 8M rows regardless of segment count; sorts are fast at runtime
but cost minutes of compile; one-hot reduction and cumsum are ~10ms):

- **one-hot path**: when every group key has a statically *provable*
  small domain (dict-encoded strings, booleans) and the composite domain
  is tiny, each accumulator is a masked broadcast-reduce against the
  one-hot key matrix — XLA fuses it into a single pass, no sort, no
  scatter. TPU analogue of the reference's array-based
  ``BigintGroupByHash`` fast path.
- **sorted path**: general keys — one stable multi-key sort brings equal
  keys together; every accumulator is then a *scan*, not a scatter:
  sums/counts are inclusive-cumsum differences at group boundaries,
  min/max are segmented associative scans read at group ends.
- Shapes stay static: the planner supplies ``max_groups`` (the output
  capacity bucket); kernels report overflow instead of reallocating, and
  the host re-runs at a bigger bucket on overflow (SURVEY.md §7 "Hard
  parts: dynamic shapes").

Aggregate functions: count(*), count(x), sum, min, max, avg. Null
semantics match SQL: aggregates skip nulls; count(*) counts rows;
min/max on dictionary ids are valid because dictionaries are
order-preserving. ``count(DISTINCT x)`` is a planner rewrite into a
two-level aggregation, not a kernel (see presto_tpu.plan).

Result types: sum(int)->bigint, sum(decimal(p,s))->decimal(18,s) exact on
int64, sum(double)->double, count->bigint, avg->double (deviation: the
reference returns decimal for decimal inputs; exact decimal avg lands
with int128), min/max preserve the input type.

Exactness note: decimal/bigint sums on the sorted path are inclusive
int64 cumsums differenced at boundaries — exact unless the *running
total over the whole page* exceeds int64, a stricter-than-SQL bound
(the reference overflows per-group). A traced overflow trap (float64
shadow cumsum compared against the int64 cumsum; a wrap displaces the
value by ~2^64, far beyond float accumulation error) raises through the
error-flag channel instead of returning silently wrong sums. Float sums
use per-segment scans (not the cumsum trick) so no cross-group
cancellation is introduced.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from presto_tpu import types as T
from presto_tpu.expr import Expr, ExprLowerer
from presto_tpu.ops.common import boundaries, sort_order
from presto_tpu.page import Block, Page


@dataclasses.dataclass(frozen=True)
class AggCall:
    """One KERNEL aggregate: func in {count, count_star, sum, min, max,
    avg, stddev_samp, stddev_pop, var_samp, var_pop, array_agg,
    approx_percentile, min_by, max_by}.

    Composed aggregates (corr, covar, skewness, checksum, ... —
    presto_tpu.functions.ComposedAgg) never reach the kernel: the
    planner lowers them to primitive AggCalls plus a finisher
    projection, so the kernel surface stays the primitive set.

    ``arg2`` is min_by/max_by's ordering argument; ``param`` is
    approx_percentile's quantile in [0, 1]."""

    func: str
    arg: Optional[Expr]  # None only for count_star
    out_name: str
    arg2: Optional[Expr] = None
    param: Optional[float] = None

    def result_type(self) -> T.DataType:
        if self.func in ("count", "count_star"):
            return T.BIGINT
        if self.func in _VARIANCE_FUNCS:
            return T.DOUBLE
        if self.func == "array_agg":
            return T.array(self.arg.dtype)
        t = self.arg.dtype
        if self.func == "sum":
            if t.is_decimal:
                return T.decimal(18, t.scale)
            if t.is_integer:
                return T.BIGINT
            return T.DOUBLE
        if self.func == "avg":
            return T.DOUBLE
        if self.func in ("min", "max", "approx_percentile",
                         "min_by", "max_by"):
            return t
        raise NotImplementedError(f"aggregate {self.func}")


_VARIANCE_FUNCS = ("stddev_samp", "stddev_pop", "var_samp", "var_pop")

#: aggregates that require the sorted layout (a per-group value order)
_ORDER_FUNCS = ("array_agg", "approx_percentile", "min_by", "max_by")


def _variance_block(
    s1: jnp.ndarray, s2: jnp.ndarray, cnt: jnp.ndarray, func: str
) -> Block:
    """Variance family from (Σx, Σx², n) in float64.

    var_pop = Σx²/n − (Σx/n)²; var_samp scales by n/(n−1). NULL when
    n == 0 (pop) or n < 2 (samp), like the reference."""
    n = jnp.maximum(cnt, 1).astype(jnp.float64)
    mean = s1 / n
    var_pop = jnp.maximum(s2 / n - mean * mean, 0.0)
    if func.endswith("_samp"):
        var = var_pop * (n / jnp.maximum(n - 1.0, 1.0))
        has = cnt > 1
    else:
        var = var_pop
        has = cnt > 0
    data = jnp.sqrt(var) if func.startswith("stddev") else var
    return Block(data=data, valid=has, dtype=T.DOUBLE)


#: one-hot path ceiling: cost is O(rows * domain) fused on the VPU;
#: 256 keeps that under ~2G lane-ops for 8M-row pages
_ONEHOT_MAX_SEGMENTS = 256


def _static_domain(e: Expr, lowerer: ExprLowerer) -> Optional[int]:
    """Provable key-domain size, or None when unbounded.

    Only *proofs* qualify (collisions would be wrong answers): dictionary
    ids are bounded by the static dictionary length; booleans by 2.
    Range-bounded ints via connector stats are estimates, not proofs, so
    they do NOT qualify.
    """
    if e.dtype.is_string:
        try:
            dic = lowerer.dictionary_of(e)
        except NotImplementedError:
            return None
        if dic is None:
            return None
        return len(dic.values)
    if e.dtype.name == "boolean":
        return 2
    return None


def hash_aggregate(
    page: Page,
    group_keys: Sequence[Tuple[str, Expr]],
    aggs: Sequence[AggCall],
    max_groups: int,
    errors_out: Optional[List] = None,
) -> Tuple[Page, jnp.ndarray]:
    """Group ``page`` by key expressions, compute aggregates.

    Returns (result_page, overflow) where overflow is a traced bool: True
    when the data had more than ``max_groups`` groups (host must re-run
    with a larger bucket; surplus groups were dropped).

    ``errors_out``, when given, collects ``(message, traced_bool)`` hard
    errors — currently the bigint-sum overflow trap of the sorted path
    (the reference raises on per-group bigint overflow; the sorted path's
    page-wide running total would otherwise wrap *silently* even when
    individual group sums are in range — see _sorted_one_agg).

    Global aggregation (no keys) is the plain-reduction degenerate case.
    """
    live = page.row_mask()
    lowerer = ExprLowerer(page)

    if not group_keys:
        return _global_aggregate(page, aggs, live, lowerer)

    keys = [(name, *lowerer.eval(e), e) for name, e in group_keys]

    domains = [_static_domain(e, lowerer) for _, _, _, e in keys]
    if any(a.func in _ORDER_FUNCS for a in aggs):
        # these need the sorted layout (array_agg: group spans ARE the
        # output arrays; percentile/min_by/max_by: a per-group value
        # ordering); skip the one-hot fast path
        return _sorted_aggregate(
            page, keys, aggs, max_groups, live, lowerer, errors_out
        )
    if all(d is not None for d in domains):
        slots = [
            d + (1 if v is not None else 0)
            for d, (_, _, v, _) in zip(domains, keys)
        ]
        nseg = 1
        for s in slots:
            nseg *= max(s, 1)
        if 0 < nseg <= _ONEHOT_MAX_SEGMENTS:
            return _onehot_aggregate(
                page, keys, domains, slots, nseg, aggs, max_groups,
                live, lowerer,
            )

    return _sorted_aggregate(
        page, keys, aggs, max_groups, live, lowerer, errors_out
    )


# --------------------------------------------------------- one-hot path


def _onehot_aggregate(
    page: Page,
    keys,
    domains: List[int],
    slots: List[int],
    nseg: int,
    aggs: Sequence[AggCall],
    max_groups: int,
    live: jnp.ndarray,
    lowerer: ExprLowerer,
) -> Tuple[Page, jnp.ndarray]:
    """Sort-free, scatter-free aggregation over a tiny provable domain.

    Strides assign the first key the most significant position, so
    ascending segment order is lexicographic in the keys (dict ids are
    order-preserving); a key's NULL slot is its largest id (nulls group
    last, matching the sorted path's NULLS LAST grouping order).
    """
    cap = page.capacity

    strides = []
    s = 1
    for sl in reversed(slots):
        strides.append(s)
        s *= sl
    strides = list(reversed(strides))

    gid = jnp.zeros((cap,), jnp.int32)
    for (name, d, v, e), dom, stride in zip(keys, domains, strides):
        comp = d.astype(jnp.int32)
        if v is not None:
            comp = jnp.where(v, comp, dom)  # null slot = largest id
        gid = gid + comp * jnp.int32(stride)
    gid = jnp.where(live, gid, nseg)  # dead rows match no one-hot column

    oh = gid[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]

    counts = jnp.sum(oh, axis=0)  # (nseg,) live rows per group
    occupied = counts > 0
    num_groups = jnp.sum(occupied).astype(jnp.int32)
    overflow = num_groups > max_groups

    # occupied segments compacted to the front, ascending (lexicographic)
    (sel,) = jnp.nonzero(occupied, size=max_groups, fill_value=nseg)
    safe_sel = jnp.minimum(sel, nseg - 1).astype(jnp.int32)

    names: List[str] = []
    blocks: List[Block] = []
    for (name, d, v, e), dom, stride, sl in zip(
        keys, domains, strides, slots
    ):
        comp = (safe_sel // jnp.int32(stride)) % jnp.int32(sl)
        valid = None if v is None else (comp != dom)
        data = comp.astype(d.dtype)
        dictionary = None
        if e.dtype.is_string:
            dictionary = lowerer.dictionary_of(e)
        names.append(name)
        blocks.append(
            Block(data=data, valid=valid, dtype=e.dtype, dictionary=dictionary)
        )

    for agg in aggs:
        full = _onehot_one_agg(agg, page, oh, live, counts, lowerer)
        blocks.append(
            dataclasses.replace(
                full,
                data=full.data[safe_sel],
                valid=None if full.valid is None else full.valid[safe_sel],
            )
        )
        names.append(agg.out_name)

    out = Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(num_groups, max_groups).astype(jnp.int32),
        names=tuple(names),
    )
    return out, overflow


def _onehot_one_agg(
    agg: AggCall,
    page: Page,
    oh: jnp.ndarray,  # (cap, nseg) bool; dead rows all-False
    live: jnp.ndarray,
    counts: jnp.ndarray,  # (nseg,) live rows per group
    lowerer: ExprLowerer,
) -> Block:
    """One aggregate as full (nseg,) arrays via masked broadcast-reduce
    (fuses into one pass; no scatter)."""
    if agg.func == "count_star":
        return Block(
            data=counts.astype(jnp.int64), valid=None, dtype=T.BIGINT
        )

    d, v = lowerer.eval(agg.arg)
    d = jnp.broadcast_to(d, (page.capacity,))
    valid = live if v is None else (live & jnp.broadcast_to(v, live.shape))

    ohv = oh & valid[:, None]
    cnt = jnp.sum(ohv, axis=0)

    if agg.func == "count":
        return Block(data=cnt.astype(jnp.int64), valid=None, dtype=T.BIGINT)

    group_has_value = cnt > 0
    at = agg.arg.dtype

    if agg.func in _VARIANCE_FUNCS:
        x = d.astype(jnp.float64)
        if at.is_decimal:
            x = x / (10 ** at.scale)
        xm = jnp.where(ohv, x[:, None], 0.0)
        s1 = jnp.sum(xm, axis=0)
        s2 = jnp.sum(jnp.where(ohv, (x * x)[:, None], 0.0), axis=0)
        return _variance_block(s1, s2, cnt, agg.func)

    if agg.func in ("sum", "avg"):
        if at.name in ("double", "real") or agg.func == "avg":
            x = d.astype(jnp.float64)
            if at.is_decimal:
                x = x / (10 ** at.scale)
            s = jnp.sum(jnp.where(ohv, x[:, None], 0.0), axis=0)
            if agg.func == "avg":
                return Block(
                    data=s / jnp.maximum(cnt, 1),
                    valid=group_has_value,
                    dtype=T.DOUBLE,
                )
            return Block(data=s, valid=group_has_value, dtype=T.DOUBLE)
        x = d.astype(jnp.int64)
        s = jnp.sum(jnp.where(ohv, x[:, None], 0), axis=0)
        return Block(data=s, valid=group_has_value, dtype=agg.result_type())

    if agg.func in ("min", "max"):
        reduce = jnp.min if agg.func == "min" else jnp.max
        if at.name in ("double", "real"):
            fill = jnp.inf if agg.func == "min" else -jnp.inf
            x = d.astype(jnp.float64)
            data = reduce(jnp.where(ohv, x[:, None], fill), axis=0)
            data = data.astype(at.jnp_dtype)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if agg.func == "min" else info.min
            x = d.astype(jnp.int64)
            data = reduce(jnp.where(ohv, x[:, None], fill), axis=0)
            data = data.astype(at.jnp_dtype)
        dictionary = None
        if at.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=data, valid=group_has_value, dtype=at, dictionary=dictionary
        )

    raise NotImplementedError(f"aggregate {agg.func}")


# ---------------------------------------------------------- sorted path


def _segmented_scan_reduce(
    x: jnp.ndarray, bnd: jnp.ndarray, op
) -> jnp.ndarray:
    """Inclusive segmented reduction scan: position p holds op-reduction
    of its segment's values up to p; segments restart where ``bnd``.
    Read at segment END positions for per-segment totals."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    vals, _ = lax.associative_scan(combine, (x, bnd))
    return vals


def _group_spans(
    bnd: jnp.ndarray, max_groups: int, cap: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(starts, ends) sorted-space positions per group (gather-safe).

    ``ends[i] = starts[i+1] - 1`` with cap-1 for the final/fill groups —
    safe because rows past the live prefix carry neutral values for every
    accumulator (0 for cumsum deltas, +-inf fills for min/max scans).
    """
    (starts,) = jnp.nonzero(bnd, size=max_groups, fill_value=cap)
    nxt = jnp.concatenate(
        [starts[1:], jnp.full((1,), cap, starts.dtype)]
    )
    ends = jnp.clip(nxt - 1, 0, cap - 1)
    safe_starts = jnp.minimum(starts, cap - 1).astype(jnp.int32)
    return safe_starts, ends.astype(jnp.int32)


def _sorted_aggregate(
    page: Page,
    keys,
    aggs: Sequence[AggCall],
    max_groups: int,
    live: jnp.ndarray,
    lowerer: ExprLowerer,
    errors_out: Optional[List] = None,
) -> Tuple[Page, jnp.ndarray]:
    cap = page.capacity
    order = sort_order(
        [(d, v, e.dtype) for _, d, v, e in keys], live
    )
    live_s = live[order]
    keys_s = [
        (name, d[order], None if v is None else v[order], e)
        for name, d, v, e in keys
    ]
    bnd = boundaries([(d, v) for _, d, v, _ in keys_s], live_s)
    num_groups = jnp.sum(bnd).astype(jnp.int32)
    overflow = num_groups > max_groups

    starts, ends = _group_spans(bnd, max_groups, cap)

    names: List[str] = []
    blocks: List[Block] = []
    for name, d, v, e in keys_s:
        names.append(name)
        dictionary = None
        if e.dtype.is_string:
            dictionary = lowerer.dictionary_of(e)
        blocks.append(
            Block(
                data=d[starts],
                valid=None if v is None else v[starts],
                dtype=e.dtype,
                dictionary=dictionary,
            )
        )

    for agg in aggs:
        if agg.func in ("approx_percentile", "min_by", "max_by"):
            blk = _order_stat_agg(
                agg, page, keys, live, starts, ends, lowerer
            )
        else:
            blk = _sorted_one_agg(
                agg, page, order, live_s, bnd, starts, ends, lowerer,
                errors_out,
            )
        names.append(agg.out_name)
        blocks.append(blk)

    out = Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(num_groups, max_groups).astype(jnp.int32),
        names=tuple(names),
    )
    return out, overflow


def _order_stat_agg(
    agg: AggCall,
    page: Page,
    keys,  # ORIGINAL (unsorted) key evals: [(name, d, v, e), ...]
    live: jnp.ndarray,
    starts: jnp.ndarray,
    ends: jnp.ndarray,
    lowerer: ExprLowerer,
) -> Block:
    """approx_percentile / min_by / max_by on the sorted path.

    Each takes its own secondary sort: (group keys, ordering value) —
    within every group the ordering value's non-null rows form an
    ascending prefix (sort_order puts value-NULLs after valid values,
    dead rows after everything). Because the secondary sort is the same
    stable lexicographic key order, every group occupies the SAME
    [start, end] span positions as in the primary order, so the caller's
    spans are reused; only the within-group permutation differs.

    - approx_percentile(x, p): element at nearest rank ceil(p*n) among
      the group's n valid values (exact — error 0 is within any qdigest
      bound the reference guarantees; SURVEY.md §2.1 approx family).
    - min_by(x, y)/max_by(x, y): x gathered at the group's first/last
      y-valid position (any tie representative, like the reference).
    """
    cap = page.capacity
    is_by = agg.func in ("min_by", "max_by")
    val = agg.arg2 if is_by else agg.arg
    vd, vv = lowerer.eval(val)
    vd = jnp.broadcast_to(vd, (cap,))
    vvb = None if vv is None else jnp.broadcast_to(vv, (cap,))
    order2 = sort_order(
        [(d, v, e.dtype) for _, d, v, e in keys]
        + [(vd, vvb, val.dtype)],
        live,
    )
    live2 = live[order2]
    valid2 = live2 if vvb is None else (live2 & vvb[order2])
    cntv = _cumsum_span(valid2.astype(jnp.int64), starts, ends)
    group_has = cntv > 0

    if agg.func == "approx_percentile":
        p = float(agg.param if agg.param is not None else 0.5)
        k = jnp.clip(
            jnp.ceil(p * cntv.astype(jnp.float64)).astype(jnp.int64) - 1,
            0,
            jnp.maximum(cntv - 1, 0),
        )
        idx = jnp.minimum(
            starts.astype(jnp.int64) + k, cap - 1
        ).astype(jnp.int32)
        return Block(
            data=vd[order2][idx], valid=group_has, dtype=agg.arg.dtype
        )

    xd, xv = lowerer.eval(agg.arg)
    xd2 = jnp.broadcast_to(xd, (cap,))[order2]
    if agg.func == "min_by":
        idx = starts
    else:
        idx = jnp.minimum(
            starts.astype(jnp.int64) + jnp.maximum(cntv - 1, 0),
            cap - 1,
        ).astype(jnp.int32)
    valid = group_has
    if xv is not None:
        valid = valid & jnp.broadcast_to(xv, (cap,))[order2][idx]
    dictionary = None
    if agg.arg.dtype.is_string:
        dictionary = lowerer.dictionary_of(agg.arg)
    return Block(
        data=xd2[idx], valid=valid, dtype=agg.arg.dtype,
        dictionary=dictionary,
    )


def _cumsum_span(
    w: jnp.ndarray, starts: jnp.ndarray, ends: jnp.ndarray
) -> jnp.ndarray:
    """Per-group totals of ``w`` via inclusive cumsum differenced over
    [start, end] spans (no scatter)."""
    c = jnp.cumsum(w)
    return c[ends] - c[starts] + w[starts]


def _sorted_one_agg(
    agg: AggCall,
    page: Page,
    order: jnp.ndarray,
    live_s: jnp.ndarray,
    bnd: jnp.ndarray,
    starts: jnp.ndarray,
    ends: jnp.ndarray,
    lowerer: ExprLowerer,
    errors_out: Optional[List] = None,
) -> Block:
    rt = agg.result_type()

    if agg.func == "count_star":
        data = _cumsum_span(live_s.astype(jnp.int64), starts, ends)
        return Block(data=data, valid=None, dtype=T.BIGINT)

    if agg.func == "array_agg":
        # the sorted layout IS the concatenated per-group arrays
        # (groups are contiguous spans); NULL inputs are SKIPPED, so
        # valid values scatter to their rank among valid rows — stable,
        # so groups stay contiguous — and group offsets are the valid
        # counts at group starts. (Deviation: the reference's
        # array_agg default INCLUDES nulls; arrays here carry no
        # element validity.)
        cap = page.capacity
        d, v = lowerer.eval(agg.arg)
        d_s = jnp.broadcast_to(d, (cap,))[order]
        valid_s = live_s if v is None else (
            live_s & jnp.broadcast_to(v, (cap,))[order]
        )
        cum = jnp.cumsum(valid_s.astype(jnp.int32))
        total = cum[-1] if cap else jnp.int32(0)
        pos = jnp.where(valid_s, cum - 1, cap)  # cap = dump slot
        out_vals = jnp.zeros((cap + 1,), d_s.dtype).at[pos].set(d_s)
        # padding group slots must read offset == total; the CLAMPED
        # starts (cap-1) would read total-1 on a completely full page
        # and silently drop the last group's last element, so detect
        # padding from the UNCLAMPED boundary positions
        (raw_starts,) = jnp.nonzero(
            bnd, size=starts.shape[0], fill_value=cap
        )
        start_off = jnp.where(
            raw_starts >= cap,
            total,
            cum[starts] - valid_s[starts].astype(jnp.int32),
        )
        offsets = jnp.concatenate(
            [
                jnp.minimum(start_off, total).astype(jnp.int32),
                total.reshape(1),
            ]
        )
        dictionary = None
        if agg.arg.dtype.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=out_vals[:cap],
            valid=None,
            dtype=rt,
            dictionary=dictionary,
            offsets=offsets,
        )

    d, v = lowerer.eval(agg.arg)
    d = jnp.broadcast_to(d, (page.capacity,))[order]
    valid_s = live_s if v is None else (
        live_s & jnp.broadcast_to(v, (page.capacity,))[order]
    )

    if agg.func == "count":
        data = _cumsum_span(valid_s.astype(jnp.int64), starts, ends)
        return Block(data=data, valid=None, dtype=T.BIGINT)

    cnt = _cumsum_span(valid_s.astype(jnp.int64), starts, ends)
    group_has_value = cnt > 0

    if agg.func in _VARIANCE_FUNCS:
        at = agg.arg.dtype
        x = d.astype(jnp.float64)
        if at.is_decimal:
            x = x / (10 ** at.scale)
        x = jnp.where(valid_s, x, 0.0)
        s1 = _segmented_scan_reduce(x, bnd, jnp.add)[ends]
        s2 = _segmented_scan_reduce(x * x, bnd, jnp.add)[ends]
        return _variance_block(s1, s2, cnt, agg.func)

    if agg.func in ("sum", "avg"):
        at = agg.arg.dtype
        if at.name in ("double", "real") or agg.func == "avg":
            # decimal avg and double sums: SEGMENTED scan, not a global
            # cumsum — differencing a whole-page running float total
            # would cancel catastrophically for small late groups
            x = d.astype(jnp.float64)
            if at.is_decimal:
                x = x / (10 ** at.scale)
            x = jnp.where(valid_s, x, 0.0)
            s = _segmented_scan_reduce(x, bnd, jnp.add)[ends]
            if agg.func == "avg":
                data = s / jnp.maximum(cnt, 1)
                return Block(
                    data=data, valid=group_has_value, dtype=T.DOUBLE
                )
            return Block(data=s, valid=group_has_value, dtype=T.DOUBLE)
        x = jnp.where(valid_s, d.astype(jnp.int64), 0)
        s = _cumsum_span(x, starts, ends)
        if errors_out is not None:
            # per-group overflow trap: the differenced int64 sums are
            # exact under modular arithmetic whenever the TRUE group sum
            # fits int64 (even if the page-wide running total wraps), so
            # the check must be per group — a float64 shadow of the same
            # span difference. A real per-group overflow displaces the
            # int result ~2^64 from the shadow; float cancellation error
            # stays many orders below the 2^62 threshold.
            sf = _cumsum_span(x.astype(jnp.float64), starts, ends)
            wrapped = jnp.any(
                jnp.abs(s.astype(jnp.float64) - sf) > 2.0**62
            )
            errors_out.append(
                (f"bigint sum overflow in {agg.out_name}", wrapped)
            )
        return Block(data=s, valid=group_has_value, dtype=rt)

    if agg.func in ("min", "max"):
        at = agg.arg.dtype
        op = jnp.minimum if agg.func == "min" else jnp.maximum
        if at.name in ("double", "real"):
            fill = jnp.inf if agg.func == "min" else -jnp.inf
            x = jnp.where(valid_s, d.astype(jnp.float64), fill)
            scan = _segmented_scan_reduce(x, bnd, op)
            data = scan[ends].astype(at.jnp_dtype)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if agg.func == "min" else info.min
            x = jnp.where(valid_s, d.astype(jnp.int64), fill)
            scan = _segmented_scan_reduce(x, bnd, op)
            data = scan[ends].astype(at.jnp_dtype)
        dictionary = None
        if at.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=data, valid=group_has_value, dtype=at, dictionary=dictionary
        )

    raise NotImplementedError(f"aggregate {agg.func}")


# ---------------------------------------------------------- global path


def _global_aggregate(
    page: Page,
    aggs: Sequence[AggCall],
    live: jnp.ndarray,
    lowerer: ExprLowerer,
) -> Tuple[Page, jnp.ndarray]:
    """No GROUP BY: plain masked whole-array reductions (no segments, no
    sort, no scatter). One output row always (SQL: global aggregates over
    zero rows emit one row; sum -> NULL via the empty-group validity
    rule, count -> 0)."""
    names, blocks = [], []
    for agg in aggs:
        blocks.append(_global_one_agg(agg, page, live, lowerer))
        names.append(agg.out_name)
    out = Page(
        blocks=tuple(blocks),
        num_valid=jnp.asarray(1, jnp.int32),
        names=tuple(names),
    )
    return out, jnp.asarray(False)


def _global_one_agg(
    agg: AggCall, page: Page, live: jnp.ndarray, lowerer: ExprLowerer
) -> Block:
    def one(x):
        return x.reshape(1)

    if agg.func == "count_star":
        return Block(
            data=one(jnp.sum(live).astype(jnp.int64)),
            valid=None,
            dtype=T.BIGINT,
        )

    if agg.func == "array_agg":
        d, v = lowerer.eval(agg.arg)
        d = jnp.broadcast_to(d, (page.capacity,))
        keep = live if v is None else (
            live & jnp.broadcast_to(v, live.shape)
        )
        # stable-compact kept values to the front (single global array;
        # NULL inputs skipped — documented deviation from include-nulls)
        order = jnp.argsort(~keep, stable=True)
        n = jnp.sum(keep).astype(jnp.int32)
        dictionary = None
        if agg.arg.dtype.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=d[order],
            valid=None,
            dtype=agg.result_type(),
            dictionary=dictionary,
            offsets=jnp.stack([jnp.int32(0), n]),
        )

    if agg.func in ("approx_percentile", "min_by", "max_by"):
        cap = page.capacity
        is_by = agg.func in ("min_by", "max_by")
        val = agg.arg2 if is_by else agg.arg
        vd, vv = lowerer.eval(val)
        vd = jnp.broadcast_to(vd, (cap,))
        vvb = None if vv is None else jnp.broadcast_to(vv, (cap,))
        order = sort_order([(vd, vvb, val.dtype)], live)
        live_s = live[order]
        valid_s = live_s if vvb is None else (live_s & vvb[order])
        cntv = jnp.sum(valid_s).astype(jnp.int64)
        has = one(cntv > 0)
        if agg.func == "approx_percentile":
            p = float(agg.param if agg.param is not None else 0.5)
            k = jnp.clip(
                jnp.ceil(p * cntv.astype(jnp.float64)).astype(jnp.int64)
                - 1,
                0,
                jnp.maximum(cntv - 1, 0),
            )
            data = one(vd[order][jnp.minimum(k, cap - 1)])
            return Block(data=data, valid=has, dtype=agg.arg.dtype)
        xd, xv = lowerer.eval(agg.arg)
        xd_s = jnp.broadcast_to(xd, (cap,))[order]
        idx = (
            jnp.int64(0)
            if agg.func == "min_by"
            else jnp.minimum(jnp.maximum(cntv - 1, 0), cap - 1)
        )
        valid = cntv > 0
        if xv is not None:
            valid = valid & jnp.broadcast_to(xv, (cap,))[order][idx]
        dictionary = None
        if agg.arg.dtype.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=one(xd_s[idx]), valid=one(valid),
            dtype=agg.arg.dtype, dictionary=dictionary,
        )

    d, v = lowerer.eval(agg.arg)
    d = jnp.broadcast_to(d, (page.capacity,))
    valid = live if v is None else (live & jnp.broadcast_to(v, live.shape))
    cnt = jnp.sum(valid).astype(jnp.int64)

    if agg.func == "count":
        return Block(data=one(cnt), valid=None, dtype=T.BIGINT)

    has = one(cnt > 0)
    at = agg.arg.dtype

    if agg.func in _VARIANCE_FUNCS:
        x = d.astype(jnp.float64)
        if at.is_decimal:
            x = x / (10 ** at.scale)
        x = jnp.where(valid, x, 0.0)
        blk = _variance_block(
            one(jnp.sum(x)), one(jnp.sum(x * x)), one(cnt), agg.func
        )
        return blk

    if agg.func in ("sum", "avg"):
        if at.name in ("double", "real") or agg.func == "avg":
            x = d.astype(jnp.float64)
            if at.is_decimal:
                x = x / (10 ** at.scale)
            s = jnp.sum(jnp.where(valid, x, 0.0))
            if agg.func == "avg":
                return Block(
                    data=one(s / jnp.maximum(cnt, 1)),
                    valid=has,
                    dtype=T.DOUBLE,
                )
            return Block(data=one(s), valid=has, dtype=T.DOUBLE)
        s = jnp.sum(jnp.where(valid, d.astype(jnp.int64), 0))
        return Block(data=one(s), valid=has, dtype=agg.result_type())

    if agg.func in ("min", "max"):
        reduce = jnp.min if agg.func == "min" else jnp.max
        if at.name in ("double", "real"):
            fill = jnp.inf if agg.func == "min" else -jnp.inf
            data = one(
                reduce(jnp.where(valid, d.astype(jnp.float64), fill))
            ).astype(at.jnp_dtype)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if agg.func == "min" else info.min
            data = one(
                reduce(jnp.where(valid, d.astype(jnp.int64), fill))
            ).astype(at.jnp_dtype)
        dictionary = None
        if at.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=data, valid=has, dtype=at, dictionary=dictionary
        )

    raise NotImplementedError(f"aggregate {agg.func}")
