"""Grouped aggregation kernel.

Reference parity: ``HashAggregationOperator`` + ``GroupByHash`` +
``InMemoryHashAggregationBuilder`` and the annotation-generated
accumulators (SURVEY.md §2.1 "Operators", "Function registry").

TPU-first redesign (SURVEY.md §7 step 3): instead of an open-addressing
hash table mutated row-at-a-time, grouping is *sort-based* — a stable
multi-key sort brings equal keys together, group boundaries fall out of a
vectorized neighbour-compare, and every accumulator is a segmented
reduction (``jax.ops.segment_*``), which XLA lowers to fast batched
scatter-reduces. Shapes stay static: the planner supplies ``max_groups``
(the output capacity bucket); kernels report overflow instead of
reallocating, and the host re-runs at a bigger bucket on overflow
(SURVEY.md §7 "Hard parts: dynamic shapes").

Aggregate functions: count(*), count(x), sum, min, max, avg. Null
semantics match SQL: aggregates skip nulls; count(*) counts rows;
min/max on dictionary ids are valid because dictionaries are
order-preserving. ``count(DISTINCT x)`` is a planner rewrite into a
two-level aggregation, not a kernel (see presto_tpu.plan).

Result types: sum(int)->bigint, sum(decimal(p,s))->decimal(18,s) exact on
int64, sum(double)->double, count->bigint, avg->double (deviation: the
reference returns decimal for decimal inputs; exact decimal avg lands
with int128), min/max preserve the input type.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import Expr, ExprLowerer
from presto_tpu.ops.common import boundaries, sort_order
from presto_tpu.page import Block, Page


@dataclasses.dataclass(frozen=True)
class AggCall:
    """One aggregate: func in {count, count_star, sum, min, max, avg}."""

    func: str
    arg: Optional[Expr]  # None only for count_star
    out_name: str

    def result_type(self) -> T.DataType:
        if self.func in ("count", "count_star"):
            return T.BIGINT
        t = self.arg.dtype
        if self.func == "sum":
            if t.is_decimal:
                return T.decimal(18, t.scale)
            if t.is_integer:
                return T.BIGINT
            return T.DOUBLE
        if self.func == "avg":
            return T.DOUBLE
        if self.func in ("min", "max"):
            return t
        raise NotImplementedError(f"aggregate {self.func}")


def hash_aggregate(
    page: Page,
    group_keys: Sequence[Tuple[str, Expr]],
    aggs: Sequence[AggCall],
    max_groups: int,
) -> Tuple[Page, jnp.ndarray]:
    """Group ``page`` by key expressions, compute aggregates.

    Returns (result_page, overflow) where overflow is a traced bool: True
    when the data had more than ``max_groups`` groups (host must re-run
    with a larger bucket; surplus groups were dropped).

    Global aggregation (no keys) is the ``max_groups=1`` degenerate case.
    """
    live = page.row_mask()
    lowerer = ExprLowerer(page)

    if not group_keys:
        return _global_aggregate(page, aggs, live, lowerer)

    keys = [(name, *lowerer.eval(e), e) for name, e in group_keys]
    order = sort_order(
        [(d, v, e.dtype) for _, d, v, e in keys], live
    )
    live_s = live[order]
    keys_s = [
        (name, d[order], None if v is None else v[order], e)
        for name, d, v, e in keys
    ]
    bnd = boundaries([(d, v) for _, d, v, _ in keys_s], live_s)
    # group id per sorted row; dead rows -> max_groups (dropped by the
    # out-of-range scatter semantics of segment_*)
    gid = jnp.cumsum(bnd.astype(jnp.int32)) - 1
    gid = jnp.where(live_s, gid, max_groups)
    gid = jnp.where(gid >= max_groups, max_groups, gid)
    num_groups = jnp.sum(bnd).astype(jnp.int32)
    overflow = num_groups > max_groups

    cap = page.capacity
    positions = jnp.arange(cap, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(
        positions, gid, num_segments=max_groups + 1
    )[:max_groups]
    first_pos = jnp.where(
        jnp.arange(max_groups) < jnp.minimum(num_groups, max_groups),
        first_pos,
        0,
    )

    names: List[str] = []
    blocks: List[Block] = []
    for name, d, v, e in keys_s:
        names.append(name)
        dictionary = None
        if e.dtype.is_string:
            dictionary = lowerer.dictionary_of(e)
        blocks.append(
            Block(
                data=d[first_pos],
                valid=None if v is None else v[first_pos],
                dtype=e.dtype,
                dictionary=dictionary,
            )
        )

    for agg in aggs:
        blk = _segment_agg(agg, page, order, live_s, gid, max_groups, lowerer)
        names.append(agg.out_name)
        blocks.append(blk)

    out = Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(num_groups, max_groups).astype(jnp.int32),
        names=tuple(names),
    )
    return out, overflow


def _segment_agg(
    agg: AggCall,
    page: Page,
    order: jnp.ndarray,
    live_s: jnp.ndarray,
    gid: jnp.ndarray,
    max_groups: int,
    lowerer: ExprLowerer,
) -> Block:
    nseg = max_groups + 1  # +1 absorbs dead rows routed to max_groups
    rt = agg.result_type()

    if agg.func == "count_star":
        data = jax.ops.segment_sum(
            live_s.astype(jnp.int64), gid, num_segments=nseg
        )[:max_groups]
        return Block(data=data, valid=None, dtype=T.BIGINT)

    d, v = lowerer.eval(agg.arg)
    d = jnp.broadcast_to(d, (page.capacity,))[order]
    valid_s = live_s if v is None else (
        live_s & jnp.broadcast_to(v, (page.capacity,))[order]
    )

    if agg.func == "count":
        data = jax.ops.segment_sum(
            valid_s.astype(jnp.int64), gid, num_segments=nseg
        )[:max_groups]
        return Block(data=data, valid=None, dtype=T.BIGINT)

    cnt = jax.ops.segment_sum(
        valid_s.astype(jnp.int64), gid, num_segments=nseg
    )[:max_groups]
    group_has_value = cnt > 0

    if agg.func in ("sum", "avg"):
        at = agg.arg.dtype
        if at.name in ("double", "real") or agg.func == "avg":
            x = d.astype(jnp.float64)
            if at.is_decimal:
                x = x / (10 ** at.scale)
            x = jnp.where(valid_s, x, 0.0)
            s = jax.ops.segment_sum(x, gid, num_segments=nseg)[:max_groups]
            if agg.func == "avg":
                data = s / jnp.maximum(cnt, 1)
                return Block(
                    data=data, valid=group_has_value, dtype=T.DOUBLE
                )
            return Block(data=s, valid=group_has_value, dtype=T.DOUBLE)
        x = jnp.where(valid_s, d.astype(jnp.int64), 0)
        s = jax.ops.segment_sum(x, gid, num_segments=nseg)[:max_groups]
        return Block(data=s, valid=group_has_value, dtype=rt)

    if agg.func in ("min", "max"):
        at = agg.arg.dtype
        if at.name in ("double", "real"):
            fill = jnp.inf if agg.func == "min" else -jnp.inf
            x = jnp.where(valid_s, d.astype(jnp.float64), fill)
            op = jax.ops.segment_min if agg.func == "min" else jax.ops.segment_max
            data = op(x, gid, num_segments=nseg)[:max_groups]
            data = data.astype(at.jnp_dtype)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if agg.func == "min" else info.min
            x = jnp.where(valid_s, d.astype(jnp.int64), fill)
            op = jax.ops.segment_min if agg.func == "min" else jax.ops.segment_max
            data = op(x, gid, num_segments=nseg)[:max_groups]
            data = data.astype(at.jnp_dtype)
        dictionary = None
        if at.is_string:
            dictionary = lowerer.dictionary_of(agg.arg)
        return Block(
            data=data, valid=group_has_value, dtype=at, dictionary=dictionary
        )

    raise NotImplementedError(f"aggregate {agg.func}")


def _global_aggregate(
    page: Page,
    aggs: Sequence[AggCall],
    live: jnp.ndarray,
    lowerer: ExprLowerer,
) -> Tuple[Page, jnp.ndarray]:
    """No GROUP BY: the max_groups=1 degenerate case of the segmented
    path — all live rows route to segment 0. One output row always (SQL:
    global aggregates over zero rows emit one row; sum -> NULL via the
    empty-group validity rule, count -> 0)."""
    gid = jnp.where(live, 0, 1)
    order = jnp.arange(page.capacity, dtype=jnp.int32)  # identity
    names, blocks = [], []
    for agg in aggs:
        blocks.append(
            _segment_agg(agg, page, order, live, gid, 1, lowerer)
        )
        names.append(agg.out_name)
    out = Page(
        blocks=tuple(blocks),
        num_valid=jnp.asarray(1, jnp.int32),
        names=tuple(names),
    )
    return out, jnp.asarray(False)
