"""Shared kernel utilities: orderable keys, lexicographic sort orders.

The TPU has no comparator trees for structs — multi-column orderings are
expressed as a sequence of stable int64 sorts (XLA sorts are fast,
vectorized, and fuse with the surrounding gather). Every SQL type maps to
an *order-preserving* int64 image (``orderable_i64``), so one code path
serves sort, group-by boundary detection, merge and join kernels.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from presto_tpu import types as T


def orderable_i64(data: jnp.ndarray, dtype: T.DataType) -> jnp.ndarray:
    """Map a column to int64 such that int comparison == SQL comparison.

    - ints/dates/decimals/dict-ids: widen to int64 (dict ids are
      order-preserving by construction, presto_tpu.page.Dictionary)
    - floats: sign-magnitude bit trick (IEEE754 totally ordered for
      non-NaN; NaN sorts last as in the reference's ORDER BY)
    """
    if dtype.is_long_decimal:
        # a (cap, 2) limb pair does not fit ONE orderable int64 — the
        # multi-lane callers (sort_order/boundaries via key_lanes)
        # handle long decimals; anything still calling the scalar form
        # (single-int64 join packing) gets the documented deviation
        raise NotImplementedError(
            "long decimals (p>18) do not reduce to a single orderable "
            "int64 lane — use key_lanes()"
        )
    if dtype.name in ("double", "real"):
        f = jnp.asarray(data, jnp.float64)
        f = jnp.where(f == 0, 0.0, f)  # -0.0 and +0.0 are SQL-equal
        bits = f.view(jnp.int64)
        # IEEE754 total order as signed int64: positives keep their bit
        # pattern in [0, 2^63); negatives map to ~bits with the sign bit
        # set, landing in [-2^63, 0) in reversed-magnitude order.
        return jnp.where(bits >= 0, bits, (~bits) | jnp.int64(-(2 ** 63)))
    if dtype.name == "boolean":
        return data.astype(jnp.int64)
    return jnp.asarray(data).astype(jnp.int64)


def key_lanes(data: jnp.ndarray, dtype: T.DataType) -> List[jnp.ndarray]:
    """A key column as 1..2 order-preserving int64 lanes, most
    significant first. Long decimals ((cap, 2) int64 limb pairs —
    types.LongDecimalType layout) expand to [hi, lo-as-unsigned]:
    lexicographic comparison of the lane pair equals int128 comparison
    (lo's int64 bit pattern gets the sign bit flipped so signed lane
    order matches its unsigned-limb order). Every other type is the
    single ``orderable_i64`` lane."""
    if dtype.is_long_decimal:
        d = jnp.asarray(data)
        hi = d[..., 0].astype(jnp.int64)
        lo = d[..., 1].astype(jnp.int64) ^ jnp.int64(-(2 ** 63))
        return [hi, lo]
    return [orderable_i64(data, dtype)]


def sort_order(
    keys: Sequence[Tuple[jnp.ndarray, Optional[jnp.ndarray], T.DataType]],
    live: jnp.ndarray,
    descending: Optional[Sequence[bool]] = None,
    nulls_first: Optional[Sequence[bool]] = None,
) -> jnp.ndarray:
    """Permutation sorting rows by keys (list of (data, valid, dtype)),
    live rows first. SQL default: nulls last in ASC, first in DESC
    (reference: NULLS LAST semantics for ASC ordering).

    Multi-lane keys (long decimals) contribute all their lanes at one
    significance position: DESC flips every lane (lexicographic reverse
    of (hi, lo) is (~hi, ~lo)), and the null rank stays per-KEY.
    """
    n = len(keys)
    descending = descending or [False] * n
    nulls_first = nulls_first or [d for d in descending]
    lex: List[jnp.ndarray] = []
    # jnp.lexsort: LAST key is primary -> emit least-significant first
    for (data, valid, dtype), desc, nf in zip(
        reversed(list(keys)), reversed(list(descending)), reversed(list(nulls_first))
    ):
        lanes = key_lanes(data, dtype)
        if desc:
            # bitwise-not reverses order without INT64_MIN overflow
            lanes = [~k for k in lanes]
        null_rank = (
            jnp.zeros(lanes[0].shape, jnp.int64)
            if valid is None
            else jnp.where(valid, 0, -1 if nf else 1)
        )
        lex.extend(reversed(lanes))
        lex.append(null_rank)  # more significant than the value
    lex.append(jnp.where(live, 0, 1).astype(jnp.int64))  # live first
    return jnp.lexsort(lex)


def boundaries(
    sorted_keys: Sequence[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
    live_sorted: jnp.ndarray,
) -> jnp.ndarray:
    """True where a new group starts (first live row or any key change).
    Inputs already sorted; nulls group together (SQL GROUP BY)."""
    first = jnp.zeros(live_sorted.shape, jnp.bool_).at[0].set(True)
    change = first
    for data, valid in sorted_keys:
        d = jnp.asarray(data)
        neq = d[1:] != d[:-1]
        if d.ndim == 2:  # long-decimal limb pairs: any limb differs
            neq = jnp.any(neq, axis=-1)
        if jnp.issubdtype(d.dtype, jnp.floating):
            # NaN != NaN, but SQL grouping puts all NaNs in one group
            neq = neq & ~(jnp.isnan(d[1:]) & jnp.isnan(d[:-1]))
        diff = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
        if valid is not None:
            v = jnp.asarray(valid)
            vdiff = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), v[1:] != v[:-1]]
            )
            diff = diff | vdiff
            # two nulls are the same group regardless of payload data
            both_null = jnp.concatenate(
                [jnp.zeros((1,), jnp.bool_), (~v[1:]) & (~v[:-1])]
            )
            diff = diff & ~both_null
        change = change | diff
    return change & live_sorted
