"""Equi-join kernels: inner / left outer / full outer / semi / anti
(right outer is planned as left outer with the sides swapped).

Reference parity: ``HashBuilderOperator`` -> ``PagesIndex`` ->
``LookupSourceFactory`` bridged to ``LookupJoinOperator`` (+``JoinProbe``)
— the two-pipeline build/probe split of SURVEY.md §3.3.

TPU-first redesign (SURVEY.md §7 step 3): no pointer-chasing hash table.
The build side is *sorted by key* once (XLA sort), and every probe row
finds its match range with two vectorized ``searchsorted`` binary
searches — a batched, branch-free probe that keeps the VPU lanes full.
Duplicate build keys become [lo, hi) ranges; the output expansion is the
classic prefix-sum + inverse-searchsorted trick, entirely static-shape:
the planner supplies ``out_capacity`` and the kernel reports overflow
(host re-runs at a bigger bucket), mirroring the engine-wide
capacity-bucket protocol (SURVEY.md §7 "Hard parts").

Keys are single int64 columns; the planner packs two int32-representable
key columns bijectively via ``pack_keys`` (wider composites: future
round). NULL keys never match (SQL equi-join); anti join keeps unmatched
probe rows (NOT EXISTS semantics — NOT IN null handling is a planner
rewrite). Join keys of exactly int64-max are unsupported (sentinel);
unreachable for real workloads.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.ops.common import orderable_i64
from presto_tpu.page import Block, Page

_I64_MAX = jnp.iinfo(jnp.int64).max


def pack_keys(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Bijectively pack two int32-representable key columns into int64."""
    return (a.astype(jnp.int64) << 32) | (b.astype(jnp.int64) & 0xFFFFFFFF)


def _key_of(page: Page, key_cols: Sequence[str]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(int64 key, ok-mask) for live rows with non-null key columns."""
    ok = page.row_mask()
    datas = []
    widths = []
    for name in key_cols:
        blk = page.block(name)
        if blk.dtype.is_long_decimal:
            # int128 limb pair -> one int64 via a splitmix64 fold. NOT
            # injective: the planner only emits a long-decimal kernel
            # key on INNER joins with a residual limb-equality filter
            # attached (JoinNode.residual), which removes any
            # mix-collision false match — collisions cost out_capacity,
            # never correctness (plan/planner.py long-decimal join path)
            d = jnp.asarray(blk.data)
            hi = d[..., 0].astype(jnp.uint64)
            z = hi + jnp.uint64(0x9E3779B97F4A7C15)
            z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
            z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
            z = z ^ (z >> jnp.uint64(31))
            mixed = (z ^ d[..., 1].astype(jnp.uint64)).astype(jnp.int64)
            datas.append(mixed)
        else:
            datas.append(orderable_i64(blk.data, blk.dtype))
        widths.append(blk.dtype.np_dtype.itemsize)
        if blk.valid is not None:
            ok = ok & blk.valid
    if len(datas) == 1:
        key = datas[0]
    elif len(datas) == 2:
        # pack is bijective only for 32-bit key columns; wider values
        # would wrap modulo 2^64 and silently collide. The planner must
        # cast bigint keys down (stats-bounded) before using a pair key.
        if any(w > 4 for w in widths):
            raise NotImplementedError(
                "two-column join keys must be 32-bit columns "
                f"(got widths {widths}); planner must narrow first"
            )
        key = pack_keys(datas[0], datas[1])
    else:
        raise NotImplementedError(
            ">2 join key columns (pack wider composites in the planner)"
        )
    return key, ok


def _mask_out(page: Page, keep: jnp.ndarray) -> Page:
    """Select rows of ``page`` lazily: keep them in place under a live
    mask (Page masked form) instead of nonzero+gather compaction — the
    downstream kernels all consume row_mask() (see ops.filter_project)."""
    return dataclasses.replace(
        page, live=keep, num_valid=jnp.sum(keep).astype(jnp.int32)
    )


def hash_join(
    probe: Page,
    build: Page,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    join_type: str = "inner",
    build_payload: Optional[Sequence[str]] = None,
    build_unique: bool = False,
    out_capacity: Optional[int] = None,
    payload_rename: Optional[dict] = None,
) -> Tuple[Page, jnp.ndarray]:
    """Join ``probe`` with ``build`` on equality of packed keys.

    Returns (result, overflow). Result columns = all probe columns plus
    ``build_payload`` columns (optionally renamed via ``payload_rename``).
    join_type: inner | left | full | semi | anti.

    FULL OUTER executes as left outer plus an appended section of
    unmatched build rows (probe columns NULL) — the appended section
    rides the Page live-mask (masked form), so no compaction gather is
    paid for it.
    """
    build_payload = list(build_payload or [])
    payload_rename = payload_rename or {}

    for pc, bc in zip(probe_keys, build_keys):
        pb, bb = probe.block(pc), build.block(bc)
        if pb.dtype.is_string or bb.dtype.is_string:
            # ids are only comparable within ONE dictionary; the planner
            # re-encodes one side before a string-keyed join
            if pb.dictionary != bb.dictionary:
                raise NotImplementedError(
                    f"string join key {pc}={bc} across different "
                    "dictionaries: planner must re-encode first"
                )

    pk, p_ok = _key_of(probe, probe_keys)
    bk, b_ok = _key_of(build, build_keys)

    # sort build by key; unmatchable rows carry the sentinel and sort last
    b_sort_key = jnp.where(b_ok, bk, _I64_MAX)
    b_order = jnp.argsort(b_sort_key, stable=True)
    bk_s = b_sort_key[b_order]
    nb = jnp.sum(b_ok).astype(jnp.int32)

    pk_eff = jnp.where(p_ok, pk, _I64_MAX)
    lo = jnp.searchsorted(bk_s, pk_eff, side="left")
    hi = jnp.searchsorted(bk_s, pk_eff, side="right")
    lo = jnp.minimum(lo, nb)
    hi = jnp.minimum(hi, nb)
    m = jnp.where(p_ok, hi - lo, 0)  # matches per probe row

    if join_type == "semi":
        return _mask_out(probe, m > 0), jnp.asarray(False)
    if join_type == "anti":
        keep = (m == 0) & probe.row_mask()
        return _mask_out(probe, keep), jnp.asarray(False)

    if build_unique:
        # PK side: m in {0,1}; output row i <-> probe row i (static!)
        matched = m > 0
        b_idx = b_order[jnp.clip(lo, 0, build.capacity - 1)]
        out = _join_output(
            probe,
            build,
            jnp.arange(probe.capacity),
            b_idx,
            matched,
            build_payload,
            payload_rename,
            left_outer=(join_type in ("left", "full")),
        )
        if join_type == "inner":
            keep = matched & probe.row_mask()
            return _mask_out(out, keep), jnp.asarray(False)
        # left/full outer keep every probe row: positional layout, so
        # the probe's own liveness (mask or prefix) carries over
        out = dataclasses.replace(out, live=probe.live)
        if join_type == "full":
            out = _append_unmatched_build(
                out, probe, build, pk_eff, p_ok, bk, b_ok,
                build_payload, payload_rename,
            )
        return out, jnp.asarray(False)

    # general duplicate-capable expansion
    if out_capacity is None:
        raise ValueError("non-unique inner/left join requires out_capacity")
    m_eff = jnp.maximum(m, 1) if join_type in ("left", "full") else m
    m_eff = jnp.where(probe.row_mask(), m_eff, 0)
    total = jnp.cumsum(m_eff)
    out_count = total[-1] if probe.capacity else jnp.asarray(0, jnp.int64)
    overflow = out_count > out_capacity

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    p_idx = jnp.searchsorted(total, j, side="right")
    p_idx = jnp.minimum(p_idx, probe.capacity - 1)
    prev = jnp.where(p_idx > 0, total[jnp.maximum(p_idx - 1, 0)], 0)
    offset = j - prev
    row_m = m[p_idx]
    matched = row_m > 0
    b_pos = lo[p_idx] + jnp.minimum(offset, jnp.maximum(row_m - 1, 0))
    b_idx = b_order[jnp.clip(b_pos, 0, build.capacity - 1)]
    out = _join_output(
        probe,
        build,
        p_idx,
        b_idx,
        matched,
        build_payload,
        payload_rename,
        left_outer=(join_type in ("left", "full")),
    )
    out = dataclasses.replace(
        out, num_valid=jnp.minimum(out_count, out_capacity).astype(jnp.int32)
    )
    if join_type == "full":
        out = _append_unmatched_build(
            out, probe, build, pk_eff, p_ok, bk, b_ok,
            build_payload, payload_rename,
        )
    return out, overflow


def cross_join(
    left: Page, right: Page, out_capacity: int
) -> Tuple[Page, jnp.ndarray]:
    """General nested-loop cross product (reference:
    NestedLoopJoinOperator — SURVEY.md §2.1 "Operators"). Static-shape:
    the same prefix-sum + inverse-searchsorted expansion the
    duplicate-key equi-join uses, with every live left row matching
    every live right row. Returns (result, overflow) under the engine's
    capacity-bucket protocol."""
    from presto_tpu.page import compact_page

    right_c = compact_page(right)  # offsets index the live prefix
    nr = right_c.num_valid.astype(jnp.int64)
    m_eff = jnp.where(left.row_mask(), nr, 0)
    total = jnp.cumsum(m_eff)
    out_count = total[-1] if left.capacity else jnp.asarray(0, jnp.int64)
    overflow = out_count > out_capacity

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    p_idx = jnp.searchsorted(total, j, side="right")
    p_idx = jnp.minimum(p_idx, left.capacity - 1)
    prev = jnp.where(p_idx > 0, total[jnp.maximum(p_idx - 1, 0)], 0)
    b_idx = jnp.clip(j - prev, 0, right_c.capacity - 1)

    names: List[str] = []
    blocks: List[Block] = []
    for name, blk in zip(left.names, left.blocks):
        blocks.append(
            dataclasses.replace(
                blk,
                data=blk.data[p_idx],
                valid=None if blk.valid is None else blk.valid[p_idx],
            )
        )
        names.append(name)
    for name, blk in zip(right_c.names, right_c.blocks):
        blocks.append(
            dataclasses.replace(
                blk,
                data=blk.data[b_idx],
                valid=None if blk.valid is None else blk.valid[b_idx],
            )
        )
        names.append(name)
    return (
        Page(
            blocks=tuple(blocks),
            num_valid=jnp.minimum(out_count, out_capacity).astype(
                jnp.int32
            ),
            names=tuple(names),
        ),
        overflow,
    )


def _append_unmatched_build(
    out: Page,
    probe: Page,
    build: Page,
    pk_eff: jnp.ndarray,
    p_ok: jnp.ndarray,
    bk: jnp.ndarray,
    b_ok: jnp.ndarray,
    build_payload: Sequence[str],
    payload_rename: dict,
) -> Page:
    """FULL OUTER's second section: build rows no probe key matched,
    appended after the left-outer section with NULL probe columns. The
    result is a masked-form Page (section 1's liveness concatenated
    with the unmatched-build mask) — zero gathers."""
    # membership of each build key among the live probe keys, by binary
    # search in the sorted probe keys; matches beyond the live count are
    # sentinel slots, not real keys — clip like the main probe path does
    pk_sorted = jnp.sort(jnp.where(p_ok, pk_eff, _I64_MAX))
    n_live = jnp.sum(p_ok)
    lo = jnp.minimum(jnp.searchsorted(pk_sorted, bk, side="left"), n_live)
    hi = jnp.minimum(jnp.searchsorted(pk_sorted, bk, side="right"), n_live)
    matched_b = b_ok & (hi > lo)
    keep_b = build.row_mask() & ~matched_b

    rename = payload_rename or {}
    payload_names = {rename.get(c, c) for c in build_payload}
    cap_b = build.capacity
    blocks = []
    for name, blk in zip(out.names, out.blocks):
        if name in payload_names:
            src_name = next(
                c for c in build_payload if rename.get(c, c) == name
            )
            b_blk = build.block(src_name)
            tail_data = b_blk.data
            tail_valid = (
                jnp.ones((cap_b,), jnp.bool_)
                if b_blk.valid is None
                else b_blk.valid
            )
        else:
            # probe column: NULL in the appended section
            tail_data = jnp.zeros((cap_b,), blk.data.dtype)
            tail_valid = jnp.zeros((cap_b,), jnp.bool_)
        head_valid = (
            jnp.ones((out.capacity,), jnp.bool_)
            if blk.valid is None
            else blk.valid
        )
        blocks.append(
            dataclasses.replace(
                blk,
                data=jnp.concatenate([blk.data, tail_data]),
                valid=jnp.concatenate([head_valid, tail_valid]),
            )
        )
    live = jnp.concatenate([out.row_mask(), keep_b])
    return Page(
        blocks=tuple(blocks),
        num_valid=(
            out.num_valid + jnp.sum(keep_b).astype(jnp.int32)
        ),
        names=out.names,
        live=live,
    )


def _join_output(
    probe: Page,
    build: Page,
    p_idx: jnp.ndarray,
    b_idx: jnp.ndarray,
    matched: jnp.ndarray,
    build_payload: Sequence[str],
    payload_rename: dict,
    left_outer: bool,
) -> Page:
    for name in list(probe.names) + list(build_payload):
        src = probe if name in probe.names else build
        blk = src.block(name)
        if blk.offsets is not None or blk.children is not None:
            # a row-index gather of the FLAT values array with stale
            # offsets (arrays/maps) or of the placeholder without the
            # children (rows) would silently corrupt nested columns
            raise NotImplementedError(
                f"nested column {name} ({blk.dtype}) cannot ride "
                "through a join output; select it before the join or "
                "join on its parent rows and access fields/unnest after"
            )
    names: List[str] = []
    blocks: List[Block] = []
    for name in probe.names:
        blk = probe.block(name)
        blocks.append(
            dataclasses.replace(
                blk,
                data=blk.data[p_idx],
                valid=None if blk.valid is None else blk.valid[p_idx],
            )
        )
        names.append(name)
    for name in build_payload:
        blk = build.block(name)
        data = blk.data[b_idx]
        valid = None if blk.valid is None else blk.valid[b_idx]
        if left_outer:
            valid = matched if valid is None else (valid & matched)
        blocks.append(dataclasses.replace(blk, data=data, valid=valid))
        names.append(payload_rename.get(name, name))
    return Page(
        blocks=tuple(blocks), num_valid=probe.num_valid, names=tuple(names)
    )
