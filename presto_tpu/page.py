"""Columnar Block/Page data model — device-resident, static-shape.

Reference parity: ``presto-common`` ``Block`` hierarchy (LongArrayBlock,
IntArrayBlock, VariableWidthBlock, DictionaryBlock, RunLengthEncodedBlock)
and ``Page`` — SURVEY.md §2.1 "Block/Page data model".

TPU-first redesign (SURVEY.md §7 "Design stance"):

- A ``Block`` is a pytree of fixed-shape JAX arrays: ``data`` plus an
  optional ``valid`` null-mask. There is no VariableWidthBlock — strings are
  dictionary ids (int32) with the dictionary held host-side (strings never
  touch the device; the VPU only ever sees fixed-width lanes).
- A ``Page`` carries a traced scalar ``num_valid``: the first ``num_valid``
  rows are live, the rest is padding. Filters *compact* survivors to the
  front (static-shape ``jnp.nonzero(size=...)``) instead of shrinking the
  array, so every downstream kernel sees the same shapes and XLA compiles
  each fragment exactly once per capacity bucket.
- Capacity (array length) is static metadata; the planner picks capacity
  buckets so selective filters can step pages down to smaller compiled
  shapes between fragments (host-side re-bucketing).

Blocks/Pages are registered as pytree dataclasses: ``data``/``valid``/
``num_valid`` are leaves (traced), everything else is static aux data that
participates in the jit cache key.
"""

from __future__ import annotations

import dataclasses
import hashlib
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T


class Dictionary:
    """Host-side, order-preserving string dictionary.

    Ids are assigned in sorted order of the distinct values, so integer
    comparison of ids agrees with lexicographic comparison of the strings
    they encode (within a single dictionary). This is what lets <, =,
    BETWEEN, ORDER BY, and min/max on varchar run entirely on-device over
    int32 lanes; LIKE and other string functions evaluate host-side over
    the (small) dictionary into a boolean lookup table that is then
    gathered on-device (SURVEY.md §7 "Strings on TPU").

    Immutable and hashable (content digest) — safe as static jit metadata.
    """

    __slots__ = ("values", "_str_values", "_index", "_digest")

    def __init__(self, sorted_values: np.ndarray):
        self.values = np.asarray(sorted_values)
        self._str_values = self.values.astype(str)
        self._index: Optional[dict] = None
        h = hashlib.blake2b(digest_size=16)
        h.update(str(len(self.values)).encode())
        for v in self._str_values:
            h.update(v.encode())
            h.update(b"\x00")
        self._digest = h.digest()

    @classmethod
    def build(cls, values: Sequence[str]) -> "Dictionary":
        return cls(np.unique(np.asarray(values, dtype=object)))

    def __len__(self) -> int:
        return len(self.values)

    def __hash__(self):
        return hash(self._digest)

    def __eq__(self, other):
        return isinstance(other, Dictionary) and self._digest == other._digest

    def id_of(self, value: str) -> int:
        """Exact id of value, or -1 if absent."""
        if self._index is None:
            self._index = {v: i for i, v in enumerate(self.values)}
        return self._index.get(value, -1)

    def searchsorted(self, value: str, side: str = "left") -> int:
        """Insertion point of value — supports range predicates on absent
        literals (e.g. ``c < 'm'`` where 'm' is not in the dictionary)."""
        return int(np.searchsorted(self._str_values, value, side=side))

    def decode(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids)
        if len(self.values) == 0:  # all-NULL column
            return np.full(ids.shape, None, dtype=object)
        out = self.values[np.clip(ids, 0, len(self.values) - 1)]
        return np.where(ids < 0, None, out)

    def predicate_lut(self, fn) -> np.ndarray:
        """Evaluate a host predicate over every dictionary entry -> bool LUT
        (device gathers LUT[id] to evaluate e.g. LIKE)."""
        return np.asarray([bool(fn(v)) for v in self.values], dtype=bool)


_NATIVE_ENCODE_MIN_ROWS = 4096


def _pad_flat_child(child: "Block", vcap: int) -> "Block":
    """Pad a flat child block (map keys/values) to the bucketed value
    capacity — same value-axis discipline as array blocks."""
    n = child.data.shape[0]
    if n >= vcap:
        return child
    pad = [(0, vcap - n)] + [(0, 0)] * (child.data.ndim - 1)
    return dataclasses.replace(
        child,
        data=jnp.pad(child.data, pad),
        valid=(
            None
            if child.valid is None
            else jnp.pad(child.valid, [(0, vcap - n)])
        ),
    )


def encode_strings(
    values: Sequence, force_numpy: bool = False
) -> tuple[np.ndarray, np.ndarray, Dictionary]:
    """Encode strings -> (int32 ids, valid mask, order-preserving dict).

    None values get id -1 and valid=False. Large columns route through
    the C++ host-agent codec when it is available (native/dict_codec.cpp
    — ~2x over the np.unique path, measured table in BASELINE.md);
    identical semantics either way."""
    arr = np.asarray(values, dtype=object)
    if len(arr) >= _NATIVE_ENCODE_MIN_ROWS and not force_numpy:
        from presto_tpu import native

        out = native.encode_strings_native(arr)
        if out is not None:
            ids, valid, uniq = out  # codec writes -1 for NULL rows
            return ids, valid, Dictionary(uniq)
    isnull = np.array([v is None for v in arr], dtype=bool)
    present = arr[~isnull].astype(str) if (~isnull).any() else np.array([], str)
    dictionary = Dictionary(np.unique(present))
    ids = np.full(len(arr), -1, dtype=np.int32)
    if len(present):
        ids[~isnull] = np.searchsorted(
            dictionary._str_values, present
        ).astype(np.int32)
    return ids, ~isnull, dictionary


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["data", "valid", "offsets", "children"],
    meta_fields=["dtype", "dictionary"],
)
@dataclasses.dataclass
class Block:
    """One column: fixed-width device array + optional null mask.

    ``valid`` is None when the column is known null-free (the common case
    for TPC-H) — that knowledge is static, so XLA never materialises or
    computes masks for non-null columns.

    Array columns (``dtype.is_array``, reference: ArrayBlock): ``data``
    is the flat VALUES array (its own padded capacity) and ``offsets``
    is an int32 (row_capacity + 1,) array — row i's elements are
    ``data[offsets[i]:offsets[i+1]]``; ``valid`` stays per-ROW. Scalar
    columns carry offsets=None.

    Map columns (``dtype.is_map``, reference: MapBlock): ``offsets`` as
    for arrays, ``children`` = (keys Block, values Block) — two flat
    blocks sharing the offsets; ``data`` is a zero-width placeholder.
    Row columns (``dtype.is_row``, reference: RowBlock): ``children`` =
    one Block per field at ROW capacity, no offsets, placeholder data.
    ``children`` is a pytree data field (None for scalar/array blocks —
    an empty pytree, so existing block traversals see no new leaves).
    """

    data: jnp.ndarray
    valid: Optional[jnp.ndarray]  # bool, True = non-null; None = all valid
    dtype: T.DataType
    dictionary: Optional[Dictionary] = None
    offsets: Optional[jnp.ndarray] = None  # int32 (capacity+1,) arrays only
    children: Optional[tuple] = None  # map: (keys, values); row: fields

    @property
    def capacity(self) -> int:
        if self.offsets is not None:
            return self.offsets.shape[0] - 1
        return self.data.shape[0]

    @staticmethod
    def placeholder_data(cap: int) -> jnp.ndarray:
        """Zero-byte per-row stand-in for blocks whose payload lives in
        ``children`` (map/row): keeps ``data.shape[0] == capacity`` with
        no device memory."""
        return jnp.zeros((cap, 0), jnp.int8)

    @classmethod
    def from_numpy(
        cls,
        values: np.ndarray,
        dtype: T.DataType,
        valid: Optional[np.ndarray] = None,
        dictionary: Optional[Dictionary] = None,
    ) -> "Block":
        data = jnp.asarray(np.asarray(values), dtype=dtype.jnp_dtype)
        v = None if valid is None else jnp.asarray(valid, dtype=jnp.bool_)
        return cls(data=data, valid=v, dtype=dtype, dictionary=dictionary)

    @classmethod
    def from_pylist(cls, values: Sequence, dtype: T.DataType) -> "Block":
        """Build from Python values (None = NULL). Handles dictionary
        encoding for varchar, scaling for decimals, and offsets+flat
        values for arrays (elements recurse through this builder)."""
        if dtype.is_array:
            lengths = [0 if v is None else len(v) for v in values]
            offsets = np.zeros(len(values) + 1, np.int32)
            np.cumsum(lengths, out=offsets[1:])
            flat: list = []
            for v in values:
                if v is not None:
                    flat.extend(v)
            if any(x is None for x in flat):
                raise NotImplementedError(
                    "NULL array elements are not supported (documented "
                    "deviation; NULL rows are)"
                )
            child = cls.from_pylist(flat, dtype.element)
            from presto_tpu.exec.staging import bucket_capacity

            vcap = bucket_capacity(len(flat))
            if child.data.shape[0] < vcap:
                # bucket the VALUE axis (same discipline as rows):
                # exact element counts would churn XLA input shapes
                child = dataclasses.replace(
                    child,
                    data=jnp.pad(
                        child.data, [(0, vcap - child.data.shape[0])]
                    ),
                )
            isnull = np.array([v is None for v in values], bool)
            return cls(
                data=child.data,
                valid=(
                    None
                    if not isnull.any()
                    else jnp.asarray(~isnull)
                ),
                dtype=dtype,
                dictionary=child.dictionary,
                offsets=jnp.asarray(offsets),
            )
        if dtype.is_map:
            # python dicts -> offsets + flat keys/values child blocks
            if dtype.key.is_nested or dtype.value.is_nested:
                raise NotImplementedError(
                    "nested map key/value types are not supported "
                    "(one nesting level; documented deviation)"
                )
            lengths = [0 if v is None else len(v) for v in values]
            offsets = np.zeros(len(values) + 1, np.int32)
            np.cumsum(lengths, out=offsets[1:])
            flat_k: list = []
            flat_v: list = []
            for v in values:
                if v is not None:
                    for k, val in v.items():
                        flat_k.append(k)
                        flat_v.append(val)
            if any(x is None for x in flat_k):
                raise NotImplementedError("NULL map keys are invalid")
            kchild = cls.from_pylist(flat_k, dtype.key)
            vchild = cls.from_pylist(flat_v, dtype.value)
            from presto_tpu.exec.staging import bucket_capacity

            vcap = bucket_capacity(len(flat_k))
            kchild = _pad_flat_child(kchild, vcap)
            vchild = _pad_flat_child(vchild, vcap)
            isnull = np.array([v is None for v in values], bool)
            return cls(
                data=cls.placeholder_data(len(values)),
                valid=None if not isnull.any() else jnp.asarray(~isnull),
                dtype=dtype,
                offsets=jnp.asarray(offsets),
                children=(kchild, vchild),
            )
        if dtype.is_row:
            # python dicts (by field name) or sequences (positional)
            if any(t.is_nested for _, t in dtype.fields):
                raise NotImplementedError(
                    "nested row field types are not supported "
                    "(one nesting level; documented deviation)"
                )
            isnull = np.array([v is None for v in values], bool)
            children = []
            for i, (fname, ftype) in enumerate(dtype.fields):
                fv = [
                    None
                    if v is None
                    else (v[fname] if isinstance(v, dict) else v[i])
                    for v in values
                ]
                children.append(cls.from_pylist(fv, ftype))
            return cls(
                data=cls.placeholder_data(len(values)),
                valid=None if not isnull.any() else jnp.asarray(~isnull),
                dtype=dtype,
                children=tuple(children),
            )
        if dtype.is_string:
            ids, valid, dictionary = encode_strings(values)
            v = None if valid.all() else valid
            return cls.from_numpy(ids, dtype, v, dictionary)
        isnull = np.array([v is None for v in values], dtype=bool)
        if dtype.is_decimal:
            # SQL half-up rounding, exact via decimal.Decimal (float
            # multiply mis-rounds e.g. 0.005 at scale 2).
            import decimal as _dec

            q = _dec.Decimal(1).scaleb(-dtype.scale)
            # default context precision (28) is too small for long
            # decimals: quantize at int128 width
            with _dec.localcontext() as ctx:
                ctx.prec = 50
                filled = [
                    0
                    if v is None
                    else int(
                        _dec.Decimal(str(v)).quantize(
                            q, rounding=_dec.ROUND_HALF_UP
                        ).scaleb(dtype.scale)
                    )
                    for v in values
                ]
            if dtype.is_long_decimal:
                arr = T.int128_limbs(filled)  # (n, 2) limb pairs
            else:
                arr = np.asarray(filled, dtype=np.int64)
        else:
            filled = [0 if v is None else v for v in values]
            arr = np.asarray(filled).astype(dtype.np_dtype)
        v = None if not isnull.any() else ~isnull
        return cls.from_numpy(arr, dtype, v)

    def to_numpy(self, n: Optional[int] = None):
        """Materialise first n rows host-side as (values, valid) numpy pair.
        Dictionary ids and decimal scaling are NOT decoded here — see
        Page.to_pylist for full decoding."""
        data = np.asarray(self.data[:n] if n is not None else self.data)
        if self.valid is None:
            valid = np.ones(len(data), dtype=bool)
        else:
            valid = np.asarray(self.valid[:n] if n is not None else self.valid)
        return data, valid


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["blocks", "num_valid", "live"],
    meta_fields=["names"],
)
@dataclasses.dataclass
class Page:
    """An ordered set of equal-capacity Blocks + live-row count.

    ``names`` is static (tuple of column names); ``blocks`` is the matching
    tuple of Blocks. Two liveness representations (SURVEY.md §7 "Design
    stance": selection is a mask/selected-indices pair):

    - **prefix form** (``live is None``): the first ``num_valid`` rows are
      live, the rest is padding. Required at program outputs, exchanges,
      and host materialization.
    - **masked form** (``live`` is a bool (capacity,) array): live rows
      are scattered in place; ``num_valid == sum(live)`` is the live
      COUNT, not a prefix length. Filters produce this form lazily — on
      TPU the nonzero+gather compaction costs far more than the masked
      reads downstream kernels do anyway, so rows stay put until an op
      genuinely needs prefix order (``compact_page``).
    """

    blocks: tuple
    num_valid: jnp.ndarray  # scalar int32: prefix length / live count
    names: tuple
    live: Optional[jnp.ndarray] = None  # bool (capacity,): masked form

    @property
    def capacity(self) -> int:
        return self.blocks[0].capacity if self.blocks else 0

    @property
    def num_columns(self) -> int:
        return len(self.blocks)

    def block(self, name: str) -> Block:
        return self.blocks[self.names.index(name)]

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def row_mask(self) -> jnp.ndarray:
        """Boolean mask over capacity: True for live rows."""
        if self.live is not None:
            return self.live
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_valid

    @property
    def is_host(self) -> bool:
        """True when block data already lives host-side as numpy (a
        materialized page) — fetches/materialization are no-ops then."""
        return bool(self.blocks) and isinstance(
            self.blocks[0].data, np.ndarray
        )

    def prefix_leaves(self, k) -> list:
        """Flat [data[:k], valid[:k]?, ...] leaf list for a batched
        device->host fetch of the first ``k`` rows — the ONE shape every
        materialization path fetches (round-trip discipline). Array
        blocks fetch offsets[:k+1] plus the FULL flat values array
        (their live extent is data-dependent; the padded fetch trades
        bytes for the round trip)."""
        leaves = []
        for blk in self.blocks:
            if blk.dtype.is_map:
                leaves.append(blk.offsets[: k + 1])
                for ch in blk.children:
                    leaves.append(ch.data)
                    if ch.valid is not None:
                        leaves.append(ch.valid)
            elif blk.dtype.is_row:
                for ch in blk.children:
                    leaves.append(ch.data[:k])
                    if ch.valid is not None:
                        leaves.append(ch.valid[:k])
            elif blk.offsets is not None:
                leaves.append(blk.offsets[: k + 1])
                leaves.append(blk.data)
            else:
                leaves.append(blk.data[:k])
            if blk.valid is not None:
                leaves.append(blk.valid[:k])
        return leaves

    def with_blocks(self, names: Sequence[str], blocks: Sequence[Block]) -> "Page":
        return Page(
            blocks=tuple(blocks),
            num_valid=self.num_valid,
            names=tuple(names),
        )

    @classmethod
    def from_pydict(
        cls, data: Dict[str, Sequence], schema: Dict[str, T.DataType],
        capacity: Optional[int] = None,
    ) -> "Page":
        """Test/ingest helper: build a page from {name: python values}.

        Pads every column to ``capacity`` (default: exact length)."""
        names = tuple(schema.keys())
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity if capacity is not None else max(n, 1)
        n = min(n, cap)  # truncated blocks must truncate the live count too
        blocks = []
        for name in names:
            vals = list(data[name])
            vals = vals + [None] * (cap - n) if cap > n else vals[:cap]
            b = Block.from_pylist(vals, schema[name])
            # padding validity is irrelevant (masked by num_valid) but keep
            # masks only when real nulls exist in the live region
            if b.valid is not None:
                live_valid = np.asarray(b.valid)[:n]
                if live_valid.all():
                    b = dataclasses.replace(b, valid=None)
            blocks.append(b)
        return cls(
            blocks=tuple(blocks),
            num_valid=jnp.asarray(n, dtype=jnp.int32),
            names=names,
        )

    def to_pylist(self) -> List[dict]:
        """Decode live rows to a list of {name: python value} dicts
        (dictionary ids -> strings, decimals -> Decimal-free floats kept
        exact via int/10**s, dates -> datetime.date)."""
        import datetime

        if self.live is not None:
            # masked form: select host-side (numpy boolean index is cheap
            # once the arrays are fetched; no device compaction needed)
            idx = np.nonzero(np.asarray(self.live))[0]
        else:
            idx = np.arange(int(self.num_valid))
        n = len(idx)
        out_cols = {}
        for name, blk in zip(self.names, self.blocks):
            if blk.dtype.is_map:
                off = np.asarray(blk.offsets)
                kc, vc = blk.children
                kdata = np.asarray(kc.data)
                vdata = np.asarray(vc.data)
                vvalid = (
                    None if vc.valid is None else np.asarray(vc.valid)
                )
                rvalid = (
                    np.ones(blk.capacity, bool)
                    if blk.valid is None
                    else np.asarray(blk.valid)
                )
                col = []
                for i in idx:
                    if not rvalid[i]:
                        col.append(None)
                        continue
                    d = {}
                    for j in range(int(off[i]), int(off[i + 1])):
                        k = _decode_value(
                            kdata[j], blk.dtype.key, kc.dictionary
                        )
                        v = (
                            None
                            if vvalid is not None and not vvalid[j]
                            else _decode_value(
                                vdata[j], blk.dtype.value, vc.dictionary
                            )
                        )
                        d[k] = v
                    col.append(d)
                out_cols[name] = col
                continue
            if blk.dtype.is_row:
                rvalid = (
                    np.ones(blk.capacity, bool)
                    if blk.valid is None
                    else np.asarray(blk.valid)
                )
                fdatas = []
                for (fname, ftype), ch in zip(
                    blk.dtype.fields, blk.children
                ):
                    fdatas.append(
                        (
                            fname,
                            ftype,
                            np.asarray(ch.data),
                            None
                            if ch.valid is None
                            else np.asarray(ch.valid),
                            ch.dictionary,
                        )
                    )
                col = []
                for i in idx:
                    if not rvalid[i]:
                        col.append(None)
                        continue
                    col.append(
                        {
                            fname: (
                                None
                                if fvalid is not None and not fvalid[i]
                                else _decode_value(fd[i], ftype, fdic)
                            )
                            for fname, ftype, fd, fvalid, fdic in fdatas
                        }
                    )
                out_cols[name] = col
                continue
            if blk.dtype.is_array:
                off = np.asarray(blk.offsets)
                vals = np.asarray(blk.data)
                rvalid = (
                    np.ones(blk.capacity, bool)
                    if blk.valid is None
                    else np.asarray(blk.valid)
                )
                et = blk.dtype.element
                col = []
                for i in idx:
                    if not rvalid[i]:
                        col.append(None)
                        continue
                    col.append(
                        [
                            _decode_value(v, et, blk.dictionary)
                            for v in vals[off[i]: off[i + 1]]
                        ]
                    )
                out_cols[name] = col
                continue
            data, valid = blk.to_numpy(None)
            data, valid = data[idx], valid[idx]
            col = []
            for i in range(n):
                if not valid[i]:
                    col.append(None)
                    continue
                col.append(
                    _decode_value(data[i], blk.dtype, blk.dictionary)
                )
            out_cols[name] = col
        return [
            {name: out_cols[name][i] for name in self.names} for i in range(n)
        ]

    def schema(self) -> Dict[str, T.DataType]:
        return {n: b.dtype for n, b in zip(self.names, self.blocks)}


def _decode_value(v, t: T.DataType, dictionary: Optional[Dictionary]):
    """One device value -> python value (shared by scalar columns and
    array elements)."""
    import datetime

    if t.is_string:
        return str(dictionary.values[int(v)])
    if t.is_long_decimal:
        # exact: int/10**s would lose precision past 2^53, and the
        # default context (prec 28) rounds scaleb
        import decimal as _dec

        unscaled = T.int128_value(int(v[0]), int(v[1]))
        with _dec.localcontext() as ctx:
            ctx.prec = 50
            return _dec.Decimal(unscaled).scaleb(-t.scale)
    if t.is_decimal:
        return int(v) / (10 ** t.scale)
    if t.name == "date":
        return datetime.date(1970, 1, 1) + datetime.timedelta(
            days=int(v)
        )
    if t.name == "boolean":
        return bool(v)
    if t.is_integer or t.name == "timestamp":
        return int(v)
    return float(v)


def compact_page(page: Page, out_capacity: Optional[int] = None) -> Page:
    """Masked form -> prefix form: gather live rows to the front
    (static-shape ``jnp.nonzero``). Identity for prefix-form pages.

    This is the one place the selection-mask design pays the gather; ops
    that can consume masks never call it (SURVEY.md §7 "Design stance")."""
    if page.live is None:
        if out_capacity is not None and out_capacity != page.capacity:
            return pad_capacity(page, out_capacity)
        return page
    cap = out_capacity if out_capacity is not None else page.capacity
    (sel,) = jnp.nonzero(page.live, size=cap, fill_value=0)
    blocks = []
    for blk in page.blocks:
        if blk.offsets is not None:
            blocks.append(
                _gather_array_block(blk, sel, page.num_valid)
            )
            continue
        if blk.dtype.is_row:
            blocks.append(_gather_row_block(blk, sel, page.num_valid))
            continue
        blocks.append(
            dataclasses.replace(
                blk,
                data=blk.data[sel],
                valid=None if blk.valid is None else blk.valid[sel],
            )
        )
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(page.num_valid, cap).astype(jnp.int32),
        names=page.names,
    )


def compact_page_window(page: Page, window: int) -> Page:
    """Masked/prefix form -> a prefix-form page of AT MOST ``window``
    rows: the first ``window`` live rows in order, ``num_valid``
    clamped to the window.

    The micro-batch program boundary (exec/local_runner batched
    entries): ``compact_page``'s full-capacity ``nonzero`` + gather is
    the dominant cost of a selective program — ~100x an elementwise
    pass on CPU — and a batched dispatch would pay it PER LANE for
    rows the demux never reads (the demux fetches at most the
    speculative window; a lane whose true count exceeds the window
    falls out of the batch and re-runs scalar). One cumsum + a
    window-sized searchsorted/gather instead: rows beyond the live
    count hold junk (masked by num_valid), exactly like compact_page's
    fill rows. Nested blocks keep the general compaction path."""
    if page.live is None:
        return pad_capacity(page, window)
    if any(
        b.offsets is not None or b.children for b in page.blocks
    ):
        return compact_page(page, window)
    cs = jnp.cumsum(page.live.astype(jnp.int32))
    sel = jnp.searchsorted(
        cs, jnp.arange(1, window + 1, dtype=jnp.int32)
    )
    sel = jnp.minimum(sel, page.capacity - 1).astype(jnp.int32)
    blocks = [
        dataclasses.replace(
            blk,
            data=blk.data[sel],
            valid=None if blk.valid is None else blk.valid[sel],
        )
        for blk in page.blocks
    ]
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(page.num_valid, window).astype(
            jnp.int32
        ),
        names=page.names,
    )


def _gather_array_block(
    blk: Block, sel: jnp.ndarray, num_live
) -> Block:
    """Row-gather an array/map block: new offsets from the selected
    rows' lengths, values re-laid-out by the prefix-sum +
    inverse-searchsorted expansion (the engine's standard static-shape
    gather-of-segments). ``sel`` fill entries (padding rows) contribute
    length 0 via the ``num_live`` cutoff. Map blocks apply the same
    flat-axis gather to both children."""
    cap = sel.shape[0]
    off = blk.offsets
    lengths = off[1:] - off[:-1]
    sel_len = jnp.where(
        jnp.arange(cap) < num_live, lengths[sel], 0
    ).astype(jnp.int32)
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sel_len).astype(jnp.int32)]
    )
    vcap = (
        blk.children[0].data.shape[0]
        if blk.dtype.is_map
        else blk.data.shape[0]
    )
    j = jnp.arange(vcap, dtype=jnp.int32)
    p = jnp.searchsorted(new_off[1:], j, side="right")
    p = jnp.minimum(p, cap - 1)
    src = off[sel[p]] + (j - new_off[p])
    src = jnp.clip(src, 0, vcap - 1)
    if blk.dtype.is_map:
        children = tuple(
            dataclasses.replace(
                ch,
                data=ch.data[src],
                valid=None if ch.valid is None else ch.valid[src],
            )
            for ch in blk.children
        )
        return dataclasses.replace(
            blk,
            data=Block.placeholder_data(cap),
            valid=None if blk.valid is None else blk.valid[sel],
            offsets=new_off,
            children=children,
        )
    return dataclasses.replace(
        blk,
        data=blk.data[src],
        valid=None if blk.valid is None else blk.valid[sel],
        offsets=new_off,
    )


def _gather_row_block(blk: Block, sel: jnp.ndarray, num_live) -> Block:
    """Row-gather a row (struct) block: children gather positionally
    with the parent. ``num_live`` zeroes the lengths of sel's fill
    entries in any offsets-bearing child (same invariant as
    _gather_array_block)."""
    children = tuple(
        _gather_row_block(ch, sel, num_live)
        if ch.dtype.is_row
        else (
            _gather_array_block(ch, sel, num_live)
            if ch.offsets is not None
            else dataclasses.replace(
                ch,
                data=ch.data[sel],
                valid=None if ch.valid is None else ch.valid[sel],
            )
        )
        for ch in blk.children
    )
    return dataclasses.replace(
        blk,
        data=Block.placeholder_data(sel.shape[0]),
        valid=None if blk.valid is None else blk.valid[sel],
        children=children,
    )


def _rebucket_row_block(blk: Block, capacity: int) -> Block:
    """Row-axis pad/slice of a row block and its children."""
    cap = blk.capacity
    if capacity == cap:
        return blk

    def fit(ch: Block) -> Block:
        if ch.dtype.is_row:
            return _rebucket_row_block(ch, capacity)
        if ch.offsets is not None:
            if capacity > cap:
                offs = jnp.pad(
                    ch.offsets, [(0, capacity - cap)], mode="edge"
                )
            else:
                offs = ch.offsets[: capacity + 1]
            return dataclasses.replace(
                ch, offsets=offs, valid=_fit_valid(ch.valid)
            )
        if capacity > cap:
            pad = [(0, capacity - cap)] + [(0, 0)] * (ch.data.ndim - 1)
            return dataclasses.replace(
                ch, data=jnp.pad(ch.data, pad), valid=_fit_valid(ch.valid)
            )
        return dataclasses.replace(
            ch, data=ch.data[:capacity], valid=_fit_valid(ch.valid)
        )

    def _fit_valid(v):
        if v is None:
            return None
        if capacity > cap:
            return jnp.pad(v, [(0, capacity - cap)])
        return v[:capacity]

    return dataclasses.replace(
        blk,
        data=Block.placeholder_data(capacity),
        valid=_fit_valid(blk.valid),
        children=tuple(fit(ch) for ch in blk.children),
    )


def pad_capacity(page: Page, capacity: int) -> Page:
    """Re-bucket a page to a new (>= live rows) capacity host-side.

    This is the fragment-boundary shape-step: selective filters hand a
    large-capacity page to a smaller compiled bucket. Runs on host between
    fragments (device->device realloc via XLA pad/slice). Prefix form
    only (masked pages go through compact_page)."""
    if page.live is not None:
        return compact_page(page, capacity)
    blocks = []
    for blk in page.blocks:
        cap = blk.capacity
        if capacity == cap:
            blocks.append(blk)
        elif blk.offsets is not None:
            # array block: re-bucket the ROW axis (offsets); the flat
            # values array keeps its own capacity. Shrink slices
            # (monotonic prefix stays valid); grow edge-pads so padding
            # rows read as empty
            if capacity > cap:
                offsets = jnp.pad(
                    blk.offsets, [(0, capacity - cap)], mode="edge"
                )
            else:
                offsets = blk.offsets[: capacity + 1]
            valid = (
                None
                if blk.valid is None
                else (
                    jnp.pad(blk.valid, [(0, capacity - cap)])
                    if capacity > cap
                    else blk.valid[:capacity]
                )
            )
            if blk.dtype.is_map:
                blk = dataclasses.replace(
                    blk, data=Block.placeholder_data(capacity)
                )
            blocks.append(
                dataclasses.replace(blk, offsets=offsets, valid=valid)
            )
        elif blk.dtype.is_row:
            blocks.append(_rebucket_row_block(blk, capacity))
        elif capacity > cap:
            # row-axis pad only (long decimals are (cap, 2) limb pairs)
            pad = [(0, capacity - cap)] + [(0, 0)] * (blk.data.ndim - 1)
            data = jnp.pad(blk.data, pad)
            valid = (
                None
                if blk.valid is None
                else jnp.pad(blk.valid, [(0, capacity - cap)])
            )
            blocks.append(dataclasses.replace(blk, data=data, valid=valid))
        else:
            data = blk.data[:capacity]
            valid = None if blk.valid is None else blk.valid[:capacity]
            blocks.append(dataclasses.replace(blk, data=data, valid=valid))
    return Page(
        blocks=tuple(blocks),
        num_valid=jnp.minimum(page.num_valid, capacity).astype(jnp.int32),
        names=page.names,
    )
