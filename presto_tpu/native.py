"""ctypes loader for the C++ host-agent codec (native/dict_codec.cpp).

Reference parity: the native-worker split (SURVEY.md §2.3) — hot host
paths in C++, everything else Python. Build is lazy (g++ on first use,
cached under native/build/) with a clean numpy fallback when the
toolchain or compiler is unavailable, so the engine never hard-depends
on native code.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "dict_codec.cpp")
_SO = os.path.join(_ROOT, "native", "build", "dict_codec.so")

_lock = threading.Lock()
_lib: Optional[object] = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or os.path.getmtime(
                _SO
            ) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                # build to a temp name, then atomic-rename: concurrent
                # processes must never CDLL a half-linked .so
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        _SRC, "-o", tmp,
                    ],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.dict_encode.restype = ctypes.c_int64
            lib.dict_encode.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
            ]
            _lib = lib
        except Exception:
            _lib = None  # toolchain absent / build failed: numpy path
        return _lib


def available() -> bool:
    return _load() is not None


def encode_strings_native(
    values,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Object array of str/None -> (int32 ids, valid mask, sorted
    unique values), or None when the native library is unavailable.
    Semantics identical to page.encode_strings' numpy path."""
    lib = _load()
    if lib is None:
        return None
    n = len(values)
    encs = []
    offsets = np.zeros(n + 1, dtype=np.int64)
    valid = np.ones(n, dtype=np.uint8)
    pos = 0
    for i, v in enumerate(values):
        if v is None:
            valid[i] = 0
            offsets[i + 1] = pos
            continue
        b = str(v).encode("utf-8")
        encs.append(b)
        pos += len(b)
        offsets[i + 1] = pos
    blob = b"".join(encs)
    ids = np.empty(n, dtype=np.int32)
    repr_rows = np.empty(max(n, 1), dtype=np.int64)
    rc = lib.dict_encode(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        valid.ctypes.data_as(ctypes.c_char_p),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        repr_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc < 0:
        return None
    uniq = np.asarray(
        [str(values[int(r)]) for r in repr_rows[:rc]], dtype=object
    )
    return ids, valid.astype(bool), uniq
