"""ctypes loader for the C++ host-agent codec (native/dict_codec.cpp).

Reference parity: the native-worker split (SURVEY.md §2.3) — hot host
paths in C++, everything else Python. Build is lazy (g++ on first use,
cached under native/build/) with a clean numpy fallback when the
toolchain or compiler is unavailable, so the engine never hard-depends
on native code.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "dict_codec.cpp")
_SO = os.path.join(_ROOT, "native", "build", "dict_codec.so")

_lock = threading.Lock()
_lib: Optional[object] = None
_tried = False


def _build_and_load(src: str, so: str, configure):
    """Lazy g++ build (mtime-checked, pid-tmp atomic rename so
    concurrent processes never CDLL a half-linked .so) + ctypes load;
    None when the toolchain is absent or the build fails. ``configure``
    sets restype/argtypes on the loaded library."""
    try:
        if not os.path.exists(so) or os.path.getmtime(
            so
        ) < os.path.getmtime(src):
            os.makedirs(os.path.dirname(so), exist_ok=True)
            tmp = f"{so}.{os.getpid()}.tmp"
            subprocess.run(
                [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    src, "-o", tmp,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
        configure(lib)
        return lib
    except Exception:
        return None  # toolchain absent / build failed: numpy path


def _configure_codec(lib):
    lib.dict_encode.restype = ctypes.c_int64
    lib.dict_encode.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]


def _load():
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _build_and_load(_SRC, _SO, _configure_codec)
        return _lib


def available() -> bool:
    return _load() is not None


def encode_strings_native(
    values,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Object array of str/None -> (int32 ids, valid mask, sorted
    unique values), or None when the native library is unavailable.
    Semantics identical to page.encode_strings' numpy path."""
    lib = _load()
    if lib is None:
        return None
    n = len(values)
    encs = []
    offsets = np.zeros(n + 1, dtype=np.int64)
    valid = np.ones(n, dtype=np.uint8)
    pos = 0
    for i, v in enumerate(values):
        if v is None:
            valid[i] = 0
            offsets[i + 1] = pos
            continue
        b = str(v).encode("utf-8")
        encs.append(b)
        pos += len(b)
        offsets[i + 1] = pos
    blob = b"".join(encs)
    ids = np.empty(n, dtype=np.int32)
    repr_rows = np.empty(max(n, 1), dtype=np.int64)
    rc = lib.dict_encode(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        valid.ctypes.data_as(ctypes.c_char_p),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        repr_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc < 0:
        return None
    uniq = np.asarray(
        [str(values[int(r)]) for r in repr_rows[:rc]], dtype=object
    )
    return ids, valid.astype(bool), uniq


# ------------------------------------------------- closed-form generator

_GEN_SRC = os.path.join(_ROOT, "native", "genstream.cpp")
_GEN_SO = os.path.join(_ROOT, "native", "build", "genstream.so")

_gen_lock = threading.Lock()
_gen_lib: Optional[object] = None
_gen_tried = False

#: below this, ctypes call overhead beats the fused-loop win
_GEN_MIN_ROWS = 65_536


def _configure_gen(lib):
    # gen_stream stays C++-exported but unbound until a caller exists
    lib.gen_uniform.restype = None
    lib.gen_uniform.argtypes = [ctypes.c_int64] * 6 + [
        ctypes.POINTER(ctypes.c_int64)
    ]


def _load_gen():
    global _gen_lib, _gen_tried
    with _gen_lock:
        if not _gen_tried:
            _gen_tried = True
            _gen_lib = _build_and_load(_GEN_SRC, _GEN_SO, _configure_gen)
        return _gen_lib


def _affine_of(idx: np.ndarray) -> Optional[Tuple[int, int]]:
    """(start, step) when idx is exactly start + step*arange(n)."""
    n = len(idx)
    if n == 0:
        return None
    start = int(idx[0])
    if n == 1:
        return start, 1
    step = int(idx[1]) - start
    if int(idx[-1]) != start + step * (n - 1):
        return None
    if not np.array_equal(
        np.diff(idx), np.full(n - 1, step, dtype=idx.dtype)
    ):
        return None
    return start, step


def gen_uniform_native(
    tag: int, idx: np.ndarray, lo: int, hi: int
) -> Optional[np.ndarray]:
    """Fused C++ stream+mod for affine index sequences; None when the
    library is unavailable, the sequence is not affine, or the batch is
    too small to pay the call overhead. Bit-exact vs the numpy path
    (tests/test_native.py)."""
    if len(idx) < _GEN_MIN_ROWS:
        return None
    lib = _load_gen()
    if lib is None:
        return None
    aff = _affine_of(idx)
    if aff is None:
        return None
    start, step = aff
    out = np.empty(len(idx), dtype=np.int64)
    lib.gen_uniform(
        tag, start, step, len(idx), lo, hi,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out
