"""Session & configuration system.

Reference parity: the three config tiers of SURVEY.md §5.6 —
  1. static node config (``etc/config.properties`` -> @Config POJOs),
  2. catalog config (``etc/catalog/*.properties``),
  3. per-query session properties (``SET SESSION k=v``,
     SystemSessionProperties).

Here: tier 1 = ``NodeConfig`` (dict + typed accessors, unknown keys fail
fast at boot, like airlift ConfigBinder); tier 3 = ``Session`` with typed,
validated, defaulted properties. The ``tpu_offload`` gate required by
BASELINE.json is a tier-3 property: when False, fragments execute on the
CPU backend (jax CPU), giving the reference's Java-worker/native-worker
dual-backend seam (SURVEY.md preamble) — same plans, different executor.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    """One typed session property (reference: PropertyMetadata<T>)."""

    name: str
    description: str
    py_type: type
    default: Any
    validate: Optional[Callable[[Any], None]] = None

    def coerce(self, value: Any) -> Any:
        if self.py_type is bool and isinstance(value, str):
            v = value.strip().lower()
            if v not in ("true", "false"):
                raise ValueError(f"{self.name}: expected boolean, got {value!r}")
            value = v == "true"
        else:
            value = self.py_type(value)
        if self.validate:
            self.validate(value)
        return value


def _positive(name):
    def check(v):
        if v <= 0:
            raise ValueError(f"{name} must be positive, got {v}")

    return check


def _non_negative(name):
    def check(v):
        if v < 0:
            raise ValueError(f"{name} must be >= 0, got {v}")

    return check


def _retry_policy_value(v):
    if str(v).upper() not in ("NONE", "TASK", "QUERY"):
        raise ValueError(
            f"retry_policy must be NONE | TASK | QUERY, got {v!r}"
        )


#: Engine-wide session properties (reference: SystemSessionProperties).
SYSTEM_SESSION_PROPERTIES: Dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        PropertyMetadata(
            "tpu_offload",
            "Execute plan fragments on the TPU backend (False = CPU oracle "
            "backend; the BASELINE.json per-session gate)",
            bool,
            True,
        ),
        PropertyMetadata(
            "task_concurrency",
            "Local drivers per task (device lanes for vmapped fragments)",
            int,
            1,
            _positive("task_concurrency"),
        ),
        PropertyMetadata(
            "speculative_result_rows",
            "Result-prefix rows piggybacked on the control fetch: "
            "results this small materialize in ONE device round trip "
            "(0 disables; the tunnel RTT is ~65ms, the speculative "
            "bytes ~1ms/MB)",
            int,
            1024,
            _non_negative("speculative_result_rows"),
        ),
        PropertyMetadata(
            "distributed_final",
            "Run keyed FINAL merges as a second worker stage reading "
            "hash partitions straight from producer workers "
            "(worker<->worker shuffle); False gathers partials at the "
            "coordinator",
            bool,
            True,
        ),
        PropertyMetadata(
            "split_queue_factor",
            "Scan ranges queued per worker for dynamic split placement "
            "(1 = static assignment; reference: SourcePartitionedScheduler "
            "split batching)",
            int,
            4,
            _positive("split_queue_factor"),
        ),
        PropertyMetadata(
            "join_distribution_type",
            "AUTOMATIC | PARTITIONED | BROADCAST (reference: AddExchanges "
            "join distribution choice)",
            str,
            "AUTOMATIC",
        ),
        PropertyMetadata(
            "join_max_broadcast_rows",
            "AUTOMATIC join distribution replicates the build side only "
            "when its estimated rows are at or below this bound; above "
            "it, qualifying joins run as hash-partitioned intermediate "
            "stages (reference: join_max_broadcast_table_size feeding "
            "AddExchanges' stats-driven choice, in rows not bytes "
            "because device pages are columnar and fixed-width)",
            int,
            1 << 21,
            _positive("join_max_broadcast_rows"),
        ),
        PropertyMetadata(
            "page_capacity",
            "Default device page capacity bucket (rows)",
            int,
            1 << 20,
            _positive("page_capacity"),
        ),
        PropertyMetadata(
            "hash_partition_count",
            "Number of partitions for hash-distributed exchanges "
            "(defaults to mesh device count at execution time when 0)",
            int,
            0,
        ),
        PropertyMetadata(
            "host_root_stage",
            "Run the final Output/Sort/Limit root stage host-side over "
            "the gathered result (the reference's single-partition root "
            "stage; avoids per-query XLA sort compiles)",
            bool,
            True,
        ),
        PropertyMetadata(
            "spill_enabled",
            "Allow larger-than-HBM execution: stream split batches "
            "through the compiled fragment and spill hash-bucketed "
            "partial states to host RAM (reference: spilling + grouped "
            "execution)",
            bool,
            True,
        ),
        PropertyMetadata(
            "max_device_rows",
            "Largest table staged whole into device memory; bigger "
            "scans use split-streamed execution (requires "
            "spill_enabled)",
            int,
            1 << 24,
            _positive("max_device_rows"),
        ),
        PropertyMetadata(
            "stream_split_cache",
            "Keep staged split-batch pages device-resident across "
            "queries (cacheable connectors only), so repeated streamed "
            "passes over the same splits skip the host->device "
            "re-staging transfer (the table cache at split "
            "granularity — SURVEY.md §5.7). Off by default: caching "
            "every split defeats larger-than-HBM discipline when the "
            "working set genuinely exceeds device memory",
            bool,
            False,
        ),
        PropertyMetadata(
            "staging_prefetch_depth",
            "Split batches staged ahead on a background host thread "
            "while the device executes the current batch (pipelined "
            "prefetch staging: compute/transfer overlap on the worker "
            "hot path). 0 disables — the serial stage->run->stage "
            "path, bit-identical results. Tier-1 twin: "
            "staging.prefetch-depth",
            int,
            2,
            _non_negative("staging_prefetch_depth"),
        ),
        PropertyMetadata(
            "max_fragment_weight",
            "Largest plan weight compiled as ONE XLA program; heavier "
            "plans execute stage-at-a-time with device-resident "
            "intermediates (reference: tasks run fragments, never whole "
            "plans — SURVEY.md §3.3). 16 keeps single-heavy-op plans "
            "(Q1-class) whole while every multi-join plan fragments — "
            "measured: Q3@SF1's ~25-weight whole-plan program exceeded "
            "20 min in the tunnel's remote_compile while its fragments "
            "compile in seconds. 0 compiles whole plans",
            int,
            16,
            _non_negative("max_fragment_weight"),
        ),
        PropertyMetadata(
            "enable_dynamic_filtering",
            "Stage-at-a-time joins fetch the executed build side's "
            "join-key min/max and pre-filter the probe side with the "
            "range (reference: dynamic filters flowing build->probe "
            "at runtime)",
            bool,
            True,
        ),
        PropertyMetadata(
            "dynamic_filtering_wait_ms",
            "Distributed dynamic filtering: how long probe split "
            "scheduling waits for the build-side filter summary before "
            "proceeding UNFILTERED (bounded — build-worker death or "
            "slowness degrades to the exact unfiltered plan, never "
            "blocks the query). Tier-1 twin: dynamic-filtering.wait-ms",
            float,
            2000.0,
            _non_negative("dynamic_filtering_wait_ms"),
        ),
        PropertyMetadata(
            "dynamic_filtering_ndv_limit",
            "Largest build-side distinct-value count kept as an "
            "IN-list summary (incl. dictionary string keys); above it "
            "only min/max bounds flow to the probe side. Tier-1 twin: "
            "dynamic-filtering.ndv-limit",
            int,
            64,
            _positive("dynamic_filtering_ndv_limit"),
        ),
        PropertyMetadata(
            "enable_plan_cache",
            "Parameterized plan cache + compiled-fragment reuse "
            "(plan/canonical.py): comparison/filter/projection literals "
            "hoist out of plans into runtime device inputs, so "
            "structurally identical statements reuse one planned and "
            "ONE compiled program, and warm PREPARE/EXECUTE does zero "
            "planning and zero compilation. False = pre-cache "
            "behavior: every literal variant plans and compiles its "
            "own program (bit-exact legacy path). Tier-1 twins: "
            "plan.cache-enabled, plan.cache-entries",
            bool,
            True,
        ),
        PropertyMetadata(
            "microbatch_wait_ms",
            "Micro-batched point-lookup serving: how long a dispatch-"
            "eligible statement may wait for concurrent same-"
            "fingerprint statements to group into ONE vmapped device "
            "dispatch (coordinator batch queue). 0 = off — the "
            "bit-exact pre-batching path, zero batches. Tier-1 twin: "
            "serving.microbatch-wait-ms",
            float,
            0.0,
            _non_negative("microbatch_wait_ms"),
        ),
        PropertyMetadata(
            "microbatch_max",
            "Largest micro-batch group (lanes of one batched "
            "dispatch). Tier-1 twin: serving.microbatch-max",
            int,
            16,
            _positive("microbatch_max"),
        ),
        PropertyMetadata(
            "enable_result_cache",
            "Serving-plane result reuse (server/result_cache.py): "
            "SELECT results cache on the canonical statement "
            "fingerprint x hoisted-literal vector x the snapshot ids "
            "pinned at plan time; a hit is zero planning and zero "
            "dispatch, invalidation is a snapshot/write-generation "
            "compare through the one audited write seam. False (the "
            "default) = bit-exact pre-cache behavior; every lane "
            "fails open to normal execution. Tier-1 twins: "
            "result-cache.enabled, result-cache.bytes",
            bool,
            False,
        ),
        PropertyMetadata(
            "result_cache_max_staleness_s",
            "Bounded-stale serving for cached SELECT results (the "
            "mview.max-staleness-s discipline generalized to tier-c "
            "reads): a result-cache entry invalidated by a write may "
            "still answer for this many seconds after going stale "
            "while ONE background refresh re-executes. 0 (the "
            "default) = stale entries never serve. Tier-1 twin: "
            "result-cache.max-staleness-s",
            float,
            0.0,
            _non_negative("result_cache_max_staleness_s"),
        ),
        PropertyMetadata(
            "mview_auto_rewrite",
            "MV-aware scan rewrite (server/result_cache.py): an "
            "eligible single-table aggregate SELECT whose shape "
            "matches a registered materialized view reads the "
            "maintained view instead of re-aggregating the base, "
            "without naming it — under the mview.max-staleness-s "
            "read-gate discipline (gate off = only provably-current "
            "views rewrite). False (the default) = no rewriting. "
            "Tier-1 twin: mview.auto-rewrite",
            bool,
            False,
        ),
        PropertyMetadata(
            "enable_operator_stats",
            "Trace per-operator output-row counters (plus static "
            "capacity/page-bytes) out of every compiled program and "
            "fold them into TaskStats/QueryStats as OperatorStats — "
            "the observability substrate history-based optimization "
            "reads. False = pre-PR programs with no counter outputs "
            "(one fewer traced scalar per operator)",
            bool,
            True,
        ),
        PropertyMetadata(
            "enable_history_stats",
            "Let optimizer.estimate_rows consult the query-history "
            "store (history.path) BEFORE connector stats: estimates "
            "for a previously-executed canonical plan shape come from "
            "observed actuals (Presto's history-based optimization). "
            "False — or no configured store — plans bit-exactly "
            "pre-history",
            bool,
            True,
        ),
        PropertyMetadata(
            "adaptive_enabled",
            "Adaptive execution (ROADMAP item 2 — Presto's HBO + "
            "adaptive-execution direction): statement-cache hits "
            "whose consulted history estimates have materially "
            "diverged REPLAN instead of serving the stale plan "
            "(epoch-versioned plan-cache entries), and the "
            "dynamic-filter build-summary barrier becomes a runtime "
            "decision point — observed build rows contradicting the "
            "estimate flip broadcast<->partitioned distribution, "
            "re-order the not-yet-scheduled join remainder, and "
            "resize the shuffle partition count. Every lane fails "
            "OPEN to the original plan. False (the default) = "
            "bit-exact pre-adaptive behavior",
            bool,
            False,
        ),
        PropertyMetadata(
            "adaptive_divergence_factor",
            "Relative change beyond which a learned/observed "
            "cardinality CONTRADICTS the estimate a plan was built "
            "on (symmetric ratio; shared by the replan seam and the "
            "runtime strategy switch). Tier-1 twin: "
            "adaptive.divergence-factor",
            float,
            4.0,
            _positive("adaptive_divergence_factor"),
        ),
        PropertyMetadata(
            "query_max_run_time_s",
            "Per-query wall-clock limit (seconds)",
            float,
            3600.0,
            _positive("query_max_run_time_s"),
        ),
        PropertyMetadata(
            "task_retry_budget",
            "Max task reassignments per query after connection-level "
            "worker failures (recoverable execution; generalizes the "
            "old retry-once-per-range — 0 disables retry entirely)",
            int,
            16,
            _non_negative("task_retry_budget"),
        ),
        PropertyMetadata(
            "speculation_enabled",
            "Straggler speculation on the gather path: re-launch a "
            "range on a second live worker when its task runs past the "
            "quantile-based threshold; first result wins, the loser is "
            "aborted (reference: MapReduce backup tasks)",
            bool,
            True,
        ),
        PropertyMetadata(
            "speculation_multiplier",
            "Straggler threshold = max(speculation_min_s, multiplier x "
            "p50 of this stage's completed-range durations)",
            float,
            4.0,
            _positive("speculation_multiplier"),
        ),
        PropertyMetadata(
            "speculation_min_s",
            "Floor of the straggler threshold (seconds) — speculation "
            "never fires on ranges faster than this",
            float,
            2.0,
            _positive("speculation_min_s"),
        ),
        PropertyMetadata(
            "retry_policy",
            "Fault-tolerant execution mode (reference: Trino Project "
            "Tardigrade's retry-policy). NONE = bit-for-bit legacy "
            "behavior; TASK = spool exchange pages (exchange.spool-path) "
            "and recover a dead worker mid-stage by rescheduling only "
            "the lost tasks, re-serving upstream inputs from the spool; "
            "QUERY = additionally allow a bounded full query restart as "
            "the last resort",
            str,
            "NONE",
            _retry_policy_value,
        ),
        PropertyMetadata(
            "query_retry_count",
            "Bounded full-query restarts under retry_policy=QUERY "
            "(0 disables query-level restart)",
            int,
            1,
            _non_negative("query_retry_count"),
        ),
        PropertyMetadata(
            "exchange_ici_enabled",
            "In-slice collective shuffle (server/exchange_spi.py): "
            "partitioned join/agg/distinct exchanges between workers "
            "co-located on one slice move device-to-device (no host "
            "copy, no serialization, no HTTP); cross-slice edges and "
            "recovery keep the HTTP/spool wire. False = bit-exact "
            "legacy HTTP shuffle. Seeded by tier-1 exchange.ici-enabled",
            bool,
            False,
        ),
        PropertyMetadata(
            "exchange_single_program",
            "Single-program collective stages (parallel/exchange.py): "
            "when every producer of a partitioned stage shares the "
            "mesh, the exchange compiles to ONE shard_map program "
            "whose all_to_all moves every partition in-program (one "
            "collective dispatch per stage instead of a per-source "
            "gather pass), transport settles per-EDGE (a lone "
            "cross-slice worker rides HTTP without demoting the "
            "co-located pairs), and the coordinator's final gather "
            "rides the ICI lane when the root stage is co-located. "
            "False = PR-14 per-source gather + all-or-nothing stage "
            "transport. Seeded by tier-1 exchange.single-program",
            bool,
            True,
        ),
    ]
}


class Session:
    """Per-query context: catalog/schema + typed session properties.

    Reference parity: presto Session + SystemSessionProperties resolution
    (typed, validated, defaulted from static config) — SURVEY.md §5.6.
    """

    def __init__(
        self,
        catalog: str = "tpch",
        schema: str = "tiny",
        properties: Optional[Dict[str, Any]] = None,
        user: str = "presto_tpu",
    ):
        self.catalog = catalog
        self.schema = schema
        self.user = user
        self._props: Dict[str, Any] = {}
        for k, v in (properties or {}).items():
            self.set(k, v)

    def set(self, name: str, value: Any) -> None:
        """SET SESSION name = value (unknown keys fail fast)."""
        meta = SYSTEM_SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        self._props[name] = meta.coerce(value)

    def get(self, name: str) -> Any:
        meta = SYSTEM_SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        return self._props.get(name, meta.default)

    def reset(self, name: str) -> None:
        self._props.pop(name, None)

    @property
    def tpu_offload(self) -> bool:
        return self.get("tpu_offload")


class NodeConfig:
    """Tier-1 static node config; unknown keys fail fast at boot."""

    KNOWN = {
        "node.id": str,
        "node.environment": str,
        "coordinator": bool,
        "http-server.port": int,
        "discovery.uri": str,
        "query.max-memory-per-node": str,
        # cluster memory governance (server/memory_arbiter.py): the
        # master gate (false = bit-exact pre-governance behavior), the
        # cluster-wide per-query cap, the admission high/low water
        # marks (fractions of the cluster's query-attributed capacity;
        # QUEUED queries are HELD, never failed, while over high
        # water), the blocked-reservation age that triggers the
        # low-memory killer, the longest a worker reservation may
        # block before failing, the victim policy
        # (total-reservation | last-admitted), and the host-RAM spill
        # budget for the degrade-before-kill lane
        "memory.governance-enabled": bool,
        "query.max-memory": str,
        "memory.admission-high-water": float,
        "memory.admission-low-water": float,
        "memory.blocked-timeout-s": float,
        "memory.reserve-block-max-s": float,
        "memory.kill-policy": str,
        "memory.host-spill-bytes": str,
        "exchange.max-buffer-size": str,
        "task.concurrency": int,
        # query-completed JSONL sink (reference: event-listener.properties)
        "event-listener.path": str,
        # unified RPC plane (server.rpc): per-call timeout + bounded
        # retries with exponential backoff + full jitter
        "rpc.request-timeout-s": float,
        "rpc.retries": int,
        "rpc.backoff-base-s": float,
        "rpc.backoff-max-s": float,
        # exchange pull pipelining: token-acked page-pull requests kept
        # in flight per pull loop (1 = strict request->ack->request)
        "rpc.pull-depth": int,
        # device-resident split cache: LRU byte budget for staged pages
        # kept across queries (0 disables), and the number of split
        # batches prefetch-staged ahead of device execution
        "staging.cache-bytes": str,
        "staging.prefetch-depth": int,
        # worker->coordinator announce cadence (healthy interval; the
        # failure backoff grows from it) and per-announce timeout
        "announcement.interval-s": float,
        "announcement.timeout-s": float,
        # per-worker circuit breaker: consecutive connection failures
        # to OPEN, and the OPEN cool-off before the half-open probe
        "failure-detector.threshold": int,
        "failure-detector.open-s": float,
        # distributed dynamic filtering: bounded wait for the build
        # summary before probe scheduling proceeds unfiltered, and the
        # NDV cap for IN-list summaries (exec/dynfilter.py)
        "dynamic-filtering.wait-ms": float,
        "dynamic-filtering.ndv-limit": int,
        # durable-exchange spool (server.spool): shared directory the
        # workers tee partitioned exchange pages into under
        # retry_policy=TASK/QUERY, its byte budget, and the TTL after
        # which committed attempts are garbage-collected
        "exchange.spool-path": str,
        "exchange.spool-bytes": str,
        "exchange.spool-ttl-s": float,
        # ICI-native collective shuffle (server/exchange_spi.py): the
        # master gate (false = bit-exact legacy HTTP shuffle; seeds the
        # exchange_ici_enabled session default) and an explicit slice
        # identity override — by default a worker derives its slice
        # from platform + host process, the co-location the in-slice
        # exchange segment actually requires
        "exchange.ici-enabled": bool,
        "exchange.slice-id": str,
        # single-program collective stages (PR 18): when every producer
        # of a partitioned stage shares the mesh, compile ONE
        # shard_map/all_to_all program per stage instead of per-source
        # gather passes, and publish single-partition (gather) root
        # output on the ICI lane too (true by default; the collective
        # path fails open to the per-source gather). The drain depth
        # bounds the background spool-tee queue (retry_policy=TASK)
        # before producers feel backpressure.
        "exchange.single-program": bool,
        "exchange.spool-drain-depth": int,
        # parameterized plan cache (plan/canonical.py): LRU entry bound
        # of the statement-level cache, and the enable_plan_cache
        # session default seed
        "plan.cache-entries": int,
        "plan.cache-enabled": bool,
        # micro-batched point-lookup serving (server/coordinator.py
        # batch queue + the vmapped compile entries in
        # plan/canonical.py): the hold window concurrent same-
        # fingerprint statements may wait to share ONE device
        # dispatch (0 = off, bit-exact pre-batching) and the largest
        # group size. Seed the microbatch_wait_ms / microbatch_max
        # session defaults
        "serving.microbatch-wait-ms": float,
        "serving.microbatch-max": int,
        # history-based statistics (plan/history.py): directory of the
        # crash-safe JSONL history store and its entry bound; the
        # optimizer consults observed per-operator actuals keyed by
        # canonical plan fingerprints before connector stats
        "history.path": str,
        "history.max-entries": int,
        # adaptive execution (epoch-versioned plan cache + runtime
        # join-strategy switching at the dynamic-filter build-summary
        # barrier): the master gate (false = bit-exact pre-adaptive;
        # seeds the adaptive_enabled session default) and the shared
        # divergence factor — relative change beyond which a learned /
        # observed cardinality contradicts the estimate a plan was
        # built on (bumps history epochs, triggers replans and
        # broadcast<->partitioned switches)
        "adaptive.enabled": bool,
        "adaptive.divergence-factor": float,
        # per-operator observability (exec/stats.OperatorStats): seeds
        # the enable_operator_stats session default
        "operator-stats.enabled": bool,
        # slow-query log: queries over the threshold append their
        # EXPLAIN ANALYZE text + plan fingerprint to the JSONL sidecar
        # (threshold absent/<=0 = off)
        "slow-query.threshold-ms": float,
        "slow-query.path": str,
        # seeds the session retry_policy default (NONE | TASK | QUERY)
        "retry-policy": str,
        # worker drain: how long a draining worker waits for running
        # tasks to finish and buffered output to be pulled/spooled
        # before exiting
        "drain.grace-s": float,
        # durable coordinator state (server.journal): directory of the
        # crash-safe admission journal; a restarted coordinator replays
        # it and re-admits every non-terminal query
        "coordinator.journal-path": str,
        # multi-coordinator control plane (server/lease.py): comma-
        # separated peer coordinator URIs. Set, the journal path
        # becomes a SHARED control directory — this coordinator
        # journals under <path>/<node.id>/, publishes a TTL'd lease
        # file carrying its admission/memory/QoS occupancy and open
        # statement ids, announces itself to every peer
        # (role=coordinator), and claims + resumes a dead peer's open
        # queries when that peer's lease expires (fencing epoch
        # prevents split-brain double-claims). Unset (the default) the
        # lease plane never constructs — single-coordinator deploys
        # are bit-exact pre-HA.
        "coordinator.peers": str,
        # lease TTL: a coordinator lease not renewed for this long is
        # expired and its journal claimable (renewal runs at TTL/3)
        "lease.ttl-s": float,
        # worker orphan-task reaper: tasks whose minting coordinator
        # incarnation (the qid boot nonce) has not heartbeated for
        # this long are DELETEd through the normal teardown path,
        # releasing their buffer-pool reservations. <=0 (the default)
        # disables the reaper — bit-exact pre-reaper behavior.
        "task.orphan-ttl-s": float,
        # elastic worker pool (server.pool): autoscaler bounds, control
        # cadence, and hysteresis (consecutive idle ticks before a
        # scale-down, cooldown after any scaling action)
        "pool.min-workers": int,
        "pool.max-workers": int,
        "pool.scale-interval-s": float,
        "pool.scale-down-ticks": int,
        "pool.cooldown-s": float,
        # preemptible capacity: marks this worker preemptible (announced
        # to discovery; gather/merge stages prefer stable nodes) and the
        # short drain grace a preemption notice gets
        "node.preemptible": bool,
        "pool.preempt-grace-s": float,
        # streaming ingest lane (server/ingest.py): directory of the
        # per-table crc32-framed WALs (unset = the lane never
        # constructs; legacy INSERT/CTAS bit-exact) and the commit-loop
        # cadence folding pending micro-batches into snapshots
        "ingest.wal-path": str,
        "ingest.commit-interval-ms": float,
        # durable lakehouse (server/manifests.py): root of the
        # manifest-committed table format (unset = no manifests, no
        # compaction thread; ingest commits stay WAL-only bit-exact),
        # the data-file size compaction targets, background-compaction
        # cadence and trigger threshold, and the orphan GC TTL (also
        # the time-travel retention window)
        "lakehouse.path": str,
        "lakehouse.target-file-bytes": str,
        "lakehouse.compaction.interval-s": float,
        "lakehouse.compaction.min-files": int,
        "lakehouse.orphan-ttl-s": float,
        # materialized views (exec/mview.py): the staleness bound the
        # read gate enforces over views of legacy-written bases, and
        # the master switch for incremental (delta-merge) maintenance
        # (false = every maintenance event is a full refresh)
        "mview.max-staleness-s": float,
        "mview.incremental-enabled": bool,
        # serving-plane result reuse (server/result_cache.py): the
        # master gate (false = bit-exact pre-cache), the LRU byte
        # budget charged to the MemoryPool's result-cache owner, the
        # bounded-stale serving window for invalidated entries, and
        # the MV-aware scan-rewrite gate
        "result-cache.enabled": bool,
        "result-cache.bytes": str,
        "result-cache.max-staleness-s": float,
        "mview.auto-rewrite": bool,
        # tail-latency QoS plane (server/qos.py): the master gate
        # (false = bit-exact legacy admission), the post-resume grace
        # during which a resumed query is immune to re-suspension, and
        # the lifetime suspension cap per query. Per-group keys
        # (qos.<group>.priority / qos.<group>.target-p99-ms) are
        # accepted dynamically — see _QOS_GROUP_KEY below
        "qos.enabled": bool,
        "qos.resume-grace-s": float,
        "qos.max-suspensions-per-query": int,
        # deterministic chaos: JSON FaultPlane spec (utils.faults)
        "fault-injection.spec": str,
        # device-plane telemetry (utils/telemetry.py): the master gate
        # for the dispatch/transfer/compile counters (false = zero
        # counter delta, bit-exact results either way), the cluster
        # sampler cadence (<=0 = sampler off — the default; when on,
        # the coordinator scrapes itself + every announced worker each
        # interval into the metrics_history ring), the ring-buffer row
        # bound, and the optional JSONL persistence path (journal
        # segment idiom, newest two segments kept)
        "telemetry.enabled": bool,
        "telemetry.sample-interval-s": float,
        "telemetry.retention": int,
        "telemetry.path": str,
    }

    #: dynamic per-group QoS keys: qos.<group>.priority (int) and
    #: qos.<group>.target-p99-ms (float) — group names are config
    #: data, so they cannot enumerate in KNOWN
    _QOS_GROUP_KEY = re.compile(
        r"^qos\.([A-Za-z0-9_\-]+)\.(priority|target-p99-ms)$"
    )

    def __init__(self, props: Optional[Dict[str, str]] = None):
        self.props: Dict[str, Any] = {}
        for k, v in (props or {}).items():
            t = self.KNOWN.get(k)
            if t is None:
                m = self._QOS_GROUP_KEY.match(k)
                if m is None:
                    raise KeyError(f"unknown config key: {k}")
                t = int if m.group(2) == "priority" else float
            self.props[k] = (
                v.lower() == "true" if t is bool and isinstance(v, str) else t(v)
            )

    def get(self, key: str, default=None):
        return self.props.get(key, default)
