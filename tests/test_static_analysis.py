"""The ONE static-analysis gate: every framework pass runs over
``presto_tpu/`` and must report zero unsuppressed findings, plus
synthetic positive/negative fixtures for the concurrency detectors
and the legacy-shim contracts.

This file replaces the per-suite lint wiring that used to live in
test_faults / test_staging_cache / test_dynfilter / test_spool /
test_elastic / test_history_stats / test_memory_governance /
test_observability / test_plan_cache — the nine ``tools/check_*.py``
CLIs still exit 0/1 exactly as before (proven here), but the rules
run once, inside ``tools/analysis``.

Reference parity: Presto gates merges with error-prone/checkstyle
custom bug patterns (concurrency ones included); the TPU-first
analogue is an AST framework that knows THIS engine's invariants —
lock order, blocking-under-lock, and plane confinement.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "presto_tpu")
sys.path.insert(0, os.path.join(REPO, "tools"))

import analysis  # noqa: E402
import analyze  # noqa: E402


# ------------------------------------------------------------ the gate


@pytest.fixture(scope="module")
def repo_findings():
    """One full-framework run over presto_tpu, shared by every
    assertion below."""
    return analysis.run_passes(SRC)


def test_all_passes_clean_on_repo(repo_findings):
    active = [f for f in repo_findings if f.active]
    assert not active, "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in active
    )


def test_blocking_allowlist_entries_all_live(repo_findings):
    """Every allowlist entry matches a real finding — a stale entry
    (site fixed or moved) must be deleted, not hoarded."""
    from analysis.allowlist import BLOCKING_ALLOWLIST

    # match each entry to its finding directly — several DISTINCT
    # blocking calls (open/fsync/replace) may report at the same
    # call-site line, so (rel, line) is not a usable identity
    stale = [
        (e.path, e.func, e.call)
        for e in BLOCKING_ALLOWLIST
        if not any(
            f.allowlisted
            and f.rel == e.path
            and f.message.startswith(f"blocking call {e.call} ")
            and f" in {e.func}" in f.message
            for f in repo_findings
        )
    ]
    assert not stale, f"allowlist has stale entries: {stale}"
    for f in repo_findings:
        if f.allowlisted:
            assert f.justification  # every exception carries its why


def test_every_rule_registered(repo_findings):
    rules = analysis.all_rules()
    for expected in (
        "lock-order",
        "blocking-under-lock",
        "plan-params",
        "history-sites",
        "serving-batch",
        "rpc-confinement",
        "staging-confinement",
        "dynfilter-confinement",
        "attempt-ids",
        "journal-sites",
        "ingest-frames",
        "reserve-sites",
        "qos-plane",
        "lease-plane",
        "result-cache-plane",
        "exchange-plane",
        "adaptive-plane",
        "metric-names",
    ):
        assert expected in rules


# ------------------------------------------- lock-order fixtures


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return p


def test_lock_order_reports_ab_ba_cycle(tmp_path):
    _write(
        tmp_path,
        "cycle.py",
        """\
        import threading


        class Pair:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()

            def forward(self):
                with self.lock_a:
                    with self.lock_b:
                        return 1

            def backward(self):
                with self.lock_b:
                    with self.lock_a:
                        return 2
        """,
    )
    found = analysis.run_passes(str(tmp_path), rules=["lock-order"])
    assert len(found) == 1
    msg = found[0].message
    # both witness paths are printed
    assert "lock_a -> cycle.Pair.lock_b" in msg
    assert "lock_b -> cycle.Pair.lock_a" in msg
    assert "Pair.forward" in msg and "Pair.backward" in msg


def test_lock_order_fixed_ordering_is_clean(tmp_path):
    _write(
        tmp_path,
        "ordered.py",
        """\
        import threading


        class Pair:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()

            def forward(self):
                with self.lock_a:
                    with self.lock_b:
                        return 1

            def backward(self):
                with self.lock_a:
                    with self.lock_b:
                        return 2
        """,
    )
    assert not analysis.run_passes(str(tmp_path), rules=["lock-order"])


def test_lock_order_sees_edits_between_runs(tmp_path):
    """The shared concurrency model is keyed by CONTENT: fixing a
    reported cycle and re-running the same process must go clean (a
    stale model would keep reporting the old parse)."""
    body = """\
        import threading


        class Pair:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()

            def forward(self):
                with self.lock_a:
                    with self.lock_b:
                        return 1

            def backward(self):
                with self.{first}:
                    with self.{second}:
                        return 2
        """
    _write(tmp_path, "c.py", body.format(first="lock_b", second="lock_a"))
    assert analysis.run_passes(str(tmp_path), rules=["lock-order"])
    _write(tmp_path, "c.py", body.format(first="lock_a", second="lock_b"))
    assert not analysis.run_passes(str(tmp_path), rules=["lock-order"])


def test_lock_order_cycle_through_call_edge(tmp_path):
    """A->B by nesting in one method, B->A through a method CALL while
    holding B — the interprocedural half of the detector."""
    _write(
        tmp_path,
        "callcycle.py",
        """\
        import threading


        class Store:
            def __init__(self):
                self.meta_lock = threading.Lock()
                self.data_lock = threading.Lock()

            def read(self):
                with self.meta_lock:
                    with self.data_lock:
                        return 1

            def _refresh_meta(self):
                with self.meta_lock:
                    return 2

            def write(self):
                with self.data_lock:
                    return self._refresh_meta()
        """,
    )
    found = analysis.run_passes(str(tmp_path), rules=["lock-order"])
    assert len(found) == 1
    assert "via call" in found[0].message


# --------------------------------------- blocking-under-lock fixtures


def test_blocking_reports_reintroduced_pr9_pattern(tmp_path):
    """The PR 9 review finding — device->host DMA under the
    split-cache lock — must be caught if anyone reintroduces it."""
    _write(
        tmp_path,
        "pr9.py",
        """\
        import threading


        class Cache:
            def __init__(self):
                self._lock = threading.RLock()

            def evict(self, page):
                with self._lock:
                    host = page_to_host(page)
                    return host
        """,
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )
    assert len(found) == 1
    assert "page_to_host" in found[0].message
    assert "device->host DMA" in found[0].message


def test_blocking_reports_rpc_sleep_and_file_io_under_lock(tmp_path):
    _write(
        tmp_path,
        "mixed.py",
        """\
        import threading
        import time

        from presto_tpu.server import rpc

        _mu = threading.Lock()


        def heartbeat(url):
            with _mu:
                rpc.call_json("GET", url)


        def backoff():
            with _mu:
                time.sleep(0.5)


        def journal(rec):
            with _mu:
                with open("/tmp/x", "a") as f:
                    f.write(rec)
        """,
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )
    whys = sorted(f.message for f in found)
    assert len(found) == 3
    assert any("rpc.call_json" in m for m in whys)
    assert any("time.sleep" in m for m in whys)
    assert any("open" in m for m in whys)


def test_blocking_dma_outside_lock_is_clean(tmp_path):
    """The FIXED shape (copy outside the critical section) passes —
    exactly what exec/staging.py does now."""
    _write(
        tmp_path,
        "fixed.py",
        """\
        import threading


        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self._entries = {}

            def evict(self, key):
                with self._lock:
                    page = self._entries.pop(key)
                host = page_to_host(page)
                return host
        """,
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )


def test_blocking_wait_on_own_condition_is_exempt(tmp_path):
    _write(
        tmp_path,
        "waits.py",
        """\
        import threading


        class Q:
            def __init__(self):
                self.cond = threading.Condition()
                self.aux = threading.Lock()

            def take(self):
                with self.cond:
                    self.cond.wait(timeout=0.1)

            def bad_take(self):
                with self.aux:
                    with self.cond:
                        self.cond.wait(timeout=0.1)
        """,
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )
    assert len(found) == 1  # only bad_take: aux held across the wait
    assert "bad_take" in found[0].message


def test_blocking_wait_propagates_through_call(tmp_path):
    """The offer_page shape: holding lock A, call a helper whose wait
    releases only ITS OWN condition — A stays wedged for the whole
    wait and must flag at the caller."""
    _write(
        tmp_path,
        "prop.py",
        """\
        import threading


        class Pool:
            def __init__(self):
                self._cond = threading.Condition()

            def reserve(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)


        class Task:
            def __init__(self):
                self.cond = threading.Condition()
                self.pool = Pool()

            def offer(self):
                with self.cond:
                    self.pool.reserve()
        """,
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )
    assert len(found) == 1
    assert "Task.offer" in found[0].message
    assert "Pool.reserve" in found[0].message


# --------------------------------------- suppressions, JSON, baseline


def test_inline_suppression_quiets_a_finding(tmp_path):
    _write(
        tmp_path,
        "s.py",
        """\
        import threading

        _mu = threading.Lock()


        def snooze():
            with _mu:
                time.sleep(1)  # lint: disable=blocking-under-lock
        """,
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["blocking-under-lock"]
    )
    assert len(found) == 1
    assert found[0].suppressed and not found[0].active
    assert analyze.main([str(tmp_path)]) == 0


def test_parse_error_is_a_finding(tmp_path):
    _write(tmp_path, "broken.py", "def f(:\n")
    found = analysis.run_passes(str(tmp_path), rules=["rpc-confinement"])
    assert [f.rule for f in found] == ["parse-error"]
    assert analyze.main([str(tmp_path)]) == 1


def test_json_output_stable_and_diffable(tmp_path, capsys):
    _write(
        tmp_path,
        "j.py",
        """\
        import threading

        _mu = threading.Lock()


        def f():
            with _mu:
                time.sleep(1)
        """,
    )
    assert analyze.main([str(tmp_path), "--json"]) == 1
    first = capsys.readouterr().out
    assert analyze.main([str(tmp_path), "--json"]) == 1
    second = capsys.readouterr().out
    assert first == second  # byte-stable across runs
    doc = json.loads(first)
    assert doc["version"] == 1
    assert doc["counts"]["active"] == 1
    f0 = doc["findings"][0]
    assert f0["rule"] == "blocking-under-lock"
    assert f0["path"] == "j.py" and f0["line"] == 8


def test_baseline_demotes_known_findings(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    _write(
        src,
        "old.py",
        """\
        import threading

        _mu = threading.Lock()


        def f():
            with _mu:
                time.sleep(1)
        """,
    )
    base = str(tmp_path / "baseline.json")
    # introduce warn-only: write the baseline, then the gate passes
    assert analyze.main([str(src), "--write-baseline", base]) == 1
    assert analyze.main([str(src), "--baseline", base]) == 0
    # a NEW finding is not covered by the old baseline
    _write(
        src,
        "new.py",
        """\
        import threading

        _mu = threading.Lock()


        def g():
            with _mu:
                time.sleep(2)
        """,
    )
    assert analyze.main([str(src), "--baseline", base]) == 1


def test_cli_runs_from_subprocess():
    """The acceptance-criteria spelling: ``python tools/analyze.py
    presto_tpu`` exits 0 on this tree."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"), SRC],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------- legacy CLI shims


def test_rpc_shim_clean_and_flags(tmp_path):
    import check_rpc_calls

    (tmp_path / "bad.py").write_text(
        "import urllib.request\n"
        "urllib.request.urlopen('http://example')\n"
    )
    assert check_rpc_calls.main([str(tmp_path)]) == 1


def test_device_put_shim_clean_and_flags(tmp_path):
    import check_device_puts

    (tmp_path / "anywhere.py").write_text(
        "import jax\njax.device_put([1, 2, 3])\n"
    )
    server_dir = tmp_path / "server"
    server_dir.mkdir()
    (server_dir / "boundary.py").write_text(
        "import jax.numpy as jnp\njnp.asarray([1, 2, 3])\n"
    )
    assert check_device_puts.main([str(tmp_path)]) == 1
    assert len(check_device_puts.scan(str(tmp_path))) == 2


def test_device_put_shim_allows_trace_time_asarray(tmp_path):
    import check_device_puts

    ops_dir = tmp_path / "ops"
    ops_dir.mkdir()
    (ops_dir / "kernel.py").write_text(
        "import jax.numpy as jnp\njnp.asarray([1, 2, 3])\n"
    )
    assert check_device_puts.main([str(tmp_path)]) == 0


def test_dynfilter_shim_clean_and_flags(tmp_path):
    import check_dynfilter_sites

    (tmp_path / "bad.py").write_text(
        "import jax.numpy as jnp\n"
        "lo = jnp.min(jnp.where(mask, keys, fill))\n"
        "s = FilterSummary(cols)\n"
    )
    assert check_dynfilter_sites.main([str(tmp_path)]) == 1
    assert len(check_dynfilter_sites.scan(str(tmp_path))) == 2


def test_attempt_id_shim_clean_and_flags(tmp_path):
    import check_attempt_ids

    (tmp_path / "bad.py").write_text(
        'task_id = f"{qid}.{uuid.uuid4().hex[:8]}"\n'
        'stage = task_id.split(".")[1]\n'
    )
    assert check_attempt_ids.main([str(tmp_path)]) == 1
    assert len(check_attempt_ids.scan(str(tmp_path))) == 2


def test_journal_shim_clean_and_flags(tmp_path):
    import check_journal_sites

    (tmp_path / "bad.py").write_text(
        "j = CoordinatorJournal(path)\n"
        'j.record_submit("q", "select 1")\n'
        'seg = open("journal-000001.jsonl", "a")\n'
    )
    assert check_journal_sites.main([str(tmp_path)]) == 1
    kinds = {k for _p, _l, k, _s in check_journal_sites.scan(
        str(tmp_path)
    )}
    assert kinds == {"frame", "consumer"}


def test_reserve_shim_clean_and_flags(tmp_path):
    import check_reserve_sites

    (tmp_path / "rogue.py").write_text(
        "from presto_tpu.utils.memory import MemoryPool\n"
        "pool = MemoryPool(100)\n"
        "pool.reserve('q', 10)\n"
        "pool.try_reserve('q', 10)\n"
        "# pool.reserve('commented', 1)\n"
    )
    assert check_reserve_sites.main([str(tmp_path)]) == 1
    assert len(check_reserve_sites.scan(str(tmp_path))) == 3


def test_plan_params_shim_clean_and_flags(tmp_path):
    import check_plan_params

    (tmp_path / "rogue.py").write_text(
        "from presto_tpu import expr as E\n"
        "p = E.RuntimeParam(0, None)\n"
        "cache = {}\n"
    )
    assert check_plan_params.main([str(tmp_path)]) == 1


def test_serving_batch_rule_flags_rogue_sites(tmp_path):
    """The micro-batch plane's privileged constructs flag outside
    their audited modules: raw vmap / stacking / batched-entry keys
    outside plan/canonical.py, queue keys outside the coordinator."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            import jax
            fn = jax.vmap(lambda p: p)
            stacked = stack_param_vectors(vectors, 4)
            entry = vmap_program(trace)
            key = batch_entry_key(cfp, True, True, 4)
            q = MicrobatchQueue(runner)
            gk = coord._microbatch_key(stmt_key)
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["serving-batch"])
    assert len(found) == 6
    assert all(f.rule == "serving-batch" for f in found)


def test_ingest_frames_rule_flags_rogue_sites(tmp_path):
    """The streaming-ingest lane's privileged constructs flag outside
    server/ingest.py: WAL frame construction/parsing, the on-disk
    ``wal-`` segment prefix, and commit_snapshot (snapshot-id
    minting)."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            line = _wal_frame(payload)
            rec = _parse_wal_line(raw)
            name = "wal-mem.default.ev.jsonl"
            n = conn.commit_snapshot(handle, delta, 7)
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["ingest-frames"])
    assert len(found) == 4
    assert all(f.rule == "ingest-frames" for f in found)


def test_ingest_frames_rule_clean_fixtures(tmp_path):
    """The audited module itself, attribute reads, and unrelated
    strings never flag — and the REPO is clean under the rule (frames
    and minting really are confined)."""
    mod = tmp_path / "server" / "ingest.py"
    mod.parent.mkdir()
    mod.write_text(
        textwrap.dedent(
            """
            def _wal_frame(payload):
                return payload

            def commit(conn, handle, delta):
                line = _wal_frame("x")
                path = "wal-a.b.c.jsonl"
                return conn.commit_snapshot(handle, delta, 1)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(conn, handle):
                # reads of the audited names are fine
                can = hasattr(conn, "commit_snapshot")
                sid = conn.current_snapshot_id(handle)
                pinned = conn.pin_snapshot(handle)
                s = "walrus-operator"  # not the wal- prefix
                return can, sid, pinned, s
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["ingest-frames"]
    )


def test_manifest_plane_rule_flags_rogue_sites(tmp_path):
    """The lakehouse commit protocol's privileged constructs flag
    outside server/manifests.py: frame construction/parsing, the
    three publication seams, the _current pointer name, and a rogue
    ManifestStore construction."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            line = _manifest_frame(payload)
            rec = _parse_manifest_line(raw)
            df = store._write_data_file(tk, 3, tbl)
            store._write_manifest(tk, m)
            store._swap_current(tdir, 3)
            ptr = "_current"
            s = ManifestStore("/lake")
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["manifest-plane"])
    assert len(found) == 7
    assert all(f.rule == "manifest-plane" for f in found)


def test_manifest_plane_rule_clean_fixtures(tmp_path):
    """The audited module itself and the audited ManifestStore
    consumer never flag; neither do reads of the public surface."""
    mod = tmp_path / "server" / "manifests.py"
    mod.parent.mkdir()
    mod.write_text(
        textwrap.dedent(
            """
            def _manifest_frame(payload):
                return payload

            def publish(self, tk, m, sid):
                line = _manifest_frame("x")
                self._write_manifest(tk, m)
                self._swap_current("d", sid)
                return "_current"
            """
        )
    )
    (tmp_path / "server" / "ingest.py").write_text(
        textwrap.dedent(
            """
            def attach(path):
                return ManifestStore(path)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(store, tk):
                # the public read surface is unprivileged
                m = store.manifest(tk)
                sids = store.sids(tk)
                rows = store.read_values(tk)
                s = "_current_user"  # not the pointer name
                return m, sids, rows, s
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["manifest-plane"]
    )


def test_serving_batch_rule_clean_fixture(tmp_path):
    """Reads/isinstance checks and unrelated calls never flag."""
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(qs):
                return qs.batched, qs.batch_size

            def g(runner, plans, sinks):
                # attribute READS of the audited names are fine
                return runner.microbatch_plan_eligible
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["serving-batch"]
    )


def test_exchange_plane_rule_flags_rogue_sites(tmp_path):
    """The exchange plane's privileged constructs flag outside their
    audited modules: device collectives / ICI kernels outside
    parallel/exchange.py, the segment + emit/fetch surface outside
    server/exchange_spi.py (+ the worker), transport selection outside
    the scheduler."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            import jax
            r = jax.lax.all_to_all(x, "workers", 0, 0)
            g = jax.lax.all_gather(x, "workers")
            d = bucket_dest(page, crc, 4, ("k",))
            out = ici_append(out, page, dest, 0, 0, {})
            seg = IciSegment()
            emit_partitioned(task, page, slice_id="s", pool=None)
            ok = emit_gather(task, page, slice_id="s", pool=None)
            got = ici_fetch("s", spec, "t", 0.0, probe)
            merged = device_merge(batches, 0, schema)
            c = collective_counts(pages, dests, 4)
            o = collective_gather(pages, dests, (), {}, 4, 1024)
            p = collective_take(o, ("k",), 0, 256)
            m = collective_merge("s", srcs, batches, 0, schema, 4)
            pl = collective_payloads("s", srcs, batches, 0, schema, 4)
            gathered = ici_gather("s", spec, 0.0, probe)
            t = select_exchange_transport(workers, True, ())
            e = select_exchange_edges(workers, True, ())
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["exchange-plane"])
    assert len(found) == 17
    assert all(f.rule == "exchange-plane" for f in found)


def test_exchange_plane_rule_clean_fixtures(tmp_path):
    """The audited modules themselves and attribute reads never
    flag — and the REPO is clean under the rule (collectives and the
    exchange surface really are confined)."""
    kern = tmp_path / "parallel" / "exchange.py"
    kern.parent.mkdir()
    kern.write_text(
        textwrap.dedent(
            """
            import jax

            def partition_exchange(page, dest, n, axis, cap):
                return jax.lax.all_to_all(page, axis, 0, 0)

            def replicate(page, n, axis):
                return jax.lax.all_gather(page, axis)

            def collective_gather(pages, dests, remaps, dt, n, cap):
                return jax.lax.all_to_all(pages, "xparts", 0, 0)
            """
        )
    )
    spi = tmp_path / "server" / "exchange_spi.py"
    spi.parent.mkdir()
    spi.write_text(
        textwrap.dedent(
            """
            def emit(task, out, slice_id):
                dest = bucket_dest(out, {}, 4, ("k",))
                SEGMENT = IciSegment()
                return dest

            def merge(slice_id, srcs, batches, part, schema, n):
                counts = collective_counts(batches, None, n)
                out = collective_gather(batches, None, (), {}, n, 64)
                return collective_take(out, ("k",), part, 64)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(spec, seg):
                # reads of the audited names are fine
                s = spec.ici_slice
                n = seg.stats()["entries"]
                return s, n
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["exchange-plane"]
    )


def test_adaptive_plane_rule_flags_rogue_sites(tmp_path):
    """The adaptive-execution plane's privileged constructs flag
    outside their audited modules: epoch reads / the divergence test
    outside plan/history.py (+ the replan seam), the replan seam
    outside plan/canonical.py (+ the runner), strategy-switch
    construction outside the coordinator."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            e = store.epoch_of(fp)
            r = store.learned_rows(fp)
            d = diverged(est, observed, 4.0)
            s = stale_consults(entry.consulted, store, 4.0)
            with capture_consults() as con:
                pass
            note_estimate(node, 50.0)
            with with_overrides({"fp": 10.0}):
                pass
            out = coord._adaptive_maybe_switch(q, root, obs, workers)
            probe = coord._adaptive_probe_build(q, J, st, workers, {})
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["adaptive-plane"])
    assert len(found) == 9
    assert all(f.rule == "adaptive-plane" for f in found)


def test_adaptive_plane_rule_clean_fixtures(tmp_path):
    """The audited modules themselves and attribute reads never
    flag."""
    hist = tmp_path / "plan" / "history.py"
    hist.parent.mkdir()
    hist.write_text(
        textwrap.dedent(
            """
            def lookup_rows(node):
                con = capture_consults()
                return diverged(1.0, 2.0, 4.0)
            """
        )
    )
    (tmp_path / "plan" / "canonical.py").write_text(
        textwrap.dedent(
            """
            def stale(consulted, store, factor):
                if diverged(1.0, store.learned_rows("fp"), factor):
                    return store.epoch_of("fp")
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(store, entry, qs):
                # reads of the audited names are fine
                factor = store.divergence_factor
                con = entry.consulted
                flag = qs.replanned or qs.adapted
                return factor, con, flag
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["adaptive-plane"]
    )


def test_qos_plane_rule_flags_rogue_sites(tmp_path):
    """The QoS plane's privileged constructs flag outside their
    audited modules: controller construction / admission seams outside
    the coordinator, and the suspend-side-effect hooks (journal
    frames, arbiter release, spool progress) outside server/qos.py."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            ctl = QosController(coord, cfg, 4)
            ctl.qos_admit(q)
            ctl.qos_checkpoint(q)
            journal.record_suspend("q_c1", 1)
            journal.record_resume("q_c1", 5.0)
            arbiter.suspend_release("q_c1")
            n = spool.committed_for_query("q_c1")
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["qos-plane"])
    assert len(found) == 7
    assert all(f.rule == "qos-plane" for f in found)


def test_qos_plane_rule_clean_fixtures(tmp_path):
    """The audited module itself and attribute reads never flag."""
    mod = tmp_path / "server" / "qos.py"
    mod.parent.mkdir()
    mod.write_text(
        textwrap.dedent(
            """
            def suspend(coord, q, entry):
                n = coord.spool.committed_for_query(q.qid)
                coord.journal.record_suspend(q.qid, n)
                coord.arbiter.suspend_release(q.qid)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(coord, q):
                # reads of the audited names are fine
                has = coord.qos is not None
                susp = getattr(q, "qos_suspensions", 0)
                return has, susp
            """
        )
    )
    assert not analysis.run_passes(str(tmp_path), rules=["qos-plane"])


def test_lease_plane_rule_flags_rogue_sites(tmp_path):
    """The lease plane's privileged constructs flag outside
    server/lease.py + the coordinator: construction, expiry claims,
    fence checks, renewal, and the on-disk lease-/claim- file-name
    prefixes. Journal claim/alias frames flag with the journal rule."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            plane = LeasePlane("/tmp/x", "coord-1")
            plane.renew({"qids": []})
            claim = plane.claim_expired("coord-2")
            plane.check_fence(claim)
            name = "lease-coord-1.json"
            cname = "claim-coord-2.json"
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["lease-plane"])
    assert len(found) == 6
    assert all(f.rule == "lease-plane" for f in found)
    (tmp_path / "rogue2.py").write_text(
        textwrap.dedent(
            """
            j = journal.record_claim("coord-1", 3)
            journal.record_alias("q_c1_aaaaaa", "q_c1_bbbbbb")
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["journal-sites"])
    assert {f.path.split("/")[-1] for f in found} == {"rogue2.py"}
    assert len(found) == 2


def test_lease_plane_rule_clean_fixtures(tmp_path):
    """The audited modules and attribute/flag reads never flag."""
    srv = tmp_path / "server"
    srv.mkdir()
    (srv / "lease.py").write_text(
        textwrap.dedent(
            """
            _LEASE_PREFIX = "lease-"
            _CLAIM_PREFIX = "claim-"

            class LeasePlane:
                def renew(self, state=None):
                    pass
            """
        )
    )
    (srv / "coordinator.py").write_text(
        textwrap.dedent(
            """
            def loop(coord):
                coord.lease.renew(coord._lease_state())
                claim = coord.lease.claim_expired("coord-2")
                coord.lease.check_fence(claim)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(coord):
                # reads of the audited names are fine
                has = coord.lease is not None
                ttl = coord.lease.ttl_s if has else 0.0
                return has, ttl
            """
        )
    )
    assert not analysis.run_passes(str(tmp_path), rules=["lease-plane"])


def test_result_cache_plane_rule_flags_rogue_sites(tmp_path):
    """The result-reuse plane's privileged constructs flag outside
    server/result_cache.py + its audited consumers: cache
    construction, key minting, snapshot-vector probing, the MV
    rewrite seam, and the refresh CAS pair."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            rc = ResultCache(runner, 1 << 20)
            key = statement_key(stmt, session)
            vec = snapshot_vector(handles, catalogs)
            got = mview_rewrite(stmt, registry, session)
            ok = rc.claim_refresh(entry)
            rc.finish_refresh(entry)
            """
        )
    )
    found = analysis.run_passes(
        str(tmp_path), rules=["result-cache-plane"]
    )
    assert len(found) == 6
    assert all(f.rule == "result-cache-plane" for f in found)


def test_result_cache_plane_rule_clean_fixtures(tmp_path):
    """The audited modules and attribute/stats reads never flag."""
    srv = tmp_path / "server"
    srv.mkdir()
    (srv / "result_cache.py").write_text(
        textwrap.dedent(
            """
            def statement_key(stmt, session):
                return None

            def snapshot_vector(handles, catalogs):
                return ()

            class ResultCache:
                pass
            """
        )
    )
    (srv / "coordinator.py").write_text(
        textwrap.dedent(
            """
            def seed(coord, runner, budget):
                coord.result_cache = ResultCache(runner, budget)
                key = statement_key(stmt, runner.session)
                if coord.result_cache.claim_refresh(entry):
                    coord.result_cache.finish_refresh(entry)
            """
        )
    )
    ex = tmp_path / "exec"
    ex.mkdir()
    (ex / "local_runner.py").write_text(
        textwrap.dedent(
            """
            def plan_seam(stmt, registry, session):
                return mview_rewrite(stmt, registry, session)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f(coord):
                # reads of the audited names are fine
                rc = coord.result_cache
                st = rc.stats() if rc is not None else {}
                return st.get("hits", 0)
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["result-cache-plane"]
    )


def test_history_shim_clean_and_flags(tmp_path):
    import check_history_sites

    (tmp_path / "bad.py").write_text(
        "store = QueryHistoryStore('/tmp/x')\n"
        "rows = lookup_rows(node)\n"
        "fp = node_fingerprint(node)\n"
        # an exempt READ on the same line must not hide the call
        "ts.plan_fingerprint = plan_history.plan_fingerprint(root)\n"
    )
    assert check_history_sites.main([str(tmp_path)]) == 1
    assert len(check_history_sites.scan(str(tmp_path))) == 4


def test_metric_shim_clean_and_flags(tmp_path):
    import check_metric_names

    (tmp_path / "bad.py").write_text(
        'REGISTRY.counter("dup.name").update()\n'
        'REGISTRY.timer("dup.name").time()\n'
    )
    assert check_metric_names.main([str(tmp_path)]) == 1


def test_metric_names_resolve_loop_registration(tmp_path):
    """The PR 7-9 coverage gap: families registered through a loop
    variable (the Autoscaler pattern) now participate in conflict
    detection — the regex predecessor skipped them entirely."""
    import check_metric_names

    (tmp_path / "fam.py").write_text(
        "for m in (\n"
        '    "pool.scale_up",\n'
        '    "pool.scale_down",\n'
        "):\n"
        "    REGISTRY.counter(m)\n"
        'REGISTRY.distribution("pool.scale_up").add(1)\n'
    )
    assert check_metric_names.main([str(tmp_path)]) == 1
    sites = check_metric_names.scan(str(tmp_path))
    assert "pool.scale_down" in sites  # loop names resolved
    conflicts = check_metric_names.find_conflicts(sites)
    assert [name for name, _ in conflicts] == ["pool.scale_up"]


def test_loop_registered_families_visible_on_repo():
    """The live Autoscaler families are actually in the scanned set."""
    import check_metric_names

    sites = check_metric_names.scan(SRC)
    for fam in (
        "pool.scale_up",
        "pool.scale_down",
        "pool.preemptions",
        "history.hit",
        "journal.writes",
        "memory.queries_killed",
        "spill.pages_spilled",
    ):
        assert fam in sites, fam


# ------------------------------------------------- telemetry plane


def test_telemetry_plane_rule_flags_rogue_sites(tmp_path):
    """The device-telemetry plane's privileged constructs flag outside
    their audited modules: counter increments outside the
    runner/staging/exchange choke points, sampler + federation
    construction outside the coordinator, probes outside the worker
    boot seam, the history-derived progress denominator outside
    plan/history.py (+ the coordinator)."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            t = DeviceTelemetry()
            DEVICE.count_dispatch()
            DEVICE.count_compile(12.5)
            DEVICE.count_h2d(1024)
            DEVICE.count_d2h(1024)
            DEVICE.count_padding(10, 16)
            runner._fold_device_stat(device_dispatches=1)
            fed = MetricsFederation(lambda uri: "")
            samp = MetricsSampler(retention=16)
            diag = probe_backend()
            record_diag(diag)
            rows = progress_total_rows(store, root)
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["telemetry-plane"])
    assert len(found) == 12
    assert all(f.rule == "telemetry-plane" for f in found)


def test_telemetry_plane_rule_clean_fixtures(tmp_path):
    """The audited modules themselves never flag — and snapshot reads
    (what bench/tests consume) are not confined at all."""
    runner = tmp_path / "exec" / "local_runner.py"
    runner.parent.mkdir()
    runner.write_text(
        textwrap.dedent(
            """
            def run(self, d2h):
                DEVICE.count_dispatch()
                DEVICE.count_d2h(d2h)
                self._fold_device_stat(device_dispatches=1)
            """
        )
    )
    staging = tmp_path / "exec" / "staging.py"
    staging.write_text(
        textwrap.dedent(
            """
            def stage(page, n, cap):
                DEVICE.count_h2d(1024)
                DEVICE.count_padding(n, cap)
            """
        )
    )
    (tmp_path / "ok.py").write_text(
        textwrap.dedent(
            """
            def f():
                # snapshot reads are not privileged
                snap = device_snapshot()
                d = last_diag_dict()
                return snap, d
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["telemetry-plane"]
    )


def test_metric_family_confinement_flags_rogue_registration(tmp_path):
    """A device.*/telemetry.* metric registered outside the owning
    modules is a metric-names finding, including loop-registered
    names."""
    (tmp_path / "rogue.py").write_text(
        textwrap.dedent(
            """
            REGISTRY.counter("device.dispatches")
            for m in ("telemetry.samples", "telemetry.scrape_failures"):
                REGISTRY.counter(m)
            """
        )
    )
    found = analysis.run_passes(str(tmp_path), rules=["metric-names"])
    assert len(found) == 3
    assert all("owning modules" in f.message for f in found)


def test_metric_family_confinement_clean_in_owner(tmp_path):
    """The same registrations inside utils/telemetry.py (and the diag
    counters in utils/devicediag.py) are clean."""
    tele = tmp_path / "utils" / "telemetry.py"
    tele.parent.mkdir()
    tele.write_text(
        textwrap.dedent(
            """
            REGISTRY.counter("device.dispatches")
            REGISTRY.counter("telemetry.samples")
            """
        )
    )
    diag = tmp_path / "utils" / "devicediag.py"
    diag.write_text(
        textwrap.dedent(
            """
            REGISTRY.counter("device.probes")
            REGISTRY.counter("device.probe_failures")
            """
        )
    )
    assert not analysis.run_passes(
        str(tmp_path), rules=["metric-names"]
    )


def test_device_families_visible_on_repo():
    """The live device/telemetry families are in the scanned set, in
    their owning modules only."""
    from analysis import metric_names

    mods, _errs = analysis.core.load_modules(SRC)
    sites = metric_names.collect_sites(mods)
    for fam in (
        "device.dispatches",
        "device.compiles",
        "device.compile_ms",
        "device.h2d_bytes",
        "device.d2h_bytes",
        "device.probes",
        "telemetry.samples",
        "telemetry.scrape_failures",
    ):
        assert fam in sites, fam
    assert not metric_names.find_family_violations(sites)
