"""Expression lowering + fused filter/project tests (SURVEY.md §7 step 2).

Hand-built Pages in the style of the reference's operator unit tests
(SURVEY.md §4.1, assertOperatorEquals pattern).
"""

import datetime

import jax
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.expr import (
    And,
    Arithmetic,
    Between,
    Case,
    Cast,
    ColumnRef,
    Coalesce,
    Compare,
    Extract,
    InList,
    IsNull,
    Like,
    Literal,
    Not,
    Or,
    arith,
    eval_expr,
    eval_predicate,
    like_to_regex,
)
from presto_tpu.ops import filter_project, project
from presto_tpu.page import Page


def make_page(**cols):
    """Build a page from name=(values, type) kwargs."""
    data = {k: v[0] for k, v in cols.items()}
    schema = {k: v[1] for k, v in cols.items()}
    return Page.from_pydict(data, schema)


def col(page, name):
    return ColumnRef(name, page.schema()[name])


def test_arith_decimal_exact():
    p = make_page(
        price=([10.25, 99.99, 0.01], T.decimal(12, 2)),
        disc=([0.05, 0.00, 0.10], T.decimal(12, 2)),
    )
    # price * (1 - disc): the TPC-H Q1 expression
    one = Literal(100, T.decimal(12, 2))  # unscaled for scale 2
    e = arith("*", col(p, "price"), arith("-", one, col(p, "disc")))
    assert e.dtype.is_decimal and e.dtype.scale == 4
    d, v = eval_expr(e, p)
    assert v is None
    # 10.25*0.95 = 9.7375 -> unscaled 97375 at scale 4
    assert np.asarray(d)[:3].tolist() == [97375, 999900, 90]


def test_arith_null_propagation():
    p = make_page(a=([1, None, 3], T.BIGINT), b=([10, 20, None], T.BIGINT))
    d, v = eval_expr(arith("+", col(p, "a"), col(p, "b")), p)
    assert list(np.asarray(v)) == [True, False, False]
    assert int(np.asarray(d)[0]) == 11


def test_division_semantics():
    p = make_page(a=([7, -7, 5], T.BIGINT), b=([2, 2, 0], T.BIGINT))
    d, v = eval_expr(arith("/", col(p, "a"), col(p, "b")), p)
    # SQL integer division truncates toward zero; x/0 -> NULL
    assert np.asarray(d)[:2].tolist() == [3, -3]
    assert list(np.asarray(v)) == [True, True, False]


def test_kleene_and_or():
    p = make_page(
        a=([True, True, None, False], T.BOOLEAN),
        b=([True, None, None, None], T.BOOLEAN),
    )
    d, v = eval_expr(And((col(p, "a"), col(p, "b"))), p)
    # T&T=T, T&N=N, N&N=N, F&N=F (false dominates)
    vals = np.asarray(d)
    valid = np.asarray(v)
    assert (valid[0], bool(vals[0])) == (True, True)
    assert not valid[1] and not valid[2]
    assert valid[3] and not vals[3]
    d, v = eval_expr(Or((col(p, "a"), col(p, "b"))), p)
    # T|T=T, T|N=T (true dominates), N|N=N, F|N=N
    valid = np.asarray(v)
    assert valid[0] and valid[1] and not valid[2] and not valid[3]


def test_string_compares_and_like():
    p = make_page(s=(["apple", "banana", None, "cherry"], T.VARCHAR))
    d, v = eval_expr(Compare("=", col(p, "s"), Literal("banana", T.VARCHAR)), p)
    assert list(np.asarray(d))[:2] == [False, True]
    assert not np.asarray(v)[2]
    d, _ = eval_expr(Compare("<", col(p, "s"), Literal("b", T.VARCHAR)), p)
    assert list(np.asarray(d))[:2] == [True, False]
    # literal absent from dictionary: range still correct
    d, _ = eval_expr(Compare(">=", col(p, "s"), Literal("bb", T.VARCHAR)), p)
    assert [bool(x) for x in np.asarray(d)[:4:3]] == [False, True]
    d, _ = eval_expr(Like(col(p, "s"), "%an%"), p)
    assert [bool(x) for x in np.asarray(d)[:2]] == [False, True]
    d, _ = eval_expr(InList(col(p, "s"), (Literal("apple", T.VARCHAR), Literal("zzz", T.VARCHAR))), p)
    assert [bool(x) for x in np.asarray(d)[:2]] == [True, False]


def test_like_regex_translation():
    assert like_to_regex("a%b_c").match("aXXbYc")
    assert not like_to_regex("a%b_c").match("aXXbYYc")
    assert like_to_regex("10.5%").match("10.5extra")
    assert not like_to_regex("10.5%").match("1035")


def test_between_case_cast_coalesce():
    p = make_page(x=([1, 5, 10, None], T.BIGINT))
    d, v = eval_expr(Between(col(p, "x"), Literal(2, T.BIGINT), Literal(9, T.BIGINT)), p)
    assert [bool(b) for b in np.asarray(d)[:3]] == [False, True, False]
    assert not np.asarray(v)[3]

    c = Case(
        whens=((Compare("<", col(p, "x"), Literal(5, T.BIGINT)), Literal(1, T.BIGINT)),),
        default=Literal(0, T.BIGINT),
        _dtype=T.BIGINT,
    )
    d, v = eval_expr(c, p)
    assert np.asarray(d)[:3].tolist() == [1, 0, 0]

    d, v = eval_expr(Cast(col(p, "x"), T.decimal(10, 2)), p)
    assert np.asarray(d)[:3].tolist() == [100, 500, 1000]

    d, v = eval_expr(Coalesce((col(p, "x"), Literal(-1, T.BIGINT)), T.BIGINT), p)
    assert np.asarray(d)[3] == -1 or not (v is not None and not np.asarray(v)[3])


def test_extract_dates():
    days = [
        (datetime.date(1995, 3, 15) - datetime.date(1970, 1, 1)).days,
        (datetime.date(1970, 1, 1) - datetime.date(1970, 1, 1)).days,
        (datetime.date(1969, 12, 31) - datetime.date(1970, 1, 1)).days,
        (datetime.date(2000, 2, 29) - datetime.date(1970, 1, 1)).days,
    ]
    p = make_page(d=(days, T.DATE))
    y, _ = eval_expr(Extract("year", col(p, "d")), p)
    m, _ = eval_expr(Extract("month", col(p, "d")), p)
    dd, _ = eval_expr(Extract("day", col(p, "d")), p)
    assert np.asarray(y).tolist() == [1995, 1970, 1969, 2000]
    assert np.asarray(m).tolist() == [3, 1, 12, 2]
    assert np.asarray(dd).tolist() == [15, 1, 31, 29]


def test_filter_project_end_to_end():
    p = make_page(
        k=([1, 2, 3, 4, 5], T.BIGINT),
        price=([10.00, 20.00, 30.00, 40.00, 50.00], T.decimal(10, 2)),
        tag=(["a", "b", "a", "c", "a"], T.VARCHAR),
    )
    pred = And(
        (
            Compare(">", col(p, "k"), Literal(1, T.BIGINT)),
            Compare("=", col(p, "tag"), Literal("a", T.VARCHAR)),
        )
    )
    out = jax.jit(
        lambda page: filter_project(
            page,
            pred,
            [
                ("k", col(p, "k")),
                ("double_price", arith("*", col(p, "price"), Literal(2, T.BIGINT))),
                ("tag", col(p, "tag")),
            ],
        )
    )(p)
    rows = out.to_pylist()
    assert [r["k"] for r in rows] == [3, 5]
    assert [r["double_price"] for r in rows] == [60.0, 100.0]
    assert [r["tag"] for r in rows] == ["a", "a"]


def test_filter_null_is_false():
    p = make_page(x=([1, None, 3], T.BIGINT))
    mask = eval_predicate(Compare(">", col(p, "x"), Literal(0, T.BIGINT)), p)
    assert [bool(b) for b in np.asarray(mask)] == [True, False, True]


def test_project_scalar_broadcast():
    p = make_page(x=([1, 2], T.BIGINT))
    out = project(p, [("one", Literal(1, T.BIGINT)), ("x", col(p, "x"))])
    assert [r["one"] for r in out.to_pylist()] == [1, 1]
