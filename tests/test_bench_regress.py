"""tools/check_bench_regress.py: the bench-trajectory gate.

Fixture-driven: synthetic BENCH_*.json artifacts exercise the skip
contract (modern ``skipped: true`` lines, the legacy r04/r05
``value: 0`` + ``error`` shape, null values), both unit directions,
and the consecutive-pair diffing — plus the real repo artifacts,
which must never fail the gate (r04/r05 carry error lines)."""

import glob
import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"),
)

from check_bench_regress import (  # noqa: E402
    check_files,
    compare,
    is_skipped,
    main,
    parse_artifact,
    parse_lines,
)


def _artifact(tmp_path, name, lines, parsed=None):
    tail = "\n".join(json.dumps(ln) for ln in lines)
    p = tmp_path / name
    p.write_text(
        json.dumps(
            {"n": 1, "cmd": "bench", "rc": 0, "tail": tail,
             "parsed": parsed}
        )
    )
    return str(p)


def _line(metric, value, unit="rows/s", **kw):
    out = {"metric": metric, "value": value, "unit": unit}
    out.update(kw)
    return out


# ------------------------------------------------------ skip contract


def test_skipped_flag_is_skip():
    assert is_skipped(
        {"metric": "m", "skipped": True, "unit": "rows/s",
         "error": "X: boom"}
    )


def test_legacy_error_beside_value_is_skip():
    # the r04/r05 pre-contract shape: a zero that was never measured
    assert is_skipped(
        {"metric": "m", "value": 0, "unit": "rows/s", "error": "X"}
    )


def test_null_or_missing_value_is_skip():
    assert is_skipped({"metric": "m", "value": None, "unit": "x"})
    assert is_skipped({"metric": "m", "unit": "x"})
    assert is_skipped({"metric": "m", "value": True, "unit": "x"})
    assert not is_skipped({"metric": "m", "value": 3.5, "unit": "x"})


def test_skipped_lines_never_flag():
    prev = {"m": _line("m", 1000)}
    cur = {"m": _line("m", 0, error="backend died")}
    assert compare(prev, cur) == []
    # and a skip as the BASELINE must not make the recovery round
    # look like a regression (or crash on the missing value)
    assert compare(cur, prev) == []


# --------------------------------------------------------- directions


def test_throughput_drop_flags():
    prev = {"m": _line("m", 1000)}
    cur = {"m": _line("m", 700)}
    (f,) = compare(prev, cur)
    assert f["metric"] == "m" and f["change_pct"] == -30.0


def test_throughput_drop_within_threshold_passes():
    assert compare({"m": _line("m", 1000)}, {"m": _line("m", 850)}) == []


def test_latency_rise_flags():
    prev = {"p99": _line("p99", 10.0, unit="ms")}
    cur = {"p99": _line("p99", 14.0, unit="ms")}
    (f,) = compare(prev, cur)
    assert f["metric"] == "p99" and f["change_pct"] == 40.0


def test_latency_drop_is_improvement():
    prev = {"p99": _line("p99", 14.0, unit="ms")}
    cur = {"p99": _line("p99", 7.0, unit="ms")}
    assert compare(prev, cur) == []


def test_zero_baseline_never_divides():
    prev = {"m": _line("m", 0.0, unit="x")}
    cur = {"m": _line("m", 5.0, unit="x")}
    assert compare(prev, cur) == []


# ------------------------------------------------------------ parsing


def test_parse_lines_skips_noise_and_keeps_last():
    tail = "\n".join(
        [
            "WARNING: not json",
            json.dumps(_line("m", 10)),
            "{torn json",
            json.dumps(_line("m", 20)),
        ]
    )
    lines = parse_lines(tail)
    assert lines["m"]["value"] == 20


def test_parse_artifact_parsed_backstops_truncated_tail():
    obj = {"tail": "no json here", "parsed": _line("hl", 42)}
    assert parse_artifact(obj)["hl"]["value"] == 42


# ------------------------------------------------- end-to-end on files


def test_check_files_consecutive_pairs(tmp_path):
    a = _artifact(tmp_path, "BENCH_t01.json", [_line("m", 1000)])
    b = _artifact(tmp_path, "BENCH_t02.json", [_line("m", 950)])
    c = _artifact(tmp_path, "BENCH_t03.json", [_line("m", 600)])
    findings, pairs = check_files([a, b, c])
    assert pairs == 2
    # only the b->c drop flags; a->c (non-consecutive, -40%) is not
    # a pair the gate judges
    (f,) = findings
    assert f["from"] == "BENCH_t02.json" and f["to"] == "BENCH_t03.json"


def test_main_exit_codes(tmp_path, capsys):
    a = _artifact(tmp_path, "BENCH_t01.json", [_line("m", 1000)])
    b = _artifact(tmp_path, "BENCH_t02.json", [_line("m", 100)])
    assert main([a, b]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    skip = _artifact(
        tmp_path, "BENCH_t03.json",
        [{"metric": "m", "skipped": True, "unit": "rows/s",
          "error": "X"}],
    )
    assert main([a, skip]) == 0
    assert main([a]) == 0  # one artifact: nothing to diff, not a failure


def test_real_repo_artifacts_pass():
    """The actual BENCH_r01..r05 trajectory must not fail the gate:
    r04/r05 are legacy error lines (skips), and the r01->r03 movement
    was an improvement."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    if len(paths) < 2:
        return
    findings, _ = check_files(paths)
    assert findings == []
