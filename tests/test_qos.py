"""Tail-latency QoS plane (server/qos.py): priority admission lanes,
preempt-and-resume of analytic queries, and per-group SLO enforcement.

Chaos acceptance: an interactive burst preempts a running analytic
join; the victim suspends through the drain+spool machinery (claimed
ranges run to completion, spool-backed producers commit), resumes when
the interactive lane drains, and finishes with results bit-identical
to an unpreempted run — asserted via per-stage attempt counters (zero
re-runs of completed producer tasks). ``qos.enabled`` unset keeps the
coordinator's bit-exact legacy admission semaphore.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu.server import CoordinatorServer, WorkerServer, task_ids
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

#: multi-stage TPC-H join forced onto the partitioned-producer path
#: (the spool-backed stage shape preempt-and-resume targets)
JOIN_SQL = (
    "select o_orderpriority, count(*) as n "
    "from tpch.tiny.orders, tpch.tiny.lineitem "
    "where o_orderkey = l_orderkey "
    "group by o_orderpriority order by o_orderpriority"
)

LOOKUP_SQL = "select count(*) as c from tpch.tiny.region"

#: two lanes: interactive strictly above batch
RESOURCE_GROUPS = {
    "rootGroups": [
        {
            "name": "interactive",
            "weight": 1,
            "hardConcurrencyLimit": 4,
            "priority": 10,
        },
        {
            "name": "batch",
            "weight": 1,
            "hardConcurrencyLimit": 4,
            "priority": 0,
        },
    ],
    "selectors": [{"user": "inter-.*", "group": "interactive"}],
    "defaultGroup": "batch",
}


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


def _mk_cluster(tmp_path, n=2, policy="TASK", extra=None, slots=1):
    cfg = {
        "exchange.spool-path": str(tmp_path / "spool"),
        "exchange.spool-bytes": "64MB",
        "qos.enabled": "true",
        "qos.resume-grace-s": "0.2",
    }
    cfg.update(extra or {})
    coord = CoordinatorServer(
        config=NodeConfig(dict(cfg)),
        max_concurrent_queries=slots,
        resource_groups=RESOURCE_GROUPS,
    ).start()
    coord.local.session.set("retry_policy", policy)
    coord.local.session.set("join_distribution_type", "PARTITIONED")
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start()
        for _ in range(n)
    ]
    _wait_workers(coord, n)
    return coord, workers


def _teardown(coord, workers):
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def _wait_attr(q, attr, val, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if getattr(q, attr, 0) >= val:
            return True
        time.sleep(0.01)
    return False


def _producer_reruns(info):
    """(logical_key, attempts) of producer-stage tasks with more than
    one attempt — the acceptance asserts this list is empty."""
    out = []
    for st in info["stages"]:
        if st["kind"] != "producer":
            continue
        by = {}
        for t in st["tasks"]:
            by.setdefault(
                task_ids.logical_key(t["task_id"]), []
            ).append(t)
        for lk, ts in by.items():
            if len(ts) != 1:
                out.append((lk, len(ts)))
    return out


# ------------------------------------------------------- fault rule


def test_suspend_storm_rule_validation():
    r = faults.FaultRule.from_dict(
        {"action": "suspend_storm", "owner": "q_c1_", "count": 3}
    )
    assert r.action == "suspend_storm" and r.count == 3
    with pytest.raises(ValueError):
        faults.FaultRule.from_dict(
            {"action": "suspend_storm", "victim": "q_c1_"}
        )
    with pytest.raises(ValueError):
        faults.FaultRule.from_dict({"action": "suspend_tornado"})


def test_suspend_storm_hook_matches_by_owner():
    faults.configure(
        {"rules": [{"action": "suspend_storm", "owner": "q_c7", "count": 1}]}
    )
    assert not faults.maybe_inject_qos("q_c9_aaaa")  # no match
    assert faults.maybe_inject_qos("q_c7_bbbb")
    assert not faults.maybe_inject_qos("q_c7_bbbb")  # count exhausted


# ------------------------------------------------- off = legacy path


def test_qos_disabled_is_legacy_admission():
    """No qos.enabled: the controller never constructs and admission
    is the legacy semaphore — and the runtime view is empty, not an
    error."""
    coord = CoordinatorServer(max_concurrent_queries=2)
    try:
        assert coord.qos is None
        q = coord.submit(LOOKUP_SQL)
        q.done.wait(60)
        assert q.state == "FINISHED", q.error
        res = coord.local.execute("select * from system.runtime.qos")
        assert res.rows() == []
        assert "qos" not in coord.query_info(q)
    finally:
        coord.shutdown()


def test_resource_group_priority_parsed():
    from presto_tpu.server.resource_groups import ResourceGroupManager

    mgr = ResourceGroupManager(RESOURCE_GROUPS)
    assert mgr.groups["interactive"].priority == 10
    assert mgr.groups["batch"].priority == 0
    snap = {g["name"]: g for g in mgr.snapshot()}
    assert snap["interactive"]["priority"] == 10


# ------------------------------------------------- admission lanes


def test_priority_lane_ordering():
    """With preemption off (max-suspensions 0), a queued interactive
    query still dequeues BEFORE earlier-queued batch work: strict
    priority across lanes."""
    coord = CoordinatorServer(
        config=NodeConfig(
            {
                "qos.enabled": "true",
                "qos.max-suspensions-per-query": "0",
            }
        ),
        max_concurrent_queries=1,
        resource_groups=RESOURCE_GROUPS,
    )
    order = []
    gate = threading.Event()
    orig = coord._run_sql

    def slow(q):
        order.append(getattr(q, "resource_group", None))
        gate.wait(timeout=30)
        return orig(q)

    coord._run_sql = slow
    try:
        assert coord.qos is not None
        b1 = coord.submit(LOOKUP_SQL, user="batch-1")
        time.sleep(0.3)  # b1 holds the one slot
        b2 = coord.submit(LOOKUP_SQL, user="batch-2")
        i1 = coord.submit(LOOKUP_SQL, user="inter-1")
        time.sleep(0.3)
        # preemption disabled: interactive waits, but dequeues first
        assert order == ["batch"]
        gate.set()
        for q in (b1, b2, i1):
            q.done.wait(60)
            assert q.state == "FINISHED", (q.state, q.error)
        assert order == ["batch", "interactive", "batch"]
        assert getattr(b1, "qos_suspensions", 0) == 0
    finally:
        gate.set()
        coord.shutdown()


# --------------------------------------- preempt-and-resume acceptance


def test_preempt_and_resume_bit_identical(tmp_path):
    """The tentpole acceptance: an interactive burst suspends a running
    analytic join through drain+spool, the victim parks SUSPENDED
    (client polls answer immediately with empty data + Retry-After),
    resumes when the interactive lane drains, and finishes with rows
    bit-identical to the unpreempted run — with ZERO re-runs of
    completed producer tasks (per-stage attempt counters)."""
    coord, workers = _mk_cluster(
        tmp_path,
        extra={"coordinator.journal-path": str(tmp_path / "journal")},
    )
    try:
        expected = [
            tuple(r) for r in coord.local.execute(JOIN_SQL).rows()
        ]
        # slow the analytic's producer tasks and the interactive
        # query's source tasks (to hold the suspension window open);
        # neither rule touches the other query's task kinds
        faults.configure(
            {
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.25},
                    {"action": "delay", "task": ".src.", "delay_s": 0.3},
                ]
            }
        )
        qa = coord.submit(JOIN_SQL, user="batch-1")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and qa.state != "RUNNING":
            time.sleep(0.01)
        time.sleep(0.4)  # let producer ranges get claimed
        qi = coord.submit(LOOKUP_SQL, user="inter-1")
        assert _wait_attr(qa, "qos_suspensions", 1), qa.state
        # satellite: a SUSPENDED query's client poll answers NOW with
        # empty data + a retry hint — it neither hangs until resume
        # nor burns the 1s long-poll
        t0 = time.monotonic()
        with urllib.request.urlopen(
            f"{coord.uri}/v1/statement/{qa.qid}/0", timeout=5
        ) as resp:
            body = json.loads(resp.read())
            assert resp.status == 200
            assert resp.headers.get("Retry-After")
        assert time.monotonic() - t0 < 0.8
        assert body["data"] == []
        assert body["stats"]["state"] == "SUSPENDED"
        assert body["nextUri"].endswith("/0")  # same token: no progress lost
        qi.done.wait(60)
        assert qi.state == "FINISHED", qi.error
        qa.done.wait(120)
        assert qa.state == "FINISHED", qa.error
        assert [tuple(r) for r in qa.rows] == expected
        # zero re-runs of completed producer tasks: nothing died, so
        # EVERY producer logical task must have exactly one attempt
        info = coord.query_info(qa)
        assert _producer_reruns(info) == []
        # suspension/resume accounting: QueryInfo + the runtime view
        assert info["qos"]["suspensions"] >= 1
        assert info["qos"]["resumes"] >= 1
        assert getattr(qa, "qos_suspended_ms", 0.0) > 0.0
        rows = {
            r[0]: r
            for r in coord.local.execute(
                'select "group", suspensions, resumes, queries '
                "from system.runtime.qos"
            ).rows()
        }
        assert rows["batch"][1] >= 1 and rows["batch"][2] >= 1
        assert rows["interactive"][3] >= 1
        # the journal carries the suspend/resume audit frames (replay-
        # inert: both queries also have terminal finish frames)
        text = "".join(
            open(os.path.join(tmp_path / "journal", f)).read()
            for f in os.listdir(tmp_path / "journal")
        )
        assert '"qos_suspend"' in text and '"qos_resume"' in text
    finally:
        _teardown(coord, workers)


def test_suspend_storm_hysteresis(tmp_path):
    """N back-to-back preemption triggers against one query (the
    ``suspend_storm`` fault rule) suspend it exactly ONCE: after the
    resume, the ``qos.resume-grace-s`` immunity window refuses the
    rest — and the query still finishes correctly."""
    coord, workers = _mk_cluster(
        tmp_path, n=1, extra={"qos.resume-grace-s": "60"}
    )
    try:
        sql = (
            "select count(*) as c, sum(l_quantity) as s "
            "from tpch.tiny.lineitem"
        )
        expected = [tuple(r) for r in coord.local.execute(sql).rows()]
        trig0 = REGISTRY.counter("qos.preempt_triggers").total
        faults.configure(
            {
                "rules": [
                    {"action": "delay", "task": ".src.", "delay_s": 0.2},
                    {
                        "action": "suspend_storm",
                        "owner": "q_c",
                        "count": 3,
                    },
                ]
            }
        )
        q = coord.submit(sql, user="batch-1")
        q.done.wait(120)
        assert q.state == "FINISHED", q.error
        assert [tuple(r) for r in q.rows] == expected
        # 3 triggers fired, hysteresis let exactly one suspend through
        assert (
            REGISTRY.counter("qos.preempt_triggers").total - trig0 == 3
        )
        assert getattr(q, "qos_suspensions", 0) == 1
        assert getattr(q, "qos_resumes", 0) == 1
    finally:
        _teardown(coord, workers)


@pytest.mark.slow
def test_worker_kill_mid_suspend_zero_failures(tmp_path):
    """Chaos: a worker dies WHILE the analytic victim is parked. On
    resume, committed producer partitions re-serve from the spool and
    lost work reschedules (QUERY restart as last resort) — zero failed
    queries, exact rows."""
    # long breaker cool-off: the dead worker must stay excluded from
    # scheduling for the whole recovery window (a half-open probe
    # re-admitting it mid-restart would feed join-task POSTs a dead
    # socket and burn the restart budget)
    coord, workers = _mk_cluster(
        tmp_path,
        policy="QUERY",
        extra={"failure-detector.open-s": "30"},
    )
    coord.local.session.set("query_retry_count", 2)
    try:
        expected = [
            tuple(r) for r in coord.local.execute(JOIN_SQL).rows()
        ]
        faults.configure(
            {
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.25},
                    {"action": "delay", "task": ".src.", "delay_s": 0.3},
                ]
            }
        )
        qa = coord.submit(JOIN_SQL, user="batch-1")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and qa.state != "RUNNING":
            time.sleep(0.01)
        time.sleep(0.4)
        qi = coord.submit(LOOKUP_SQL, user="inter-1")
        assert _wait_attr(qa, "qos_suspensions", 1), qa.state
        # kill a worker while the victim is parked: its committed
        # producer attempts survive in the spool
        workers[0]._fault_kill()
        qi.done.wait(60)
        qa.done.wait(180)
        assert qi.state == "FINISHED", qi.error
        assert qa.state == "FINISHED", qa.error
        assert [tuple(r) for r in qa.rows] == expected
    finally:
        _teardown(coord, workers)


# ------------------------------------------------ SLO / speculation


def test_speculation_scale_tightens_near_slo():
    """Deadline-aware straggler speculation: the threshold scale is
    1.0 with no SLO, shrinks as elapsed time eats the target-p99-ms
    budget, and floors at 0.25 past it."""
    from presto_tpu.exec.stats import QueryStats

    coord = CoordinatorServer(
        config=NodeConfig(
            {
                "qos.enabled": "true",
                "qos.interactive.target-p99-ms": "1000",
            }
        ),
        max_concurrent_queries=1,
        resource_groups=RESOURCE_GROUPS,
    )
    try:
        qos = coord.qos

        class FakeQ:
            def __init__(self, group, age_s):
                self.qid = "q_fake"
                self.resource_group = group
                self.stats = QueryStats(
                    query_id="q_fake",
                    sql="",
                    create_time=time.time() - age_s,
                )

        assert qos.speculation_scale(FakeQ("batch", 10.0)) == 1.0
        mid = qos.speculation_scale(FakeQ("interactive", 0.5))
        assert 0.3 < mid < 0.7
        assert qos.speculation_scale(FakeQ("interactive", 5.0)) == 0.25
        # the view surfaces the configured SLO target
        row = [
            r
            for r in qos.view_rows()
            if r["group"] == "interactive"
        ][0]
        assert row["target_p99_ms"] == 1000.0
    finally:
        coord.shutdown()


def test_slo_miss_counted(tmp_path):
    """A finished query over its group's target-p99-ms counts an SLO
    miss and lands in the group's latency reservoir."""
    coord = CoordinatorServer(
        config=NodeConfig(
            {
                "qos.enabled": "true",
                # everything misses a 0.001ms target
                "qos.batch.target-p99-ms": "0.001",
            }
        ),
        max_concurrent_queries=2,
        resource_groups=RESOURCE_GROUPS,
    )
    try:
        q = coord.submit(LOOKUP_SQL, user="batch-1")
        q.done.wait(60)
        assert q.state == "FINISHED", q.error
        row = [
            r
            for r in coord.qos.view_rows()
            if r["group"] == "batch"
        ][0]
        assert row["queries"] >= 1
        assert row["slo_misses"] >= 1
        assert row["p99_ms"] > 0.0
    finally:
        coord.shutdown()
