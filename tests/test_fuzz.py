"""Differential fuzzing (SURVEY.md §5.2): seeded random SELECTs, every
one oracle-diffed. A failing seed reproduces exactly via
``python -m presto_tpu.fuzz --seed N``."""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.fuzz import generate_query, run_fuzz
from presto_tpu.verifier import SqliteOracle


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


def test_generator_is_deterministic():
    assert generate_query(7) == generate_query(7)
    assert generate_query(7) != generate_query(8)


def test_fuzz_corpus_oracle_exact(runner, oracle):
    """A pinned seed range must stay oracle-exact (regressions in
    planner rewrites / null semantics / dictionary handling show up
    here first)."""
    failures = run_fuzz(range(0, 40), runner=runner, oracle=oracle)
    msg = "\n".join(
        f"seed {s}: {q}\n  -> {str(d)[:300]}" for s, q, d in failures[:5]
    )
    assert not failures, f"{len(failures)} fuzz failures:\n{msg}"
