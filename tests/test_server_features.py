"""Server-path features landed in round 3 (VERDICT r2 items 6/7 + the
ordered MERGE exchange): always-on memory accounting with the
kill-largest policy, concurrent worker pulls, and merge-exchange
ordered gathers."""

import threading
import time

import pytest

from presto_tpu.server import CoordinatorServer, PrestoTpuClient, WorkerServer
from presto_tpu.server.client import QueryFailed
from presto_tpu.session import NodeConfig
from presto_tpu.verifier import SqliteOracle, verify_query


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


# ------------------------------------------------------ memory accounting


def test_memory_accounting_always_on_server_path():
    """A too-big query fails on ACCOUNTING (MemoryLimitExceeded), not
    OOM — the pool is constructed by default from tier-1 config."""
    coord = CoordinatorServer(
        config=NodeConfig({"query.max-memory-per-node": "64kB"})
    ).start()
    try:
        assert coord.memory_pool.limit == 64 * 1024
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        with pytest.raises(QueryFailed, match="[Mm]emory"):
            client.execute("select count(*) as c from tpch.tiny.lineitem")
    finally:
        coord.shutdown()


def test_memory_pool_default_on():
    coord = CoordinatorServer()
    try:
        assert coord.memory_pool is not None
        assert coord.local.memory_pool is coord.memory_pool
    finally:
        coord.shutdown()
    w = WorkerServer()
    try:
        assert w.memory_pool is not None
        assert w.runner.memory_pool is w.memory_pool
    finally:
        w.shutdown(graceful=False)


def test_kill_largest_policy():
    """Pool exhaustion kills the largest RUNNING query, never the
    requester or the shared table cache."""
    from presto_tpu.server.coordinator import _Query

    coord = CoordinatorServer(
        config=NodeConfig({"query.max-memory-per-node": "1000B"})
    )
    try:
        pool = coord.memory_pool
        big = _Query("q_big", "select 1")
        small = _Query("q_small", "select 2")
        coord.queries["q_big"] = big
        coord.queries["q_small"] = small
        pool.reserve("table-cache", 200)
        pool.reserve("q_big", 500)
        pool.reserve("q_small", 100)
        # q_new needs 400B: pool exhausted -> q_big is evicted
        pool.reserve("q_new", 400)
        assert big.state == "FAILED"
        assert "memory" in big.error.lower()
        assert small.state != "FAILED"
        assert pool.used_bytes("q_big") == 0
        assert pool.used_bytes("q_new") == 400
        assert pool.used_bytes("table-cache") == 200  # never evicted
    finally:
        coord.shutdown()


# --------------------------------------------------- concurrent pulls


class _CountingWorker(WorkerServer):
    """Counts created tasks (DELETE pops worker.tasks, so live counts
    don't survive the pull acks)."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.created = 0

    def create_task(self, spec):
        self.created += 1
        return super().create_task(spec)


class _SlowWorker(_CountingWorker):
    """Worker whose scan staging sleeps; records each staging interval
    so concurrency is assertable from event ORDER, not wall-clock
    ratios (load-insensitive — VERDICT r3 weak 3)."""

    DELAY_S = 0.6

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.spans = []

    def _load_range(self, scan, lo, hi):
        t0 = time.time()
        time.sleep(self.DELAY_S)
        out = super()._load_range(scan, lo, hi)
        self.spans.append((t0, time.time()))
        return out


def test_dynamic_splits_favor_fast_worker():
    """Work stealing: with one slow and one fast worker, the fast one
    drains most of the over-partitioned split queue (reference:
    dynamic split placement, SURVEY.md §2.4)."""
    from presto_tpu.session import Session

    coord = CoordinatorServer(
        session=Session(
            properties={"page_capacity": 4096, "split_queue_factor": 8}
        )
    ).start()
    slow = _SlowWorker(coordinator_uri=coord.uri)
    slow.DELAY_S = 0.4
    slow.start()
    fast = _CountingWorker(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 2)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert res.rows() == [(59997,)]
        # the fast worker must have claimed more ranges than the slow
        assert fast.created > slow.created, (slow.created, fast.created)
    finally:
        slow.shutdown(graceful=False)
        fast.shutdown(graceful=False)
        coord.shutdown()


def test_stage_time_is_slowest_worker_not_sum():
    """3 slow workers, one batch each: tasks dispatch CONCURRENTLY, so
    the stage costs ~max(worker), not ~sum(worker) (VERDICT r2 item 7).

    Asserted from event ORDER — the three staging intervals must
    overlap (serial dispatch would make them disjoint no matter how
    loaded the box is) — not from wall-clock ratios, which flaked under
    load on the 1-vCPU CI host (VERDICT r3 weak 3)."""
    coord = CoordinatorServer()
    coord.local.session.set("page_capacity", 1 << 20)  # one batch/worker
    coord.local.session.set("split_queue_factor", 1)  # one range/worker
    workers = [
        _SlowWorker(coordinator_uri=coord.uri).start() for _ in range(3)
    ]
    for w in workers:
        w.DELAY_S = 1.5  # overlap margin >> scheduler jitter under load
    coord.start()
    try:
        _wait_workers(coord, 3)
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        client.execute("select count(*) as c from tpch.tiny.region")
        for w in workers:
            w.spans.clear()  # warmup staging is not part of the stage
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert res.rows() == [(59997,)]
        spans = [s for w in workers for s in w.spans]
        assert len(spans) == 3, spans  # one range per worker
        latest_start = max(s for s, _ in spans)
        earliest_end = min(e for _, e in spans)
        assert latest_start < earliest_end, (
            f"staging intervals did not overlap (serial dispatch?): "
            f"{spans}"
        )
    finally:
        for w in workers:
            w.shutdown(graceful=False)
        coord.shutdown()


# --------------------------------------------------- ordered MERGE


@pytest.fixture(scope="module")
def merge_cluster():
    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    _wait_workers(coord, 2)
    yield coord, workers
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


def test_ordered_merge_exchange(merge_cluster, oracle, monkeypatch):
    """ORDER BY over a no-cut fragment takes the merge-exchange path
    (workers emit sorted runs; the coordinator k-way merges instead of
    re-sorting) and stays oracle-exact."""
    from presto_tpu.server import coordinator as coord_mod

    coord, _ = merge_cluster
    calls = []
    orig = coord_mod._merge_sorted_runs

    def spy(payloads, schema, sort_node):
        calls.append(len(payloads))
        return orig(payloads, schema, sort_node)

    monkeypatch.setattr(coord_mod, "_merge_sorted_runs", spy)
    client = PrestoTpuClient(coord.uri, timeout_s=120)
    sql = (
        "select o_orderkey, o_totalprice from tpch.tiny.orders "
        "where o_custkey <= 200 "
        "order by o_totalprice desc, o_orderkey"
    )
    diff = verify_query(client, oracle, sql)
    assert diff is None, diff
    assert calls and calls[0] >= 2, "merge path did not engage"


def test_ordered_merge_topn(merge_cluster, oracle, monkeypatch):
    from presto_tpu.server import coordinator as coord_mod

    coord, _ = merge_cluster
    calls = []
    orig = coord_mod._merge_sorted_runs

    def spy(payloads, schema, sort_node):
        calls.append(sort_node.limit)
        return orig(payloads, schema, sort_node)

    monkeypatch.setattr(coord_mod, "_merge_sorted_runs", spy)
    client = PrestoTpuClient(coord.uri, timeout_s=120)
    sql = (
        "select l_orderkey, l_extendedprice from tpch.tiny.lineitem "
        "order by l_extendedprice desc, l_orderkey, l_linenumber "
        "limit 25"
    )
    diff = verify_query(client, oracle, sql)
    assert diff is None, diff
    assert calls == [25]


def test_bucketed_gather_merge(oracle, monkeypatch):
    """Partial states beyond the device budget hash-bucket at the
    gather and merge one bucket at a time (grouped execution at the
    coordinator; VERDICT r2 weak 5) — oracle-exact.

    Pins ``distributed_final=false``: with the worker<->worker shuffle
    on (the default), keyed FINAL merges run on workers and the
    coordinator's bucketed gather is the fallback discipline under
    test here."""
    from presto_tpu.exec import streaming as S
    from presto_tpu.session import Session

    coord = CoordinatorServer(
        session=Session(
            properties={
                "max_device_rows": 4096,
                "distributed_final": "false",
            }
        )
    ).start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    calls = []
    orig = S.bucketize_payloads

    def spy(payloads, schema, keys, n_buckets):
        calls.append(n_buckets)
        return orig(payloads, schema, keys, n_buckets)

    monkeypatch.setattr(S, "bucketize_payloads", spy)
    try:
        _wait_workers(coord, 2)
        client = PrestoTpuClient(coord.uri, timeout_s=300)
        sql = (
            "select l_orderkey, count(*) as c, sum(l_quantity) as s "
            "from tpch.tiny.lineitem group by l_orderkey"
        )
        diff = verify_query(client, oracle, sql)
        assert diff is None, diff
        assert calls and calls[0] > 1, "bucketed gather did not engage"
    finally:
        for w in workers:
            w.shutdown(graceful=False)
        coord.shutdown()


def test_agg_query_skips_merge_path(merge_cluster, monkeypatch):
    """A stage with an aggregation cut must NOT take the merge path
    (sorted runs of partial states would be wrong)."""
    from presto_tpu.server import coordinator as coord_mod

    coord, _ = merge_cluster
    calls = []
    orig = coord_mod._merge_sorted_runs

    def spy(*a):
        calls.append(1)
        return orig(*a)

    monkeypatch.setattr(coord_mod, "_merge_sorted_runs", spy)
    client = PrestoTpuClient(coord.uri, timeout_s=120)
    res = client.execute(
        "select l_returnflag, count(*) as n from tpch.tiny.lineitem "
        "group by l_returnflag order by l_returnflag"
    )
    assert len(res.rows()) == 3
    assert not calls


def test_statement_surface_over_http():
    """The round-5 statement surface — DDL, DML, DESCRIBE, prepared
    statements — works over the client protocol (result pages incl.
    the two-varchar DESCRIBE page serialize on the wire)."""
    from presto_tpu.connectors import create_connector
    from presto_tpu.exec.staging import CatalogManager

    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("mem", create_connector("memory"))
    coord = CoordinatorServer(catalogs=catalogs)
    coord.start()
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        client.execute(
            "create table mem.default.wire (k bigint, v varchar)"
        )
        assert client.execute(
            "show columns from mem.default.wire"
        ).data == [["k", "bigint"], ["v", "varchar"]]
        client.execute(
            "insert into mem.default.wire values (1, 'a'), (2, 'b')"
        )
        assert client.execute(
            "update mem.default.wire set v = 'z' where k = 2"
        ).data == [[1]]
        assert client.execute(
            "delete from mem.default.wire where k = 1"
        ).data == [[1]]
        assert client.execute(
            "select k, v from mem.default.wire"
        ).data == [[2, "z"]]
        client.execute(
            "prepare wp from select v from mem.default.wire "
            "where k = ?"
        )
        assert client.execute("execute wp using 2").data == [["z"]]
        client.execute("drop table mem.default.wire")
    finally:
        coord.shutdown()
