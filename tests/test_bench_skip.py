"""bench.py failure lines (BENCH_r05 regression): a config that could
not be measured — backend-init failure included — must emit a
``"skipped": true`` line with NO value, never ``value: 0`` (a zero
reads as a measured 0 rows/s and poisons the metric trajectory)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import bench  # noqa: E402


def test_skip_line_has_no_value():
    line = bench.skip_line(
        "tpch_q1_sf1_rows_per_sec",
        RuntimeError("Unable to initialize backend 'axon'"),
    )
    assert line["skipped"] is True
    assert "value" not in line
    assert line["metric"] == "tpch_q1_sf1_rows_per_sec"
    assert "Unable to initialize backend" in line["error"]
    json.dumps(line)  # driver contract: one JSON-able line


def test_skip_line_truncates_long_errors():
    line = bench.skip_line("m", RuntimeError("x" * 1000))
    assert len(line["error"]) <= 300


def test_bench_source_never_emits_zero_value_error_lines():
    """Every failure path in the driver must route through skip_line:
    no hand-built '"value": 0 + error' dict may reappear."""
    src = open(bench.__file__, encoding="utf-8").read()
    assert '"value": 0' not in src
    assert src.count("skip_line(") >= 3  # def + both failure paths


def test_every_print_site_routes_through_emit():
    """The ONE raw print of a result line lives inside _emit — every
    other site calls it, so the skip contract is enforced at the last
    moment for every line the driver will ever emit (the BENCH_r04/r05
    hole was a failure path that printed its own dict)."""
    src = open(bench.__file__, encoding="utf-8").read()
    assert src.count("print(json.dumps(") == 1  # _emit's own print
    assert src.count("_emit(") >= 15


def test_emit_converts_error_value_line_to_skip(capsys):
    """Defense in depth: a line that somehow carries BOTH an error and
    a value is demoted to a skip at print time — value: 0 beside an
    error can never reach the metric trajectory again."""
    bench._emit(
        {
            "metric": "tpch_q1_sf1_rows_per_sec",
            "value": 0,
            "unit": "rows/s",
            "error": "Unable to initialize backend 'axon'",
        }
    )
    line = json.loads(capsys.readouterr().out.strip())
    assert line["skipped"] is True
    assert "value" not in line
    assert line["metric"] == "tpch_q1_sf1_rows_per_sec"
    assert "axon" in line["error"]


def test_emit_passes_clean_lines_through(capsys):
    good = {"metric": "m", "value": 42, "unit": "rows/s"}
    bench._emit(good)
    line = json.loads(capsys.readouterr().out.strip())
    assert line == good


def test_emit_leaves_real_skips_alone(capsys):
    skip = bench.skip_line("m", RuntimeError("boom"))
    bench._emit(skip)
    line = json.loads(capsys.readouterr().out.strip())
    assert line == skip
