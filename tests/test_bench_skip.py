"""bench.py failure lines (BENCH_r05 regression): a config that could
not be measured — backend-init failure included — must emit a
``"skipped": true`` line with NO value, never ``value: 0`` (a zero
reads as a measured 0 rows/s and poisons the metric trajectory)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import bench  # noqa: E402


def test_skip_line_has_no_value():
    line = bench.skip_line(
        "tpch_q1_sf1_rows_per_sec",
        RuntimeError("Unable to initialize backend 'axon'"),
    )
    assert line["skipped"] is True
    assert "value" not in line
    assert line["metric"] == "tpch_q1_sf1_rows_per_sec"
    assert "Unable to initialize backend" in line["error"]
    json.dumps(line)  # driver contract: one JSON-able line


def test_skip_line_truncates_long_errors():
    line = bench.skip_line("m", RuntimeError("x" * 1000))
    assert len(line["error"]) <= 300


def test_bench_source_never_emits_zero_value_error_lines():
    """Every failure path in the driver must route through skip_line:
    no hand-built '"value": 0 + error' dict may reappear."""
    src = open(bench.__file__, encoding="utf-8").read()
    assert '"value": 0' not in src
    assert src.count("skip_line(") >= 3  # def + both failure paths
