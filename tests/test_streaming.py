"""Larger-than-HBM streaming execution: the TPC-H corpus with the
device-residency budget forced far below lineitem's size, so every
lineitem query takes the split-stream + bucket-spill path — verified
against the sqlite oracle (reference: spilling/grouped-execution tests;
SURVEY.md §5.7)."""

import jax
import numpy as np
import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.session import Session
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES

#: tiny-SF lineitem is ~60k rows; 16384 forces it (and only it) to
#: stream in ~8 batches of 4096 with >= 16 spill buckets
MAX_DEVICE_ROWS = 16_384
BATCH_ROWS = 4_096


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": MAX_DEVICE_ROWS,
                "page_capacity": BATCH_ROWS,
                "spill_enabled": True,
            }
        )
    )


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


#: queries that scan lineitem (stream) — the others stay resident
LINEITEM_QUERIES = [
    q
    for q in sorted(QUERIES)
    if "lineitem" in QUERIES[q]
]


@pytest.mark.parametrize("qnum", LINEITEM_QUERIES)
def test_tpch_streamed(qnum, runner, oracle):
    diff = verify_query(runner, oracle, QUERIES[qnum], rel_tol=1e-6)
    assert diff is None, f"Q{qnum} streamed mismatch: {diff}"


def test_streaming_actually_engaged(runner):
    """The path must really stream: count partial-fragment executions
    by spying on the spill function."""
    from presto_tpu.exec import streaming

    calls = []
    orig = streaming._spill_partial

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    streaming._spill_partial = spy
    try:
        fresh = LocalQueryRunner(
            session=Session(
                properties={
                    "max_device_rows": MAX_DEVICE_ROWS,
                    "page_capacity": BATCH_ROWS,
                }
            )
        )
        fresh.execute(
            "select l_returnflag, sum(l_quantity) as s "
            "from tpch.tiny.lineitem group by l_returnflag"
        )
    finally:
        streaming._spill_partial = orig
    assert len(calls) >= 10, f"expected >=10 streamed batches, {len(calls)}"


def test_spill_disabled_fails_cleanly():
    from presto_tpu.exec.streaming import StreamingError

    r = LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": MAX_DEVICE_ROWS,
                "spill_enabled": False,
            }
        )
    )
    with pytest.raises(StreamingError):
        r.execute("select count(*) as c from tpch.tiny.lineitem")


#: non-aggregate streamed shapes (VERDICT r2 item 10): big sort and big
#: join-probe plans must stream too, not raise StreamingError
NON_AGG_STREAMED = {
    "sort_topn": """
        select l_orderkey, l_extendedprice from tpch.tiny.lineitem
        order by l_extendedprice desc, l_orderkey, l_linenumber
        limit 20""",
    "sort_full": """
        select l_orderkey, l_linenumber, l_extendedprice
        from tpch.tiny.lineitem
        order by l_extendedprice, l_orderkey, l_linenumber""",
    "join_probe_agg": """
        select o_orderpriority, count(*) as n
        from tpch.tiny.orders, tpch.tiny.lineitem
        where o_orderkey = l_orderkey and l_quantity > 45
        group by o_orderpriority order by o_orderpriority""",
    "join_output_no_agg": """
        select o_orderkey, l_quantity
        from tpch.tiny.orders, tpch.tiny.lineitem
        where o_orderkey = l_orderkey and l_quantity > 49
          and o_totalprice > 400000
        order by o_orderkey, l_quantity limit 30""",
}


@pytest.mark.parametrize("name", sorted(NON_AGG_STREAMED))
def test_non_agg_streamed_shapes(name, runner, oracle):
    """Sort and join-output plans over a scan exceeding the device
    budget stream through the split pipeline (resident build side,
    streamed probe) instead of failing."""
    diff = verify_query(runner, oracle, NON_AGG_STREAMED[name], rel_tol=1e-6)
    assert diff is None, f"{name} streamed mismatch: {diff}"


def test_bucket_hash_stable_across_dictionaries():
    """The same value must land in the same bucket even when two
    batches encode it with different dictionary ids."""
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec.streaming import _bucket_of

    p1 = {
        "k": DictColumn(
            ids=np.array([0, 1], np.int32),
            values=np.array(["apple", "banana"], object),
        )
    }
    p2 = {
        "k": DictColumn(
            ids=np.array([1, 0], np.int32),
            values=np.array(["aardvark", "apple"], object),
        )
    }
    b1 = _bucket_of(p1, ["k"], 2, 64)
    b2 = _bucket_of(p2, ["k"], 2, 64)
    assert b1[0] == b2[0]  # "apple" agrees across id spaces


# ------------------------------------------- join build-side spill


@pytest.fixture(scope="module")
def tight_runner():
    """Budget below ORDERS (15k rows): a join building orders must take
    the partitioned build-side spill (no replicated cut exists)."""
    return LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": 8_192,
                "page_capacity": 4_096,
                "spill_enabled": True,
            }
        )
    )


def test_join_build_spill_semi(tight_runner, oracle):
    """Semi join with a >budget build side: both sides hash-partition
    to host buckets, per-bucket joins concatenate (reference:
    HashBuilderOperator partitioned spill + unspill replay)."""
    q = (
        "select count(*) as c from tpch.tiny.customer "
        "where c_custkey in (select o_custkey from tpch.tiny.orders "
        "where o_totalprice > 100000)"
    )
    diff = verify_query(tight_runner, oracle, q)
    assert diff is None, diff


def test_join_build_spill_anti(tight_runner, oracle):
    q = (
        "select count(*) as c from tpch.tiny.customer "
        "where c_custkey not in (select o_custkey from tpch.tiny.orders "
        "where o_totalprice > 150000)"
    )
    diff = verify_query(tight_runner, oracle, q)
    assert diff is None, diff


def test_join_build_spill_left_payload(tight_runner, oracle):
    """LEFT join building raw >budget orders with payload columns:
    preserved probe rows and bucket-scattered matches reassemble
    oracle-exact (no agg cut exists, so only the partitioned build
    spill can run this)."""
    q = (
        "select count(*) as c, sum(o_totalprice) as s "
        "from tpch.tiny.customer left join tpch.tiny.orders "
        "on c_custkey = o_custkey"
    )
    diff = verify_query(tight_runner, oracle, q)
    assert diff is None, diff


def test_split_cache_skips_restaging(oracle):
    """With stream_split_cache on, the SECOND streamed pass over the
    same scan must not touch the connector for split batches (the
    table cache at split granularity — SURVEY.md §5.7; the bench's
    q18_sf1_streamed protocol fix)."""
    r = LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": MAX_DEVICE_ROWS,
                "page_capacity": BATCH_ROWS,
                "stream_split_cache": True,
            }
        )
    )
    conn = r.catalogs.get("tpch")
    calls = []
    orig = conn.create_page_source

    def spy(split, columns):
        calls.append(split)
        return orig(split, columns)

    q = (
        "select l_returnflag, sum(l_quantity) as s, count(*) as c "
        "from tpch.tiny.lineitem group by l_returnflag"
    )
    conn.create_page_source = spy
    try:
        first = r.execute(q)
        n_first = len(calls)
        calls.clear()
        second = r.execute(q)
        n_second = len(calls)
    finally:
        conn.create_page_source = orig
    assert n_first >= 10, f"expected >=10 staged batches, {n_first}"
    assert n_second == 0, (
        f"second pass re-staged {n_second} splits through the cache"
    )
    assert sorted(first.rows()) == sorted(second.rows())


def test_split_cache_off_by_default(oracle):
    """Default sessions must re-stage (caching every split defeats
    larger-than-HBM discipline when the set genuinely exceeds HBM)."""
    r = LocalQueryRunner(
        session=Session(
            properties={
                "max_device_rows": MAX_DEVICE_ROWS,
                "page_capacity": BATCH_ROWS,
            }
        )
    )
    conn = r.catalogs.get("tpch")
    calls = []
    orig = conn.create_page_source

    def spy(split, columns):
        calls.append(split)
        return orig(split, columns)

    q = (
        "select count(*) as c from tpch.tiny.lineitem "
        "where l_quantity < 10"
    )
    conn.create_page_source = spy
    try:
        r.execute(q)
        n_first = len(calls)
        calls.clear()
        r.execute(q)
        n_second = len(calls)
    finally:
        conn.create_page_source = orig
    assert n_second == n_first >= 10
