"""Dynamic filtering (exec/dynfilter.py): build-side runtime filters
pushed into probe-side scans and split pruning.

Covers the PR-4 acceptance surface: oracle/dual-path equality with
filtering on vs off across join types (inner/semi, dictionary string
keys, empty build side), distributed connector-level pruning
(``dynamic_filter.splits_pruned > 0`` on a hive-partitioned probe
scan), the bounded wait (slow/killed build degrades to the unfiltered
plan), native-dtype bound conservativeness (the float32/int64
truncation regression), parquet row-group / ORC stripe min-max
pruning, the distributed fuzz toggle, and the summary-site lint.
"""

import os
import time

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from presto_tpu import types as T  # noqa: E402
from presto_tpu.connectors import create_connector  # noqa: E402
from presto_tpu.connectors.spi import (  # noqa: E402
    RangeSet,
    TableHandle,
)
from presto_tpu.exec import dynfilter  # noqa: E402
from presto_tpu.exec.local_runner import LocalQueryRunner  # noqa: E402
from presto_tpu.exec.staging import CatalogManager  # noqa: E402
from presto_tpu.utils import faults  # noqa: E402
from presto_tpu.utils.metrics import REGISTRY  # noqa: E402


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _counter(name: str) -> int:
    return REGISTRY.counter(name).total


def _on_off(runner, sql):
    """Execute with dynamic filtering ON then OFF; return both row
    lists (session state restored)."""
    saved = str(runner.session.get("enable_dynamic_filtering"))
    try:
        runner.session.set("enable_dynamic_filtering", "true")
        on = runner.execute(sql).rows()
        runner.session.set("enable_dynamic_filtering", "false")
        off = runner.execute(sql).rows()
    finally:
        runner.session.set("enable_dynamic_filtering", saved)
    return on, off


# ------------------------------------------------------- local runner


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner()
    r.catalogs.register("memory", create_connector("memory"))
    # small fragment budget: every join plan runs stage-at-a-time, so
    # the build side executes first and the dynamic filter engages
    r.session.set("max_fragment_weight", "6")
    r.execute(
        "create table memory.default.fact_str "
        "(id bigint, tag varchar)"
    )
    r.execute(
        "insert into memory.default.fact_str values "
        "(1, 'a'), (2, 'b'), (3, 'c'), (4, 'd'), (5, 'b')"
    )
    r.execute("create table memory.default.fact_n (id bigint, k bigint)")
    r.execute(
        "insert into memory.default.fact_n values "
        "(1, 10), (2, 20), (3, 30), (4, 40), (5, 20)"
    )
    r.execute("create table memory.default.dim_n (k bigint)")
    r.execute("insert into memory.default.dim_n values (20), (30)")
    return r


def test_inner_join_on_off_equal(runner):
    sql = (
        "select count(*) as n, sum(l_extendedprice) as s "
        "from tpch.tiny.lineitem l join tpch.tiny.part p "
        "on l.l_partkey = p.p_partkey "
        "where p.p_container = 'MED BOX'"
    )
    pruned0 = _counter("dynamic_filter.rows_pruned")
    on, off = _on_off(runner, sql)
    assert on == off
    assert _counter("dynamic_filter.rows_pruned") > pruned0, (
        "the selective build side should prune probe rows"
    )


def test_semi_join_on_off_equal(runner):
    sql = (
        "select count(*) as n from tpch.tiny.lineitem "
        "where l_orderkey in (select o_orderkey from tpch.tiny.orders "
        "where o_totalprice > 400000)"
    )
    on, off = _on_off(runner, sql)
    assert on == off


def test_dict_string_key_on_off_equal(runner):
    """Dictionary-encoded string join keys summarize as a present-id
    LUT resolved through the dictionary into an IN-list of VALUES
    (same-dictionary self-join: the fragmented executor's supported
    string-join shape)."""
    sql = (
        "select count(*) as n from memory.default.fact_str a join "
        "(select tag from memory.default.fact_str where id >= 4) b "
        "on a.tag = b.tag"
    )
    pruned0 = _counter("dynamic_filter.rows_pruned")
    on, off = _on_off(runner, sql)
    assert on == off == [(3,)]
    assert _counter("dynamic_filter.rows_pruned") > pruned0, (
        "tags outside the build's dictionary subset should be pruned"
    )


def test_empty_build_on_off_equal(runner):
    sql = (
        "select count(*) as n from tpch.tiny.lineitem l "
        "join tpch.tiny.part p on l.l_partkey = p.p_partkey "
        "where p.p_name = 'zzz_no_such_part'"
    )
    on, off = _on_off(runner, sql)
    assert on == off == [(0,)]


def test_left_outer_join_not_filtered(runner):
    """Outer joins preserve unmatched probe rows: the dynamic filter
    must NOT engage (and results must match either way)."""
    sql = (
        "select count(*) as n from memory.default.fact_n f "
        "left join memory.default.dim_n d on f.k = d.k"
    )
    on, off = _on_off(runner, sql)
    assert on == off == [(5,)]


# --------------------------------------- native-dtype bound regression


def test_float32_bounds_native_dtype():
    """Bounds of a REAL (float32) build key must be the EXACT float32
    values — not decimal/widened roundings that can exclude matching
    probe rows (the old astype-to-float64 path narrowed to float32
    under x64-off and filled with wrapped iinfo values)."""
    import jax.numpy as jnp

    from presto_tpu.page import Block, Page

    # 0.1 and 16777217 are NOT exactly representable in float32: the
    # stored values differ from the decimal spelling, so a bound
    # computed anywhere but the native dtype risks excluding them
    vals = np.asarray([0.1, 16777217.0, 2.5], dtype=np.float32)
    page = Page(
        blocks=(
            Block(
                data=jnp.asarray(vals), valid=None, dtype=T.REAL
            ),
        ),
        num_valid=jnp.asarray(3, jnp.int32),
        names=("k",),
    )
    conjuncts, n = dynfilter.device_conjuncts(
        page, [("k", "k")], {"k": T.REAL}
    )
    assert n == 1
    between = conjuncts[0]
    lo, hi = between.low.value, between.high.value
    assert lo == float(vals.min()) and hi == float(vals.max())
    # round-tripping the bound back to float32 must be exact
    assert np.float32(lo) == vals.min()
    assert np.float32(hi) == vals.max()


def test_int64_bounds_beyond_int32():
    """int64 keys past 2^31 must not wrap (the old path's
    astype(jnp.int64) + iinfo(int64) fills narrowed under x64-off)."""
    import jax.numpy as jnp

    from presto_tpu.page import Block, Page

    vals = np.asarray(
        [2**31 + 5, 2**31 + 11, 2**33], dtype=np.int64
    )
    page = Page(
        blocks=(
            Block(
                data=jnp.asarray(vals), valid=None, dtype=T.BIGINT
            ),
        ),
        num_valid=jnp.asarray(3, jnp.int32),
        names=("k",),
    )
    conjuncts, n = dynfilter.device_conjuncts(
        page, [("k", "k")], {"k": T.BIGINT}
    )
    assert n == 1
    assert conjuncts[0].low.value == 2**31 + 5
    assert conjuncts[0].high.value == 2**33


def test_real_key_join_on_off_equal():
    """End-to-end: REAL join keys straddling float32 rounding stay
    matched under dynamic filtering."""
    r = LocalQueryRunner()
    r.catalogs.register("memory", create_connector("memory"))
    r.session.set("max_fragment_weight", "6")
    r.execute("create table memory.default.dimf (x real)")
    r.execute(
        "insert into memory.default.dimf values (0.1), (16777217.0)"
    )
    r.execute("create table memory.default.factf (x real, v bigint)")
    r.execute(
        "insert into memory.default.factf values "
        "(0.1, 1), (16777217.0, 2), (99.5, 3)"
    )
    sql = (
        "select count(*) as n, sum(f.v) as s "
        "from memory.default.factf f "
        "join memory.default.dimf d on f.x = d.x"
    )
    on, off = _on_off(r, sql)
    assert on == off == [(2, 3)]


def test_nan_build_keys_do_not_poison_bounds():
    """NaN float build keys match nothing but must NOT read as an
    empty build (NaN min/max would emit constant-false and drop REAL
    matches)."""
    import jax.numpy as jnp

    from presto_tpu.page import Block, Page

    vals = np.asarray([1.0, np.nan, 5.0], dtype=np.float64)
    page = Page(
        blocks=(
            Block(
                data=jnp.asarray(vals), valid=None, dtype=T.DOUBLE
            ),
        ),
        num_valid=jnp.asarray(3, jnp.int32),
        names=("k",),
    )
    conjuncts, n = dynfilter.device_conjuncts(
        page, [("k", "k")], {"k": T.DOUBLE}
    )
    assert n == 1
    assert (conjuncts[0].low.value, conjuncts[0].high.value) == (1.0, 5.0)


# ------------------------------------------------- summary unit tests


def test_summary_merge_and_json_roundtrip():
    a = dynfilter.ColumnFilter(
        column="k", lo=5, hi=9, values=(5, 7, 9), empty=False
    )
    b = dynfilter.ColumnFilter(
        column="k", lo=1, hi=6, values=(1, 6), empty=False
    )
    m = a.merge(b, ndv_limit=10)
    assert (m.lo, m.hi) == (1, 9)
    assert m.values == (1, 5, 6, 7, 9)
    # NDV overflow drops the value set, keeps bounds
    m2 = a.merge(b, ndv_limit=3)
    assert m2.values is None and (m2.lo, m2.hi) == (1, 9)
    # empty merges are identity
    e = dynfilter.ColumnFilter(column="k")
    assert e.merge(a, 10) == a and a.merge(e, 10) == a
    s = dynfilter.FilterSummary(columns=(a,))
    assert dynfilter.FilterSummary.from_json(s.to_json()) == s


def test_to_constraint_forms():
    s = dynfilter.subset_summary([
        dynfilter.ColumnFilter(
            column="a", lo=1, hi=4, values=(1, 4), empty=False
        ),
        dynfilter.ColumnFilter(column="b", lo=2.5, hi=9.5, empty=False),
        dynfilter.ColumnFilter(column="c"),
    ])
    con = dynfilter.to_constraint(
        s, [("a", T.BIGINT), ("b", T.DOUBLE), ("c", T.BIGINT)]
    )
    d = dict(con)
    assert d["a"] == (1, 4)
    assert d["b"] == RangeSet(lo=2.5, hi=9.5)
    assert d["c"] == ()  # empty build: nothing matches


# --------------------------------------- connector-level split pruning


def test_parquet_rowgroup_pruning(tmp_path):
    (tmp_path / "s").mkdir()
    n = 1000
    pq.write_table(
        pa.table({"k": pa.array(np.arange(n, dtype=np.int64))}),
        tmp_path / "s" / "t.parquet",
        row_group_size=100,
    )
    conn = create_connector("parquet", root=str(tmp_path))
    h = TableHandle("pq", "s", "t")
    base = conn.get_splits(h, target_split_rows=100)._splits
    kept = conn.get_splits(
        h,
        target_split_rows=100,
        constraint=(("k", RangeSet(lo=250, hi=349)),),
    )._splits
    assert len(kept) < len(base)
    covered = sum(s.row_end - s.row_start for s in kept)
    assert covered <= 200  # at most two row groups survive
    # surviving splits still contain every matching row
    rows = []
    for s in kept:
        rows.extend(conn.create_page_source(s, ["k"])["k"].tolist())
    assert set(range(250, 350)) <= set(rows)
    # empty value set (empty build): nothing is read
    none = conn.get_splits(
        h, target_split_rows=100, constraint=(("k", ()),)
    )._splits
    assert sum(s.row_end - s.row_start for s in none) == 0


def test_orc_stripe_pruning(tmp_path):
    orc = pytest.importorskip("pyarrow.orc")
    (tmp_path / "s").mkdir()
    n = 200_000  # several stripes even at the 64 KiB stripe floor
    orc.write_table(
        pa.table({"k": pa.array(np.arange(n, dtype=np.int64))}),
        tmp_path / "s" / "t.orc",
        stripe_size=65536,
    )
    conn = create_connector("orc", root=str(tmp_path))
    h = TableHandle("orc", "s", "t")
    base = conn.get_splits(h, target_split_rows=1)._splits
    if len(base) < 2:
        pytest.skip("writer produced a single stripe")
    kept = conn.get_splits(
        h,
        target_split_rows=1,
        constraint=(("k", RangeSet(lo=0, hi=10)),),
    )._splits
    assert len(kept) < len(base)
    rows = []
    for s in kept:
        rows.extend(conn.create_page_source(s, ["k"])["k"].tolist())
    assert set(range(0, 11)) <= set(rows)


def test_pruned_ranges_middle_rowgroup(tmp_path):
    """Pruning the MIDDLE of a coalesced split increases the split
    count while still saving reads: the decision must compare covered
    rows, not split counts (review regression)."""
    from types import SimpleNamespace

    from presto_tpu.plan import nodes as N
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.coordinator import _Query

    (tmp_path / "s").mkdir()
    n = 300
    pq.write_table(
        pa.table({"k": pa.array(np.arange(n, dtype=np.int64))}),
        tmp_path / "s" / "t.parquet",
        row_group_size=100,
    )
    cats = CatalogManager()
    cats.register("tpch", create_connector("tpch"))
    cats.register("pq", create_connector("parquet", root=str(tmp_path)))
    coord = CoordinatorServer(catalogs=cats)
    try:
        scan = N.TableScanNode(
            handle=TableHandle("pq", "s", "t"),
            columns=("k",),
            schema=(("k", T.BIGINT),),
        )
        q = _Query("q_t0", "test")
        ranges = coord._pruned_ranges(
            q,
            SimpleNamespace(partition_rows=n),
            scan,
            (("k", RangeSet(lo=0, hi=49)),),  # prunes groups 2+3
        )
        assert ranges is not None
        assert sum(hi - lo for lo, hi in ranges) <= 100
        # middle-ONLY pruning: the one coalesced [0,300) split becomes
        # TWO surviving splits — count comparison would read that as
        # "nothing pruned"; covered rows must decide
        ranges2 = coord._pruned_ranges(
            q,
            SimpleNamespace(partition_rows=n),
            scan,
            (("k", (50, 250)),),  # group 2 (100..199) can't match
        )
        assert ranges2 is not None
        assert sum(hi - lo for lo, hi in ranges2) == 200
        assert (100, 200) not in [
            (lo, hi) for lo, hi in ranges2
        ]
    finally:
        coord.shutdown()


# ------------------------------------------------- distributed cluster


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    """Hive-partitioned probe table: year=2022..2025 partitions."""
    root = tmp_path_factory.mktemp("dynf_warehouse")
    rng = np.random.RandomState(11)
    expected = {}
    i = 0
    for year in (2022, 2023, 2024, 2025):
        d = root / "sales" / "orders" / f"year={year}"
        d.mkdir(parents=True)
        n = 150
        amt = rng.randint(1, 100, n).astype(np.int64)
        pq.write_table(
            pa.table(
                {
                    "id": pa.array(
                        np.arange(i, i + n, dtype=np.int64)
                    ),
                    "amount": pa.array(amt),
                }
            ),
            d / "part-0.parquet",
            row_group_size=64,
        )
        expected[year] = (n, int(amt.sum()))
        i += n
    return root, expected


@pytest.fixture(scope="module")
def cluster(warehouse):
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )

    root, _ = warehouse
    mem = create_connector("memory")

    def catalogs():
        c = CatalogManager()
        c.register("tpch", create_connector("tpch"))
        c.register("hive", create_connector("hive", root=str(root)))
        c.register("memory", mem)  # shared: writes visible cluster-wide
        return c

    coord = CoordinatorServer(catalogs=catalogs()).start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri, catalogs=catalogs())
        .start()
        for _ in range(2)
    ]
    _wait_workers(coord, 2)
    client = PrestoTpuClient(coord.uri, timeout_s=300)
    client.execute("create table memory.default.dim (y bigint)")
    client.execute("insert into memory.default.dim values (2024)")
    yield coord, workers, client
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


_JOIN_SQL = (
    "select count(*) as n, sum(o.amount) as s "
    "from hive.sales.orders o "
    "join memory.default.dim d on o.year = d.y"
)


def _set_session(coord, key, value):
    coord.local.session.set(key, value)


def test_distributed_splits_pruned(cluster, warehouse):
    """The acceptance headline: a selective build prunes hive
    partitions of the probe scan at SPLIT level, and filtering off
    reproduces the same results."""
    coord, _workers, client = cluster
    _, expected = warehouse
    splits0 = _counter("dynamic_filter.splits_pruned")
    built0 = _counter("dynamic_filter.built")
    on = client.execute(_JOIN_SQL)
    assert on.data == [[expected[2024][0], expected[2024][1]]]
    assert _counter("dynamic_filter.splits_pruned") > splits0
    assert _counter("dynamic_filter.built") > built0
    # per-query stats rolled into QueryInfo
    q = coord.queries[on.query_id]
    assert q.stats.dynamic_filter_splits_pruned > 0
    assert q.stats.dynamic_filters > 0
    assert q.stats.dynamic_filter_wait_ms > 0
    info = coord.query_info(q)
    assert info["dynamic_filter_splits_pruned"] > 0
    # dynfilter span recorded on the query trace
    names = {s.name for s in q.trace.spans()}
    assert "dynfilter" in names
    # OFF must reproduce the results exactly (and prune nothing)
    _set_session(coord, "enable_dynamic_filtering", "false")
    try:
        splits1 = _counter("dynamic_filter.splits_pruned")
        off = client.execute(_JOIN_SQL)
        assert off.data == on.data
        assert _counter("dynamic_filter.splits_pruned") == splits1
    finally:
        _set_session(coord, "enable_dynamic_filtering", "true")


def test_distributed_explain_analyze_renders_dynfilter(cluster):
    _coord, _workers, client = cluster
    res = client.execute("explain analyze " + _JOIN_SQL)
    text = "\n".join(r[0] for r in res.data)
    assert "dynamic filtering:" in text
    assert "splits_pruned" in text


def test_wait_timeout_proceeds_unfiltered(cluster, warehouse):
    """A zero wait budget expires before any summary arrives: the
    probe runs the exact unfiltered plan, correctly."""
    coord, _workers, client = cluster
    _, expected = warehouse
    built0 = _counter("dynamic_filter.built")
    expired0 = _counter("dynamic_filter.wait_expired")
    _set_session(coord, "dynamic_filtering_wait_ms", "0")
    try:
        res = client.execute(_JOIN_SQL)
    finally:
        _set_session(coord, "dynamic_filtering_wait_ms", "2000")
    assert res.data == [[expected[2024][0], expected[2024][1]]]
    assert _counter("dynamic_filter.built") == built0
    assert _counter("dynamic_filter.wait_expired") > expired0


def test_build_worker_kill_degrades_to_unfiltered(cluster, warehouse):
    """Chaos: the worker executing a build-summary task dies abruptly
    mid-filter. The wait degrades to the unfiltered plan and the
    query still answers correctly on the survivors."""
    from presto_tpu.server import WorkerServer

    coord, workers, client = cluster
    _, expected = warehouse
    # replacement worker keeps the cluster at 2 after the kill
    spare = WorkerServer(
        coordinator_uri=coord.uri,
        catalogs=workers[0].runner.catalogs,
    ).start()
    try:
        _wait_workers(coord, 3)
        faults.configure(
            {
                "rules": [
                    {
                        "action": "kill_worker",
                        "task": ".df.",
                        "count": 1,
                    }
                ]
            }
        )
        res = client.execute(_JOIN_SQL)
        assert res.data == [[expected[2024][0], expected[2024][1]]]
    finally:
        faults.configure(None)
        spare.shutdown(graceful=False)


def test_distributed_fuzz_draw_covers_both_toggles():
    """The per-seed session draw the distributed fuzz path applies
    must exercise dynamic filtering both ON and OFF over a short
    pinned range (the full mesh replay is the slow-tier test below)."""
    from presto_tpu.fuzz import session_draw

    draws = {
        session_draw(s)["enable_dynamic_filtering"] for s in range(8)
    }
    assert draws == {"true", "false"}


@pytest.mark.slow
def test_fuzz_distributed_toggles_dynamic_filtering():
    """The distributed fuzz path draws enable_dynamic_filtering per
    seed (fuzz.session_draw) — a pinned range must stay oracle-exact
    on the mesh (shard_map compiles make this slow-tier)."""
    from presto_tpu.fuzz import run_fuzz_distributed
    from presto_tpu.verifier import SqliteOracle

    failures = run_fuzz_distributed(
        range(0, 8), oracle=SqliteOracle("tiny")
    )
    msg = "\n".join(
        f"seed {s}: {q}\n  -> {str(d)[:300]}"
        for s, q, d in failures[:5]
    )
    assert not failures, f"{len(failures)} fuzz failures:\n{msg}"


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
