"""ORC connector (SURVEY.md §2.2 L9 file-format readers): read
pyarrow-written ORC files through the SPI, with column pruning,
stripe-aligned splits, nulls, decimals, dates, and strings — the same
engine-facing contract as the parquet connector, different physical
format."""

import datetime
import decimal

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
orc = pytest.importorskip("pyarrow.orc")

from presto_tpu.connectors import create_connector  # noqa: E402
from presto_tpu.connectors.spi import TableHandle  # noqa: E402
from presto_tpu.exec.local_runner import LocalQueryRunner  # noqa: E402
from presto_tpu.exec.staging import CatalogManager  # noqa: E402


@pytest.fixture(scope="module")
def lake(tmp_path_factory):
    root = tmp_path_factory.mktemp("orclake")
    (root / "sales").mkdir()
    n = 10_000
    rng = np.random.RandomState(11)
    region = rng.choice(["east", "west", "north", None], n, p=[.4, .3, .2, .1])
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "qty": pa.array(rng.randint(1, 100, n).astype(np.int32)),
            "price": pa.array(
                [
                    decimal.Decimal(int(v)) / 100
                    for v in rng.randint(100, 100000, n)
                ],
                type=pa.decimal128(12, 2),
            ),
            "day": pa.array(
                [
                    datetime.date(2024, 1, 1) + datetime.timedelta(days=int(d))
                    for d in rng.randint(0, 365, n)
                ]
            ),
            "region": pa.array(region.tolist()),
            "score": pa.array(rng.rand(n)),
        }
    )
    # small stripes so split tests exercise multi-stripe mapping
    orc.write_table(table, root / "sales" / "orders.orc", stripe_size=65536)
    return root, table


@pytest.fixture(scope="module")
def runner(lake):
    root, _ = lake
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("lake", create_connector("orc", root=str(root)))
    return LocalQueryRunner(catalogs=catalogs)


def test_metadata_and_stats(lake):
    root, _ = lake
    conn = create_connector("orc", root=str(root))
    md = conn.metadata()
    assert md.list_schemas() == ["sales"]
    assert md.list_tables("sales") == ["orders"]
    h = TableHandle("lake", "sales", "orders")
    schema = md.get_table_schema(h)
    assert schema["id"].name == "bigint"
    assert schema["price"].is_decimal and schema["price"].scale == 2
    assert schema["region"].is_string
    st = md.get_table_stats(h)
    assert st.row_count == 10_000


def test_stripe_splits_cover_exactly(lake):
    root, _ = lake
    conn = create_connector("orc", root=str(root))
    h = TableHandle("lake", "sales", "orders")
    src = conn.get_splits(h, target_split_rows=1024)
    splits = []
    while not src.exhausted:
        splits.extend(src.next_batch(64))
    assert splits[0].row_start == 0
    assert splits[-1].row_end == 10_000
    for a, b in zip(splits, splits[1:]):
        assert a.row_end == b.row_start
    assert len(splits) >= 2


def test_arbitrary_range_read_matches_source(lake):
    """Page source must honor exact row ranges, including ranges that
    straddle stripe boundaries at unaligned offsets."""
    root, table = lake
    conn = create_connector("orc", root=str(root))
    h = TableHandle("lake", "sales", "orders")
    offs = conn._stripe_offsets(h)
    assert offs[-1] == 10_000
    mid = offs[1] if len(offs) > 2 else 5000
    from presto_tpu.connectors.spi import ConnectorSplit

    lo, hi = mid - 7, mid + 13
    page = conn.create_page_source(ConnectorSplit(h, lo, hi), ["id", "qty"])
    np.testing.assert_array_equal(
        np.asarray(page["id"]), np.arange(lo, hi, dtype=np.int64)
    )
    np.testing.assert_array_equal(
        np.asarray(page["qty"]),
        table.column("qty").to_numpy()[lo:hi].astype(np.int32),
    )


def test_full_scan_agg(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select count(*) as n, sum(qty) as q from lake.sales.orders"
    ).rows()
    assert rows == [(10_000, int(np.sum(table.column("qty").to_numpy())))]


def test_strings_nulls_and_groupby(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select region, count(*) as n from lake.sales.orders "
        "group by region order by region nulls last"
    ).rows()
    import collections

    expect = collections.Counter(table.column("region").to_pylist())
    got = {r: n for r, n in rows}
    assert got == dict(expect)


def test_decimal_exactness(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select sum(price) as s from lake.sales.orders where qty < 10"
    ).rows()
    qty = np.asarray(table.column("qty").to_numpy())
    price = [decimal.Decimal(str(v)) for v in table.column("price").to_pylist()]
    expect = sum(p for p, q in zip(price, qty) if q < 10)
    assert rows[0][0] == pytest.approx(float(expect), rel=1e-12)


def test_date_filter(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select count(*) as n from lake.sales.orders "
        "where day >= date '2024-07-01'"
    ).rows()
    days = table.column("day").to_pylist()
    expect = sum(1 for d in days if d >= datetime.date(2024, 7, 1))
    assert rows == [(expect,)]


def test_empty_orc_table(tmp_path):
    """A 0-row ORC file (0 stripes) must scan as an empty result, not
    crash on null-typed arrays."""
    (tmp_path / "s").mkdir()
    empty = pa.table(
        {
            "a": pa.array([], type=pa.int64()),
            "b": pa.array([], type=pa.string()),
        }
    )
    orc.write_table(empty, tmp_path / "s" / "t.orc")
    from presto_tpu.exec.staging import CatalogManager

    catalogs = CatalogManager()
    catalogs.register("lake", create_connector("orc", root=str(tmp_path)))
    r = LocalQueryRunner(catalogs=catalogs)
    assert r.execute("select count(*) as n from lake.s.t").rows() == [(0,)]
    assert r.execute("select a, b from lake.s.t").rows() == []


def test_join_orc_with_tpch(runner, lake):
    _, table = lake
    rows = runner.execute(
        "select r_name, count(*) as n "
        "from lake.sales.orders, tpch.tiny.region "
        "where qty = r_regionkey group by r_name order by r_name"
    ).rows()
    qty = table.column("qty").to_numpy()
    expect = sum(1 for q in qty if 0 <= q <= 4)
    assert sum(n for _, n in rows) == expect
    assert 0 < len(rows) <= 5
