"""Admission control + memory guardrails (VERDICT #10; reference:
DispatchManager/resource groups + MemoryPool/ClusterMemoryManager,
SURVEY.md §2.1)."""

import threading
import time

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.server import CoordinatorServer
from presto_tpu.session import Session
from presto_tpu.utils.memory import (
    MemoryLimitExceeded,
    MemoryPool,
    QueryMemoryContext,
)


def test_memory_pool_reserve_release():
    pool = MemoryPool(1000)
    pool.reserve("q1", 600)
    pool.reserve("q2", 300)
    with pytest.raises(MemoryLimitExceeded):
        pool.reserve("q3", 200)
    assert pool.used_bytes() == 900
    pool.release("q1")
    pool.reserve("q3", 600)
    assert pool.used_bytes("q3") == 600


def test_query_context_noop_without_pool():
    ctx = QueryMemoryContext(None, "q")
    ctx.reserve(1 << 40)  # no pool: accounting disabled
    ctx.release_all()


def test_runner_accounts_staged_pages():
    pool = MemoryPool(1 << 30)
    r = LocalQueryRunner(memory_pool=pool)
    r.execute("select count(*) as c from tpch.tiny.region")
    # tpch is cacheable: staged bytes land under the shared cache owner
    assert pool.used_bytes("table-cache") > 0


def test_runner_memory_limit_fails_query():
    pool = MemoryPool(1024)  # far below any staged table
    r = LocalQueryRunner(memory_pool=pool)
    with pytest.raises(MemoryLimitExceeded):
        r.execute("select count(*) as c from tpch.tiny.region")


def test_coordinator_sheds_load_beyond_queue():
    """Submissions beyond max_queued are REJECTED, not accumulated."""
    coord = CoordinatorServer(
        max_concurrent_queries=1, max_queued_queries=2
    )
    # no .start(): exercise submit() directly.  Block the single
    # execution slot so later submissions must queue.
    release = threading.Event()
    orig = coord._run_sql

    def slow(q):
        release.wait(timeout=30)
        return orig(q)

    coord._run_sql = slow
    try:
        qs = [
            coord.submit("select count(*) as c from tpch.tiny.region")
            for _ in range(4)
        ]
        time.sleep(0.3)
        states = [q.state for q in qs]
        assert states.count("FAILED") == 2, states  # shed, not queued
        assert all(
            "rejected" in (q.error or "").lower()
            for q in qs
            if q.state == "FAILED"
        )
        release.set()
        for q in qs:
            if q.state != "FAILED":
                q.done.wait(timeout=60)
        done_states = [q.state for q in qs]
        assert done_states.count("FINISHED") == 2, done_states
    finally:
        release.set()
        coord.shutdown()
