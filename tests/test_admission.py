"""Admission control + memory guardrails (VERDICT #10; reference:
DispatchManager/resource groups + MemoryPool/ClusterMemoryManager,
SURVEY.md §2.1)."""

import threading
import time

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.server import CoordinatorServer
from presto_tpu.session import Session
from presto_tpu.utils.memory import (
    MemoryLimitExceeded,
    MemoryPool,
    QueryMemoryContext,
)


def test_memory_pool_reserve_release():
    pool = MemoryPool(1000)
    pool.reserve("q1", 600)
    pool.reserve("q2", 300)
    with pytest.raises(MemoryLimitExceeded):
        pool.reserve("q3", 200)
    assert pool.used_bytes() == 900
    pool.release("q1")
    pool.reserve("q3", 600)
    assert pool.used_bytes("q3") == 600


def test_query_context_noop_without_pool():
    ctx = QueryMemoryContext(None, "q")
    ctx.reserve(1 << 40)  # no pool: accounting disabled
    ctx.release_all()


def test_runner_accounts_staged_pages():
    pool = MemoryPool(1 << 30)
    r = LocalQueryRunner(memory_pool=pool)
    r.execute("select count(*) as c from tpch.tiny.region")
    # tpch is cacheable: staged bytes land under the shared cache owner
    assert pool.used_bytes("table-cache") > 0


def test_runner_memory_limit_fails_query():
    pool = MemoryPool(1024)  # far below any staged table
    r = LocalQueryRunner(memory_pool=pool)
    with pytest.raises(MemoryLimitExceeded):
        r.execute("select count(*) as c from tpch.tiny.region")


def test_coordinator_sheds_load_beyond_queue():
    """Submissions beyond max_queued are REJECTED, not accumulated."""
    coord = CoordinatorServer(
        max_concurrent_queries=1, max_queued_queries=2
    )
    # no .start(): exercise submit() directly.  Block the single
    # execution slot so later submissions must queue.
    release = threading.Event()
    orig = coord._run_sql

    def slow(q):
        release.wait(timeout=30)
        return orig(q)

    coord._run_sql = slow
    try:
        qs = [
            coord.submit("select count(*) as c from tpch.tiny.region")
            for _ in range(4)
        ]
        time.sleep(0.3)
        states = [q.state for q in qs]
        assert states.count("FAILED") == 2, states  # shed, not queued
        assert all(
            "rejected" in (q.error or "").lower()
            for q in qs
            if q.state == "FAILED"
        )
        release.set()
        for q in qs:
            if q.state != "FAILED":
                q.done.wait(timeout=60)
        done_states = [q.state for q in qs]
        assert done_states.count("FINISHED") == 2, done_states
    finally:
        release.set()
        coord.shutdown()


# ------------------------------------------------- resource groups


def test_resource_group_selection_and_limits():
    from presto_tpu.server.resource_groups import ResourceGroupManager

    mgr = ResourceGroupManager(
        {
            "rootGroups": [
                {"name": "etl", "weight": 3, "hardConcurrencyLimit": 2,
                 "maxQueued": 1},
                {"name": "adhoc", "weight": 1, "hardConcurrencyLimit": 1},
            ],
            "selectors": [{"user": "etl-.*", "group": "etl"}],
            "defaultGroup": "adhoc",
        }
    )
    assert mgr.group_of("etl-nightly").name == "etl"
    assert mgr.group_of("alice").name == "adhoc"

    started = []
    state, g = mgr.submit("etl-a", lambda: started.append("a"))
    assert (state, g) == ("run", "etl") and started == ["a"]
    state, _ = mgr.submit("etl-b", lambda: started.append("b"))
    assert state == "run"
    state, _ = mgr.submit("etl-c", lambda: started.append("c"))
    assert state == "queued" and started == ["a", "b"]
    state, msg = mgr.submit("etl-d", lambda: started.append("d"))
    assert state == "rejected" and "queue is full" in msg
    mgr.finish("etl")  # frees a slot -> queued c starts
    assert started == ["a", "b", "c"]


def test_resource_group_weighted_fairness():
    """When both groups have queued work, freed slots go to the group
    with the smallest running/weight ratio — the weight-3 group ends up
    with ~3x the admissions of the weight-1 group."""
    from presto_tpu.server.resource_groups import ResourceGroupManager

    mgr = ResourceGroupManager(
        {
            "rootGroups": [
                {"name": "heavy", "weight": 3, "hardConcurrencyLimit": 8},
                {"name": "light", "weight": 1, "hardConcurrencyLimit": 8},
            ],
            "selectors": [{"user": "heavy", "group": "heavy"}],
            "defaultGroup": "light",
        }
    )
    # saturate both groups' slots artificially: fill 4 running in each
    running = {"heavy": 0, "light": 0}
    admitted = []

    def starter(name):
        def go():
            admitted.append(name)
        return go

    # 4 running each (global cap pretend = 8), then queue 8 more per group
    for g in ("heavy", "light"):
        mgr.groups[g].running = 4
        for _ in range(8):
            mgr.groups[g].queue.append(starter(g))

    # free 8 slots, alternating finishes: fairness picks by running/weight
    for _ in range(4):
        mgr.finish("heavy")
        mgr.finish("light")
    # heavy: ratio running/3 vs light: running/1 -> heavy admitted ~3x
    h = admitted.count("heavy")
    l = admitted.count("light")
    assert h > l, admitted
    assert h >= 2 * l, admitted


def test_coordinator_routes_users_to_groups():
    """Two users share a cluster per their groups' limits: the adhoc
    group (limit 1) queues its second query while etl (limit 2) runs
    both — per-group concurrency, not global FIFO."""
    coord = CoordinatorServer(
        max_concurrent_queries=8,
        resource_groups={
            "rootGroups": [
                {"name": "etl", "weight": 3, "hardConcurrencyLimit": 2},
                {"name": "adhoc", "weight": 1,
                 "hardConcurrencyLimit": 1},
            ],
            "selectors": [{"user": "etl-.*", "group": "etl"}],
            "defaultGroup": "adhoc",
        },
    )
    release = threading.Event()
    orig = coord._run_sql

    def slow(q):
        release.wait(timeout=30)
        return orig(q)

    coord._run_sql = slow
    try:
        sql = "select count(*) as c from tpch.tiny.region"
        e1 = coord.submit(sql, user="etl-1")
        e2 = coord.submit(sql, user="etl-2")
        a1 = coord.submit(sql, user="alice")
        a2 = coord.submit(sql, user="alice")
        time.sleep(0.3)
        assert e1.resource_group == "etl" and a1.resource_group == "adhoc"
        snap = {
            g["name"]: g for g in coord.resource_groups.snapshot()
        }
        assert snap["etl"]["running"] == 2, snap
        assert snap["adhoc"]["running"] == 1, snap
        assert snap["adhoc"]["queued"] == 1, snap
        release.set()
        for q in (e1, e2, a1, a2):
            q.done.wait(timeout=60)
            assert q.state == "FINISHED", (q.state, q.error)
        snap = {g["name"]: g for g in coord.resource_groups.snapshot()}
        assert snap["adhoc"]["running"] == 0 and snap["adhoc"]["queued"] == 0
    finally:
        release.set()
        coord.shutdown()
