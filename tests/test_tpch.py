"""TPC-H correctness suite: every query verified against the sqlite
oracle over the SAME generated data (SURVEY.md §4.5 plan-correctness
harness + §4.7 cross-engine verifier pattern)."""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES

@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query(qnum, runner, oracle):
    diff = verify_query(runner, oracle, QUERIES[qnum], rel_tol=1e-6)
    assert diff is None, f"Q{qnum} mismatch: {diff}"
