"""Multi-host runtime suite: a real coordinator + N workers in one
process (loopback HTTP, real discovery, real token-acked paged
exchange), running the TPC-H corpus through ``POST /v1/statement`` —
the reference's DistributedQueryRunner pattern (SURVEY.md §4.3) applied
to the cross-host tier, plus failure-path tests (SURVEY.md §5.3).
"""

import threading
import time

import numpy as np
import pytest

from presto_tpu.server import CoordinatorServer, PrestoTpuClient, WorkerServer
from presto_tpu.server.client import QueryFailed
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES

def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError(
        f"only {len(coord.active_workers())}/{n} workers discovered"
    )


@pytest.fixture(scope="module")
def cluster():
    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    _wait_workers(coord, 2)
    yield coord, workers
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


@pytest.fixture(scope="module")
def client(cluster):
    coord, _ = cluster
    return PrestoTpuClient(coord.uri, timeout_s=600)


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_over_http(qnum, client, oracle):
    diff = verify_query(client, oracle, QUERIES[qnum], rel_tol=1e-6)
    assert diff is None, f"Q{qnum} over HTTP mismatch: {diff}"


def test_discovery_lists_workers(cluster):
    coord, workers = cluster
    ids = {w.node_id for w in coord.active_workers()}
    assert {w.node_id for w in workers} <= ids


def test_query_error_surfaces(client):
    with pytest.raises(QueryFailed):
        client.execute("select no_such_column from tpch.tiny.lineitem")


def test_worker_death_retries_on_live_worker(oracle):
    """Kill a worker mid-cluster: its range is REASSIGNED to a live
    worker and the query succeeds (recoverable execution, VERDICT r2
    item 8); the TTL eventually drops the dead node from discovery."""
    from presto_tpu.server import coordinator as coord_mod
    from presto_tpu.utils.metrics import REGISTRY

    coord = CoordinatorServer().start()
    w1 = WorkerServer(coordinator_uri=coord.uri).start()
    w2 = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 2)
        # hard-kill w2 (no graceful drain) but leave it in discovery:
        # the coordinator will schedule to it and hit a dead socket
        w2._shutting_down = True  # stop the announcer
        w2.httpd.shutdown()
        w2.httpd.server_close()  # release the socket: connection refused
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        before = REGISTRY.counter("coordinator.tasks_retried").total
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert res.rows() == [(59997,)]
        assert (
            REGISTRY.counter("coordinator.tasks_retried").total > before
        )
        # discovery TTL removes the dead node
        old_ttl = coord_mod.NODE_TTL_S
        coord_mod.NODE_TTL_S = 0.5
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                ids = {w.node_id for w in coord.active_workers()}
                if w2.node_id not in ids:
                    break
                time.sleep(0.1)
            assert w2.node_id not in {
                w.node_id for w in coord.active_workers()
            }
        finally:
            coord_mod.NODE_TTL_S = old_ttl
    finally:
        w1.shutdown(graceful=False)
        coord.shutdown()


def test_all_workers_dead_falls_back_local(oracle):
    """No spare worker to retry on: graceful degradation runs the
    fragment on the coordinator's local engine instead of failing the
    query (recoverable execution, last resort)."""
    from presto_tpu.utils.metrics import REGISTRY

    coord = CoordinatorServer().start()
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 1)
        w._shutting_down = True
        w.httpd.shutdown()
        w.httpd.server_close()
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        before = REGISTRY.counter("coordinator.local_fallbacks").total
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert res.rows() == [(59997,)]
        assert (
            REGISTRY.counter("coordinator.local_fallbacks").total
            > before
        )
    finally:
        coord.shutdown()


def test_graceful_shutdown_drains(oracle):
    """SHUTTING_DOWN: stop accepting tasks, finish running ones."""
    coord = CoordinatorServer().start()
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 1)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute("select count(*) as c from tpch.tiny.orders")
        assert res.rows() == [(15000,)]
        w.shutdown(graceful=True)
        assert w.status()["state"] == "SHUTTING_DOWN"
        from presto_tpu.server.protocol import FragmentSpec

        with pytest.raises(RuntimeError):
            w.create_task(
                FragmentSpec(
                    task_id="t",
                    query_id="q",
                    fragment=None,
                    partition_scan=0,
                    split_start=0,
                    split_end=0,
                )
            )
    finally:
        coord.shutdown()


def test_output_buffer_backpressure():
    """Producer blocks when the per-task buffer is full and resumes
    when the consumer acks by token advance."""
    from presto_tpu.server import worker as worker_mod
    from presto_tpu.server.protocol import FragmentSpec

    spec = FragmentSpec(
        task_id="t", query_id="q", fragment=None,
        partition_scan=0, split_start=0, split_end=0,
    )
    task = worker_mod._Task(spec)
    task.state = "RUNNING"
    old = worker_mod.MAX_BUFFERED_PAGES
    worker_mod.MAX_BUFFERED_PAGES = 2
    try:
        produced = []

        def produce():
            for i in range(4):
                task.offer_page(b"page%d" % i)
                produced.append(i)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        time.sleep(0.3)
        assert produced == [0, 1], "producer must block at capacity"
        task.ack_below(2)  # consumer pulled tokens 0,1
        t.join(timeout=5)
        assert produced == [0, 1, 2, 3]
        assert task.pages[0] is None and task.pages[1] is None  # freed
        assert task.pages[2] == b"page2"
    finally:
        worker_mod.MAX_BUFFERED_PAGES = old


def test_abort_unblocks_producer():
    from presto_tpu.server import worker as worker_mod
    from presto_tpu.server.protocol import FragmentSpec

    spec = FragmentSpec(
        task_id="t", query_id="q", fragment=None,
        partition_scan=0, split_start=0, split_end=0,
    )
    task = worker_mod._Task(spec)
    task.state = "RUNNING"
    old = worker_mod.MAX_BUFFERED_PAGES
    worker_mod.MAX_BUFFERED_PAGES = 1
    try:
        task.offer_page(b"p0")
        err = []

        def produce():
            try:
                task.offer_page(b"p1")
            except RuntimeError as e:
                err.append(e)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        time.sleep(0.2)
        task.abort()
        t.join(timeout=5)
        assert err, "blocked producer must raise on abort"
    finally:
        worker_mod.MAX_BUFFERED_PAGES = old


def test_merge_payloads_dictionary_remap():
    """Workers with different dictionaries merge into one id space."""
    from presto_tpu import types as T
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.server.pages_wire import merge_payloads

    p1 = {
        "s": DictColumn(
            ids=np.array([0, 1, 0], np.int32),
            values=np.array(["apple", "cherry"], object),
        ),
        "x": np.array([1, 2, 3], np.int64),
    }
    p2 = {
        "s": DictColumn(
            ids=np.array([1, 0], np.int32),
            values=np.array(["banana", "apple"], object)[[1, 0]][[0, 1]],
        ),
        "x": np.array([4, 5], np.int64),
    }
    # p2's dictionary sorted-unique: ["apple", "banana"]
    p2["s"] = DictColumn(
        ids=np.array([1, 0], np.int32),
        values=np.array(["apple", "banana"], object),
    )
    schema = {"s": T.VARCHAR, "x": T.BIGINT}
    merged = merge_payloads(
        [(p1, schema, 3), (p2, schema, 2)], schema
    )
    s = merged["s"]
    strings = [s.values[i] for i in s.ids]
    assert strings == ["apple", "cherry", "apple", "banana", "apple"]
    assert merged["x"].tolist() == [1, 2, 3, 4, 5]


def test_varchar_codec_roundtrip():
    from presto_tpu import types as T
    from presto_tpu.server.protocol import decode, encode

    for t in [T.varchar(25), T.VARCHAR, T.decimal(12, 2), T.BIGINT]:
        assert decode(encode(t)) == t
