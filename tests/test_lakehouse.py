"""Durable lakehouse snapshots (server/manifests.py + the ingest
lane's durable publish).

Covers the PR's acceptance contracts: kill-mid-commit chaos at every
publish point (data file, manifest, ``_current`` pointer, WAL commit
frame) with post-restart reads equal to the pre-kill committed state
and the acked WAL tail replayed exactly once; torn/corrupt-manifest
rollback to the parent snapshot; ``FOR VERSION AS OF`` time travel
bit-equal to what was committed — including across restart and after
compaction; compaction under concurrently pinned readers; injected
``io_error`` on all three write sites degrading to a clean commit
retry; orphan-file GC past the TTL; fsync-before-ack ordering in the
ingest WAL; and ``lakehouse.path`` unset staying bit-exact legacy
(no manifests, no new threads).
"""

import datetime
import decimal
import os
import threading
import time
from types import SimpleNamespace

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.plan.planner import PlanningError
from presto_tpu.server.ingest import IngestManager
from presto_tpu.server.manifests import ManifestStore
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

EV = TableHandle("mem", "default", "ev")
TK = ("mem", "default", "ev")


def fresh_runner():
    """A runner with a FRESH memory connector (the crash-simulation
    primitive: a new connector is an empty volatile store)."""
    catalogs = CatalogManager()
    mem = create_connector("memory")
    catalogs.register("mem", mem)
    return LocalQueryRunner(catalogs=catalogs), mem


def make_ev(mem):
    mem.create_table(EV, {"k": T.BIGINT, "v": T.DOUBLE})


def count(runner):
    return runner.execute("select count(*) from mem.default.ev").rows()[0][0]


def keys(runner, sql="select k from mem.default.ev order by k"):
    return [r[0] for r in runner.execute(sql).rows()]


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    faults.configure(None)


# --------------------------------------------------- commit + chain


def test_commit_builds_manifest_chain(tmp_path):
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, str(tmp_path / "wal"), start_thread=False,
        lakehouse_path=str(tmp_path / "lake"),
    )
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    ing.append("mem.default.ev", columns={"k": [3], "v": [3.0]})
    ing.flush()
    sids = ing.store.sids(TK)
    assert sids == sorted(sids) and len(sids) == 2
    # the chain is parent-linked back from the tip
    tip = ing.store.manifest(TK)
    assert tip.parent == sids[0]
    assert tip.row_count == 3
    # manifest contents round-trip bit-equal
    vals = ing.store.read_values(TK)
    assert vals["k"] == [1, 2, 3]
    assert vals["v"] == [1.0, 2.0, 3.0]
    ing.close(final_flush=False)


# ------------------------------------------- kill-mid-commit chaos


@pytest.mark.parametrize("site", ["data/", ".manifest", "_current"])
def test_kill_mid_publish_never_half_commits(tmp_path, site):
    """Killing the process at ANY of the three publish points leaves
    either the old snapshot or the new one — post-restart reads equal
    the pre-kill committed state and the acked tail commits exactly
    once on the new incarnation."""
    wal, lake = str(tmp_path / "wal"), str(tmp_path / "lake")
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, wal, start_thread=False, lakehouse_path=lake
    )
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    pre_kill_keys = keys(runner)
    pre_kill_sids = ing.store.sids(TK)
    # the publish dies mid-write at this site; the "process" dies with
    # it (the manager is abandoned without another flush)
    faults.configure(
        {"rules": [{"action": "io_error", "path": site, "count": 1}]}
    )
    ing.append("mem.default.ev", columns={"k": [3], "v": [3.0]})
    assert not ing.flush()
    faults.configure(None)
    ing.close(final_flush=False)

    # restart over the same WAL + lakehouse dirs, EMPTY memory store
    runner2, mem2 = fresh_runner()
    ing2 = IngestManager(
        runner2, wal, start_thread=False, lakehouse_path=lake
    )
    # pre-kill committed state is intact — never a half-commit
    assert keys(runner2) == pre_kill_keys
    assert ing2.store.sids(TK) == pre_kill_sids
    # the acked-but-uncommitted batch replayed into pending: exactly
    # one commit completes it, no duplicates
    assert ing2.stats()["pending_batches"] == 1
    ing2.flush()
    assert keys(runner2) == [1, 2, 3]
    ing2.close(final_flush=False)


def test_kill_after_publish_before_wal_frame_keeps_commit(tmp_path):
    """The fourth pipeline point: the manifest tip published but the
    WAL commit frame was lost. The tip carries the commit — replay
    reconciles committed = max(wal upto, manifest tip) and the batch
    is folded exactly once, never twice."""
    wal, lake = str(tmp_path / "wal"), str(tmp_path / "lake")
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, wal, start_thread=False, lakehouse_path=lake
    )
    ing.append("mem.default.ev", columns={"k": [1], "v": [1.0]})
    ing.flush()
    ing.append("mem.default.ev", columns={"k": [2], "v": [2.0]})
    # arm AFTER the appends so only the commit frame's write matches
    faults.configure(
        {"rules": [{"action": "io_error", "path": "wal-", "op": "write"}]}
    )
    assert ing.flush()  # publish succeeded; frame write was injected
    faults.configure(None)
    assert keys(runner) == [1, 2]
    tip = ing.store.current_sid(TK)
    ing.close(final_flush=False)

    runner2, mem2 = fresh_runner()
    ing2 = IngestManager(
        runner2, wal, start_thread=False, lakehouse_path=lake
    )
    # the manifest-carried commit survived; the WAL tail (whose frame
    # was lost) did NOT replay a second time
    assert keys(runner2) == [1, 2]
    assert ing2.stats()["pending_batches"] == 0
    assert ing2.store.current_sid(TK) == tip
    ing2.close(final_flush=False)


def test_io_error_on_each_site_degrades_to_clean_retry(tmp_path):
    """Disk-full / EIO on the data-file, manifest, or pointer write:
    the batches return to the pending front and the NEXT flush
    commits them — never an acked-batch loss, never a torn tip."""
    for i, site in enumerate(("data/", ".manifest", "_current")):
        wal = str(tmp_path / f"w{i}")
        lake = str(tmp_path / f"l{i}")
        runner, mem = fresh_runner()
        make_ev(mem)
        ing = IngestManager(
            runner, wal, start_thread=False, lakehouse_path=lake
        )
        ing.append("mem.default.ev", columns={"k": [1], "v": [1.0]})
        ing.flush()
        before = REGISTRY.counter("lakehouse.commit_retries").total
        faults.configure(
            {"rules": [{"action": "io_error", "path": site, "count": 1}]}
        )
        ing.append("mem.default.ev", columns={"k": [2], "v": [2.0]})
        assert not ing.flush()
        assert keys(runner) == [1]  # old tip intact
        assert REGISTRY.counter("lakehouse.commit_retries").total == (
            before + 1
        )
        # fault exhausted (count=1): the retry commits cleanly
        assert ing.flush()
        assert keys(runner) == [1, 2]
        assert ing.store.read_values(TK)["k"] == [1, 2]
        faults.configure(None)
        ing.close(final_flush=False)


# -------------------------------------------------- torn manifests


def test_torn_tip_rolls_back_to_parent_and_repairs_pointer(tmp_path):
    store = ManifestStore(str(tmp_path))
    store.create_table(TK, {"k": T.BIGINT})
    store.commit(TK, {"k": T.BIGINT}, {"k": [1, 2]}, 1)
    store.commit(TK, {"k": T.BIGINT}, {"k": [3]}, 2)
    # tear the tip manifest on disk (crash mid-write / bit rot)
    tip_path = tmp_path / "mem.default.ev" / "manifests" / "2.manifest"
    tip_path.write_text("garbage that fails the crc frame\n")
    before = REGISTRY.counter("lakehouse.rollbacks").total
    fresh = ManifestStore(str(tmp_path))  # no warm cache
    m = fresh.manifest(TK)
    assert m.snapshot == 1  # rolled back to the parent
    assert fresh.read_values(TK)["k"] == [1, 2]
    assert REGISTRY.counter("lakehouse.rollbacks").total == before + 1
    # the pointer was repaired: the NEXT store sees snapshot 1 as the
    # tip without another rollback
    assert ManifestStore(str(tmp_path)).current_sid(TK) == 1
    assert REGISTRY.counter("lakehouse.rollbacks").total == before + 1
    # the chain continues from the repaired parent
    fresh.commit(TK, {"k": T.BIGINT}, {"k": [4]}, 3)
    assert fresh.read_values(TK)["k"] == [1, 2, 4]


def test_missing_pointer_falls_back_to_newest_valid_manifest(tmp_path):
    store = ManifestStore(str(tmp_path))
    store.create_table(TK, {"k": T.BIGINT})
    store.commit(TK, {"k": T.BIGINT}, {"k": [1]}, 1)
    os.remove(tmp_path / "mem.default.ev" / "_current")
    fresh = ManifestStore(str(tmp_path))
    assert fresh.current_sid(TK) == 1
    assert fresh.read_values(TK)["k"] == [1]


# ------------------------------------------------- restart recovery


def test_restart_restores_rows_and_snapshot_lineage(tmp_path):
    """A restart with an EMPTY volatile store rebuilds the table from
    the manifest tip, re-registers the snapshot lineage (time travel
    survives the process), and replays the acked WAL tail exactly
    once."""
    wal, lake = str(tmp_path / "wal"), str(tmp_path / "lake")
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, wal, start_thread=False, lakehouse_path=lake
    )
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    ing.append("mem.default.ev", columns={"k": [3], "v": [3.0]})
    ing.flush()
    sid_v1, sid_v2 = ing.store.sids(TK)
    v1_rows = keys(
        runner,
        f"select k from mem.default.ev for version as of {sid_v1} "
        "order by k",
    )
    ing.append("mem.default.ev", columns={"k": [4], "v": [4.0]})  # acked tail
    ing.close(final_flush=False)

    before = REGISTRY.counter("lakehouse.restores").total
    runner2, mem2 = fresh_runner()
    ing2 = IngestManager(
        runner2, wal, start_thread=False, lakehouse_path=lake
    )
    assert REGISTRY.counter("lakehouse.restores").total == before + 1
    # committed state restored bit-equal from the durable tip
    assert keys(runner2) == [1, 2, 3]
    # time travel works across the restart, bit-equal
    assert keys(
        runner2,
        f"select k from mem.default.ev for version as of {sid_v1} "
        "order by k",
    ) == v1_rows == [1, 2]
    assert mem2.current_snapshot_id(EV) == sid_v2
    # the acked tail replays exactly once
    assert ing2.stats()["pending_batches"] == 1
    ing2.flush()
    assert keys(runner2) == [1, 2, 3, 4]
    ing2.close(final_flush=False)


def test_pre_lakehouse_history_bootstraps_into_first_manifest(tmp_path):
    """Enabling the lakehouse on a table with existing WAL-committed
    rows folds that history into the first manifest — a later restart
    serves the FULL table from the tip, not just post-enable rows."""
    wal = str(tmp_path / "wal")
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(runner, wal, start_thread=False)  # no lakehouse
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    ing.close(final_flush=False)

    lake = str(tmp_path / "lake")
    runner2, mem2 = fresh_runner()
    ing2 = IngestManager(
        runner2, wal, start_thread=False, lakehouse_path=lake
    )
    ing2.append("mem.default.ev", columns={"k": [3], "v": [3.0]})
    ing2.flush()
    assert ing2.store.read_values(TK)["k"] == [1, 2, 3]
    ing2.close(final_flush=False)

    runner3, _ = fresh_runner()
    ing3 = IngestManager(
        runner3, wal, start_thread=False, lakehouse_path=lake
    )
    assert keys(runner3) == [1, 2, 3]
    ing3.close(final_flush=False)


# ------------------------------------------------------ time travel


def test_for_version_as_of_bit_equal_on_parquet_lakehouse(tmp_path):
    """Historic pins on a manifest-backed parquet table serve the
    committed value domain bit-equal — BIGINT, DOUBLE, DECIMAL, DATE,
    VARCHAR, BOOLEAN and NULLs round-trip exactly."""
    catalogs = CatalogManager()
    pconn = create_connector(
        "parquet", root=str(tmp_path / "files"),
        lakehouse=str(tmp_path / "lake"), catalog="lake",
    )
    catalogs.register("lake", pconn)
    runner = LocalQueryRunner(catalogs=catalogs)
    tk = ("lake", "default", "t")
    schema = {
        "a": T.BIGINT,
        "b": T.DOUBLE,
        "c": T.parse_type("decimal(10,2)"),
        "d": T.DATE,
        "e": T.VARCHAR,
        "f": T.BOOLEAN,
    }
    store = pconn.manifest_store
    store.create_table(tk, schema)
    row1 = (
        1, 1.5, decimal.Decimal("12.25"),
        datetime.date(2020, 1, 31), "alpha", True,
    )
    row2 = (2, None, decimal.Decimal("-0.01"), None, None, False)
    store.commit(
        tk, schema,
        {c: [v] for c, v in zip(schema, row1)}, 1,
    )
    snap1 = runner.execute(
        "select * from lake.default.t order by a"
    ).rows()
    assert len(snap1) == 1
    store.commit(
        tk, schema,
        {c: [v] for c, v in zip(schema, row2)}, 2,
    )
    tip = runner.execute(
        "select * from lake.default.t order by a"
    ).rows()
    assert len(tip) == 2
    # the historic pin reproduces the pre-commit result bit-equal
    v1 = runner.execute(
        "select * from lake.default.t for version as of 1 order by a"
    ).rows()
    assert v1 == snap1
    # pinned-tip query equals the implicit tip, bit-equal
    v2 = runner.execute(
        "select * from lake.default.t for version as of 2 order by a"
    ).rows()
    assert v2 == tip
    # the manifest round-trips the committed Python domain exactly —
    # DECIMAL stays exact, DATE is a date, NULLs stay NULL
    vals = store.read_values(tk, 1)
    assert [vals[c][0] for c in schema] == list(row1)
    vals2 = store.read_values(tk)
    assert [vals2[c][1] for c in schema] == list(row2)


def test_for_version_as_of_validation():
    runner, mem = fresh_runner()
    make_ev(mem)
    with pytest.raises(PlanningError, match="not available"):
        runner.execute("select * from mem.default.ev for version as of 9")
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    r2 = LocalQueryRunner(catalogs=catalogs)
    with pytest.raises(PlanningError, match="does not support"):
        r2.execute(
            "select * from tpch.tiny.nation for version as of 1"
        )


# ------------------------------------------------------- compaction


def test_compaction_preserves_pinned_readers_and_bit_equality(tmp_path):
    """Compaction rewrites the tip's small files as a NEW snapshot:
    the tip stays bit-equal, historic pins keep serving the OLD files,
    and nothing is deleted until the GC TTL expires them."""
    wal, lake = str(tmp_path / "wal"), str(tmp_path / "lake")
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, wal, start_thread=False, lakehouse_path=lake,
        lakehouse_orphan_ttl_s=0.0,  # GC off during the test
    )
    for i in range(4):
        ing.append(
            "mem.default.ev", columns={"k": [i], "v": [float(i)]}
        )
        ing.flush()
    sids = ing.store.sids(TK)
    assert len(ing.store.manifest(TK).files) == 4
    pre_tip = keys(runner)
    old_sid = sids[1]
    pinned = ing.store.manifest(TK, old_sid)  # a reader's pin
    pre_old = keys(
        runner,
        f"select k from mem.default.ev for version as of {old_sid} "
        "order by k",
    )
    before = REGISTRY.counter("lakehouse.compactions").total
    assert ing.compaction_tick(force=True) == 1
    assert REGISTRY.counter("lakehouse.compactions").total == before + 1
    tip = ing.store.manifest(TK)
    assert tip.compaction and len(tip.files) == 1
    assert tip.snapshot > sids[-1]
    # tip reads bit-equal through the compacted file
    assert keys(runner) == pre_tip
    assert ing.store.read_values(TK)["k"] == pre_tip
    # the pinned reader's OLD files still serve, bit-equal
    assert ing.store.read_values(TK, old_sid)["k"] == pre_old
    assert [
        r.as_py() if hasattr(r, "as_py") else r
        for r in ing.store.read_arrow(TK, pinned).column("k").to_pylist()
    ] == pre_old
    assert keys(
        runner,
        f"select k from mem.default.ev for version as of {old_sid} "
        "order by k",
    ) == pre_old
    # a second tick is a no-op (one big file; nothing small to merge)
    assert ing.compaction_tick(force=True) == 0
    ing.close(final_flush=False)


def test_compaction_defers_to_foreground_qos_load(tmp_path):
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, str(tmp_path / "wal"), start_thread=False,
        lakehouse_path=str(tmp_path / "lake"),
    )
    for i in range(4):
        ing.append(
            "mem.default.ev", columns={"k": [i], "v": [float(i)]}
        )
        ing.flush()
    runner.cluster = SimpleNamespace(
        qos=SimpleNamespace(background_idle=lambda: False)
    )
    before = REGISTRY.counter("lakehouse.compaction_deferred").total
    assert ing.compaction_tick() == 0  # busy lanes: the tick yields
    assert REGISTRY.counter(
        "lakehouse.compaction_deferred"
    ).total == before + 1
    runner.cluster.qos = SimpleNamespace(background_idle=lambda: True)
    assert ing.compaction_tick() == 1  # idle: housekeeping proceeds
    ing.close(final_flush=False)


def test_qos_background_idle_tracks_lane_occupancy():
    from presto_tpu.server.qos import QosController

    coord = SimpleNamespace(resource_groups=None, _shutting_down=False)
    qos = QosController(coord, None, 2)
    assert qos.background_idle()
    q = SimpleNamespace(
        qid="q_c1_x", resource_group="adhoc", qos_suspensions=0,
        done=threading.Event(), state="FAILED",
    )
    assert qos.qos_admit(q)
    assert not qos.background_idle()
    qos.qos_release(q)
    assert qos.background_idle()


# --------------------------------------------------------------- gc


def test_gc_reclaims_orphans_and_expired_history_past_ttl(tmp_path):
    store = ManifestStore(str(tmp_path))
    store.create_table(TK, {"k": T.BIGINT})
    for sid in (1, 2, 3):
        store.commit(TK, {"k": T.BIGINT}, {"k": [sid]}, sid)
    # a failed commit strands a data file with no manifest (the
    # manifest write dies after the data file landed)
    faults.configure(
        {"rules": [{"action": "io_error", "path": ".manifest"}]}
    )
    with pytest.raises(OSError):
        store.commit(TK, {"k": T.BIGINT}, {"k": [99]}, 4)
    faults.configure(None)
    ddir = tmp_path / "mem.default.ev" / "data"
    assert len(list(ddir.iterdir())) == 4  # 3 live + 1 orphan
    # within the TTL nothing is reclaimed — pinned readers of recent
    # snapshots keep their files
    assert store.gc_orphans(ttl_s=3600.0) == 0
    # age everything past the TTL: the orphan and the non-tip history
    # expire; the tip keeps serving
    for sub in ("data", "manifests"):
        for p in (tmp_path / "mem.default.ev" / sub).iterdir():
            os.utime(p, (time.time() - 10, time.time() - 10))
    removed = store.gc_orphans(ttl_s=1.0)
    assert removed > 0
    fresh = ManifestStore(str(tmp_path))
    assert fresh.current_sid(TK) == 3
    assert fresh.read_values(TK)["k"] == [1, 2, 3]
    # expired history is gone from the chain (time travel truncated)
    assert fresh.manifest(TK, 1) is None
    # every surviving data file is referenced by the tip
    tip_files = {f.name for f in fresh.manifest(TK).files}
    assert {p.name for p in ddir.iterdir()} == tip_files


# ---------------------------------------------------- fsync-discipline


def test_wal_append_fsyncs_before_ack(tmp_path, monkeypatch):
    """The acked-durable contract: every WAL append syncs (write,
    then fsync, same file) BEFORE append() returns."""
    ops = []
    real = faults.maybe_inject_io
    monkeypatch.setattr(
        faults, "maybe_inject_io",
        lambda op, path: (ops.append((op, path)), real(op, path))[1],
    )
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(runner, str(tmp_path), start_thread=False)
    ing.append("mem.default.ev", columns={"k": [1], "v": [1.0]})
    writes = [(o, p) for o, p in ops if "wal-" in p]
    assert [o for o, _ in writes] == ["write", "fsync"]
    assert writes[0][1] == writes[1][1]
    ing.close(final_flush=False)


def test_spool_commit_fsyncs_pages_before_marker(tmp_path, monkeypatch):
    from presto_tpu.server.spool import ExchangeSpool

    ops = []
    real = faults.maybe_inject_io
    monkeypatch.setattr(
        faults, "maybe_inject_io",
        lambda op, path: (ops.append((op, path)), real(op, path))[1],
    )
    sp = ExchangeSpool(str(tmp_path))
    tid = "q_c9.prod.0.a0"
    sp.append(tid, 0, b"payload")
    sp.commit(tid)
    kinds = [(o, os.path.basename(p)) for o, p in ops]
    # pages fsync strictly precedes the marker write
    assert kinds.index(("fsync", f"{tid}.0.pages")) < kinds.index(
        ("write", f"{tid}.ok")
    )


# --------------------------------------------------- legacy bit-exact


def test_lakehouse_unset_is_bit_exact_legacy(tmp_path):
    """No ``lakehouse.path``: no manifest store, no compaction
    thread, no manifest files anywhere — the WAL-only lane behaves
    exactly as before."""
    runner, mem = fresh_runner()
    make_ev(mem)
    threads_before = {t.name for t in threading.enumerate()}
    ing = IngestManager(runner, str(tmp_path / "wal"), start_thread=True)
    assert ing.store is None
    assert ing._compact_thread is None
    assert not any(
        t.name == "lakehouse-compaction" for t in threading.enumerate()
    )
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    assert keys(runner) == [1, 2]
    ing.close()
    # the WAL dir holds only WAL segments — zero manifest artifacts
    names = os.listdir(str(tmp_path / "wal"))
    assert names and all(n.startswith("wal-") for n in names)
    assert {t.name for t in threading.enumerate()} - threads_before <= set()
    # a parquet connector without the lakehouse config has no store
    # and serves nothing versioned
    pconn = create_connector("parquet", root=str(tmp_path / "files"))
    assert pconn.manifest_store is None


# ----------------------------------------------------- runtime view


def test_system_runtime_snapshots_view(tmp_path):
    runner, mem = fresh_runner()
    make_ev(mem)
    ing = IngestManager(
        runner, str(tmp_path / "wal"), start_thread=False,
        lakehouse_path=str(tmp_path / "lake"),
    )
    runner.ingest = ing
    ing.append("mem.default.ev", columns={"k": [1, 2], "v": [1.0, 2.0]})
    ing.flush()
    rows = runner.execute(
        "select * from system.runtime.snapshots"
    ).rows()
    assert len(rows) == 1
    table, sid, snaps, files, nbytes, nrows, state = rows[0]
    assert table == "mem.default.ev"
    assert sid == ing.store.current_sid(TK)
    assert (snaps, files, nrows) == (1, 1, 2)
    assert nbytes > 0
    assert state in ("none", "pending", "compacted")
    # no lakehouse mounted: the view is empty, never an error
    runner2, _ = fresh_runner()
    assert runner2.execute(
        "select count(*) from system.runtime.snapshots"
    ).rows() == [(0,)]
    ing.close(final_flush=False)
