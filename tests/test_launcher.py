"""Config-file bootstrap (SURVEY.md §5.6 tiers 1+2 + PrestoServer
launcher): etc/ directories boot real coordinator/worker nodes."""

import time

import pytest

from presto_tpu.server.launcher import launch, load_etc, parse_properties
from presto_tpu.server import PrestoTpuClient


def _write_etc(tmp_path, name, config_lines, catalogs=None):
    etc = tmp_path / name
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text("\n".join(config_lines) + "\n")
    for cat, lines in (catalogs or {}).items():
        (etc / "catalog" / f"{cat}.properties").write_text(
            "\n".join(lines) + "\n"
        )
    return str(etc)


def test_parse_properties(tmp_path):
    p = tmp_path / "x.properties"
    p.write_text("# comment\n\na=1\nb = two words \n")
    assert parse_properties(str(p)) == {"a": "1", "b": "two words"}


def test_unknown_config_key_fails_fast(tmp_path):
    etc = _write_etc(tmp_path, "bad", ["coordinator=true", "no.such.key=1"])
    with pytest.raises(KeyError, match="no.such.key"):
        load_etc(etc)


def test_catalog_requires_connector_name(tmp_path):
    etc = _write_etc(
        tmp_path, "badcat", ["coordinator=true"], {"broken": ["foo=1"]}
    )
    with pytest.raises(ValueError, match="connector.name"):
        load_etc(etc)


def test_launch_cluster_from_etc(tmp_path):
    coord_etc = _write_etc(
        tmp_path,
        "coord",
        ["coordinator=true", "query.max-memory-per-node=2GB"],
        {"tpch": ["connector.name=tpch"], "mem": ["connector.name=memory"]},
    )
    coord = launch(coord_etc)
    try:
        assert coord.memory_pool.limit == 2 << 30
        assert coord.local.catalogs.has("mem")
        worker_etc = _write_etc(
            tmp_path,
            "worker",
            ["coordinator=false", f"discovery.uri={coord.uri}"],
            {"tpch": ["connector.name=tpch"]},
        )
        worker = launch(worker_etc)
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not coord.active_workers():
                time.sleep(0.05)
            assert coord.active_workers(), "worker not discovered"
            client = PrestoTpuClient(coord.uri, timeout_s=120)
            res = client.execute(
                "select count(*) as c from tpch.tiny.region"
            )
            assert res.rows() == [(5,)]
        finally:
            worker.shutdown(graceful=False)
    finally:
        coord.shutdown()
