"""Null-aware NOT IN (VERDICT r3 missing item 9): ``x NOT IN (S)``
follows SQL three-valued logic, not NOT-EXISTS semantics —

  - S empty                -> TRUE for every x, including NULL x
  - x NULL, S non-empty    -> UNKNOWN (row dropped)
  - S contains a NULL      -> no row can pass (match -> FALSE,
                              non-match -> UNKNOWN)

Reference parity: the null-aware anti join rewrite (SURVEY.md §2.1
"Logical planner" subquery rewrites)."""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager


@pytest.fixture(scope="module")
def runner():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    for name in ("probe", "s_plain", "s_null", "s_empty"):
        mem.create_table(
            TableHandle("mem", "default", name),
            {"k": T.INTEGER} if name != "probe" else {
                "id": T.INTEGER, "k": T.INTEGER
            },
        )
    catalogs.register("mem", mem)
    r = LocalQueryRunner(catalogs=catalogs)
    r.execute(
        "insert into mem.default.probe values "
        "(1, 10), (2, 20), (3, 30), (4, null)"
    )
    r.execute("insert into mem.default.s_plain values (10), (99)")
    r.execute("insert into mem.default.s_null values (10), (null)")
    return r


def q(runner, sub):
    return runner.execute(
        "select id from mem.default.probe "
        f"where k not in (select k from mem.default.{sub}) order by id"
    ).rows()


def test_not_in_plain(runner):
    # 10 matches -> out; 20, 30 keep; NULL k -> UNKNOWN -> dropped
    assert q(runner, "s_plain") == [(2,), (3,)]


def test_not_in_null_in_subquery(runner):
    # S contains NULL: no probe row can ever satisfy NOT IN
    assert q(runner, "s_null") == []


def test_not_in_empty_subquery(runner):
    # S empty: every row passes, including the NULL-k row
    assert q(runner, "s_empty") == [(1,), (2,), (3,), (4,)]


def test_in_unchanged(runner):
    rows = runner.execute(
        "select id from mem.default.probe "
        "where k in (select k from mem.default.s_plain) order by id"
    ).rows()
    assert rows == [(1,)]


def test_not_in_tpch_regression(runner):
    """A null-free TPC-H-shaped NOT IN keeps its old (anti join)
    answer under the null-aware rewrite."""
    rows = runner.execute(
        "select count(*) from tpch.tiny.customer "
        "where c_custkey not in (select o_custkey from tpch.tiny.orders "
        "where o_orderkey < 1000)"
    ).rows()
    rows2 = runner.execute(
        "select count(*) from tpch.tiny.customer c "
        "where not exists (select 1 from tpch.tiny.orders o "
        "where o.o_custkey = c.c_custkey and o.o_orderkey < 1000)"
    ).rows()
    assert rows == rows2
    assert 0 < rows[0][0] < 1500
