"""TPC-DS correctness suite: BASELINE.json configs Q64/Q95 plus a
breadth corpus, every query verified against the sqlite oracle over the
SAME generated data (the TPC-H suite's §4.5/§4.7 harness applied to the
second benchmark catalog)."""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query

from presto_tpu.queries_tpcds import BREADTH, OFFICIAL, Q64, Q95


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny", catalog="tpcds")


@pytest.mark.parametrize("name", sorted(BREADTH))
def test_tpcds_breadth(name, runner, oracle):
    diff = verify_query(runner, oracle, BREADTH[name], rel_tol=1e-6)
    assert diff is None, f"{name} mismatch: {diff}"


#: queries whose official filters select nothing at the tiny scale
#: (arm selectivity below one row — q41's color/size/unit combos over
#: 180 items; q44/q76's NULL-key filters over NULL-free generator
#: columns; q4's triple-channel growth conjunction) — they stay
#: oracle-exact, and SF1 provides the non-vacuous coverage
EMPTY_AT_TINY = {"q4", "q24", "q41", "q44", "q54", "q58", "q76", "q91"}

#: compile-heavy shapes (many-subquery / many-CTE-instance plans) kept
#: out of the default CI run; the slow tier still exercises them
HEAVY = {"q4", "q9", "q11", "q14", "q23", "q49", "q66", "q67", "q72", "q74", "q88"}


@pytest.mark.parametrize(
    "name",
    [
        pytest.param(n, marks=pytest.mark.slow) if n in HEAVY else n
        for n in sorted(OFFICIAL)
    ],
)
def test_tpcds_official(name, runner, oracle):
    """Official TPC-DS templates beyond the BASELINE pair, oracle-exact
    and non-vacuous (substitution parameters probed against the
    deterministic generator)."""
    diff = verify_query(runner, oracle, OFFICIAL[name], rel_tol=1e-6)
    assert diff is None, f"{name} mismatch: {diff}"
    # diff None => engine rows == oracle rows, so the cheap sqlite side
    # suffices for the non-vacuousness check
    if name not in EMPTY_AT_TINY:
        assert len(oracle.execute(OFFICIAL[name])) > 0, (
            f"{name} selected nothing"
        )


def test_tpcds_q95(runner, oracle):
    diff = verify_query(runner, oracle, Q95, rel_tol=1e-6)
    assert diff is None, f"Q95 mismatch: {diff}"
    # the parameters must select a real slice, not a vacuous empty set
    rows = runner.execute(Q95).rows()
    assert rows[0][0] > 0, f"Q95 selected nothing: {rows}"


def test_tpcds_q64(runner, oracle):
    diff = verify_query(runner, oracle, Q64, rel_tol=1e-6)
    assert diff is None, f"Q64 mismatch: {diff}"
    rows = runner.execute(Q64).rows()
    assert len(rows) > 0, "Q64 selected nothing"
