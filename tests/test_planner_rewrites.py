"""Planner decorrelation rewrites — targeted semantics tests over the
memory connector (the reference's ApplyNode-transformation unit-test
style, SURVEY.md §4.2 plan-correctness harness)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.session import Session


@pytest.fixture()
def mem_runner():
    conn = create_connector("memory")
    outer = TableHandle("mem", "default", "outer_t")
    conn.create_table(outer, {"k": T.INTEGER, "c": T.INTEGER})
    conn.append_rows(
        outer,
        {
            "k": np.asarray([1, 1, 2, 3, 4]),
            "c": np.asarray([5, 6, 7, None, 9], dtype=object),
        },
    )
    inner = TableHandle("mem", "default", "inner_t")
    conn.create_table(inner, {"k": T.INTEGER, "c": T.INTEGER})
    conn.append_rows(
        inner,
        {
            # k=1: rows c=5, NULL      k=2: row c=7     k=4: rows 9, 10
            "k": np.asarray([1, 1, 2, 4, 4]),
            "c": np.asarray([5, None, 7, 9, 10], dtype=object),
        },
    )
    cats = CatalogManager()
    cats.register("mem", conn)
    return LocalQueryRunner(
        catalogs=cats, session=Session(catalog="mem", schema="default")
    )


EXISTS_SQL = (
    "select k, c from mem.default.outer_t o where exists ("
    "  select * from mem.default.inner_t i"
    "  where i.k = o.k and i.c <> o.c) order by k, c"
)

NOT_EXISTS_SQL = (
    "select k, c from mem.default.outer_t o where not exists ("
    "  select * from mem.default.inner_t i"
    "  where i.k = o.k and i.c <> o.c) order by k, c"
)


def test_exists_inequality_null_semantics(mem_runner):
    """Inner NULLs never satisfy <>; outer NULL c forces EXISTS false.

    outer (1,5): inner k=1 non-null c = {5} -> no c<>5 -> false
    outer (1,6): inner k=1 non-null c = {5} -> 5<>6    -> true
    outer (2,7): inner k=2 c={7}            -> false
    outer (3,NULL): no inner k=3            -> false
    outer (4,9): inner k=4 c={9,10} -> 10<>9 -> true
    """
    rows = mem_runner.execute(EXISTS_SQL).rows()
    assert rows == [(1, 6), (4, 9)]


def test_not_exists_inequality_null_semantics(mem_runner):
    """NOT EXISTS is the complement, including UNKNOWN->false rows."""
    rows = mem_runner.execute(NOT_EXISTS_SQL).rows()
    assert rows == [(1, 5), (2, 7), (3, None)]


def test_not_exists_outer_null_c(mem_runner):
    """An outer row with c NULL: every comparison UNKNOWN -> EXISTS
    false -> NOT EXISTS true, even when inner rows share the key."""
    conn = mem_runner.catalogs.get("mem")
    h = TableHandle("mem", "default", "outer2")
    conn.create_table(h, {"k": T.INTEGER, "c": T.INTEGER})
    conn.append_rows(
        h,
        {
            "k": np.asarray([4]),
            "c": np.asarray([None], dtype=object),
        },
    )
    sql = (
        "select k from mem.default.outer2 o where not exists ("
        "  select * from mem.default.inner_t i"
        "  where i.k = o.k and i.c <> o.c)"
    )
    assert mem_runner.execute(sql).rows() == [(4,)]
