"""Registry-driven aggregates + window functions (VERDICT r4 ask 4).

The planner resolves every aggregate through functions.AGGREGATE and
every window call through functions.WINDOW — no hardcoded name sets.
Composed aggregates (avg/variance/corr/covar/regr/moments/checksum/
count_if) lower to primitive mergeable states + a finisher projection;
order-statistic kernels (approx_percentile/min_by/max_by) ride the
sorted aggregation path. Verification: sqlite oracle where sqlite has
the function, numpy closed forms elsewhere (SURVEY.md §4.7 pattern).
"""

import math

import numpy as np
import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


def _col(runner, sql):
    return np.array(
        [r[0] for r in runner.execute(sql).rows()], dtype=float
    )


# --------------------------------------------------- two-arg aggregates


def test_corr_covar_regr_vs_numpy(runner):
    rows = runner.execute(
        "select l_quantity, l_extendedprice from tpch.tiny.lineitem"
    ).rows()
    x = np.array([r[0] for r in rows], float)  # quantity
    y = np.array([r[1] for r in rows], float)  # extendedprice
    got = runner.execute(
        "select corr(l_extendedprice, l_quantity) c, "
        "covar_samp(l_extendedprice, l_quantity) cs, "
        "covar_pop(l_extendedprice, l_quantity) cp, "
        "regr_slope(l_extendedprice, l_quantity) sl, "
        "regr_intercept(l_extendedprice, l_quantity) ic "
        "from tpch.tiny.lineitem"
    ).rows()[0]
    n = len(x)
    cov_pop = ((x - x.mean()) * (y - y.mean())).mean()
    cov_samp = cov_pop * n / (n - 1)
    corr = cov_pop / (x.std() * y.std())
    slope = cov_pop / x.var()
    icept = y.mean() - slope * x.mean()
    for got_v, want in zip(
        got, (corr, cov_samp, cov_pop, slope, icept)
    ):
        assert math.isclose(got_v, want, rel_tol=1e-9), (got, want)


def test_corr_skips_null_pairs(runner):
    # nullif injects NULLs into one side; corr must drop those PAIRS
    rows = runner.execute(
        "select l_quantity, l_extendedprice from tpch.tiny.lineitem "
        "where l_quantity != 25"
    ).rows()
    x = np.array([r[0] for r in rows], float)
    y = np.array([r[1] for r in rows], float)
    got = runner.execute(
        "select corr(l_extendedprice, nullif(l_quantity, 25)) "
        "from tpch.tiny.lineitem"
    ).rows()[0][0]
    cov = ((x - x.mean()) * (y - y.mean())).mean()
    want = cov / (x.std() * y.std())
    assert math.isclose(got, want, rel_tol=1e-9)


def test_corr_grouped(runner):
    got = runner.execute(
        "select l_returnflag, corr(l_extendedprice, l_quantity) c "
        "from tpch.tiny.lineitem group by l_returnflag "
        "order by l_returnflag"
    ).rows()
    for flag, c in got:
        rows = runner.execute(
            "select l_quantity, l_extendedprice from tpch.tiny.lineitem "
            f"where l_returnflag = '{flag}'"
        ).rows()
        x = np.array([r[0] for r in rows], float)
        y = np.array([r[1] for r in rows], float)
        cov = ((x - x.mean()) * (y - y.mean())).mean()
        want = cov / (x.std() * y.std())
        assert math.isclose(c, want, rel_tol=1e-9), (flag, c, want)


# ------------------------------------------------------ moment family


def test_skewness_kurtosis_geometric_mean(runner):
    x = _col(runner, "select l_quantity from tpch.tiny.lineitem")
    got = runner.execute(
        "select skewness(l_quantity) s, kurtosis(l_quantity) k, "
        "geometric_mean(l_quantity) g from tpch.tiny.lineitem"
    ).rows()[0]
    n = len(x)
    d = x - x.mean()
    m2, m3, m4 = (d**2).sum(), (d**3).sum(), (d**4).sum()
    skew = math.sqrt(n) * m3 / m2**1.5
    kurt = (
        (n * (n + 1) / ((n - 1) * (n - 2) * (n - 3)))
        * ((n - 1) ** 2 * m4 / m2**2)
        - 3 * (n - 1) ** 2 / ((n - 2) * (n - 3))
    )
    gm = math.exp(np.log(x).mean())
    assert math.isclose(got[0], skew, rel_tol=1e-6, abs_tol=1e-9)
    assert math.isclose(got[1], kurt, rel_tol=1e-6)
    assert math.isclose(got[2], gm, rel_tol=1e-9)


def test_count_if_vs_oracle(runner, oracle):
    # sqlite spells it sum(case ...) — compare totals directly
    got = runner.execute(
        "select l_returnflag, count_if(l_quantity > 25) c "
        "from tpch.tiny.lineitem group by l_returnflag "
        "order by l_returnflag"
    ).rows()
    want = runner.execute(
        "select l_returnflag, count(*) c from tpch.tiny.lineitem "
        "where l_quantity > 25 group by l_returnflag "
        "order by l_returnflag"
    ).rows()
    assert [(f, int(c)) for f, c in got] == [
        (f, int(c)) for f, c in want
    ]


# ----------------------------------------------------------- checksum


def test_checksum_order_insensitive(runner):
    a = runner.execute(
        "select checksum(l_orderkey) from tpch.tiny.lineitem"
    ).rows()[0][0]
    b = runner.execute(
        "select checksum(k) from (select l_orderkey as k "
        "from tpch.tiny.lineitem order by l_quantity desc) t"
    ).rows()[0][0]
    assert a == b and a != 0
    c = runner.execute(
        "select checksum(l_orderkey + 1) from tpch.tiny.lineitem"
    ).rows()[0][0]
    assert a != c  # value-sensitive
    # NULLs contribute (not skipped): masking values must change it
    d = runner.execute(
        "select checksum(nullif(l_orderkey, 1)) from tpch.tiny.lineitem"
    ).rows()[0][0]
    assert a != d


# ---------------------------------------------------- order statistics


def test_approx_percentile_exact(runner):
    x = np.sort(
        _col(runner, "select l_quantity from tpch.tiny.lineitem")
    )
    n = len(x)
    for p in (0.0, 0.25, 0.5, 0.9, 1.0):
        got = runner.execute(
            f"select approx_percentile(l_quantity, {p}) "
            "from tpch.tiny.lineitem"
        ).rows()[0][0]
        k = min(max(int(math.ceil(p * n)) - 1, 0), n - 1)
        assert float(got) == x[k], (p, got, x[k])


def test_approx_percentile_grouped(runner):
    got = runner.execute(
        "select l_linestatus, approx_percentile(l_extendedprice, 0.5) "
        "from tpch.tiny.lineitem group by l_linestatus "
        "order by l_linestatus"
    ).rows()
    for status, med in got:
        x = np.sort(
            _col(
                runner,
                "select l_extendedprice from tpch.tiny.lineitem "
                f"where l_linestatus = '{status}'",
            )
        )
        k = min(max(int(math.ceil(0.5 * len(x))) - 1, 0), len(x) - 1)
        assert math.isclose(float(med), x[k], rel_tol=1e-12), (
            status, med, x[k],
        )


def test_min_by_max_by(runner):
    rows = runner.execute(
        "select o_orderkey, o_totalprice from tpch.tiny.orders"
    ).rows()
    by_price = sorted(rows, key=lambda r: (r[1], r[0]))
    got = runner.execute(
        "select min_by(o_orderkey, o_totalprice) a, "
        "max_by(o_orderkey, o_totalprice) b from tpch.tiny.orders"
    ).rows()[0]
    # ties broken arbitrarily: check the VALUE of the ordering column
    prices = {r[0]: r[1] for r in rows}
    assert prices[got[0]] == by_price[0][1]
    assert prices[got[1]] == by_price[-1][1]


def test_min_by_grouped(runner):
    got = runner.execute(
        "select o_orderstatus, min_by(o_orderkey, o_totalprice) k, "
        "min(o_totalprice) p from tpch.tiny.orders "
        "group by o_orderstatus order by o_orderstatus"
    ).rows()
    for status, k, p in got:
        price = runner.execute(
            f"select o_totalprice from tpch.tiny.orders "
            f"where o_orderkey = {int(k)}"
        ).rows()[0][0]
        assert math.isclose(price, p, rel_tol=1e-12), (status, price, p)


# ----------------------------------------------- composed + other paths


def test_composed_agg_with_having(runner, oracle):
    diff = verify_query(
        runner,
        oracle,
        "select l_returnflag, avg(l_quantity) a "
        "from tpch.tiny.lineitem group by l_returnflag "
        "having avg(l_quantity) > 25 order by l_returnflag",
        rel_tol=1e-9,
    )
    assert diff is None, diff


def test_composed_agg_mixed_distinct(runner, oracle):
    diff = verify_query(
        runner,
        oracle,
        "select l_returnflag, count(distinct l_suppkey) d, "
        "avg(l_quantity) a from tpch.tiny.lineitem "
        "group by l_returnflag order by l_returnflag",
        rel_tol=1e-9,
    )
    assert diff is None, diff


def test_composed_agg_distributed(runner):
    """Composed aggregates split partial/final through the PRIMITIVE
    states (agg_split.py has no avg/variance code anymore): the
    8-device mesh result must match local exactly."""
    from presto_tpu.parallel import DistributedQueryRunner

    q = (
        "select l_returnflag, avg(l_quantity) a, "
        "stddev_samp(l_extendedprice) s, "
        "corr(l_extendedprice, l_quantity) c "
        "from tpch.tiny.lineitem group by l_returnflag "
        "order by l_returnflag"
    )
    dist = DistributedQueryRunner(n_devices=8)
    got = dist.execute(q).rows()
    want = runner.execute(q).rows()
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert a[0] == b[0]
        for x, y in zip(a[1:], b[1:]):
            assert math.isclose(x, y, rel_tol=1e-9), (a, b)


# ------------------------------------------------------ window registry


def test_percent_rank_cume_dist_nth_value(runner, oracle):
    diff = verify_query(
        runner,
        oracle,
        "select o_orderkey, "
        "percent_rank() over (partition by o_orderstatus "
        "order by o_orderkey) pr, "
        "cume_dist() over (partition by o_orderstatus "
        "order by o_orderkey) cd, "
        "nth_value(o_orderkey, 3) over (partition by o_orderstatus "
        "order by o_orderkey) nv "
        "from tpch.tiny.orders where o_orderkey <= 200 "
        "order by o_orderkey",
        rel_tol=1e-9,
    )
    assert diff is None, diff


def test_unknown_window_function_rejected(runner):
    from presto_tpu.plan.planner import PlanningError

    with pytest.raises(PlanningError):
        runner.execute(
            "select no_such_wf() over (order by o_orderkey) "
            "from tpch.tiny.orders"
        )


# ----------------------------------------------------------- new scalars


def test_width_bucket(runner):
    rows = runner.execute(
        "select width_bucket(l_quantity, 0, 50, 5) b, count(*) n "
        "from tpch.tiny.lineitem group by 1 order by 1"
    ).rows()
    # quantities are 1..50: buckets 1..5 plus the over-bound bucket 6
    # for exactly x = 50 (width_bucket is right-open)
    assert [b for b, _ in rows] == [1, 2, 3, 4, 5, 6]
    x = _col(runner, "select l_quantity from tpch.tiny.lineitem")
    for b, n in rows:
        if b <= 5:
            want = ((x >= (b - 1) * 10) & (x < b * 10)).sum()
        else:
            want = (x >= 50).sum()
        assert int(n) == int(want), (b, n, want)


def test_hyperbolic(runner):
    got = runner.execute(
        "select sinh(1.0) a, cosh(1.0) b, tanh(1.0) c"
    ).rows()[0]
    assert math.isclose(got[0], math.sinh(1.0), rel_tol=1e-12)
    assert math.isclose(got[1], math.cosh(1.0), rel_tol=1e-12)
    assert math.isclose(got[2], math.tanh(1.0), rel_tol=1e-12)


def test_registry_is_the_resolver(runner):
    """Adding an aggregate touches only functions.py: a registry entry
    injected at runtime must be immediately plannable."""
    from presto_tpu import functions as F

    name = "test_sum_squares"
    assert name not in F.AGGREGATE

    def build(args):
        x = F._f64(F._numeric_arg(args[0], name))
        return F.ComposedAgg(
            states=(("s", "sum", F._fmul(x, x)),),
            finish=lambda s: s["s"],
            dtype=F.T.DOUBLE,
        )

    F.AGGREGATE[name] = F.AggregateFunction(
        name=name, min_args=1, max_args=1, build=build
    )
    try:
        got = runner.execute(
            "select test_sum_squares(l_quantity) from tpch.tiny.lineitem"
        ).rows()[0][0]
        x = _col(runner, "select l_quantity from tpch.tiny.lineitem")
        assert math.isclose(got, float((x**2).sum()), rel_tol=1e-12)
    finally:
        del F.AGGREGATE[name]

def test_round5_string_builtins():
    """New registry scalars (initcap/md5/sha256/crc32/codepoint/
    repeat/translate/levenshtein_distance/char_length) — pinned
    against Python's reference implementations."""
    import hashlib
    import zlib

    from presto_tpu.exec.local_runner import LocalQueryRunner

    r = LocalQueryRunner()
    rows = r.execute(
        "select initcap(n_name) as a, md5(n_name) as b, "
        "crc32(n_name) as c, codepoint(n_name) as d, "
        "repeat(n_name, 2) as e, translate(n_name, 'AE', 'ae') as f, "
        "levenshtein_distance(n_name, 'ALGERIA') as g, "
        "char_length(n_name) as h, sha256(n_name) as i "
        "from tpch.tiny.nation order by n_nationkey limit 2"
    ).rows()
    a = rows[0]
    assert a[0] == "Algeria"
    assert a[1] == hashlib.md5(b"ALGERIA").hexdigest()
    assert a[2] == zlib.crc32(b"ALGERIA")
    assert a[3] == ord("A")
    assert a[4] == "ALGERIAALGERIA"
    assert a[5] == "aLGeRIa"
    assert a[6] == 0 and rows[1][6] == 4
    assert a[7] == 7
    assert a[8] == hashlib.sha256(b"ALGERIA").hexdigest()


def test_date_format_family():
    """date_format (MySQL directives) and format_datetime (Joda
    tokens) over DATE columns via the bounded int->dictionary LUT."""
    from presto_tpu.exec.local_runner import LocalQueryRunner

    r = LocalQueryRunner()
    rows = r.execute(
        "select date_format(o_orderdate, '%Y-%m-%d') as a, "
        "format_datetime(o_orderdate, 'yyyy/MM/dd EEE') as b, "
        "o_orderdate as d from tpch.tiny.orders "
        "order by o_orderkey limit 2"
    ).rows()
    for a, b, d in rows:
        assert a == d.isoformat()
        assert b == d.strftime("%Y/%m/%d %a")
    # formatted strings as group keys
    g = r.execute(
        "select date_format(o_orderdate, '%Y') as y, count(*) as c "
        "from tpch.tiny.orders group by 1 order by 1"
    ).rows()
    assert [int(y) for y, _ in g] == sorted(int(y) for y, _ in g)
    assert sum(c for _, c in g) == 15000
