"""Adaptive execution (ISSUE 15): epoch-versioned replanning on
history divergence + mid-query join-strategy switching.

Covers the acceptance surface: epoch bumps on MATERIAL divergence only
(small drift never invalidates), statement-cache hits replanning
against learned cardinalities (old entry replaced; replan failure
serves the cached plan, never a failed query), adaptive-off
bit-exactness, both runtime switch directions at the dynamic-filter
build-summary barrier (broadcast->partitioned on an under-estimated
build, partitioned->broadcast on an over-estimated one) with on/off
result equality, remainder-replan after an already-scheduled stage,
and chaos: a build-worker kill during the decision window degrades to
the original plan with zero failed queries.
"""

import time

import pytest

from presto_tpu.connectors import create_connector  # noqa: E402
from presto_tpu.exec.local_runner import LocalQueryRunner  # noqa: E402
from presto_tpu.exec.staging import CatalogManager  # noqa: E402
from presto_tpu.plan import canonical  # noqa: E402
from presto_tpu.plan.history import (  # noqa: E402
    QueryHistoryStore,
    diverged,
)
from presto_tpu.utils import faults  # noqa: E402
from presto_tpu.utils.metrics import REGISTRY  # noqa: E402


def _counter(name: str) -> int:
    return int(REGISTRY.counter(name).total)


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


# ------------------------------------------------------ the epoch plane


def test_diverged_is_symmetric_and_bounded():
    assert not diverged(100, 350, 4.0)  # 3.5x: within the factor
    assert diverged(100, 401, 4.0)
    assert diverged(401, 100, 4.0)  # symmetric
    assert not diverged(None, 100, 4.0)
    assert not diverged(100, None, 4.0)
    assert not diverged(0, 3, 4.0)  # clamped floor: 1 vs 3
    # negative = unknown-sentinel (FilterSummary.rows uses -1): never
    # evidence, never a divergence
    assert not diverged(5000, -1, 4.0)
    assert not diverged(-1, 5000, 4.0)


def test_epoch_bumps_on_divergence_only(tmp_path):
    store = QueryHistoryStore(str(tmp_path), divergence_factor=4.0)
    store.record_query("s1", "q", {"n1": {"rows": 100, "label": "x"}})
    assert store.epoch_of("n1") == 1  # first learn = new evidence
    store.record_query("s1", "q", {"n1": {"rows": 150, "label": "x"}})
    assert store.epoch_of("n1") == 1  # 1.5x drift: NO bump
    store.record_query("s1", "q", {"n1": {"rows": 1000, "label": "x"}})
    assert store.epoch_of("n1") == 2  # ~6.7x: material change
    assert store.learned_rows("n1") == 1000.0
    assert store.epoch_of("never-seen") == 0
    assert store.learned_rows("never-seen") is None


def test_query_history_view_carries_epoch(tmp_path):
    store = QueryHistoryStore(str(tmp_path))
    store.record_query("s1", "q", {"s1": {"rows": 10, "label": "x"}})
    (row,) = store.snapshot()
    assert row["epoch"] == 1
    store.record_query("s1", "q", {"s1": {"rows": 9000, "label": "x"}})
    (row,) = store.snapshot()
    assert row["epoch"] == 2


def test_stale_consults_judges_against_captured_estimate(tmp_path):
    store = QueryHistoryStore(str(tmp_path), divergence_factor=4.0)
    # the entry planned on a classic estimate of 50 for n1 (a miss)
    consulted = {"n1": {"epoch": 0, "rows": None, "est": 50.0}}
    assert canonical.stale_consults(consulted, store, 4.0) is None
    # learning 60 bumps the epoch (first learn) but 60 vs 50 is NOT
    # material — the plan survives the bump
    store.record_query("s", "q", {"n1": {"rows": 60, "label": "x"}})
    assert store.epoch_of("n1") == 1
    assert canonical.stale_consults(consulted, store, 4.0) is None
    # re-learning 5000 is material versus the captured base
    store.record_query("s", "q", {"n1": {"rows": 5000, "label": "x"}})
    got = canonical.stale_consults(consulted, store, 4.0)
    assert got == ("n1", 0, 2)


def test_stale_consults_honors_tighter_session_factor(tmp_path):
    """A session divergence factor TIGHTER than the store's epoch-bump
    factor must still replan: the epoch pre-filter only applies when
    the caller's factor is at least the store's (a 3x drift bumps no
    epoch at the store's 4x, but a factor-2 caller must see it)."""
    store = QueryHistoryStore(str(tmp_path), divergence_factor=4.0)
    store.record_query("s", "q", {"n1": {"rows": 100, "label": "x"}})
    consulted = {"n1": {"epoch": 1, "rows": 100.0, "est": None}}
    store.record_query("s", "q", {"n1": {"rows": 300, "label": "x"}})
    assert store.epoch_of("n1") == 1  # 3x: no bump at the store's 4x
    assert canonical.stale_consults(consulted, store, 4.0) is None
    got = canonical.stale_consults(consulted, store, 2.0)
    assert got is not None and got[0] == "n1"


# --------------------------------------------- statement-cache replan

#: every row of the build table carries key 7, so the classic
#: ``k = 7 and v > -1e6`` selectivity math (0.1 x 0.33 with no column
#: stats on the memory connector) under-estimates the build ~30x
_SKEW_SQL = (
    "select count(*) as n, sum(s.v) as sv "
    "from mem.default.adaptive_skew s "
    "join tpch.tiny.customer c on s.k = c.c_custkey "
    "where s.k = 7 and s.v > -1000000"
)


def _skew_runner(tmp_path, adaptive: bool) -> LocalQueryRunner:
    r = LocalQueryRunner(history_path=str(tmp_path / "hist"))
    r.session.set("adaptive_enabled", "true" if adaptive else "false")
    r.catalogs.register("mem", create_connector("memory"))
    r.execute(
        "create table mem.default.adaptive_skew as "
        "select 7 as k, c_acctbal as v from tpch.tiny.customer"
    )
    return r


def test_cache_hit_replan_serves_new_plan(tmp_path):
    r = _skew_runner(tmp_path, adaptive=True)
    replans0 = _counter("plan.replans")
    div0 = _counter("adaptive.divergence_detected")
    cold = r.execute(_SKEW_SQL).rows()
    (key, entry_before) = next(
        (k, e)
        for k, e in r.plan_cache._od.items()
        if isinstance(e, canonical.PlanCacheEntry)
    )
    assert entry_before.consulted, "planning must capture its consults"
    warm = r.execute(_SKEW_SQL).rows()
    assert warm == cold
    assert _counter("plan.replans") == replans0 + 1
    assert _counter("adaptive.divergence_detected") == div0 + 1
    # the stale entry was REPLACED, not served
    entry_after = r.plan_cache._od[key]
    assert entry_after is not entry_before
    assert r.plan_cache.replans == 1
    assert r.plan_cache.stats()["replans"] == 1
    # steady state: the replanned entry's consulted evidence matches
    # today's history — a third run serves it without replanning
    warm2 = r.execute(_SKEW_SQL).rows()
    assert warm2 == cold
    assert _counter("plan.replans") == replans0 + 1


def test_small_drift_does_not_invalidate(tmp_path):
    r = _skew_runner(tmp_path, adaptive=True)
    r.execute(_SKEW_SQL)
    r.execute(_SKEW_SQL)  # the one replan
    replans0 = _counter("plan.replans")
    # re-recording identical actuals is zero drift: no epoch bumps, no
    # further replans — the hot shape stays zero-planning
    for _ in range(3):
        r.execute(_SKEW_SQL)
    assert _counter("plan.replans") == replans0


def test_replan_failure_serves_cached_plan(tmp_path):
    r = _skew_runner(tmp_path, adaptive=True)
    cold = r.execute(_SKEW_SQL).rows()
    fails0 = _counter("plan.replan_failures")
    replans0 = _counter("plan.replans")
    orig = r._plan_statement

    def boom(stmt):
        raise RuntimeError("injected replan failure")

    r._plan_statement = boom
    try:
        warm = r.execute(_SKEW_SQL).rows()
    finally:
        r._plan_statement = orig
    # the divergence WAS detected, the replan failed, and the cached
    # plan answered — never a failed query
    assert warm == cold
    assert _counter("plan.replan_failures") == fails0 + 1
    assert _counter("plan.replans") == replans0


def test_adaptive_off_is_bit_exact(tmp_path):
    r = _skew_runner(tmp_path, adaptive=False)
    replans0 = _counter("plan.replans")
    div0 = _counter("adaptive.divergence_detected")
    cold = r.execute(_SKEW_SQL).rows()
    warm = r.execute(_SKEW_SQL).rows()
    assert warm == cold
    # off = the pre-adaptive world: zero divergence checks, zero
    # replans, the warm run is a plain statement-cache hit
    assert _counter("plan.replans") == replans0
    assert _counter("adaptive.divergence_detected") == div0
    assert r.plan_cache.hits >= 1
    assert r.plan_cache.replans == 0


def test_runtime_query_history_epoch_column(tmp_path):
    r = _skew_runner(tmp_path, adaptive=True)
    r.execute(_SKEW_SQL)
    rows = r.execute(
        "select fingerprint, epoch from system.runtime.query_history"
    ).rows()
    assert rows and all(int(e) >= 1 for _fp, e in rows)


# ------------------------------------------- runtime strategy switching


def _wait_workers(coord, n, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def cluster():
    """2-worker cluster with adaptive on (no history store: the
    runtime layer acts on classic estimates vs observed rows) and a
    SHARED memory connector so worker scans see coordinator writes."""
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )
    from presto_tpu.session import NodeConfig

    mem = create_connector("memory")

    def catalogs():
        c = CatalogManager()
        c.register("tpch", create_connector("tpch"))
        c.register("mem", mem)
        return c

    cfg = NodeConfig({"adaptive.enabled": "true"})
    coord = CoordinatorServer(config=cfg, catalogs=catalogs()).start()
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=cfg, catalogs=catalogs()
        ).start()
        for _ in range(2)
    ]
    _wait_workers(coord, 2)
    client = PrestoTpuClient(coord.uri, timeout_s=300)
    # under-estimated build: every row passes f = 7 but the memory
    # connector has no column stats, so classic math says 10%
    client.execute(
        "create table mem.default.skew as "
        "select o_orderkey as k, 7 as f from tpch.tiny.orders"
    )
    # over-estimated build: v = 999999 matches NOTHING but is
    # classically estimated at 10% of 60k rows
    client.execute(
        "create table mem.default.big as "
        "select l_orderkey as k, l_linenumber as v "
        "from tpch.tiny.lineitem"
    )
    coord.local.session.set("join_max_broadcast_rows", "2000")
    coord.local.session.set("page_capacity", "8192")
    yield coord, workers, client
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def _adaptive_on_off(coord, client, sql):
    """Run ``sql`` with adaptive OFF (the oracle) then ON; return both
    results and the ON run's coordinator query."""
    coord.local.session.set("adaptive_enabled", "false")
    try:
        off = client.execute(sql).data
    finally:
        coord.local.session.set("adaptive_enabled", "true")
    res = client.execute(sql)
    return off, res.data, coord.queries[res.query_id]


_UNDER_SQL = (
    "select count(*) as n, sum(l.l_extendedprice) as s "
    "from tpch.tiny.lineitem l join mem.default.skew s "
    "on l.l_orderkey = s.k where s.f = 7"
)

_OVER_SQL = (
    "select count(*) as n "
    "from tpch.tiny.lineitem l join mem.default.big b "
    "on l.l_orderkey = b.k where b.v = 999999"
)


def test_switch_broadcast_to_partitioned(cluster):
    """The build summary observes 15000 rows where the estimate said
    1500: past the divergence factor AND the broadcast bound, so the
    not-yet-scheduled probe+join remainder re-plans as a partitioned
    join — results bit-equal to the un-adapted plan."""
    coord, _workers, client = cluster
    sw0 = _counter("adaptive.strategy_switches")
    pj0 = _counter("coordinator.partitioned_join_stages")
    off, on, q = _adaptive_on_off(coord, client, _UNDER_SQL)
    assert on == off
    assert _counter("adaptive.strategy_switches") == sw0 + 1
    # the switched join really ran partitioned
    assert _counter("coordinator.partitioned_join_stages") == pj0 + 1
    assert q.stats.adapted
    assert any(
        "SWITCHED broadcast→partitioned" in n
        for n in q.stats.adaptive_notes
    )
    # rolled into QueryInfo
    info = coord.query_info(q)
    assert info["adapted"] is True
    assert info["replanned"] is False


def test_switch_partitioned_to_broadcast(cluster):
    """The estimates pick a partitioned join (both sides 'big'), the
    build probe observes an (actually empty) build far below the
    broadcast bound: the join goes back to the replicated-build path —
    zero partitioned stages, equal results."""
    coord, _workers, client = cluster
    sw0 = _counter("adaptive.strategy_switches")
    off_pj0 = _counter("coordinator.partitioned_join_stages")
    coord.local.session.set("adaptive_enabled", "false")
    try:
        off = client.execute(_OVER_SQL).data
    finally:
        coord.local.session.set("adaptive_enabled", "true")
    # adaptive OFF runs it partitioned, as estimated
    assert _counter("coordinator.partitioned_join_stages") == off_pj0 + 1
    pj0 = _counter("coordinator.partitioned_join_stages")
    res = client.execute(_OVER_SQL)
    q = coord.queries[res.query_id]
    assert res.data == off
    assert _counter("adaptive.strategy_switches") == sw0 + 1
    assert _counter("coordinator.partitioned_join_stages") == pj0
    assert any(
        "SWITCHED partitioned→broadcast" in n
        for n in q.stats.adaptive_notes
    )


def test_remainder_replan_after_scheduled_stage(cluster):
    """The decision window opens only after a stage has ALREADY been
    scheduled (the build-summary tasks ran on workers); only the
    not-yet-scheduled remainder re-plans. The ON run must carry both
    the scheduled dynfilter stage and the switched join's stages."""
    coord, _workers, client = cluster
    off, on, q = _adaptive_on_off(coord, client, _UNDER_SQL)
    assert on == off
    kinds = [s.kind for s in q.stats.stages]
    assert "dynfilter" in kinds  # the already-scheduled decision stage
    assert "producer" in kinds and "join" in kinds  # the re-planned rest


def test_switch_renders_in_explain_analyze(cluster):
    coord, _workers, client = cluster
    res = client.execute("explain analyze " + _UNDER_SQL)
    text = "\n".join(r[0] for r in res.data)
    assert "adaptive: SWITCHED broadcast→partitioned" in text


def test_switch_resizes_partition_count(cluster):
    """The switched shuffle is sized by the OBSERVED build (one
    partition per page_capacity rows, clamped to the pool) — recorded
    on the decision note."""
    coord, _workers, client = cluster
    _off, _on, q = _adaptive_on_off(coord, client, _UNDER_SQL)
    note = next(
        n for n in q.stats.adaptive_notes if "SWITCHED broadcast" in n
    )
    # observed 15000 rows / page_capacity 8192 -> 2 partitions
    assert "parts 2" in note


def test_build_worker_kill_during_decision_window(cluster):
    """Chaos: the worker running the build-summary (decision) task is
    killed mid-window. The barrier degrades exactly like the dynamic-
    filter plane — the ORIGINAL plan runs, the query succeeds, and no
    strategy switch is claimed."""
    from presto_tpu.server import WorkerServer

    coord, workers, client = cluster
    spare = WorkerServer(
        coordinator_uri=coord.uri,
        catalogs=workers[0].runner.catalogs,
    ).start()
    try:
        _wait_workers(coord, 3)
        sw0 = _counter("adaptive.strategy_switches")
        faults.configure(
            {
                "rules": [
                    {
                        "action": "kill_worker",
                        "task": ".df.",
                        "count": 1,
                    }
                ]
            }
        )
        res = client.execute(_UNDER_SQL)
        q = coord.queries[res.query_id]
        assert q.state == "FINISHED"
        # the dead build summary degraded: no switch was claimed on
        # evidence that never arrived
        assert _counter("adaptive.strategy_switches") == sw0
        # and the answer is still exact
        coord.local.session.set("adaptive_enabled", "false")
        try:
            off = client.execute(_UNDER_SQL).data
        finally:
            coord.local.session.set("adaptive_enabled", "true")
        assert res.data == off
    finally:
        faults.configure(None)
        spare.shutdown(graceful=False)
