"""Spooled exchange + stage-level recovery + worker drain (the
fault-tolerant execution mode).

Reference parity: Presto/Trino fault-tolerant execution ("Project
Tardigrade") — exchange data spooled to shared storage so recovery
restarts only LOST tasks, with upstream stages re-served from the
spool; plus the graceful-drain half of rolling restarts (a draining
worker stops accepting work, announces itself, finishes + serves its
buffers, and exits without failing a single query).

Chaos tests assert via per-stage ATTEMPT counters (deterministic
task-attempt ids, server.task_ids) that killing a worker mid
multi-stage TPC-H join re-runs only the lost stage's tasks — upstream
producer attempts stay at one — and that draining a worker mid-query
loses zero queries.
"""

import os
import signal
import threading
import time

import pytest

from presto_tpu.server import CoordinatorServer, PrestoTpuClient, WorkerServer
from presto_tpu.server import rpc, task_ids
from presto_tpu.server.spool import ExchangeSpool
from presto_tpu.session import NodeConfig, Session
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY


#: multi-stage TPC-H join: scan+join+partial-agg producer stage that
#: hash-partitions into per-worker buffers, merge stage running the
#: FINAL agg on workers (the shuffle path both chaos tests target)
JOIN_SQL = (
    "select o_orderpriority, count(*) as n "
    "from tpch.tiny.orders, tpch.tiny.lineitem "
    "where o_orderkey = l_orderkey "
    "group by o_orderpriority order by o_orderpriority"
)


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


def _mk_cluster(tmp_path, n=2, policy="TASK", extra=None):
    cfg = {
        "exchange.spool-path": str(tmp_path / "spool"),
        "exchange.spool-bytes": "64MB",
    }
    cfg.update(extra or {})
    coord = CoordinatorServer(config=NodeConfig(dict(cfg))).start()
    coord.local.session.set("retry_policy", policy)
    workers = [
        WorkerServer(
            coordinator_uri=coord.uri, config=NodeConfig(dict(cfg))
        ).start()
        for _ in range(n)
    ]
    _wait_workers(coord, n)
    return coord, workers


def _teardown(coord, workers):
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def _expected_rows(coord, sql):
    """Oracle for the chaos runs: the coordinator's local engine on the
    same catalogs (computed healthy, before any chaos)."""
    return [tuple(r) for r in coord.local.execute(sql).rows()]


def _attempts_by_logical(stage: dict):
    by = {}
    for t in stage["tasks"]:
        by.setdefault(task_ids.logical_key(t["task_id"]), []).append(t)
    return by


# ------------------------------------------------- task-attempt ids


def test_task_id_mint_parse_roundtrip():
    tid = task_ids.mint("q_c7", task_ids.PRODUCER, 3)
    assert tid == "q_c7.prod.3.a0"
    t = task_ids.parse(tid)
    assert (t.query_id, t.kind, t.seq, t.attempt) == ("q_c7", "prod", 3, 0)
    assert str(t) == tid
    assert task_ids.logical_key(tid) == "q_c7.prod.3"
    nxt = task_ids.next_attempt(tid)
    assert nxt == "q_c7.prod.3.a1"
    assert task_ids.logical_key(nxt) == task_ids.logical_key(tid)
    assert task_ids.attempt_of(nxt) == 1


def test_task_id_legacy_ids_are_their_own_key():
    # hand-written test specs ("t") never gain phantom attempt structure
    assert task_ids.try_parse("t") is None
    assert task_ids.logical_key("t") == "t"
    assert task_ids.attempt_of("t") == 0
    with pytest.raises(ValueError):
        task_ids.next_attempt("t")
    with pytest.raises(ValueError):
        task_ids.mint("q.1", "t", 0)  # dotted query id would break parse


def test_query_ids_unique_across_coordinator_restarts():
    """A restarted coordinator must never re-mint a previous
    incarnation's attempt ids — the shared spool would serve the dead
    run's pages inside the TTL window (review finding)."""
    a = CoordinatorServer()
    b = CoordinatorServer()
    try:
        qa = a.submit("select 1")
        qb = b.submit("select 1")
        qa.done.wait(30)
        qb.done.wait(30)
        assert qa.qid != qb.qid
    finally:
        a.shutdown()
        b.shutdown()


# ------------------------------------------------- spool unit tests


def test_spool_roundtrip_and_attempt_dedup(tmp_path):
    sp = ExchangeSpool(str(tmp_path))
    a0 = "q_c1.prod.0.a0"
    sp.append(a0, 0, b"page-zero")
    sp.append(a0, 0, b"page-one")
    sp.append(a0, 1, b"other-part")
    # uncommitted attempts never serve (a crash mid-spool must not
    # expose partial output)
    assert sp.serve("q_c1.prod.0", 0) is None
    sp.commit(a0)
    assert sp.serve("q_c1.prod.0", 0) == [b"page-zero", b"page-one"]
    assert sp.serve("q_c1.prod.0", 1) == [b"other-part"]
    # committed attempt, empty partition: recoverable as zero pages
    assert sp.serve("q_c1.prod.0", 2) == []
    # a second committed attempt does not double-serve: exactly one
    # attempt's pages per call, lowest attempt wins deterministically
    a1 = "q_c1.prod.0.a1"
    sp.append(a1, 0, b"dup-zero")
    sp.commit(a1)
    assert sp.serve("q_c1.prod.0", 0) == [b"page-zero", b"page-one"]
    # discard drops an attempt entirely
    sp.discard(a0)
    assert sp.serve("q_c1.prod.0", 0) == [b"dup-zero"]
    # disk-full on the marker write (injected io_error): the attempt
    # stays uncommitted — never served — and a retried commit after
    # the transient clears publishes it cleanly
    a2 = "q_c1.prod.9.a0"
    sp.append(a2, 0, b"late")
    faults.configure(
        {"rules": [{"action": "io_error", "path": ".ok", "op": "write"}]}
    )
    try:
        with pytest.raises(OSError):
            sp.commit(a2)
        assert sp.serve("q_c1.prod.9", 0) is None
    finally:
        faults.configure(None)
    sp.commit(a2)
    assert sp.serve("q_c1.prod.9", 0) == [b"late"]


def test_spool_checksum_detects_on_disk_corruption(tmp_path):
    sp = ExchangeSpool(str(tmp_path))
    tid = "q_c1.prod.1.a0"
    sp.append(tid, 0, b"x" * 100)
    sp.commit(tid)
    fn = tmp_path / f"{tid}.0.pages"
    raw = bytearray(fn.read_bytes())
    raw[20] ^= 0xFF  # flip a payload byte
    fn.write_bytes(bytes(raw))
    before = REGISTRY.counter("spool.corrupt").total
    assert sp.serve("q_c1.prod.1", 0) is None
    assert REGISTRY.counter("spool.corrupt").total == before + 1


def test_spool_corrupt_fault_rule_falls_back_to_next_attempt(tmp_path):
    sp = ExchangeSpool(str(tmp_path))
    for a, payload in (("a0", b"first"), ("a1", b"second")):
        tid = f"q_c1.prod.2.{a}"
        sp.append(tid, 0, payload)
        sp.commit(tid)
    faults.configure(
        {"rules": [{"action": "spool_corrupt", "task": ".a0", "count": 1}]}
    )
    before = REGISTRY.counter("spool.corrupt").total
    # a0 reads corrupt (injected), recovery falls to the a1 attempt
    assert sp.serve("q_c1.prod.2", 0) == [b"second"]
    assert REGISTRY.counter("spool.corrupt").total == before + 1


def test_spool_ttl_and_budget_gc(tmp_path):
    sp = ExchangeSpool(str(tmp_path), budget_bytes=64, ttl_s=0.2)
    sp.append("q_c1.prod.3.a0", 0, b"y" * 40)
    sp.commit("q_c1.prod.3.a0")
    time.sleep(0.25)
    sp.gc(force=True)
    assert os.listdir(str(tmp_path)) == []  # TTL expired the attempt
    # byte budget: oldest committed attempt evicted when over budget
    sp2 = ExchangeSpool(str(tmp_path), budget_bytes=64, ttl_s=600.0)
    sp2.append("q_c1.prod.4.a0", 0, b"a" * 48)
    sp2.commit("q_c1.prod.4.a0")
    time.sleep(0.02)
    sp2.append("q_c1.prod.5.a0", 0, b"b" * 48)
    sp2.commit("q_c1.prod.5.a0")
    sp2.gc(force=True)
    assert sp2.serve("q_c1.prod.4", 0) is None  # evicted (oldest)
    assert sp2.serve("q_c1.prod.5", 0) == [b"b" * 48]
    st = sp2.stats()
    assert st["entries"] == 1 and st["budget_bytes"] == 64


def test_retry_policy_session_validation():
    s = Session()
    assert s.get("retry_policy") == "NONE"
    s.set("retry_policy", "task")  # case-insensitive
    with pytest.raises(ValueError):
        s.set("retry_policy", "SOMETIMES")


# ------------------------------------------------- chaos: recovery


def test_retry_policy_none_never_touches_spool(tmp_path):
    """NONE is bit-for-bit legacy: spool configured but cold — no spec
    carries the flag, no file is written, no recovery stat moves."""
    coord, ws = _mk_cluster(tmp_path, policy="NONE")
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(JOIN_SQL)
        assert [tuple(r) for r in res.rows()] == _expected_rows(
            coord, JOIN_SQL
        )
        assert os.listdir(str(tmp_path / "spool")) == []
        info = client.query_info(res.query_id)
        assert info["retry_policy"] == "NONE"
        assert info["task_recoveries"] == 0
        assert info["spool_pages_served"] == 0
    finally:
        _teardown(coord, ws)


def _seal_observed(workers):
    """The coordinator's source-seal broadcast reached a merge task:
    every producer range completed FROM THE COORDINATOR'S PERSPECTIVE,
    so no producer can legitimately be re-attempted past this point —
    the exact boundary the 'upstream not re-run' assertion needs."""
    for w in workers:
        with w._lock:
            tasks = list(w.tasks.values())
        for t in tasks:
            if t.spec.partition_scan < 0 and t.sources_done:
                return True
    return False


def test_chaos_kill_worker_mid_join_recovers_from_spool(tmp_path):
    """THE acceptance chaos test: kill a worker mid multi-stage TPC-H
    join under retry_policy=TASK, after the producer (upstream) stage
    finished. The query completes, re-running ONLY the dead worker's
    merge task — asserted via per-stage attempt counters: every
    producer logical task keeps exactly one attempt, while the lost
    merge partition gains an a1 attempt whose upstream inputs are
    re-served from the durable spool."""
    coord, ws = _mk_cluster(tmp_path, policy="TASK")
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        # hold the merge stage's start back so the kill (armed on the
        # coordinator's seal broadcast) always lands BEFORE the merge
        # gather completes
        faults.configure(
            {
                "seed": 2,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.05},
                    {"action": "delay", "task": ".merge.", "delay_s": 0.8},
                ],
            }
        )
        out, errs = {}, []

        def run():
            try:
                out["res"] = client.execute(JOIN_SQL)
            except Exception as e:  # surfaced by the assert below
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not _seal_observed(ws):
            time.sleep(0.002)
        assert _seal_observed(ws), "producer stage never sealed"
        served_before = REGISTRY.counter("spool.pages_served").total
        victim = ws[0]
        victim._fault_kill()  # abrupt crash: dead sockets, no drain
        t.join(120)
        assert not errs, f"query failed despite TASK recovery: {errs}"
        assert [tuple(r) for r in out["res"].rows()] == expected

        info = client.query_info(out["res"].query_id)
        stages = {st["stage_id"]: st for st in info["stages"]}
        prod = next(
            st for st in stages.values() if st["kind"] == "producer"
        )
        merge = next(
            st for st in stages.values() if st["kind"] == "merge"
        )
        # upstream stage NOT re-run: one attempt per producer logical
        for lk, attempts in _attempts_by_logical(prod).items():
            assert len(attempts) == 1, (
                f"upstream producer {lk} was re-run: "
                f"{[a['task_id'] for a in attempts]}"
            )
        # the lost merge partition WAS re-run (a0 lost, a1 recovered)
        merge_attempts = _attempts_by_logical(merge)
        recovered = [a for a in merge_attempts.values() if len(a) > 1]
        assert recovered, f"no merge recovery recorded: {merge_attempts}"
        # and the replacement re-served the dead worker's partitions
        # from the spool instead of re-running the upstream stage
        assert info["spool_pages_served"] > 0
        assert (
            REGISTRY.counter("spool.pages_served").total > served_before
        )
        assert info["task_recoveries"] >= 1
        assert info["retry_policy"] == "TASK"
    finally:
        _teardown(coord, ws)


def test_chaos_kill_worker_mid_producer_stage_no_double_count(tmp_path):
    """Kill a worker while the upstream stage is still RUNNING: lost
    producer ranges re-run as a1 attempts of the SAME logical tasks,
    and attempt-id dedup guarantees merge consumers fold exactly one
    attempt per logical task — the result is exact, never doubled."""
    coord, ws = _mk_cluster(tmp_path, policy="TASK")
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        faults.configure(
            {
                "seed": 3,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.2}
                ],
            }
        )
        out, errs = {}, []

        def run():
            try:
                out["res"] = client.execute(JOIN_SQL)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        victim = ws[0]

        def victim_committed():
            with victim._lock:
                tasks = list(victim.tasks.values())
            return any(
                x.state == "FINISHED" and len(x.parts) > 1 and x.spooled
                for x in tasks
            )

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not victim_committed():
            time.sleep(0.002)
        assert victim_committed(), "victim never committed a producer"
        victim._fault_kill()
        t.join(120)
        assert not errs, f"query failed despite TASK recovery: {errs}"
        # double-counting is the failure mode this guards: a retried
        # producer racing its announced original must contribute once
        assert [tuple(r) for r in out["res"].rows()] == expected
        info = client.query_info(out["res"].query_id)
        assert info["task_recoveries"] >= 1
    finally:
        _teardown(coord, ws)


def test_query_retry_policy_full_restart(tmp_path):
    """retry_policy=QUERY, task retry disabled: losing a worker fails
    the attempt, and the bounded full-query restart completes it on
    the surviving cluster (the last-resort path)."""
    coord, ws = _mk_cluster(
        tmp_path,
        policy="QUERY",
        extra={"failure-detector.threshold": "1"},
    )
    try:
        coord.local.session.set("task_retry_budget", 0)
        faults.configure(
            {
                "rules": [
                    {
                        "action": "kill_worker",
                        "node": ws[1].node_id,
                        "count": 1,
                    }
                ]
            }
        )
        before = REGISTRY.counter("coordinator.query_restarts").total
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert [tuple(r) for r in res.rows()] == [(59997,)]
        assert (
            REGISTRY.counter("coordinator.query_restarts").total > before
        )
        info = client.query_info(res.query_id)
        assert info["query_restarts"] >= 1
        assert info["retry_policy"] == "QUERY"
    finally:
        coord.local.session.reset("task_retry_budget")
        _teardown(coord, ws)


# ------------------------------------------------- drain protocol


def test_drain_under_load_zero_query_failures(tmp_path):
    """Rolling-restart half of the acceptance test: drain a worker mid
    multi-stage query — the query (and followers) complete with ZERO
    failures, the coordinator stops scheduling to the draining worker,
    and the worker exits clean once its buffers are consumed."""
    coord, ws = _mk_cluster(tmp_path, policy="TASK")
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        faults.configure(
            {
                "seed": 5,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.1}
                ],
            }
        )
        results, errs = [], []

        def run():
            try:
                results.append(client.execute(JOIN_SQL).rows())
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        # drain mid-query, over the real endpoint
        time.sleep(0.15)
        rpc.call_json("PUT", ws[0].uri + "/v1/state/drain")
        t.join(120)
        assert not errs, f"drain lost a query: {errs}"
        assert [tuple(r) for r in results[0]] == expected
        faults.configure(None)
        # discovery: the drained worker left scheduling...
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ids = {w.node_id for w in coord.active_workers()}
            if ws[0].node_id not in ids:
                break
            time.sleep(0.05)
        assert ws[0].node_id not in {
            w.node_id for w in coord.active_workers()
        }
        # ...and exits clean once consumers are done
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not ws[0]._shutting_down:
            time.sleep(0.05)
        assert ws[0]._shutting_down, "drained worker did not exit"
        # the cluster keeps serving on the survivor, zero loss
        res = client.execute("select count(*) as c from tpch.tiny.orders")
        assert [tuple(r) for r in res.rows()] == [(15000,)]
    finally:
        _teardown(coord, ws)


def test_drain_reroute_is_free_even_with_zero_retry_budget(tmp_path):
    """A drain rejection re-routes without charging task_retry_budget
    or the circuit breaker (the task was never created): draining must
    keep its zero-failure promise even with retry disabled (review
    finding)."""
    coord, ws = _mk_cluster(tmp_path, policy="NONE")
    try:
        coord.local.session.set("task_retry_budget", 0)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        # drain first, THEN query: every range the drained worker's
        # thread claims is rejected with 503 and must re-route free
        ws[0]._draining = True  # flag only: the server stays up
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert [tuple(r) for r in res.rows()] == [(59997,)]
        assert coord.breakers.get(ws[0].node_id) is None or (
            coord.breakers[ws[0].node_id].peek() == "CLOSED"
        ), "drain rejection penalized the breaker"
        info = client.query_info(res.query_id)
        assert info["task_recoveries"] == 0
    finally:
        coord.local.session.reset("task_retry_budget")
        _teardown(coord, ws)


def test_launcher_main_exits_after_http_drain(tmp_path, monkeypatch):
    """A launcher-run worker drained over HTTP must end main() — a
    rolling restart waits on process exit (review finding)."""
    from presto_tpu.server import launcher

    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "coordinator=false\n"
        "discovery.uri=http://127.0.0.1:9\n"  # coordinator not needed
        "drain.grace-s=5\n"
    )
    (etc / "catalog" / "tpch.properties").write_text(
        "connector.name=tpch\n"
    )
    captured = {}
    orig_launch = launcher.launch

    def spy(etc_dir):
        captured["server"] = orig_launch(etc_dir)
        return captured["server"]

    monkeypatch.setattr(launcher, "launch", spy)
    done = threading.Event()

    def run_main():
        try:
            launcher.main(["--etc-dir", str(etc)])
        finally:
            done.set()

    threading.Thread(target=run_main, daemon=True).start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and "server" not in captured:
        time.sleep(0.05)
    srv = captured["server"]
    rpc.call_json("PUT", srv.uri + "/v1/state/drain")
    assert done.wait(20), "main() kept sleeping after the drain"


def test_draining_worker_rejects_new_tasks_with_503(tmp_path):
    coord, ws = _mk_cluster(tmp_path, n=1, policy="NONE")
    try:
        w = ws[0]
        w._draining = True  # flag only: keep the server up to probe it
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            rpc.call_json("POST", w.uri + "/v1/task", {"x": 1})
        assert ei.value.code == 503
        assert rpc.is_task_recoverable(ei.value)
        # status reports the drain state to pollers
        st = rpc.call_json("GET", w.uri + "/v1/status")
        assert st["state"] == "DRAINING"
    finally:
        _teardown(coord, ws)


def test_chaos_kill_worker_while_draining(tmp_path):
    """The drain protocol must stay recoverable mid-handshake: a
    kill_worker_draining rule crashes the worker the moment it starts
    draining, and TASK-level recovery still completes the query."""
    coord, ws = _mk_cluster(tmp_path, policy="TASK")
    try:
        expected = _expected_rows(coord, JOIN_SQL)
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        faults.configure(
            {
                "seed": 7,
                "rules": [
                    {"action": "delay", "task": ".prod.", "delay_s": 0.1},
                    {
                        "action": "kill_worker_draining",
                        "node": ws[0].node_id,
                    },
                ],
            }
        )
        out, errs = {}, []

        def run():
            try:
                out["res"] = client.execute(JOIN_SQL)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.15)
        try:
            rpc.call_json("PUT", ws[0].uri + "/v1/state/drain")
        except Exception:
            pass  # the injected crash may race the response
        t.join(120)
        assert not errs, f"query failed despite TASK recovery: {errs}"
        assert [tuple(r) for r in out["res"].rows()] == expected
    finally:
        _teardown(coord, ws)


def test_launcher_signal_handlers_drain():
    """SIGTERM/SIGINT install a drain-first handler (satellite: Ctrl-C
    during tests used to leave workers undrained)."""
    from presto_tpu.server import launcher

    class FakeServer:
        drained = False

        def drain(self):
            self.drained = True

    srv = FakeServer()
    exits = []
    saved = {
        s: signal.getsignal(s) for s in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        handler = launcher.install_signal_handlers(srv, exit=exits.append)
        assert signal.getsignal(signal.SIGTERM) is handler
        assert signal.getsignal(signal.SIGINT) is handler
        handler(signal.SIGTERM, None)
        assert srv.drained
        assert exits == [0]
    finally:
        for s, h in saved.items():
            signal.signal(s, h)


# ------------------------------------ observability + config surface


def test_spool_occupancy_in_runtime_caches_and_explain(tmp_path):
    coord, ws = _mk_cluster(tmp_path, policy="TASK")
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        client.execute(JOIN_SQL)
        rows = client.execute(
            "select cache, entries, bytes, budget_bytes "
            "from system.runtime.caches order by cache"
        ).rows()
        spool_rows = [r for r in rows if r[0] == "exchange.spool"]
        assert spool_rows, rows
        assert spool_rows[0][1] > 0  # committed attempts present
        assert spool_rows[0][2] > 0  # occupancy bytes
        assert spool_rows[0][3] == 64 << 20
        # the EXPLAIN ANALYZE recovery line renders under TASK policy
        text = "\n".join(
            r[0]
            for r in client.execute(
                "explain analyze " + JOIN_SQL
            ).rows()
        )
        assert "fault tolerance: retry_policy=TASK" in text
        assert "task_recoveries" in text
    finally:
        _teardown(coord, ws)


def test_launcher_boots_spool_and_drain_config(tmp_path):
    from presto_tpu.server.launcher import load_etc

    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "coordinator=true\n"
        f"exchange.spool-path={tmp_path}/sp\n"
        "exchange.spool-bytes=1MB\n"
        "exchange.spool-ttl-s=60\n"
        "retry-policy=TASK\n"
        "drain.grace-s=5\n"
    )
    (etc / "catalog" / "tpch.properties").write_text(
        "connector.name=tpch\n"
    )
    config, _catalogs = load_etc(str(etc))
    assert config.get("retry-policy") == "TASK"
    assert config.get("drain.grace-s") == 5.0
    sp = ExchangeSpool.from_config(config)
    assert sp is not None and sp.budget_bytes == 1 << 20
    assert sp.ttl_s == 60.0


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
