"""Physical ARRAY columns (VERDICT r3 missing 4; reference: ArrayType /
ArrayBlock / UnnestOperator / array_agg — SURVEY.md §2.1 "Type system",
"Operators"): offsets + flat-values blocks, build -> store(memory) ->
scan -> unnest round trips, subscript/cardinality kernels, array_agg on
the sorted aggregation path.

Documented deviations: NULL array ELEMENTS are unsupported (NULL rows
are); array_agg skips NULL inputs (the reference includes them);
subscript out-of-range returns NULL (the reference raises; element_at
matches)."""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.plan.planner import PlanningError


@pytest.fixture(scope="module")
def runner():
    cat = CatalogManager()
    cat.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    mem.create_table(
        TableHandle("mem", "default", "t"),
        {"id": T.INTEGER, "arr": T.array(T.BIGINT)},
    )
    mem.create_table(
        TableHandle("mem", "default", "s"),
        {"id": T.INTEGER, "tags": T.array(T.VARCHAR)},
    )
    cat.register("mem", mem)
    r = LocalQueryRunner(catalogs=cat)
    r.execute(
        "insert into mem.default.t values (1, array[10, 20, 30]), "
        "(2, array[5]), (3, null), (4, array[])"
    )
    r.execute(
        "insert into mem.default.s values (1, array['x', 'y']), "
        "(2, array['y'])"
    )
    return r


def test_store_scan_roundtrip(runner):
    rows = runner.execute(
        "select id, arr from mem.default.t order by id"
    ).rows()
    assert rows == [
        (1, [10, 20, 30]),
        (2, [5]),
        (3, None),
        (4, []),
    ]


def test_cardinality_and_subscript(runner):
    rows = runner.execute(
        "select id, cardinality(arr), element_at(arr, 2), arr[1], "
        "element_at(arr, -1) from mem.default.t order by id"
    ).rows()
    assert rows == [
        (1, 3, 20, 10, 30),
        (2, 1, None, 5, 5),
        (3, None, None, None, None),  # NULL row propagates
        (4, 0, None, None, None),  # out-of-range -> NULL
    ]


def test_unnest_column_with_ordinality(runner):
    rows = runner.execute(
        "select id, e, o from mem.default.t "
        "cross join unnest(arr) with ordinality as u(e, o) "
        "order by id, o"
    ).rows()
    assert rows == [(1, 10, 1), (1, 20, 2), (1, 30, 3), (2, 5, 1)]


def test_unnest_feeds_aggregation(runner):
    rows = runner.execute(
        "select sum(e) as s, count(*) as c from mem.default.t "
        "cross join unnest(arr) as u(e)"
    ).rows()
    assert rows == [(65, 4)]


def test_filter_preserves_arrays(runner):
    rows = runner.execute(
        "select id, arr from mem.default.t where id >= 2 order by id"
    ).rows()
    assert rows == [(2, [5]), (3, None), (4, [])]


def test_array_agg_grouped_and_global(runner):
    rows = runner.execute(
        "select id % 2 as g, array_agg(id) as a from mem.default.t "
        "group by 1 order by g"
    ).rows()
    assert rows == [(0, [2, 4]), (1, [1, 3])]
    rows = runner.execute(
        "select array_agg(id) from mem.default.t"
    ).rows()
    assert rows == [([1, 2, 3, 4],)]


def test_array_agg_roundtrip_unnest(runner):
    """array_agg -> CTAS -> scan -> unnest: the full build/store/read
    cycle over a computed array column."""
    runner.execute(
        "create table mem.default.agged as "
        "select id % 2 as g, array_agg(id) as a from mem.default.t "
        "group by 1"
    )
    rows = runner.execute(
        "select g, e from mem.default.agged "
        "cross join unnest(a) as u(e) order by g, e"
    ).rows()
    assert rows == [(0, 2), (0, 4), (1, 1), (1, 3)]


def test_varchar_arrays(runner):
    rows = runner.execute(
        "select id, tags, cardinality(tags), tags[2] "
        "from mem.default.s order by id"
    ).rows()
    assert rows == [(1, ["x", "y"], 2, "y"), (2, ["y"], 1, None)]
    rows = runner.execute(
        "select e, count(*) as c from mem.default.s "
        "cross join unnest(tags) as u(e) group by e order by e"
    ).rows()
    assert rows == [("x", 1), ("y", 2)]


def test_array_agg_from_tpch(runner):
    """array_agg over a generated catalog column, grouped."""
    rows = runner.execute(
        "select r_regionkey, array_agg(n_nationkey) as ks "
        "from tpch.tiny.nation join tpch.tiny.region "
        "on n_regionkey = r_regionkey "
        "group by r_regionkey order by r_regionkey"
    ).rows()
    assert len(rows) == 5
    all_keys = sorted(k for _, ks in rows for k in ks)
    assert all_keys == list(range(25))


def test_array_guards(runner):
    with pytest.raises(PlanningError):
        runner.execute("select arr from mem.default.t group by arr")
    with pytest.raises(PlanningError):
        runner.execute("select arr from mem.default.t order by arr")


def test_array_wire_roundtrip():
    """Array columns across the exchange wire: serialize -> deserialize
    -> merge (offset rebase) -> row-slice, all exact."""
    import numpy as np

    from presto_tpu.exec.staging import ArrayColumn
    from presto_tpu.server.pages_wire import (
        deserialize_page,
        merge_payloads,
        serialize_page,
    )

    col = ArrayColumn(
        offsets=np.asarray([0, 2, 2, 5], np.int32),
        values=np.asarray([1, 2, 10, 11, 12], np.int64),
        valid=np.asarray([True, False, True]),
    )
    at = T.array(T.BIGINT)
    buf = serialize_page([("a", col, col.valid, at, None)], 3)
    payload, schema, n = deserialize_page(buf)
    assert n == 3 and schema["a"] == at
    got = payload["a"]
    assert got.offsets.tolist() == [0, 2, 2, 5]
    assert got.values.tolist() == [1, 2, 10, 11, 12]
    assert got.valid.tolist() == [True, False, True]

    merged = merge_payloads(
        [(payload, schema, 3), (payload, schema, 3)], {"a": at}
    )
    m = merged["a"]
    assert m.offsets.tolist() == [0, 2, 2, 5, 7, 7, 10]
    assert m.values.tolist() == [1, 2, 10, 11, 12, 1, 2, 10, 11, 12]

    sliced = m[1:3]
    assert sliced.offsets.tolist() == [0, 0, 3]
    assert sliced.values.tolist() == [10, 11, 12]
