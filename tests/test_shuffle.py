"""Worker<->worker data plane (VERDICT r2 "missing #5"): producers
hash-partition PARTIAL states into per-consumer output buffers; merge
tasks on workers pull their partition straight from producer peers and
run the FINAL step — intermediate pages never touch the coordinator.
Reference shape: PartitionedOutputBuffer + ExchangeClient feeding
intermediate stages (SURVEY.md §2.5, §3.4)."""

import time

import pytest

from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.server.client import PrestoTpuClient
from presto_tpu.server.worker import WorkerServer
from presto_tpu.utils.metrics import REGISTRY
from presto_tpu.verifier import SqliteOracle, verify_query


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def cluster3():
    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(3)
    ]
    _wait_workers(coord, 3)
    yield coord, workers
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


@pytest.fixture(scope="module")
def client(cluster3):
    coord, _ = cluster3
    return PrestoTpuClient(coord.uri, timeout_s=600)


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


def _shuffles() -> int:
    return REGISTRY.counter("coordinator.shuffled_stages").total


def test_keyed_agg_takes_shuffle_path(client, oracle):
    """String + numeric group keys across 3 workers: partitioning must
    hash VALUES (per-producer dictionaries differ), and the shuffled
    result must be oracle-exact."""
    before = _shuffles()
    sql = (
        "select l_returnflag, l_linestatus, sum(l_quantity) as q, "
        "count(*) as n from tpch.tiny.lineitem "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus"
    )
    diff = verify_query(client, oracle, sql, rel_tol=1e-6)
    assert diff is None, diff
    assert _shuffles() > before, "keyed agg did not take the shuffle path"


def test_high_cardinality_keys_shuffled(client, oracle):
    before = _shuffles()
    sql = (
        "select l_orderkey, sum(l_extendedprice) as v "
        "from tpch.tiny.lineitem group by l_orderkey "
        "order by v desc, l_orderkey limit 20"
    )
    diff = verify_query(client, oracle, sql, rel_tol=1e-6)
    assert diff is None, diff
    assert _shuffles() > before


def test_merge_tasks_ran_on_workers(cluster3, client):
    """The FINAL step's tasks must run on the workers themselves."""
    before = REGISTRY.counter("worker.merge_tasks").total
    client.execute(
        "select o_orderpriority, count(*) as n from tpch.tiny.orders "
        "group by o_orderpriority order by o_orderpriority"
    ).rows()
    after = REGISTRY.counter("worker.merge_tasks").total
    # one merge task per worker partition
    assert after - before >= 3, (before, after)


def test_session_flag_disables_shuffle(client, oracle):
    client.execute("set session distributed_final = false")
    try:
        before = _shuffles()
        sql = (
            "select o_orderstatus, count(*) as n from tpch.tiny.orders "
            "group by o_orderstatus order by o_orderstatus"
        )
        diff = verify_query(client, oracle, sql, rel_tol=1e-6)
        assert diff is None, diff
        assert _shuffles() == before, "flag off but stage still shuffled"
    finally:
        client.execute("set session distributed_final = true")


def test_pipelined_source_attachment(cluster3, client, oracle, monkeypatch):
    """Merge tasks exist BEFORE stage 1 completes: producers are
    announced one by one (addExchangeLocations parity) and the set is
    sealed once — not attached as a single post-barrier batch."""
    from presto_tpu.server import worker as worker_mod

    events = []
    orig = worker_mod._Task.add_sources

    def spy(self, sources, done):
        events.append((len(list(sources)), bool(done)))
        return orig(self, sources, done)

    monkeypatch.setattr(worker_mod._Task, "add_sources", spy)
    sql = (
        "select l_shipmode, count(*) as n from tpch.tiny.lineitem "
        "group by l_shipmode order by l_shipmode"
    )
    diff = verify_query(client, oracle, sql, rel_tol=1e-6)
    assert diff is None, diff
    incremental = [e for e in events if not e[1] and e[0] > 0]
    seals = [e for e in events if e[1]]
    assert incremental, "no incremental source announcements"
    assert seals, "source set never sealed"


def test_global_agg_skips_shuffle(client, oracle):
    """No group keys -> nothing to partition; direct gather."""
    before = _shuffles()
    diff = verify_query(
        client,
        oracle,
        "select count(*) as n, sum(l_quantity) as q "
        "from tpch.tiny.lineitem",
        rel_tol=1e-6,
    )
    assert diff is None, diff
    assert _shuffles() == before


# ------------------------------------- partitioned intermediate JOIN stage


def _pjoins() -> int:
    return REGISTRY.counter(
        "coordinator.partitioned_join_stages"
    ).total


def test_partitioned_join_stage(cluster3, client, oracle):
    """join_distribution_type=PARTITIONED: a two-table join runs as two
    hash-partitioned producer stages + a join stage consuming matching
    partitions from both — neither side replicated (VERDICT r3 missing
    5: FIXED_HASH_DISTRIBUTION intermediate stages)."""
    coord, _ = cluster3
    before = _pjoins()
    client.execute(
        "set session join_distribution_type = 'PARTITIONED'"
    )
    try:
        q = (
            "select o_orderpriority, count(*) as c, "
            "sum(l_quantity) as q "
            "from tpch.tiny.lineitem join tpch.tiny.orders "
            "on l_orderkey = o_orderkey "
            "where l_shipdate >= date '1995-01-01' "
            "group by o_orderpriority order by o_orderpriority"
        )
        res = client.execute(q)
        assert _pjoins() > before
        local = coord.local.execute(q).rows()
        diff = verify_query(coord.local, oracle, q)
        assert diff is None, diff
        assert len(res.rows()) == len(local)
        for a, b in zip(res.rows(), local):
            assert a[0] == b[0] and int(a[1]) == int(b[1]), (a, b)
            assert abs(float(a[2]) - float(b[2])) < 1e-6, (a, b)
    finally:
        client.execute(
            "set session join_distribution_type = 'AUTOMATIC'"
        )


def test_partitioned_join_auto_choice(cluster3, client, oracle):
    """AUTOMATIC join distribution chooses the partitioned stage from
    STATS, without any session force (VERDICT r4 ask 3: AddExchanges'
    cost-driven choice): with the broadcast bound lowered beneath both
    sides' estimated rows, a two-big-table join auto-partitions
    (counter asserts), oracle-exact; at the default bound the same
    query keeps the replicated fast path."""
    coord, _ = cluster3
    q = (
        "select o_orderpriority, count(*) as c, "
        "sum(l_quantity) as q "
        "from tpch.tiny.lineitem join tpch.tiny.orders "
        "on l_orderkey = o_orderkey "
        "group by o_orderpriority order by o_orderpriority"
    )
    # default bound (2M rows) dwarfs tiny tables: replicated path
    before = _pjoins()
    client.execute(q)
    assert _pjoins() == before
    # lower the bound beneath orders' ~15k rows: auto-partitioned
    client.execute("set session join_max_broadcast_rows = 1000")
    try:
        res = client.execute(q)
        assert _pjoins() > before
        diff = verify_query(coord.local, oracle, q)
        assert diff is None, diff
        local = coord.local.execute(q).rows()
        assert len(res.rows()) == len(local)
        for a, b in zip(res.rows(), local):
            assert a[0] == b[0] and int(a[1]) == int(b[1]), (a, b)
            assert abs(float(a[2]) - float(b[2])) < 1e-6, (a, b)
    finally:
        client.execute("set session join_max_broadcast_rows = 2097152")


def test_partitioned_join_semi(cluster3, client, oracle):
    """Semi join under PARTITIONED distribution: probe rows route by
    key next to their build partition; result oracle-exact."""
    coord, _ = cluster3
    before = _pjoins()
    client.execute(
        "set session join_distribution_type = 'PARTITIONED'"
    )
    try:
        q = (
            "select count(*) as c from tpch.tiny.orders "
            "where o_orderkey in (select l_orderkey from "
            "tpch.tiny.lineitem where l_quantity > 45)"
        )
        res = client.execute(q)
        assert _pjoins() > before
        assert res.rows() == coord.local.execute(q).rows()
        diff = verify_query(coord.local, oracle, q)
        assert diff is None, diff
    finally:
        client.execute(
            "set session join_distribution_type = 'AUTOMATIC'"
        )
