"""Parameterized plan cache + compiled-fragment reuse
(plan/canonical.py): canonical-form equality across literal variants,
on/off bit-exactness, dtype bucketing, PREPARE/EXECUTE zero-recompile,
write-path invalidation, concurrency, and distributed fragment
reuse."""

import threading
import time

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.plan import canonical
from presto_tpu.plan.planner import plan_statement
from presto_tpu.sql import parse_statement
from presto_tpu.utils.metrics import REGISTRY


def _misses() -> int:
    return int(REGISTRY.counter("compile.cache_miss").total)


def _plan_hits() -> int:
    return int(REGISTRY.counter("plan.cache_hit").total)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def runner_off():
    r = LocalQueryRunner()
    r.session.set("enable_plan_cache", "false")
    return r


# ------------------------------------------------------- canonical form


def test_canonical_fingerprint_equal_across_literals(runner):
    q = (
        "select l_returnflag, count(*) c from tpch.tiny.lineitem "
        "where l_quantity < {} group by l_returnflag"
    )
    roots = []
    vals = []
    for v in (24, 30):
        plan = plan_statement(
            parse_statement(q.format(v)), runner.catalogs, runner.session
        )
        croot, pvals = canonical.hoist_params(plan.root)
        roots.append(croot)
        vals.append(pvals)
    assert roots[0].fingerprint() == roots[1].fingerprint()
    assert [int(v) for v in vals[0]] != [int(v) for v in vals[1]]


def test_dtype_boundary_params_bucket_separately(runner):
    # int64 vs decimal literals are DIFFERENT canonical forms — a
    # param's dtype is program structure, never a silent cast
    q = "select count(*) c from tpch.tiny.orders where o_totalprice < {}"
    fps = []
    for v in ("100000", "100000.5"):
        plan = plan_statement(
            parse_statement(q.format(v)), runner.catalogs, runner.session
        )
        croot, _ = canonical.hoist_params(plan.root)
        fps.append(croot.fingerprint())
    assert fps[0] != fps[1]
    # and the statement-level keys differ the same way
    k1, _, _ = canonical.canonicalize_statement(
        parse_statement(q.format("100000")), runner.session
    )
    k2, _, _ = canonical.canonicalize_statement(
        parse_statement(q.format("100000.5")), runner.session
    )
    k3, _, _ = canonical.canonicalize_statement(
        parse_statement(q.format("200000")), runner.session
    )
    assert k1 != k2
    assert k1 == k3


def test_statement_key_string_literals_stay_distinct(runner):
    q = (
        "select count(*) c from tpch.tiny.orders "
        "where o_orderpriority = '{}'"
    )
    k1, _, v1 = canonical.canonicalize_statement(
        parse_statement(q.format("1-URGENT")), runner.session
    )
    k2, _, _ = canonical.canonicalize_statement(
        parse_statement(q.format("2-HIGH")), runner.session
    )
    # strings are not parameterized: distinct values key distinct
    # entries (correct, just less sharing) and hoist no values
    assert k1 != k2
    assert v1 == []


def test_compile_cache_hit_across_literal_variants(runner):
    q = (
        "select l_returnflag, count(*) c, sum(l_extendedprice) s "
        "from tpch.tiny.lineitem where l_quantity < {} "
        "group by l_returnflag order by l_returnflag"
    )
    runner.execute(q.format(24))
    m0 = _misses()
    res = runner.execute(q.format(30))
    assert _misses() == m0, "literal variant must not recompile"
    assert res.rows()  # and it really ran


# ------------------------------------------------------ on/off equality

_EQUIV_QUERIES = [
    # range filter + aggregation + decimal projection arithmetic
    "select l_returnflag, count(*) c, sum(l_extendedprice * (1 - "
    "l_discount)) rev from tpch.tiny.lineitem where l_quantity < 24 "
    "group by l_returnflag order by l_returnflag",
    # BETWEEN over decimals + date comparison
    "select count(*) c from tpch.tiny.lineitem where l_discount "
    "between 0.05 and 0.07 and l_shipdate < date '1996-01-01'",
    # IN list over integers, negated IN, negative literal
    "select count(*) c from tpch.tiny.lineitem where l_linenumber in "
    "(1, 2, 3) and l_suppkey not in (5, 7) and l_quantity > -5",
    # string equality + LIKE stay constants beside hoisted numerics
    "select count(*) c from tpch.tiny.orders where o_orderpriority = "
    "'1-URGENT' and o_comment like '%special%' and o_totalprice < "
    "150000.5",
    # join + HAVING (the Q18 shape, scaled down)
    "select o_orderkey, sum(l_quantity) q from tpch.tiny.orders, "
    "tpch.tiny.lineitem where o_orderkey = l_orderkey and "
    "o_totalprice > 400000 group by o_orderkey having "
    "sum(l_quantity) > 250 order by q desc limit 5",
    # scalar subquery (hoisting inside the subquery's WHERE too)
    "select count(*) c from tpch.tiny.part where p_retailprice > "
    "(select avg(p_retailprice) from tpch.tiny.part where p_size < 25)",
]


@pytest.mark.parametrize("qi", range(len(_EQUIV_QUERIES)))
def test_on_off_equivalence(runner, runner_off, qi):
    q = _EQUIV_QUERIES[qi]
    assert runner.execute(q).rows() == runner_off.execute(q).rows()


def test_null_literal_not_parameterized(runner, runner_off):
    # NULL comparisons keep their structure (validity lanes differ)
    q = (
        "select count(*) c from tpch.tiny.orders "
        "where o_custkey = null or o_totalprice < 100000"
    )
    assert runner.execute(q).rows() == runner_off.execute(q).rows()


# --------------------------------------------- PREPARE/EXECUTE fast lane


def test_execute_warm_is_zero_recompile(runner):
    # acceptance criterion: EXECUTE of a prepared statement with FRESH
    # literals is a plan.cache_hit + compile.cache_hit — zero recompile
    runner.execute(
        "prepare pc_t1 from select count(*) c from tpch.tiny.orders "
        "where o_totalprice < ?"
    )
    runner.execute("execute pc_t1 using 100000")  # cold: plan + compile
    m0, h0 = _misses(), _plan_hits()
    res = runner.execute("execute pc_t1 using 150000")
    assert _misses() == m0, "warm EXECUTE must not compile"
    assert _plan_hits() > h0, "warm EXECUTE must hit the plan cache"
    # the fresh literal really applied (not a stale cached value)
    off = LocalQueryRunner()
    off.session.set("enable_plan_cache", "false")
    expect = off.execute(
        "select count(*) c from tpch.tiny.orders "
        "where o_totalprice < 150000"
    ).rows()
    assert res.rows() == expect


def test_execute_argument_validation(runner):
    runner.execute(
        "prepare pc_t2 from select count(*) c from tpch.tiny.region "
        "where r_regionkey < ?"
    )
    with pytest.raises(Exception, match="parameter"):
        runner.execute("execute pc_t2 using 1, 2")
    runner.execute("deallocate prepare pc_t2")
    with pytest.raises(Exception, match="not found"):
        runner.execute("execute pc_t2 using 1")


# ----------------------------------------------------- write invalidation


@pytest.fixture()
def mem_runner():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    from presto_tpu.connectors.spi import TableHandle

    mem.create_table(
        TableHandle("mem", "default", "kv"),
        {"k": T.BIGINT, "v": T.VARCHAR},
    )
    catalogs.register("mem", mem)
    return LocalQueryRunner(catalogs=catalogs)


def test_insert_visible_through_cached_plan(mem_runner):
    r = mem_runner
    r.execute("insert into mem.default.kv values (1, 'one'), (2, 'two')")
    q = "select count(*) c from mem.default.kv where k < {}"
    assert r.execute(q.format(10)).rows() == [(2,)]
    r.execute("insert into mem.default.kv values (3, 'three')")
    # same canonical shape, fresh data: the plan cache entry survives
    # (schema unchanged) but the split cache invalidated, so the new
    # row is visible
    assert r.execute(q.format(10)).rows() == [(3,)]
    assert r.execute(q.format(3)).rows() == [(2,)]


def test_drop_recreate_invalidates_plan_cache(mem_runner):
    r = mem_runner
    r.execute("insert into mem.default.kv values (1, 'one')")
    q = "select k from mem.default.kv where k < {} order by k"
    assert r.execute(q.format(5)).rows() == [(1,)]
    entries0 = r.plan_cache.stats()["entries"]
    assert entries0 >= 1
    r.execute("drop table mem.default.kv")
    # every entry over the dropped table is gone
    assert r.plan_cache.stats()["entries"] < entries0
    # recreate with a DIFFERENT schema: the same query text must plan
    # against the new table, not a stale cached plan
    r.execute("create table mem.default.kv (k double, x bigint)")
    r.execute("insert into mem.default.kv values (0.5, 7)")
    assert r.execute(q.format(5)).rows() == [(0.5,)]


# ------------------------------------------------------------ LRU bounds


def test_lru_eviction_bounded_entries():
    r = LocalQueryRunner(plan_cache_entries=2)
    ev0 = int(REGISTRY.counter("plan.cache_evict").total)
    qs = [
        "select count(*) c from tpch.tiny.region where r_regionkey < 3",
        "select count(*) c from tpch.tiny.nation where n_nationkey < 7",
        "select r_name from tpch.tiny.region where r_regionkey = 1",
    ]
    for q in qs:
        r.execute(q)
    assert r.plan_cache.stats()["entries"] <= 2
    assert int(REGISTRY.counter("plan.cache_evict").total) > ev0
    # evicted shapes still execute correctly (they just replan)
    assert r.execute(qs[0]).rows() == [(3,)]


# ----------------------------------------------------------- concurrency


def test_concurrent_literal_variants_compile_once():
    r = LocalQueryRunner()
    r.execute(
        "prepare pc_cc from select count(*) c from tpch.tiny.region "
        "where r_regionkey < ?"
    )
    m0 = _misses()
    results = {}
    errors = []
    barrier = threading.Barrier(10)

    def client(i):
        try:
            barrier.wait(timeout=30)
            for j in range(5):
                v = (i * 5 + j) % 50
                rows = r.execute(f"execute pc_cc using {v}").rows()
                results[(i, j)] = (v, rows)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(10)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # 50 literal-variants of one shape: exactly ONE compile
    assert _misses() - m0 == 1
    for (i, j), (v, rows) in results.items():
        assert rows == [(min(v, 5),)], (i, j, v)


# ----------------------------------------------- observability surfaces


def test_plan_cache_hit_in_history_and_caches_view(runner):
    q = "select count(*) c from tpch.tiny.nation where n_regionkey < {}"
    runner.execute(q.format(2))
    runner.execute(q.format(4))
    hist = {s.sql: s for s in runner.history.snapshot()}
    assert hist[q.format(4)].plan_cache_hit is True
    assert hist[q.format(4)].to_dict()["plan_cache_hit"] is True
    rows = runner.execute(
        "select cache, entries, hits from system.runtime.caches"
    ).rows()
    caches = {r[0]: r for r in rows}
    assert "plan.cache" in caches
    assert caches["plan.cache"][1] >= 1  # entries
    assert caches["plan.cache"][2] >= 1  # hits


def test_explain_analyze_keeps_literals(runner):
    text = "\n".join(
        r[0]
        for r in runner.execute(
            "explain analyze select count(*) c from tpch.tiny.region "
            "where r_regionkey < 3"
        ).rows()
    )
    # analyzed plans keep literals in place: the rendered predicate
    # shows the query's actual value, never a parameter slot
    assert "3" in text
    assert "?p" not in text


def test_canonicalize_ms_metric_recorded(runner):
    runner.execute("select count(*) c from tpch.tiny.region")
    names = [n for n, _k, _v in REGISTRY.snapshot()]
    assert any(n.startswith("plan.canonicalize_ms") for n in names)


# ------------------------------------------------------ session off = legacy


def test_cache_off_compiles_per_variant():
    r = LocalQueryRunner()
    r.session.set("enable_plan_cache", "false")
    q = "select count(*) c from tpch.tiny.nation where n_nationkey < {}"
    r.execute(q.format(5))
    m0 = _misses()
    r.execute(q.format(9))
    # legacy behavior: every literal variant is its own program
    assert _misses() > m0
    assert r.plan_cache.stats()["entries"] == 0


def test_split_pruning_connectors_bypass_statement_cache(tmp_path):
    # hive/parquet/orc read equality/IN literals as scan constraints
    # (partition / row-group / stripe pruning); their statements must
    # keep literal planning — see test_hive.py's pruning assertions
    from presto_tpu.connectors.hive import HiveConnector
    from presto_tpu.connectors.orc import OrcConnector
    from presto_tpu.connectors.parquet import ParquetConnector

    assert create_connector("tpch").prunes_splits() is False
    assert create_connector("memory").prunes_splits() is False
    assert HiveConnector(str(tmp_path)).prunes_splits() is True
    assert ParquetConnector(str(tmp_path)).prunes_splits() is True
    assert OrcConnector(str(tmp_path)).prunes_splits() is True


# ------------------------------------------------------------ distributed


@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )

    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    deadline = time.time() + 15
    while time.time() < deadline and len(coord.active_workers()) < 2:
        time.sleep(0.05)
    assert len(coord.active_workers()) >= 2
    client = PrestoTpuClient(coord.uri, timeout_s=300)
    yield coord, workers, client
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def test_distributed_fragment_reuse(cluster):
    coord, workers, client = cluster
    q = "select count(*) c from tpch.tiny.lineitem where l_quantity < {}"
    r1 = client.execute(q.format(24))
    m0 = _misses()
    r2 = client.execute(q.format(30))
    # coordinator planned from cache AND every worker hit its compile
    # cache on the literal-variant fragment: zero compiles anywhere
    assert _misses() == m0
    assert client.query_info(r2.query_id)["plan_cache_hit"] is True
    assert r1.rows() == [(27628,)]
    assert r2.rows() == [(34706,)]


def test_prepared_statements_over_http(cluster):
    coord, workers, client = cluster
    res = client.execute(
        "prepare pc_http from select count(*) c from tpch.tiny.orders "
        "where o_totalprice < ?"
    )
    assert res.rows() == [("PREPARE",)]
    assert "pc_http" in client.prepared  # added-prepare header absorbed
    a = client.execute("execute pc_http using 100000")
    m0 = _misses()
    b = client.execute("execute pc_http using 150000")
    assert _misses() == m0  # warm HTTP EXECUTE: zero recompile
    assert client.query_info(b.query_id)["plan_cache_hit"] is True
    assert a.rows() == [(2614,)]
    assert b.rows() == [(4060,)]
    res = client.execute("deallocate prepare pc_http")
    assert res.rows() == [("DEALLOCATE",)]
    assert "pc_http" not in client.prepared


def test_prepared_header_rides_fresh_client(cluster):
    # a SECOND client sharing nothing server-side can EXECUTE a
    # statement it PREPAREd itself — the map rides its own headers
    coord, workers, _ = cluster
    from presto_tpu.server import PrestoTpuClient

    c2 = PrestoTpuClient(coord.uri, timeout_s=300)
    c2.execute(
        "prepare pc_own from select count(*) c from tpch.tiny.nation "
        "where n_nationkey < ?"
    )
    assert c2.execute("execute pc_own using 10").rows() == [(10,)]


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
