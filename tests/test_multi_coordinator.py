"""Multi-coordinator control plane (ISSUE 17): shared admission,
live query failover, and orphan-state reaping.

Acceptance surface:

- lease claim / expiry / fencing units (server/lease.py): atomic-rename
  renewal, exactly-one-winner claims, stale-claim supersede, fenced
  writes rejected (split-brain structurally impossible);
- 2-coordinator shared-quota admission: a worker-side hog admitted via
  peer A trips the cluster resource-group limit for peer B;
- kill-coordinator-mid-load chaos: zero failed queries, exact results,
  and a statement URI minted by the dead coordinator survives TWO
  bounces through the cross-coordinator alias chain;
- client spray: round-robin statement distribution, re-target on
  connection failure, and the fast "statement gone on every
  coordinator" verdict (no reconnect-budget spin on a dead alias);
- worker orphan-task reaper (``task.orphan-ttl-s``) and history-epoch
  persistence (a failed-over coordinator keeps its learned plans).

Single-coordinator deploys must stay bit-exact: the lease plane is
never constructed without ``coordinator.peers``.
"""

import os
import socket
import threading
import time

import pytest

from presto_tpu.plan.history import QueryHistoryStore
from presto_tpu.server import (
    CoordinatorServer,
    PrestoTpuClient,
    WorkerServer,
)
from presto_tpu.server.client import QueryFailed
from presto_tpu.server.journal import CoordinatorJournal
from presto_tpu.server.lease import FencedError, LeasePlane
from presto_tpu.server.protocol import FragmentSpec
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

REGION_SQL = "select count(*) as c from tpch.tiny.region"


@pytest.fixture(autouse=True)
def clear_fault_plane():
    yield
    faults.configure(None)


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _mk_coords(
    tmp_path,
    n=2,
    ttl=0.75,
    extra=None,
    start=True,
    **coord_kwargs,
):
    """N coordinators sharing one control directory (lease files +
    per-coordinator journal segments), each listing the others as
    ``coordinator.peers``. Ports are pre-reserved so every peer list
    is known at construction."""
    ctl = str(tmp_path / "ctl")
    ports = _free_ports(n)
    uris = [f"http://127.0.0.1:{p}" for p in ports]
    coords = []
    for i in range(n):
        cfg = {
            "node.id": f"coord-{i}",
            "coordinator.journal-path": ctl,
            "coordinator.peers": ",".join(
                u for j, u in enumerate(uris) if j != i
            ),
            "lease.ttl-s": str(ttl),
        }
        cfg.update(extra or {})
        c = CoordinatorServer(
            port=ports[i], config=NodeConfig(cfg), **coord_kwargs
        )
        if start:
            c.start()
        coords.append(c)
    return coords


def _teardown(coords, workers=()):
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    for c in coords:
        try:
            c.shutdown()
        except Exception:
            pass


# --------------------------------------------------------- lease units


def test_lease_renew_peers_and_expiry(tmp_path):
    d = str(tmp_path / "ctl")
    a = LeasePlane(d, "c-a", uri="http://a", ttl_s=0.3)
    b = LeasePlane(d, "c-b", uri="http://b", ttl_s=0.3)
    a.renew({"qids": ["q_c1_aaaaaa"]})
    b.renew()
    # peers() excludes self and carries the state payload through
    (pa,) = b.peers()
    assert pa.owner == "c-a" and pa.uri == "http://a"
    assert pa.state == {"qids": ["q_c1_aaaaaa"]}
    assert [p.owner for p in a.peers()] == ["c-b"]
    assert not a.is_expired(b.read_lease("c-b"))
    time.sleep(0.4)  # both leases age past the TTL
    assert a.peers(live_only=True) == []
    assert a.is_expired(a.read_lease("c-b"))
    b.renew()  # a heartbeat revives the lease
    assert [p.owner for p in a.peers(live_only=True)] == ["c-b"]


def test_lease_claim_exactly_one_winner(tmp_path):
    d = str(tmp_path / "ctl")
    dead = LeasePlane(d, "c-dead", ttl_s=0.2)
    dead.renew()
    a = LeasePlane(d, "c-a", ttl_s=0.2)
    b = LeasePlane(d, "c-b", ttl_s=0.2)
    # a live owner is not claimable
    a.renew()
    assert b.claim_expired("c-a") is None
    # an absent owner (never leased / retired) is not claimable
    assert b.claim_expired("c-ghost") is None
    time.sleep(0.3)
    a.renew()  # a's own lease must be live for its claim to stand
    before = REGISTRY.counter("lease.claims").total
    ca = a.claim_expired("c-dead")
    assert ca is not None and ca.claimant == "c-a"
    assert ca.epoch == dead.epoch + 1  # strictly above the dead lease
    assert REGISTRY.counter("lease.claims").total == before + 1
    # O_EXCL picked exactly one winner: b loses while a's claim stands
    assert b.claim_expired("c-dead") is None
    a.check_fence(ca)  # the winner's fence holds
    # retire clears both files: nothing left to claim or fence
    a.retire("c-dead")
    assert a.read_lease("c-dead") is None
    assert b.claim_expired("c-dead") is None
    with pytest.raises(FencedError):
        a.check_fence(ca)


def test_lease_split_brain_stale_claim_superseded(tmp_path):
    """Split-brain fencing: claimant A stalls past its own TTL, B
    supersedes the stale claim at a strictly higher epoch, and every
    write A still intends is rejected by its fence check."""
    d = str(tmp_path / "ctl")
    dead = LeasePlane(d, "c-dead", ttl_s=0.2)
    dead.renew()
    a = LeasePlane(d, "c-a", ttl_s=0.2)
    b = LeasePlane(d, "c-b", ttl_s=0.2)
    time.sleep(0.3)
    a.renew()
    ca = a.claim_expired("c-dead")
    assert ca is not None
    # A goes silent: its lease expires, so its claim is STALE
    time.sleep(0.3)
    b.renew()
    cb = b.claim_expired("c-dead")
    assert cb is not None and cb.claimant == "c-b"
    assert cb.epoch > ca.epoch  # superseded strictly above
    fenced_before = REGISTRY.counter("lease.fenced_writes").total
    with pytest.raises(FencedError):
        a.check_fence(ca)  # the stalled claimant may write NOTHING
    assert (
        REGISTRY.counter("lease.fenced_writes").total
        == fenced_before + 1
    )
    b.check_fence(cb)  # the superseding claimant proceeds


def test_lease_epoch_monotonic_across_restarts(tmp_path):
    d = str(tmp_path / "ctl")
    p1 = LeasePlane(d, "c-x", ttl_s=0.2)
    assert p1.epoch == 1
    p1.renew()
    # a restart rejoins strictly above its previous incarnation
    p2 = LeasePlane(d, "c-x", ttl_s=0.2)
    assert p2.epoch == 2
    p2.renew()
    # ... and strictly above any claim a survivor fenced it at
    time.sleep(0.3)
    y = LeasePlane(d, "c-y", ttl_s=0.2)
    y.renew()
    cy = y.claim_expired("c-x")
    assert cy is not None and cy.epoch == 3
    p3 = LeasePlane(d, "c-x", ttl_s=0.2)
    assert p3.epoch == 4


def test_lease_stop_withdraws_instead_of_expiring(tmp_path):
    d = str(tmp_path / "ctl")
    a = LeasePlane(d, "c-a", ttl_s=0.2)
    b = LeasePlane(d, "c-b", ttl_s=0.2)
    a.renew()
    b.renew()
    a.stop()  # clean shutdown: the lease file is GONE, not expiring
    assert b.read_lease("c-a") is None
    time.sleep(0.3)
    assert b.claim_expired("c-a") is None  # nothing to claim


# ------------------------------------------- journal claim/alias frames


def test_journal_claim_and_alias_frames_replay(tmp_path):
    j = CoordinatorJournal(str(tmp_path / "j"))
    j.record_submit("q_c1_aaaaaa", "select 1")
    j.record_alias("q_c9_dddddd", "q_c1_aaaaaa")
    j.record_claim("coord-7", 5)
    state = CoordinatorJournal(str(tmp_path / "j")).replay()
    assert [r["qid"] for r in state.open] == ["q_c1_aaaaaa"]
    assert state.aliases == {"q_c9_dddddd": "q_c1_aaaaaa"}
    assert state.claim is not None
    assert state.claim["claimant"] == "coord-7"
    assert state.claim["epoch"] == 5


# -------------------------------------------------------- client spray


def test_client_sprays_statements_round_robin(tmp_path):
    c1 = CoordinatorServer().start()
    c2 = CoordinatorServer().start()
    try:
        cl = PrestoTpuClient([c1.uri, c2.uri], timeout_s=60)
        for _ in range(2):
            assert cl.execute(REGION_SQL).rows() == [(5,)]
        # one statement landed on each coordinator
        assert len(c1.queries) == 1 and len(c2.queries) == 1
    finally:
        _teardown([c1, c2])


def test_client_post_retargets_dead_coordinator(tmp_path):
    (dead_port,) = _free_ports(1)
    c = CoordinatorServer().start()
    try:
        cl = PrestoTpuClient(
            [f"http://127.0.0.1:{dead_port}", c.uri], timeout_s=60
        )
        before = REGISTRY.counter("client.spray_retargets").total
        # round-robin starts at the dead peer: connection refused must
        # re-target the POST (never delivered => no duplicate query)
        assert cl.execute(REGION_SQL).rows() == [(5,)]
        assert (
            REGISTRY.counter("client.spray_retargets").total
            == before + 1
        )
        assert len(c.queries) == 1
    finally:
        _teardown([c])


def test_client_statement_gone_everywhere_fails_fast(tmp_path):
    """404 from EVERY coordinator = alias chain exhausted: surface
    QueryFailed immediately instead of spinning the full reconnect
    budget. A single-coordinator client keeps the legacy behavior
    (HTTP errors surface as-is)."""
    import urllib.error

    c1 = CoordinatorServer().start()
    c2 = CoordinatorServer().start()
    try:
        cl = PrestoTpuClient(
            [c1.uri, c2.uri], timeout_s=60, reconnect_attempts=50
        )
        url = f"{c1.uri}/v1/statement/q_c9_ffffff/0"
        t0 = time.monotonic()
        with pytest.raises(QueryFailed, match="statement gone"):
            cl._get_with_reconnect(url, time.monotonic() + 60)
        # fast verdict: one sweep, not 50 backoff rounds
        assert time.monotonic() - t0 < 10.0
        solo = PrestoTpuClient(c1.uri, timeout_s=60)
        with pytest.raises(urllib.error.HTTPError):
            solo._get_with_reconnect(url, time.monotonic() + 60)
    finally:
        _teardown([c1, c2])


# ------------------------------------------------ single-node bit-exact


def test_no_peers_means_no_lease_plane(tmp_path):
    """The bit-exact guard: without ``coordinator.peers`` the lease
    plane is never constructed and the journal lives at the configured
    path itself (not a per-coordinator subdirectory)."""
    jp = str(tmp_path / "jr")
    c = CoordinatorServer(
        config=NodeConfig({"coordinator.journal-path": jp})
    )
    try:
        assert c.lease is None
        assert c.journal is not None and c.journal.path == jp
        assert c.locate_peer("q_c1_aaaaaa") == ""
    finally:
        c.shutdown()
    # peers without a journal path: nothing to share through => no plane
    c2 = CoordinatorServer(
        config=NodeConfig({"coordinator.peers": "http://127.0.0.1:9"})
    )
    try:
        assert c2.lease is None and c2.journal is None
    finally:
        c2.shutdown()


# ------------------------------------------------- shared admission


def _fake_query(coord, qid, group=None):
    from presto_tpu.server.coordinator import _Query

    q = _Query(qid, "select 1")
    q.state = "RUNNING"
    q.resource_group = group
    coord.queries[qid] = q
    return q


def _report(limit=1 << 20, queries=None):
    return {
        "limit": limit,
        "reserved": sum(q["bytes"] for q in (queries or {}).values()),
        "queries": queries or {},
        "blocked": [],
    }


def test_shared_group_quota_trips_across_admitters(tmp_path):
    """THE shared-admission acceptance: a worker-side memory hog
    admitted via coordinator A counts against the resource-group
    quota coordinator B enforces — `softMemoryLimit` holds across N
    admitters, not per process."""
    rg = {
        "rootGroups": [
            {"name": "etl", "hardConcurrencyLimit": 4,
             "softMemoryLimit": "1KB"},
        ],
    }
    ca, cb = _mk_coords(
        tmp_path, n=2, start=False, resource_groups=dict(rg)
    )
    try:
        hog = _fake_query(ca, "q_c1_abcdef", group="etl")
        # the worker heartbeats EVERY coordinator: both arbiters hold
        # the hog's worker-side bytes
        rep = _report(queries={"q_c1_abcdef": {"bytes": 4096,
                                               "peak": 4096}})
        ca.arbiter.observe("w1", rep)
        cb.arbiter.observe("w1", rep)
        # before A publishes its lease state, B knows nothing of the
        # hog's group membership
        assert cb._group_memory("etl") == 0
        ca.lease.renew(ca._lease_state())
        # B folds A's published group occupancy: the hog's qid rides
        # the lease payload, its bytes ride the worker heartbeat
        assert cb._group_memory("etl") == 4096
        g = cb.resource_groups.groups["etl"]
        assert cb.resource_groups._over_memory(g) is True
        # A's local-pool report joins B's cluster admission view
        assert "coord:coord-0" in cb.arbiter._view()
        # ... and B can point a sprayed client at the hog's owner
        assert cb.locate_peer("q_c1_abcdef") == ca.uri
        assert cb.locate_peer("q_c9_zzzzzz") == ""
        _ = hog
    finally:
        _teardown([ca, cb])


def test_peer_coordinators_in_nodes_view_never_schedulable(tmp_path):
    c0, c1 = _mk_coords(tmp_path, n=2, ttl=0.75)
    workers = []
    try:
        w = WorkerServer(coordinator_uri=[c0.uri, c1.uri]).start()
        workers.append(w)
        # peers announce through the worker channel (role=coordinator)
        _wait(
            lambda: "coord-1" in c0.workers and "coord-0" in c1.workers,
            msg="peer coordinator announcements",
        )
        _wait(
            lambda: w.node_id in c0.workers and w.node_id in c1.workers,
            msg="worker announced to both coordinators",
        )
        rows = c0.local.execute(
            "select node_id, coordinator from system.runtime.nodes"
        ).rows()
        by_id = dict(rows)
        assert by_id["coord-1"] is True
        assert by_id[w.node_id] is False
        # visible, but NEVER schedulable: no tasks route to a peer
        for c in (c0, c1):
            sched = [x.node_id for x in c.active_workers()]
            assert w.node_id in sched
            assert not any(n.startswith("coord-") for n in sched)
    finally:
        _teardown([c0, c1], workers)


# --------------------------------------------------- live failover


def test_failover_resumes_dead_peers_queued_queries(tmp_path):
    """A survivor claims an expired peer's journal and resumes its
    open queries under new qids, with the old statement ids aliased
    to the resumed runs."""
    c0, c1 = _mk_coords(tmp_path, n=2, ttl=0.6, max_concurrent_queries=1)
    try:
        c0._admit.acquire()  # pin submissions QUEUED on c0
        qs = [c0.submit(REGION_SQL) for _ in range(2)]
        assert all(q.state == "QUEUED" for q in qs)
        claims_before = REGISTRY.counter(
            "coordinator.failover_claims"
        ).total
        c0._fault_kill()  # abrupt: lease EXPIRES, journal stays open
        _wait(
            lambda: c1.failover_claims == 1,
            timeout=20,
            msg="survivor claims the dead lease",
        )
        assert (
            REGISTRY.counter("coordinator.failover_claims").total
            == claims_before + 1
        )
        assert c1.failover_resumed == 2
        for q in qs:
            rq = c1.lookup_query(q.qid)  # dead-boot qid -> resumed run
            assert rq is not None and rq.qid != q.qid
            assert rq.done.wait(60)
            assert rq.state == "FINISHED", rq.error
            assert rq.rows == [[5]]
        # fully failed over: the dead lease + claim were retired, so
        # nothing re-claims (and a c0 restart would rejoin fresh)
        assert c1.lease.read_lease("coord-0") is None
        assert c1.failover_claims == 1
    finally:
        _teardown([c0, c1])


def test_kill_coordinator_chaos_zero_failed_queries(tmp_path):
    """THE chaos acceptance: 3 coordinators under concurrent sprayed
    client load; the fault plane kills one mid-query. Zero failed
    queries, exact results — open queries resume on a peer and
    statement URIs keep resolving through the alias chain."""
    coords = _mk_coords(tmp_path, n=3, ttl=0.75)
    try:
        uris = [c.uri for c in coords]
        faults.configure({
            "rules": [
                {"action": "kill_coordinator", "node": "coord-0",
                 "count": 1},
            ],
        })
        results, errors = [], []

        def run_queries():
            cl = PrestoTpuClient(
                uris, timeout_s=90, reconnect_attempts=16
            )
            try:
                for _ in range(3):
                    results.append(cl.execute(REGION_SQL).rows())
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)

        threads = [
            threading.Thread(target=run_queries, daemon=True)
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "load hung"
        assert errors == [], errors
        assert results == [[(5,)]] * 9
        # the kill fired and exactly one survivor claimed the journal.
        # The claim is ASYNCHRONOUS to the load: when every in-flight
        # statement rode the 503/connection re-target path, the last
        # client can finish before the dead lease even expires — wait
        # for the scan, don't assert the instantaneous count
        survivors = coords[1:]
        _wait(
            lambda: sum(c.failover_claims for c in survivors) == 1,
            msg="survivor claim of coord-0's journal",
        )
        # the query the kill interrupted had journaled its submit frame
        # (and could not journal a finish) — the claimant resumes it
        _wait(
            lambda: sum(c.failover_resumed for c in survivors) >= 1,
            msg="claimant resume of the interrupted query",
        )
    finally:
        _teardown(coords)


def test_statement_uri_survives_two_failover_bounces(tmp_path):
    """A statement URI minted by coordinator 0 keeps resolving after
    its query failed over TWICE (coord-0 dies, the claimant dies too):
    transitive alias frames collapse the chain onto the live run."""
    coords = _mk_coords(
        tmp_path, n=3, ttl=0.6, max_concurrent_queries=1
    )
    c0, c1, c2 = coords
    try:
        # survivors' single admission slot is held, so each resumed
        # run stays QUEUED (open in the claimant's journal) until the
        # final survivor is released
        c1._admit.acquire()
        c2._admit.acquire()
        faults.configure({
            "rules": [
                {"action": "kill_coordinator", "node": "coord-0",
                 "count": 1},
            ],
        })
        out, errors = [], []

        def run_query():
            cl = PrestoTpuClient(
                [c0.uri, c1.uri, c2.uri],
                timeout_s=120,
                reconnect_attempts=40,
            )
            try:
                out.append(cl.execute(REGION_SQL))
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)

        t = threading.Thread(target=run_query, daemon=True)
        t.start()  # round-robin starts at c0: the kill rule fires
        _wait(
            lambda: c1.failover_claims + c2.failover_claims == 1,
            timeout=20,
            msg="first failover claim",
        )
        s1, s2 = (c1, c2) if c1.failover_claims else (c2, c1)
        _wait(
            lambda: s1.failover_resumed == 1,
            msg="first resume journaled",
        )
        s1._fault_kill()  # bounce TWO: the claimant dies as well
        _wait(
            lambda: s2.failover_claims >= 1,
            timeout=20,
            msg="second failover claim",
        )
        _wait(
            lambda: s2.failover_resumed >= 1,
            msg="second resume journaled",
        )
        s2._admit.release()  # let the twice-resumed run execute
        t.join(timeout=120)
        assert not t.is_alive(), "client never completed"
        assert errors == [], errors
        (res,) = out
        assert res.rows() == [(5,)]
        # the ORIGINAL c0-minted qid still routes on the final survivor
        q = s2.lookup_query(res.query_id)
        assert q is not None, "boot-1 qid lost after two bounces"
        assert q.state == "FINISHED", q.error
        assert q.rows == [[5]]
    finally:
        _teardown(coords)


# ------------------------------------------------- orphan-task reaper


def test_worker_reaps_orphaned_tasks(tmp_path):
    w = WorkerServer(
        config=NodeConfig({"task.orphan-ttl-s": "0.5"})
    ).start()
    try:
        before = REGISTRY.counter("worker.orphans_reaped").total
        # a coordinator-minted task whose boot nonce never heartbeats
        w.create_task(FragmentSpec(
            task_id="t-orphan", query_id="q_c1_deadbe",
            fragment=None, partition_scan=0, split_start=0,
            split_end=0,
        ))
        # a non-coordinator qid carries no boot nonce: NEVER reaped
        w.create_task(FragmentSpec(
            task_id="t-local", query_id="adhoc",
            fragment=None, partition_scan=0, split_start=0,
            split_end=0,
        ))
        _wait(
            lambda: "t-orphan" not in w.tasks,
            timeout=20,
            msg="orphan reaped",
        )
        assert (
            REGISTRY.counter("worker.orphans_reaped").total
            == before + 1
        )
        assert "t-local" in w.tasks
    finally:
        w.shutdown(graceful=False)


def test_task_creation_refreshes_boot_liveness(tmp_path):
    """An actively scheduling coordinator is not an orphan-maker: each
    created task refreshes its boot's last-seen time, so a busy boot
    with laggy announce acks keeps its earlier tasks alive."""
    w = WorkerServer(
        config=NodeConfig({"task.orphan-ttl-s": "1.0"})
    ).start()
    try:
        w.create_task(FragmentSpec(
            task_id="t-1", query_id="q_c1_aaaaaa", fragment=None,
            partition_scan=0, split_start=0, split_end=0,
        ))
        deadline = time.monotonic() + 2.0
        i = 0
        while time.monotonic() < deadline:
            i += 1
            w.create_task(FragmentSpec(
                task_id=f"t-fresh-{i}", query_id="q_c2_aaaaaa",
                fragment=None, partition_scan=0, split_start=0,
                split_end=0,
            ))
            time.sleep(0.3)
        # the boot kept minting tasks: t-1 outlived its own TTL window
        assert "t-1" in w.tasks
    finally:
        w.shutdown(graceful=False)


# -------------------------------------------- history-epoch durability


def test_history_epochs_persist_across_store_reload(tmp_path):
    """PR 15's documented limit, closed: the per-fingerprint epoch is
    written beside each record and restored at load — a failed-over
    (or restarted) coordinator keeps its learned plans instead of
    serving cold-epoch cache hits."""
    store = QueryHistoryStore(str(tmp_path), divergence_factor=4.0)
    store.record_query("s1", "q", {"n1": {"rows": 100, "label": "x"}})
    store.record_query("s1", "q", {"n1": {"rows": 1000, "label": "x"}})
    assert store.epoch_of("n1") == 2
    reloaded = QueryHistoryStore(str(tmp_path), divergence_factor=4.0)
    assert reloaded.epoch_of("n1") == 2
    assert reloaded.learned_rows("n1") == 1000.0
