"""History-based statistics plane (reference: Presto's history-based
optimization, PAPER.md L2): per-operator OperatorStats populated on
every executor tier, the crash-safe QueryHistoryStore
(plan/history.py), est-vs-actual + provenance in EXPLAIN / EXPLAIN
ANALYZE, the ``estimate_rows`` history read path, runtime view +
metrics, and the slow-query log.
"""

import json
import os
import re
import time

import pytest

from presto_tpu.connectors import create_connector
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.exec.stats import (
    JsonlQueryEventListener,
    OperatorStats,
    SlowQueryLog,
    TaskStats,
)
from presto_tpu.utils.metrics import REGISTRY


def _runner(tmp_path=None, **kw):
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("memory", create_connector("memory"))
    if tmp_path is not None:
        kw.setdefault("history_path", str(tmp_path / "hist"))
    return LocalQueryRunner(catalogs=catalogs, **kw)


SKEW_SQL = (
    "select count(*) c from memory.default.probe "
    "join memory.default.build on probe.k = build.k"
)


def _skew_tables(r):
    """A skewed join the classic estimator badly under-estimates:
    100 probe rows x 50 build rows, ALL on one key -> 5000 join rows
    while est = max(probe, build) = 100 (the memory connector reports
    row counts but no NDVs)."""
    r.execute("create table memory.default.probe (k bigint, v bigint)")
    r.execute(
        "insert into memory.default.probe values "
        + ", ".join(f"(1, {i})" for i in range(100))
    )
    r.execute("create table memory.default.build (k bigint, w bigint)")
    r.execute(
        "insert into memory.default.build values "
        + ", ".join(f"(1, {i})" for i in range(50))
    )


def _join_line(text):
    return next(l for l in text.splitlines() if "InnerJoin" in l)


def _max_error(text):
    """Largest ``error ×N`` factor printed in an EXPLAIN ANALYZE."""
    errs = [float(m) for m in re.findall(r"error ×([0-9.]+)", text)]
    assert errs, text
    return max(errs)


# ------------------------------------------------ operator stats: tiers


def test_operator_stats_local(tmp_path):
    r = _runner(tmp_path)
    r.execute(
        "select l_returnflag, count(*) c from tpch.tiny.lineitem "
        "group by l_returnflag"
    )
    qs = r.history.snapshot()[-1]
    assert qs.plan_fingerprint  # canonical statement identity stamped
    ops = qs.all_operator_stats()
    labels = " ".join(op.label for op in ops)
    assert "TableScan" in labels and "Aggregate" in labels
    scan = next(op for op in ops if "TableScan" in op.label)
    agg = next(op for op in ops if "Aggregate" in op.label)
    assert scan.output_rows == 59997
    assert agg.output_rows == 3
    assert agg.input_rows >= 59997  # child rows fold into input_rows
    assert all(op.fingerprint for op in ops)
    assert all(op.output_capacity > 0 for op in ops)
    assert all(op.peak_page_bytes > 0 for op in ops)
    # whole-program wall/device time is attributed to the program root
    assert any(op.wall_ms > 0 for op in ops)
    assert any(op.device_ms > 0 for op in ops)


def test_operator_stats_disabled_is_empty(tmp_path):
    r = _runner(tmp_path)
    r.session.set("enable_operator_stats", "false")
    res = r.execute("select count(*) c from tpch.tiny.region")
    assert res.rows() == [(5,)]
    qs = r.history.snapshot()[-1]
    assert qs.all_operator_stats() == []


def test_operator_stats_streamed_tier(tmp_path):
    """Split-streamed execution (exec/streaming.py): every batch runs
    the ONE compiled partial program; its operator stats must SUM
    across batches, not report one batch."""
    r = _runner(tmp_path)
    r.session.set("max_device_rows", 4096)
    res = r.execute(
        "select l_returnflag, count(*) c from tpch.tiny.lineitem "
        "group by l_returnflag order by l_returnflag"
    )
    assert sum(row[1] for row in res.rows()) == 59997
    qs = r.history.snapshot()[-1]
    ops = qs.all_operator_stats()
    scan = next(op for op in ops if "TableScan" in op.label)
    assert scan.batches > 1  # streamed split batches folded in
    assert scan.output_rows == 59997  # summed across the stream


# ----------------------------------- est vs actual in EXPLAIN (ANALYZE)


def test_explain_labels_estimate_provenance(tmp_path):
    r = _runner(tmp_path)
    text = "\n".join(
        row[0]
        for row in r.execute(
            "explain select count(*) c from tpch.tiny.region"
        ).rows()
    )
    assert "est rows:" in text
    assert "(stats)" in text or "(heuristic)" in text


def test_explain_analyze_est_actual_error(tmp_path):
    r = _runner(tmp_path)
    _skew_tables(r)
    text = "\n".join(
        row[0] for row in r.execute("explain analyze " + SKEW_SQL).rows()
    )
    line = _join_line(text)
    assert "est:" in line and "error ×" in line
    assert "[rows: 5000" in line  # actual beside the estimate


def test_warm_run_shrinks_estimate_error(tmp_path):
    """THE acceptance loop: the same skewed join twice — the cold run
    records per-operator actuals under canonical fingerprints; the warm
    run's estimates come from history (``history.hit > 0``) and its max
    per-operator error is STRICTLY smaller."""
    r = _runner(tmp_path)
    _skew_tables(r)
    cold = "\n".join(
        row[0] for row in r.execute("explain analyze " + SKEW_SQL).rows()
    )
    h0 = REGISTRY.counter("history.hit").total
    warm = "\n".join(
        row[0] for row in r.execute("explain analyze " + SKEW_SQL).rows()
    )
    assert REGISTRY.counter("history.hit").total > h0
    assert "(history" in warm
    cold_err, warm_err = _max_error(cold), _max_error(warm)
    assert cold_err >= 50.0  # the classic estimator misses the skew
    assert warm_err < cold_err  # strictly smaller on the warm run
    assert warm_err < 1.5  # history is the observed truth


def test_enable_history_stats_false_is_bit_exact(tmp_path):
    """``enable_history_stats=false`` must plan exactly as a runner
    with NO store ever configured — history can steer estimates only
    when asked."""
    r = _runner(tmp_path)
    _skew_tables(r)
    r.execute("explain analyze " + SKEW_SQL)  # populate the store
    r.session.set("enable_history_stats", "false")
    off = "\n".join(
        row[0] for row in r.execute("explain " + SKEW_SQL).rows()
    )
    fresh = _runner(None)  # no store at all
    _skew_tables(fresh)
    base = "\n".join(
        row[0] for row in fresh.execute("explain " + SKEW_SQL).rows()
    )
    assert off == base
    assert "(history" not in off


# ------------------------------------------------------------ the store


def test_history_store_round_trip(tmp_path):
    from presto_tpu.plan.history import QueryHistoryStore

    p = str(tmp_path / "store")
    s1 = QueryHistoryStore(p, max_entries=16)
    s1.record_query(
        "stmt1", "select 1", {"nodeA": {"rows": 42, "label": "Scan"}}
    )
    assert s1.lookup("nodeA") == 42.0
    # crash-safe reload: a fresh instance over the same directory
    s2 = QueryHistoryStore(p, max_entries=16)
    assert s2.lookup("nodeA") == 42.0
    assert s2.lookup("unknown") is None
    assert s1.stats()["writes"] == 1


def test_history_store_eviction_bounded(tmp_path):
    from presto_tpu.plan.history import QueryHistoryStore

    s = QueryHistoryStore(str(tmp_path / "store"), max_entries=4)
    e0 = REGISTRY.counter("history.evict").total
    for i in range(10):
        s.record_query(
            f"stmt{i}", "q", {f"n{i}": {"rows": i, "label": "x"}}
        )
    assert s.stats()["entries"] <= 4
    assert s.evictions > 0
    assert REGISTRY.counter("history.evict").total > e0
    # evicted statements' nodes left the derived index too
    assert s.lookup("n0") is None
    assert s.lookup("n9") == 9.0


def test_history_store_tolerates_corrupt_lines(tmp_path):
    from presto_tpu.plan.history import QueryHistoryStore

    p = str(tmp_path / "store")
    s = QueryHistoryStore(p, max_entries=16)
    s.record_query("stmtA", "q", {"nA": {"rows": 7, "label": "x"}})
    s.record_query("stmtB", "q", {"nB": {"rows": 9, "label": "x"}})
    seg = sorted(
        f for f in os.listdir(p) if f.endswith(".jsonl")
    )[-1]
    with open(os.path.join(p, seg), "a") as f:
        f.write("{torn json line without a clos\n")
        f.write("not json at all\n")
    s2 = QueryHistoryStore(p, max_entries=16)
    assert s2.lookup("nA") == 7.0
    assert s2.lookup("nB") == 9.0


def test_history_store_segment_gc(tmp_path):
    from presto_tpu.plan.history import QueryHistoryStore

    p = str(tmp_path / "store")
    s = QueryHistoryStore(p, max_entries=8)
    for i in range(100):
        s.record_query(
            f"stmt{i}", "q", {f"n{i}": {"rows": i, "label": "x"}}
        )
    segs = [f for f in os.listdir(p) if f.endswith(".jsonl")]
    # bounded on disk: ceil(8 / seg_entries) + 1 segments survive
    assert len(segs) <= 3
    s2 = QueryHistoryStore(p, max_entries=8)
    assert s2.lookup("n99") == 99.0


def test_history_write_metric_and_view(tmp_path):
    r = _runner(tmp_path)
    w0 = REGISTRY.counter("history.write").total
    r.execute("select count(*) c from tpch.tiny.nation")
    assert REGISTRY.counter("history.write").total > w0
    rows = r.execute(
        "select fingerprint, node_count, total_rows "
        "from system.runtime.query_history"
    ).rows()
    assert rows
    fp, node_count, total_rows = rows[-1]
    assert len(fp) == 16
    assert node_count >= 1
    assert total_rows >= 1


# ---------------------------------------------------- satellite: events


def test_event_jsonl_enriched_with_fingerprint_and_operators(tmp_path):
    r = _runner(tmp_path)
    path = str(tmp_path / "events.jsonl")
    r.history.add_listener(JsonlQueryEventListener(path))
    r.execute("select count(*) c from tpch.tiny.region")
    with open(path) as f:
        ev = json.loads(f.readlines()[-1])
    # old consumers keep their fields
    assert ev["event"] == "query_completed"
    assert ev["state"] == "FINISHED"
    assert "stages" in ev and "elapsed_ms" in ev
    # new: the canonical fingerprint + per-operator actuals
    assert len(ev["plan_fingerprint"]) == 16
    assert ev["operators"]
    op = ev["operators"][0]
    assert {"label", "fingerprint", "output_rows"} <= set(op)


def test_task_stats_operators_roundtrip():
    ts = TaskStats(task_id="t1", query_id="q1")
    ts.operators.append(
        OperatorStats(
            node_id=0, label="Scan", fingerprint="abc", output_rows=5
        )
    )
    back = TaskStats.from_dict(ts.to_dict())
    assert back.operators == ts.operators
    assert isinstance(back.operators[0], OperatorStats)


# ----------------------------------------- satellite: planning visibility


def test_planning_and_optimization_ms(tmp_path):
    r = _runner(tmp_path)
    r.execute(
        "select n_name from tpch.tiny.nation where n_regionkey = 1"
    )
    qs = r.history.snapshot()[-1]
    assert qs.planning_ms > 0
    assert qs.optimization_ms >= 0
    d = qs.to_dict()
    assert "optimization_ms" in d and "plan_fingerprint" in d
    vals = REGISTRY.distribution("plan.planning_ms").values()
    assert vals.get("count", 0) >= 1


# ------------------------------------------- satellite: slow-query log


def test_slow_query_log(tmp_path):
    r = _runner(tmp_path)
    path = str(tmp_path / "slow.jsonl")
    r.history.add_listener(SlowQueryLog(path, threshold_ms=0.001))
    s0 = REGISTRY.counter("query.slow").total
    r.execute("select count(*) c from tpch.tiny.region")
    assert REGISTRY.counter("query.slow").total > s0
    with open(path) as f:
        rec = json.loads(f.readlines()[-1])
    assert rec["event"] == "slow_query"
    assert len(rec["plan_fingerprint"]) == 16
    assert rec["elapsed_ms"] >= rec["threshold_ms"]
    # the full EXPLAIN-ANALYZE text, rendered with NO re-run
    assert "Operators (est -> actual" in rec["explain_analyze"]
    assert "actual" in rec["explain_analyze"]


def test_slow_query_log_off_by_default(tmp_path):
    r = _runner(tmp_path)
    path = str(tmp_path / "slow_off.jsonl")
    r.history.add_listener(SlowQueryLog(path, threshold_ms=0.0))
    r.execute("select count(*) c from tpch.tiny.region")
    assert not os.path.exists(path)  # threshold <= 0 = disabled


# -------------------------------------------------- distributed tier


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )
    from presto_tpu.session import NodeConfig

    hist = str(tmp_path_factory.mktemp("hist") / "store")
    coord = CoordinatorServer(
        config=NodeConfig({"history.path": hist})
    ).start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    _wait_workers(coord, 2)
    client = PrestoTpuClient(coord.uri, timeout_s=600)
    yield coord, workers, client
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


def test_distributed_operator_stats_and_rollup(cluster):
    coord, _workers, client = cluster
    res = client.execute(
        "select o_orderpriority, count(*) c from tpch.tiny.orders "
        "group by o_orderpriority"
    )
    assert len(res.rows()) == 5
    q = coord.queries[res.query_id]
    ops = q.stats.all_operator_stats()
    assert ops, "distributed query must carry operator stats"
    scan = next(op for op in ops if "TableScan" in op.label)
    # split tasks of the stage SUM into the full scan count
    assert scan.output_rows == 15000
    assert scan.fingerprint
    # worker TaskStats shipped them over the status wire
    assert any(
        t.operators for s in q.stats.stages for t in s.tasks
    )


def test_distributed_explain_analyze_est_actual(cluster):
    _coord, _workers, client = cluster
    sql = (
        "explain analyze select o_orderpriority, count(*) c "
        "from tpch.tiny.orders group by o_orderpriority"
    )
    text = "\n".join(r[0] for r in client.execute(sql).rows())
    assert "Distributed EXPLAIN ANALYZE" in text
    assert "Operators (est -> actual" in text
    assert "error ×" in text
    assert "wall" in text and "device" in text
    assert "plan fingerprint: " in text


def test_distributed_query_history_view(cluster):
    _coord, _workers, client = cluster
    client.execute("select count(*) c from tpch.tiny.region")
    rows = client.execute(
        "select fingerprint, node_count from system.runtime.query_history"
    ).rows()
    assert rows  # the coordinator-side store received the actuals


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).


# ------------------------------------------- rollup/dedup regressions


def _finished_task(task_id, fp, rows, node_id=0, speculative=False):
    t = TaskStats(task_id=task_id, query_id="q", state="FINISHED")
    t.speculative = speculative
    t.operators = [
        OperatorStats(
            node_id=node_id,
            label="TableScan",
            fingerprint=fp,
            output_rows=rows,
            batches=1,
        )
    ]
    return t


def test_all_operator_stats_counts_one_attempt_per_logical_task():
    """A speculative loser (or a retried-but-completed attempt) also
    reports FINISHED — only one attempt per logical task may count, or
    the history store learns doubled cardinalities."""
    from presto_tpu.exec.stats import QueryStats, StageStats

    qs = QueryStats(query_id="q", sql="s")
    qs.stages = [
        StageStats(
            stage_id=0,
            tasks=[
                _finished_task("q.scan.0.a0", "fpX", 100),
                # backup attempt of the SAME logical task, also done
                _finished_task(
                    "q.scan.0.a1", "fpX", 100, speculative=True
                ),
                # a DIFFERENT logical task of the stage still sums
                _finished_task("q.scan.1.a0", "fpX", 40),
            ],
        )
    ]
    ops = qs.all_operator_stats()
    assert sum(op.output_rows for op in ops) == 140


def test_all_operator_stats_keeps_same_shape_nodes_separate():
    """Two distinct plan nodes sharing a canonical fingerprint (a
    self-join's two scans) must not fold into one summed entry."""
    from presto_tpu.exec.stats import QueryStats, StageStats

    qs = QueryStats(query_id="q", sql="s")
    t = TaskStats(task_id="q.scan.0.a0", query_id="q", state="FINISHED")
    t.operators = [
        OperatorStats(
            node_id=3, label="TableScan", fingerprint="fpT",
            output_rows=25, batches=1,
        ),
        OperatorStats(
            node_id=7, label="TableScan", fingerprint="fpT",
            output_rows=25, batches=1,
        ),
    ]
    qs.stages = [StageStats(stage_id=0, tasks=[t])]
    ops = [o for o in qs.all_operator_stats() if o.fingerprint == "fpT"]
    assert [o.output_rows for o in ops] == [25, 25]


def test_self_join_history_learns_per_node_rows(tmp_path):
    """End-to-end: a self-join's two same-fingerprint scans must teach
    the store |t| rows, not 2|t|."""
    r = _runner(tmp_path)
    r.execute(
        "select count(*) c from tpch.tiny.nation a "
        "join tpch.tiny.nation b on a.n_nationkey = b.n_nationkey"
    )
    qs = r.history.snapshot()[-1]
    scans = [
        op for op in qs.all_operator_stats() if "TableScan" in op.label
    ]
    assert len(scans) == 2  # instance-level entries
    assert all(op.output_rows == 25 for op in scans)
    # and the store learned the per-node cardinality
    assert r.history_store.lookup(scans[0].fingerprint) == 25.0


def test_history_store_gc_keeps_cold_entries_replayable(tmp_path):
    """Segment GC is checkpoint-based: a hot statement re-recording
    hundreds of times must not push the only on-disk copy of colder
    live entries out of the replayable window."""
    from presto_tpu.plan.history import QueryHistoryStore

    p = str(tmp_path / "store")
    s = QueryHistoryStore(p, max_entries=8)
    for i in range(8):
        s.record_query(
            f"stmt{i}", "q", {f"n{i}": {"rows": i + 1, "label": "x"}}
        )
    for _ in range(60):  # duplicate-heavy: one hot statement
        s.record_query("stmt7", "q", {"n7": {"rows": 8, "label": "x"}})
    assert len(
        [f for f in os.listdir(p) if f.endswith(".jsonl")]
    ) <= 3
    s2 = QueryHistoryStore(p, max_entries=8)
    for i in range(8):  # every live entry survived the restart
        assert s2.lookup(f"n{i}") == float(i + 1), i


def test_analyzed_run_updates_same_statement_entry(tmp_path):
    """EXPLAIN ANALYZE records under the SAME statement fingerprint as
    the normal run (pre-peel root) with a real query text — no forked
    blank-query twin entry."""
    r = _runner(tmp_path)
    sql = "select n_name from tpch.tiny.nation order by n_name"
    r.execute(sql)  # host root stage peels the Sort/Output chain
    store = r.history_store
    before = {rec["fingerprint"] for rec in store.snapshot()}
    r.execute("explain analyze " + sql)
    snap = store.snapshot()
    assert {rec["fingerprint"] for rec in snap} == before
    assert all(rec["query"] for rec in snap)


def test_subquery_programs_do_not_inflate_history(tmp_path):
    """Scalar-subquery pre-passes run as separate programs that reuse
    walk positions — their same-shape scans must not sum with the main
    program's (the store would learn a multiple of the true rows)."""
    r = _runner(tmp_path)
    r.execute(
        "select count(*) c from tpch.tiny.nation where "
        "n_nationkey < (select count(*) from tpch.tiny.nation) and "
        "n_regionkey < (select count(*) from tpch.tiny.nation)"
    )
    qs = r.history.snapshot()[-1]
    scans = [
        op
        for op in qs.all_operator_stats()
        if "TableScan" in op.label and "nation" in op.label
    ]
    assert scans and all(op.output_rows == 25 for op in scans), [
        (op.label, op.output_rows, op.batches) for op in scans
    ]
    assert r.history_store.lookup(scans[0].fingerprint) == 25.0
