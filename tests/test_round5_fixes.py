"""Round-5 planner/engine regression tests: mark joins, deferred LEFT
joins with WHERE equi-edges, the bushy join rescue, build-uniqueness
inference, two-column concat, string coalesce, IN-list expressions.

Each case is the minimal shape of a TPC-DS query that exposed the
defect (cited in the test docstrings); all oracle-diffed or pinned.
"""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


@pytest.fixture(scope="module")
def ds_oracle():
    return SqliteOracle("tiny", catalog="tpcds")


def test_fk_stats_do_not_prove_uniqueness(runner, ds_oracle):
    """customer x customer_demographics on c_current_cdemo_sk: the FK
    column's ESTIMATED distinct count equals the row count, but values
    collide — treating the build as unique kept one match per probe
    row and silently dropped the rest (Q10/Q35/Q69 regression)."""
    q = (
        "select count(*) as c from tpcds.tiny.customer c, "
        "tpcds.tiny.customer_demographics "
        "where cd_demo_sk = c.c_current_cdemo_sk"
    )
    assert runner.execute(q).rows() == [(1000,)]
    assert verify_query(runner, ds_oracle, q) is None


def test_mark_join_in_under_or(runner, oracle):
    """Q45 shape: IN-subquery OR'd with a plain predicate."""
    q = (
        "select count(*) as c from tpch.tiny.customer "
        "where c_nationkey = 3 or c_custkey in "
        "(select o_custkey from tpch.tiny.orders "
        " where o_totalprice > 200000)"
    )
    assert verify_query(runner, oracle, q) is None


def test_mark_join_exists_or_exists(runner, oracle):
    """Q10/Q35 shape: two correlated EXISTS OR'd together."""
    q = (
        "select count(*) as c from tpch.tiny.customer where "
        "exists (select 1 from tpch.tiny.orders "
        "        where o_custkey = c_custkey "
        "          and o_orderpriority = '1-URGENT') "
        "or exists (select 1 from tpch.tiny.orders "
        "           where o_custkey = c_custkey "
        "             and o_orderpriority = '2-HIGH')"
    )
    assert verify_query(runner, oracle, q) is None


def test_mark_join_not_exists_under_or(runner, oracle):
    q = (
        "select count(*) as c from tpch.tiny.customer "
        "where c_nationkey = 3 or not exists "
        "(select 1 from tpch.tiny.orders where o_custkey = c_custkey)"
    )
    assert verify_query(runner, oracle, q) is None


def test_mark_join_under_not(runner, oracle):
    """Outer NOT inverts the marker test naturally (EXISTS is
    2-valued)."""
    q = (
        "select count(*) as c from tpch.tiny.customer "
        "where not (c_nationkey = 3 or exists "
        "(select 1 from tpch.tiny.orders where o_custkey = c_custkey))"
    )
    assert verify_query(runner, oracle, q) is None


def test_deferred_left_join_where_edge_composites(runner, ds_oracle):
    """Q72's core: the WHERE's d1.d_week_seq = d2.d_week_seq edge must
    reach the join pool even when the FROM is an explicit JOIN chain
    wrapped in LEFT joins — pre-fix it degraded to a fan-out item-only
    join plus a post-filter."""
    q = (
        "select count(*) as c "
        "from tpcds.tiny.catalog_sales "
        "  join tpcds.tiny.inventory on cs_item_sk = inv_item_sk "
        "  join tpcds.tiny.date_dim d1 on cs_sold_date_sk = d1.d_date_sk "
        "  join tpcds.tiny.date_dim d2 on inv_date_sk = d2.d_date_sk "
        "  left join tpcds.tiny.promotion on cs_promo_sk = p_promo_sk "
        "where d1.d_week_seq = d2.d_week_seq "
        "  and inv_quantity_on_hand < cs_quantity "
        "  and d1.d_year = 1999"
    )
    res = runner.execute(q)
    assert verify_query(runner, ds_oracle, q) is None
    # the composite must actually be in the plan: both edges as keys
    plan = "\n".join(
        r[0] for r in runner.execute("explain " + q).rows()
    )
    assert "d_week_seq" in plan.split("Filter")[0] or (
        "'inv_item_sk', " in plan and "week" in plan
    ), plan


def test_where_filter_on_left_join_build_applies_post(runner, ds_oracle):
    """Q93 shape: WHERE touching the LEFT join's build side must apply
    AFTER the join (effectively inner), not push into the probe."""
    q = (
        "select count(*) as c "
        "from tpcds.tiny.store_sales "
        "  left join tpcds.tiny.store_returns "
        "    on sr_item_sk = ss_item_sk "
        "   and sr_ticket_number = ss_ticket_number, "
        "  tpcds.tiny.reason "
        "where sr_reason_sk = r_reason_sk"
    )
    assert verify_query(runner, ds_oracle, q) is None


def test_two_column_concat(runner, oracle):
    q = (
        "select c_name || '_' || c_mktsegment as x "
        "from tpch.tiny.customer order by c_custkey limit 5"
    )
    assert verify_query(runner, oracle, q) is None


def test_concat_as_join_key(runner, oracle):
    q = (
        "select count(*) as c from tpch.tiny.nation n1, "
        "tpch.tiny.nation n2 "
        "where n1.n_name || 'x' = n2.n_name || 'x'"
    )
    assert verify_query(runner, oracle, q) is None


def test_string_coalesce(runner, oracle):
    q = (
        "select coalesce(c_name, '') || '!' as x "
        "from tpch.tiny.customer order by c_custkey limit 3"
    )
    assert verify_query(runner, oracle, q) is None


def test_in_list_arithmetic(runner, oracle):
    """Q29 shape: d_year in (1999, 1999 + 1, 1999 + 2)."""
    q = (
        "select count(*) as c from tpch.tiny.orders "
        "where extract(year from o_orderdate) in "
        "(1995, 1994 + 1, 1997 - 1)"
    )
    assert verify_query(runner, oracle, q) is None


def test_in_list_column_expr(runner, oracle):
    """Non-constant IN member becomes an OR'd equality."""
    q = (
        "select count(*) as c from tpch.tiny.lineitem "
        "where l_quantity in (1, l_linenumber + 10)"
    )
    assert verify_query(runner, oracle, q) is None


def test_multiple_count_distinct(runner, oracle):
    """N DISTINCT aggregates per group (reference: MarkDistinct) —
    each gets its own two-level tree, stitched per group."""
    q = (
        "select l_returnflag, count(distinct l_suppkey) as a, "
        "count(distinct l_partkey) as b, sum(l_quantity) as s "
        "from tpch.tiny.lineitem group by l_returnflag order by 1"
    )
    assert verify_query(runner, oracle, q, rel_tol=1e-6) is None


def test_multiple_count_distinct_global(runner, oracle):
    q = (
        "select count(distinct l_suppkey) as a, "
        "count(distinct l_partkey) as b, avg(l_quantity) as c "
        "from tpch.tiny.lineitem"
    )
    assert verify_query(runner, oracle, q, rel_tol=1e-6) is None


def test_correlated_in_subquery(runner, oracle):
    """Correlated IN rewrites to correlated EXISTS with the membership
    as one more equality."""
    q = (
        "select count(*) as c from tpch.tiny.orders o "
        "where o_orderkey in (select l_orderkey "
        "from tpch.tiny.lineitem l where l.l_suppkey = o.o_custkey)"
    )
    assert verify_query(runner, oracle, q) is None
    q2 = (
        "select count(*) as c from tpch.tiny.customer c "
        "where c.c_mktsegment in (select c2.c_mktsegment "
        "from tpch.tiny.customer c2 "
        "where c2.c_nationkey = c.c_nationkey "
        "and c2.c_acctbal > 9000)"
    )
    assert verify_query(runner, oracle, q2) is None


def test_correlated_in_shadowed_arg_rejected(runner):
    """An UNQUALIFIED left side whose name also exists in the subquery
    relations must be rejected, not silently rewritten into an inner
    self-equality (oracle-caught during development)."""
    from presto_tpu.plan.planner import PlanningError

    with pytest.raises(PlanningError, match="shadowed"):
        runner.execute(
            "select count(*) as c from tpch.tiny.customer c "
            "where c_mktsegment in (select c2.c_mktsegment "
            "from tpch.tiny.customer c2 "
            "where c2.c_nationkey = c.c_nationkey)"
        )
