"""Micro-batched point-lookup serving (coordinator batch queue +
vmapped compile entries in plan/canonical.py).

Contracts under test:

- ``serving.microbatch-wait-ms=0`` (the default) is bit-exact pre-PR:
  zero batches, identical results, identical (scalar-shaped)
  compile-cache keys, no ``batched`` flags.
- An N-way batch answers every member exactly like N scalar runs —
  point lookups AND small aggregates, mixed/duplicate literals
  included — while dispatching strictly fewer device programs than
  statements served.
- Ineligible members (non-hoistable shapes, over-window outputs) fall
  out of the batch and ride the existing scalar path: correct answers,
  never a failed query.
- A statement parked by the admission high-water hold does not also
  accrue the batch window after release (the window starts at
  dispatch-eligibility, not submit).
- Observability: serving.* metrics, QueryStats.batched/batch_size,
  the system.runtime.queries column, the EXPLAIN ANALYZE line.
"""

import threading
import time

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.utils.metrics import REGISTRY

POINT = (
    "select c_custkey, c_name, c_acctbal "
    "from tpch.tiny.customer where c_custkey = ?"
)
AGG = (
    "select count(*) as n, sum(c_acctbal) as s "
    "from tpch.tiny.customer where c_custkey < ?"
)
PREPARED = {"point": POINT, "agg": AGG}

#: tiny customer row count (literal values must stay in key range)
N_KEYS = 1500


def _coord(wait_ms=0.0, max_size=16, concurrency=64, **kw):
    c = CoordinatorServer(max_concurrent_queries=concurrency, **kw)
    if wait_ms:
        c.local.session.set("microbatch_wait_ms", wait_ms)
        c.local.session.set("microbatch_max", max_size)
    return c


def _submit_concurrent(coord, sqls, prepared=None):
    """Submit all statements at once (barrier start) and wait for
    completion; returns the _Query objects in submission order."""
    out = [None] * len(sqls)
    barrier = threading.Barrier(len(sqls))

    def run(i):
        barrier.wait(30)
        q = coord.submit(sqls[i], prepared=dict(prepared or {}))
        q.done.wait(180)
        out[i] = q

    threads = [
        threading.Thread(target=run, args=(i,))
        for i in range(len(sqls))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
    return out


def _scalar_expected(sqls, prepared):
    """Reference answers from a plain (batch-less) runner."""
    r = LocalQueryRunner()
    for name, text in prepared.items():
        r.execute(f"prepare {name} from {text}")
    return [[list(row) for row in r.execute(s).rows()] for s in sqls]


def _batch_counters():
    return (
        int(REGISTRY.counter("serving.batches").total),
        int(REGISTRY.counter("serving.batched_statements").total),
    )


# ------------------------------------------------------------ off = legacy


def test_off_by_default_bit_exact():
    """wait-ms=0 (default): zero batches, scalar-shaped compile keys
    only, no batched flags, correct concurrent results."""
    coord = _coord()
    try:
        sqls = [
            f"execute point using {7 + 11 * i}" for i in range(6)
        ]
        expected = _scalar_expected(sqls, PREPARED)
        b0, s0 = _batch_counters()
        qs = _submit_concurrent(coord, sqls, PREPARED)
        b1, s1 = _batch_counters()
        assert (b1 - b0, s1 - s0) == (0, 0)
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
            assert q.stats.batched is False
            assert q.stats.batch_size == 0
        # the compile cache holds only pre-PR-shaped scalar keys:
        # (fingerprint, analyzed, counted, offload) 4-tuples, never a
        # "batch"-tagged entry
        for key in coord.local._compiled:
            assert len(key) == 4
            assert "batch" not in key
    finally:
        coord.shutdown()


# ------------------------------------------------- batched == scalar


def test_nway_batch_equals_scalar_point_lookups():
    coord = _coord(wait_ms=400.0)
    try:
        # warm the plan/compile path so the batch window isn't racing
        # a cold XLA compile
        q = coord.submit("execute point using 3", prepared=PREPARED)
        q.done.wait(120)
        vals = [5, 118, 119, 700, 701, 42, 1499, 12]
        sqls = [f"execute point using {v}" for v in vals]
        expected = _scalar_expected(sqls, PREPARED)
        b0, s0 = _batch_counters()
        qs = _submit_concurrent(coord, sqls, PREPARED)
        b1, s1 = _batch_counters()
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
        # strictly fewer dispatches than statements: at least one
        # multi-member batch formed
        assert b1 - b0 >= 1
        assert s1 - s0 > b1 - b0
        batched = [q for q in qs if q.stats.batched]
        assert batched, "no member rode the batch"
        assert all(q.stats.batch_size >= 2 for q in batched)
        # a batch-tagged compile entry exists beside the scalar one
        assert any(
            "batch" in key for key in coord.local._compiled
        )
    finally:
        coord.shutdown()


def test_nway_batch_equals_scalar_aggregates():
    """Small-aggregate shapes batch too (flags lanes stay clean when
    no lane overflows) and answer exactly like scalar runs."""
    coord = _coord(wait_ms=400.0)
    try:
        q = coord.submit("execute agg using 10", prepared=PREPARED)
        q.done.wait(120)
        vals = [2, 55, 340, 1100, 1500, 9]
        sqls = [f"execute agg using {v}" for v in vals]
        expected = _scalar_expected(sqls, PREPARED)
        qs = _submit_concurrent(coord, sqls, PREPARED)
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
        assert any(q.stats.batched for q in qs)
    finally:
        coord.shutdown()


def test_mixed_and_duplicate_literals_demux_correctly():
    """Duplicate values in one batch each get their own (identical)
    answer; distinct values each get their own row."""
    coord = _coord(wait_ms=400.0)
    try:
        q = coord.submit("execute point using 3", prepared=PREPARED)
        q.done.wait(120)
        vals = [77, 77, 901, 14, 901, 77]
        sqls = [f"execute point using {v}" for v in vals]
        expected = _scalar_expected(sqls, PREPARED)
        qs = _submit_concurrent(coord, sqls, PREPARED)
        for q, exp, v in zip(qs, expected, vals):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
            assert q.rows[0][0] == v  # the row really is THIS member's
    finally:
        coord.shutdown()


# ------------------------------------------------------- fallout lanes


def test_non_hoistable_shape_falls_back_scalar():
    """A shape with no hoistable literal (string predicate) has no
    parameter vector to stack: the whole group rides the scalar path,
    correctly, with zero batches."""
    coord = _coord(wait_ms=300.0)
    try:
        sql = (
            "select count(*) as n from tpch.tiny.customer "
            "where c_mktsegment = 'BUILDING'"
        )
        r = LocalQueryRunner()
        expected = [list(row) for row in r.execute(sql).rows()]
        b0, _ = _batch_counters()
        qs = _submit_concurrent(coord, [sql] * 4)
        b1, _ = _batch_counters()
        assert b1 - b0 == 0
        for q in qs:
            assert q.state == "FINISHED", q.error
            assert q.rows == expected
            assert q.stats.batched is False
    finally:
        coord.shutdown()


def test_over_window_output_falls_back_scalar():
    """Lanes whose true row count exceeds the speculative window fall
    out of the batch and materialize scalar — full correct results,
    never a truncated answer."""
    coord = _coord(wait_ms=300.0)
    try:
        coord.local.session.set("speculative_result_rows", 4)
        sqls = [
            f"execute agg2_{i} using {100 + i}" for i in range(3)
        ]
        prepared = {
            f"agg2_{i}": (
                "select c_custkey from tpch.tiny.customer "
                "where c_custkey <= ?"
            )
            for i in range(3)
        }
        # one prepared NAME per client is unrealistic; same text =
        # same canonical fingerprint, so they still group
        expected = _scalar_expected(sqls, prepared)
        qs = _submit_concurrent(coord, sqls, prepared)
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
            # >4 rows: the lane fell out, answered scalar
            assert q.stats.batched is False
    finally:
        coord.shutdown()


def test_plan_cache_off_keeps_scalar_path():
    coord = _coord(wait_ms=300.0)
    try:
        coord.local.session.set("enable_plan_cache", False)
        sqls = [f"execute point using {v}" for v in (4, 9, 44)]
        expected = _scalar_expected(sqls, PREPARED)
        b0, _ = _batch_counters()
        qs = _submit_concurrent(coord, sqls, PREPARED)
        assert _batch_counters()[0] == b0
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
    finally:
        coord.shutdown()


# --------------------------------------------- concurrency at fleet scale


def test_hundred_client_demux_correctness():
    """100 concurrent clients, distinct literals, threads racing into
    one queue: every client gets ITS OWN row back (no crossed lanes),
    and dispatches are strictly fewer than statements."""
    coord = _coord(wait_ms=400.0, max_size=32, concurrency=128)
    try:
        q = coord.submit("execute point using 2", prepared=PREPARED)
        q.done.wait(180)
        vals = [1 + ((i * 37) % (N_KEYS - 1)) for i in range(100)]
        sqls = [f"execute point using {v}" for v in vals]
        b0, s0 = _batch_counters()
        qs = _submit_concurrent(coord, sqls, PREPARED)
        b1, s1 = _batch_counters()
        for q, v in zip(qs, vals):
            assert q.state == "FINISHED", q.error
            assert len(q.rows) == 1
            assert q.rows[0][0] == v  # demux: my literal, my row
        batches, stmts = b1 - b0, s1 - s0
        assert batches >= 1
        assert stmts > batches  # mean occupancy > 1
        # total device dispatches = batches + scalar fallthroughs
        scalar_runs = len(sqls) - stmts
        assert batches + scalar_runs < len(sqls)
        occ = REGISTRY.distribution("serving.batch_occupancy").values()
        assert occ["count"] > 0
        wait = REGISTRY.distribution("serving.batch_wait_ms").values()
        assert wait["count"] > 0
    finally:
        coord.shutdown()


# ------------------------------------------- admission-hold interplay


def test_admission_parked_statement_skips_batch_window():
    """PR 9 interplay: a statement parked by the admission high-water
    hold must not ALSO accrue microbatch_wait_ms after release — with
    a 3-second window configured, the released query completes far
    inside the window instead of holding it open as a leader."""
    coord = _coord(wait_ms=3000.0)
    try:
        # warm (also proves the lane works before we start parking)
        q = coord.submit("execute point using 3", prepared=PREPARED)
        q.done.wait(120)
        held = {"v": True}
        coord.arbiter.admission_held = lambda: held["v"]
        q = coord.submit("execute point using 888", prepared=PREPARED)
        time.sleep(0.5)
        assert not q.done.is_set()  # parked at admission
        held["v"] = False
        t0 = time.monotonic()
        assert q.done.wait(30)
        after_release = time.monotonic() - t0
        assert q.state == "FINISHED", q.error
        assert q._admission_parked is True
        assert q.stats.batched is False
        # far under the 3s window: the parked statement dispatched
        # immediately at release instead of opening a batch window
        assert after_release < 2.0, after_release
    finally:
        coord.shutdown()


def test_unparked_solo_leader_pays_at_most_the_window():
    """Control for the parked case: a solo statement with the lane on
    holds its window open (that is the price of leadership) but never
    more than wait + scalar time."""
    coord = _coord(wait_ms=700.0)
    try:
        q = coord.submit("execute point using 5", prepared=PREPARED)
        q.done.wait(120)  # warm: plan + compile
        t0 = time.monotonic()
        q = coord.submit("execute point using 6", prepared=PREPARED)
        assert q.done.wait(60)
        dt = time.monotonic() - t0
        assert q.state == "FINISHED", q.error
        assert dt >= 0.6  # it really held the window...
        assert q.stats.batched is False  # ...and answered scalar
    finally:
        coord.shutdown()


# ------------------------------------------------------- observability


def test_runtime_queries_column_and_explain_line():
    """Plain (non-prepared) SELECT literal variants batch on the local
    lane too, surface batched=true in system.runtime.queries, and the
    analyze render prints the micro-batch line."""
    from presto_tpu.exec.explain import render_query_analyze

    coord = _coord(wait_ms=400.0)
    try:
        sql = "select c_acctbal from tpch.tiny.customer where c_custkey = {}"
        q = coord.submit(sql.format(2))
        q.done.wait(120)
        qs = _submit_concurrent(
            coord, [sql.format(v) for v in (31, 44, 57, 68)]
        )
        expected = _scalar_expected(
            [sql.format(v) for v in (31, 44, 57, 68)], {}
        )
        for q, exp in zip(qs, expected):
            assert q.state == "FINISHED", q.error
            assert q.rows == exp
        batched = [q for q in qs if q.stats.batched]
        assert batched
        rows = coord.local.execute(
            "select query_id, batched from system.runtime.queries "
            "where batched"
        ).rows()
        assert {q.qid for q in batched} <= {r[0] for r in rows}
        text = render_query_analyze(batched[0].stats)
        assert "micro-batch:" in text
        assert f"{batched[0].stats.batch_size}-way" in text
    finally:
        coord.shutdown()
