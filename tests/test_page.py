"""Unit tests for the columnar Block/Page core (SURVEY.md §7 step 1).

Modeled on the reference's per-class operator tests with hand-built Pages
(SURVEY.md §4.1).
"""

import datetime

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.page import Block, Dictionary, Page, encode_strings, pad_capacity


def test_type_parse_roundtrip():
    assert T.parse_type("bigint") is T.BIGINT
    d = T.parse_type("decimal(12,2)")
    assert d.precision == 12 and d.scale == 2
    assert T.parse_type("varchar(25)").length == 25
    with pytest.raises(ValueError):
        T.parse_type("blob")


def test_common_super_type():
    assert T.common_super_type(T.INTEGER, T.BIGINT) is T.BIGINT
    assert T.common_super_type(T.BIGINT, T.DOUBLE) is T.DOUBLE
    d = T.common_super_type(T.decimal(12, 2), T.decimal(10, 4))
    assert d.scale == 4
    assert T.common_super_type(T.decimal(12, 2), T.INTEGER).is_decimal


def test_dictionary_order_preserving():
    ids, valid, d = encode_strings(["pear", "apple", None, "mango", "apple"])
    assert list(d.values) == ["apple", "mango", "pear"]
    assert list(ids) == [2, 0, -1, 1, 0]
    assert list(valid) == [True, True, False, True, True]
    # order preservation: id comparison == string comparison
    assert d.id_of("apple") < d.id_of("mango") < d.id_of("pear")
    assert d.id_of("absent") == -1
    assert d.searchsorted("b") == 1  # between apple and mango


def test_dictionary_hashable_and_lut():
    d1 = Dictionary.build(["a", "b", "c"])
    d2 = Dictionary.build(["c", "b", "a", "a"])
    assert d1 == d2 and hash(d1) == hash(d2)
    lut = d1.predicate_lut(lambda s: s >= "b")
    assert list(lut) == [False, True, True]


def test_page_from_pydict_roundtrip():
    schema = {
        "k": T.BIGINT,
        "price": T.decimal(12, 2),
        "name": T.VARCHAR,
        "d": T.DATE,
        "x": T.DOUBLE,
    }
    day = (datetime.date(1995, 3, 15) - datetime.date(1970, 1, 1)).days
    page = Page.from_pydict(
        {
            "k": [1, 2, None],
            "price": [10.25, 99.99, 0.01],
            "name": ["alice", None, "bob"],
            "d": [day, day + 1, day + 2],
            "x": [1.5, 2.5, 3.5],
        },
        schema,
        capacity=8,
    )
    assert page.capacity == 8
    assert int(page.num_valid) == 3
    rows = page.to_pylist()
    assert rows[0]["k"] == 1 and rows[2]["k"] is None
    assert rows[0]["price"] == 10.25 and rows[1]["price"] == 99.99
    assert rows[0]["name"] == "alice" and rows[1]["name"] is None
    assert rows[0]["d"] == datetime.date(1995, 3, 15)
    # decimal exactness: stored as scaled int64
    assert np.asarray(page.block("price").data)[:3].tolist() == [1025, 9999, 1]


def test_page_is_pytree():
    page = Page.from_pydict({"a": [1, 2, 3]}, {"a": T.BIGINT}, capacity=4)
    leaves = jax.tree_util.tree_leaves(page)
    # data + num_valid (no null masks here)
    assert len(leaves) == 2

    @jax.jit
    def double(p: Page) -> Page:
        blk = p.blocks[0]
        import dataclasses

        return dataclasses.replace(
            p, blocks=(dataclasses.replace(blk, data=blk.data * 2),)
        )

    out = double(page)
    assert [r["a"] for r in out.to_pylist()] == [2, 4, 6]


def test_row_mask_and_pad_capacity():
    page = Page.from_pydict({"a": [1, 2, 3]}, {"a": T.BIGINT}, capacity=4)
    assert list(np.asarray(page.row_mask())) == [True, True, True, False]
    bigger = pad_capacity(page, 16)
    assert bigger.capacity == 16 and int(bigger.num_valid) == 3
    smaller = pad_capacity(bigger, 4)
    assert smaller.capacity == 4
    assert [r["a"] for r in smaller.to_pylist()] == [1, 2, 3]


def test_block_null_mask_static_none():
    b = Block.from_pylist([1, 2, 3], T.BIGINT)
    assert b.valid is None  # null-free => no mask materialised
    b2 = Block.from_pylist([1, None, 3], T.BIGINT)
    assert b2.valid is not None
    assert list(np.asarray(b2.valid)) == [True, False, True]
