"""MAP/ROW physical types (reference: presto-common MapType/RowType,
MapBlock/RowBlock — SURVEY.md §2.1 "Type system" / "Block/Page data
model"). Device layout: maps = shared offsets over flat key/value child
blocks; rows = shredded per-field child blocks (Block.children).
Oracle: the host language (sqlite has no nested types)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager, obj_array
from presto_tpu.page import Page
from presto_tpu.plan.planner import PlanningError


def test_parse_nested_types():
    m = T.parse_type("map(varchar, bigint)")
    assert m.is_map and m.key.is_string and m.value.name == "bigint"
    r = T.parse_type("row(a bigint, b varchar)")
    assert r.is_row and r.fields[0] == ("a", T.BIGINT)
    nested = T.parse_type("map(integer, row(x double, y double))")
    assert nested.value.is_row
    assert not T.BIGINT.is_nested and m.is_nested and r.is_nested


MAPS = [
    {"a": 1, "b": 2},
    {},
    None,
    {"c": 30, "a": 10},
    {"z": None, "q": 7},
]
ROWS = [
    {"x": 1.5, "y": "one"},
    {"x": -2.0, "y": "two"},
    None,
    {"x": 0.25, "y": None},
    {"x": 9.0, "y": "nine"},
]


def test_page_roundtrip_map_row():
    mt = T.map_(T.VARCHAR, T.BIGINT)
    rt = T.row(("x", T.DOUBLE), ("y", T.VARCHAR))
    p = Page.from_pydict(
        {"m": MAPS, "r": ROWS}, {"m": mt, "r": rt}, capacity=8
    )
    out = p.to_pylist()
    assert [row["m"] for row in out] == MAPS
    assert [row["r"] for row in out] == ROWS


@pytest.fixture(scope="module")
def runner():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    catalogs.register("mem", mem)
    h = TableHandle("mem", "s", "t")
    mem.create_table(
        h,
        {
            "id": T.BIGINT,
            "m": T.map_(T.VARCHAR, T.BIGINT),
            "im": T.map_(T.INTEGER, T.DOUBLE),
            "r": T.row(("x", T.DOUBLE), ("y", T.VARCHAR)),
        },
    )
    mem.append_rows(
        h,
        {
            "id": np.arange(5, dtype=np.int64),
            "m": obj_array(MAPS),
            "im": obj_array(
                [{1: 0.5}, {2: 1.5, 3: -2.5}, {}, None, {1: 9.0}]
            ),
            "r": obj_array(ROWS),
        },
    )
    return LocalQueryRunner(catalogs=catalogs)


def test_select_whole_map_and_row(runner):
    rows = runner.execute("select id, m, r from mem.s.t").rows()
    assert [r[1] for r in rows] == MAPS
    assert [r[2] for r in rows] == ROWS


def test_map_subscript_string_key(runner):
    rows = runner.execute("select id, m['a'] as v from mem.s.t").rows()
    assert rows == [(0, 1), (1, None), (2, None), (3, 10), (4, None)]


def test_map_element_at_int_key(runner):
    rows = runner.execute(
        "select id, element_at(im, 1) as v from mem.s.t"
    ).rows()
    assert rows == [
        (0, 0.5), (1, None), (2, None), (3, None), (4, 9.0),
    ]


def test_map_subscript_null_value(runner):
    rows = runner.execute("select m['z'] as v from mem.s.t").rows()
    assert [r[0] for r in rows] == [None, None, None, None, None]


def test_map_cardinality(runner):
    rows = runner.execute(
        "select id, cardinality(m) as n from mem.s.t"
    ).rows()
    assert rows == [(0, 2), (1, 0), (2, None), (3, 2), (4, 2)]


def test_row_field_access(runner):
    rows = runner.execute("select id, r.x, r.y from mem.s.t").rows()
    assert rows == [
        (0, 1.5, "one"),
        (1, -2.0, "two"),
        (2, None, None),
        (3, 0.25, None),
        (4, 9.0, "nine"),
    ]


def test_filter_on_row_field(runner):
    rows = runner.execute(
        "select id from mem.s.t where r.x > 0 order by id"
    ).rows()
    assert rows == [(0,), (3,), (4,)]


def test_filter_on_map_subscript(runner):
    rows = runner.execute(
        "select id from mem.s.t where m['a'] >= 10"
    ).rows()
    assert rows == [(3,)]


def test_group_by_row_field(runner):
    rows = runner.execute(
        "select r.y is null as has_null, count(*) as n from mem.s.t "
        "where id <> 2 group by r.y is null order by has_null"
    ).rows()
    assert rows == [(False, 3), (True, 1)]


def test_nested_key_bans(runner):
    for sql in [
        "select m from mem.s.t group by m",
        "select m from mem.s.t order by m",
        "select count(*) from mem.s.t a, mem.s.t b where a.m = b.m",
    ]:
        with pytest.raises(PlanningError):
            runner.execute(sql).rows()


def test_row_field_missing(runner):
    with pytest.raises(PlanningError) as ei:
        runner.execute("select r.zz from mem.s.t").rows()
    assert "no field" in str(ei.value)


def test_nested_in_nested_gated():
    """One nesting level (documented deviation): constructing a block
    whose map/row CHILD is itself nested raises loud instead of
    silently mis-decoding (review finding r5)."""
    rt = T.row(("a", T.array(T.BIGINT)), ("b", T.BIGINT))
    with pytest.raises(NotImplementedError):
        Page.from_pydict(
            {"r": [{"a": [1, 2], "b": 3}]}, {"r": rt}
        )
    mt = T.map_(T.VARCHAR, T.row(("x", T.BIGINT)))
    with pytest.raises(NotImplementedError):
        Page.from_pydict({"m": [{"k": {"x": 1}}]}, {"m": mt})


def test_map_subscript_key_domain(runner):
    """Numeric subscripts normalize into the key child's exact value
    domain: 1.0 (decimal) finds integer key 1; fractional keys are
    rejected at plan time, never truncated (review finding r5)."""
    rows = runner.execute(
        "select id, element_at(im, 1.0) as v from mem.s.t order by id"
    ).rows()
    assert [r[1] for r in rows] == [0.5, None, None, None, 9.0]
    with pytest.raises(PlanningError):
        runner.execute("select m[1] from mem.s.t").rows()


def test_nested_through_join_window_raise_loud(runner):
    """Row/map columns riding a join output or a window operator would
    be silently mis-gathered (children unpermuted) — they must raise at
    the kernel guard instead (review finding r5 #2)."""
    with pytest.raises(Exception) as ei:
        runner.execute(
            "select a.id, a.r from mem.s.t a, mem.s.t b "
            "where a.id = b.id"
        ).rows()
    assert "nested column" in str(ei.value)
    with pytest.raises(Exception) as ei:
        runner.execute(
            "select id, r, row_number() over (order by id) as rn "
            "from mem.s.t"
        ).rows()
    assert "nested column" in str(ei.value)


def test_map_subscript_wide_key_no_wrap(runner):
    """A bigint subscript of 2^32+1 must MISS integer key 1, not wrap
    onto it (review finding r5 #3)."""
    rows = runner.execute(
        "select id, element_at(im, 4294967297) as v from mem.s.t "
        "order by id"
    ).rows()
    assert [r[1] for r in rows] == [None] * 5


def test_whole_map_through_order_by_id(runner):
    """Host root-stage sort permutes object-form map/row columns."""
    rows = runner.execute(
        "select id, m, r from mem.s.t order by id desc"
    ).rows()
    assert [r[0] for r in rows] == [4, 3, 2, 1, 0]
    assert [r[1] for r in rows] == MAPS[::-1]
    assert [r[2] for r in rows] == ROWS[::-1]
