"""Distributed execution suite: the full TPC-H corpus on an 8-virtual-
device CPU mesh, verified against the sqlite oracle — the reference's
DistributedQueryRunner pattern (SURVEY.md §4.3): multi-node correctness
without a cluster, exercising real shard_map fragments and real
all_to_all / all_gather exchanges.

Also unit-covers the exchange collectives directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.page import Page
from presto_tpu.parallel import DistributedQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES

@pytest.fixture(scope="module")
def runner():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    # low thresholds so tiny-SF queries actually take the partitioned
    # exchange paths instead of degenerating to broadcast everywhere
    return DistributedQueryRunner(
        broadcast_threshold=1 << 11, repl_threshold=1 << 10
    )


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_distributed(qnum, runner, oracle):
    diff = verify_query(runner, oracle, QUERIES[qnum], rel_tol=1e-6)
    assert diff is None, f"Q{qnum} distributed mismatch: {diff}"


def test_partitioned_agg_path(runner, oracle):
    """High max_groups forces the all_to_all partial/final agg path."""
    sql = (
        "select l_orderkey, count(*) as c, sum(l_quantity) as s "
        "from tpch.tiny.lineitem group by l_orderkey"
    )
    diff = verify_query(runner, oracle, sql)
    assert diff is None, diff


def test_partition_exchange_roundtrip():
    """Every live row lands on exactly the worker its key hashes to."""
    from presto_tpu.parallel.exchange import (
        partition_exchange,
        partition_hash,
    )
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = 8
    cap = 64
    devices = jax.devices()[:n]
    mesh = Mesh(np.array(devices), ("workers",))
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 1000, size=(n * cap,)).astype(np.int64)
    counts = rng.randint(0, cap + 1, size=(n,)).astype(np.int32)

    from presto_tpu.page import Block

    flat = Page(
        blocks=(
            Block(data=jnp.asarray(keys), valid=None, dtype=T.BIGINT),
        ),
        num_valid=jnp.asarray(counts),
        names=("k",),
    )

    def prog(page):
        import dataclasses

        local = dataclasses.replace(page, num_valid=page.num_valid[0])
        h = partition_hash(local, ["k"])
        dest = (h % jnp.uint64(n)).astype(jnp.int32)
        out, ovf = partition_exchange(local, dest, n, "workers", cap)
        return (
            dataclasses.replace(out, num_valid=out.num_valid.reshape(1)),
            ovf.reshape(1),
        )

    from jax import shard_map

    fn = jax.jit(
        shard_map(
            prog, mesh=mesh, in_specs=(P("workers"),), out_specs=P("workers")
        )
    )
    out, ovf = fn(jax.device_put(flat, NamedSharding(mesh, P("workers"))))
    assert not np.any(np.asarray(ovf))

    # reconstruct: rows received per worker must match the hash routing
    out_cap = out.capacity // n
    got = []
    data = np.asarray(out.blocks[0].data).reshape(n, out_cap)
    nv = np.asarray(out.num_valid)
    for w in range(n):
        got.append(sorted(data[w][: nv[w]].tolist()))

    # expected routing computed host-side with the same mixer
    def mix(h):
        h = np.uint64(h)
        h ^= h >> np.uint64(30)
        h = np.uint64(h * np.uint64(0xBF58476D1CE4E5B9))
        h ^= h >> np.uint64(27)
        h = np.uint64(h * np.uint64(0x94D049BB133111EB))
        return h ^ (h >> np.uint64(31))

    expected = [[] for _ in range(n)]
    with np.errstate(over="ignore"):
        for w in range(n):
            for j in range(counts[w]):
                k = keys[w * cap + j]
                h = mix(np.uint64(0x9E3779B97F4A7C15) ^ np.uint64(k))
                expected[int(h % np.uint64(n))].append(int(k))
    assert got == [sorted(e) for e in expected]
    total = sum(counts)
    assert sum(len(e) for e in got) == total


def test_replicate_matches_concat():
    from presto_tpu.parallel.exchange import replicate
    from presto_tpu.page import Block
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax import shard_map
    import dataclasses

    n, cap = 8, 16
    mesh = Mesh(np.array(jax.devices()[:n]), ("workers",))
    rng = np.random.RandomState(3)
    vals = rng.randint(0, 100, size=(n * cap,)).astype(np.int64)
    counts = rng.randint(0, cap + 1, size=(n,)).astype(np.int32)
    flat = Page(
        blocks=(Block(data=jnp.asarray(vals), valid=None, dtype=T.BIGINT),),
        num_valid=jnp.asarray(counts),
        names=("v",),
    )

    def prog(page):
        local = dataclasses.replace(page, num_valid=page.num_valid[0])
        out = replicate(local, n, "workers")
        return dataclasses.replace(out, num_valid=out.num_valid.reshape(1))

    fn = jax.jit(
        shard_map(
            prog, mesh=mesh, in_specs=(P("workers"),), out_specs=P("workers")
        )
    )
    out = fn(jax.device_put(flat, NamedSharding(mesh, P("workers"))))
    total = int(sum(counts))
    expected = sorted(
        int(vals[w * cap + j]) for w in range(n) for j in range(counts[w])
    )
    data = np.asarray(out.blocks[0].data).reshape(n, n * cap)
    nv = np.asarray(out.num_valid)
    for w in range(n):
        assert nv[w] == total
        assert sorted(data[w][:total].tolist()) == expected
