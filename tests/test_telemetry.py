"""Device-plane telemetry suite.

Covers the tentpole end to end: the accounting choke points produce
nonzero dispatch/transfer counts on a real distributed query; the
plane disabled is BIT-EXACT off (zero counter delta, identical
results); federation merge math; sampler ring retention + rates;
live-progress monotonicity observed MID-query; backend-diag shape on
a forced failure; and the QueryCompletedEvent JSONL sink's
back-compat (every pre-existing field still present beside the new
``device`` section).
"""

import json
import threading
import time

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.utils import devicediag
from presto_tpu.utils.telemetry import (
    DEVICE,
    MetricsFederation,
    MetricsSampler,
    device_snapshot,
    pad_waste_pct,
    parse_prometheus,
)


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Every test starts (and leaves) the plane enabled — the process
    default."""
    DEVICE.set_enabled(True)
    yield
    DEVICE.set_enabled(True)


@pytest.fixture(scope="module")
def cluster():
    from presto_tpu.server import (
        CoordinatorServer,
        PrestoTpuClient,
        WorkerServer,
    )

    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start()
        for _ in range(2)
    ]
    deadline = time.time() + 10
    while time.time() < deadline and len(coord.active_workers()) < 2:
        time.sleep(0.05)
    client = PrestoTpuClient(coord.uri, timeout_s=600)
    yield coord, client
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


# ------------------------------------------------- device accounting


def test_distributed_query_counts_device_work(cluster):
    """A distributed join moves real bytes and launches real
    programs: the process counters AND the per-query rollup must both
    see it."""
    coord, client = cluster
    before = device_snapshot()
    res = client.execute(
        "SELECT o.o_orderpriority, COUNT(*) FROM orders o "
        "JOIN lineitem l ON o.o_orderkey = l.l_orderkey "
        "GROUP BY o.o_orderpriority"
    )
    assert len(res.rows()) > 0
    after = device_snapshot()
    assert after["dispatches"] > before["dispatches"]
    assert (
        after["h2d_bytes"] + after["d2h_bytes"]
        > before["h2d_bytes"] + before["d2h_bytes"]
    )
    # per-query attribution: the QueryInfo device section is populated
    info = client.query_info(res.query_id)
    dev = info["device"]
    assert dev["dispatches"] > 0
    assert dev["h2d_bytes"] + dev["d2h_bytes"] > 0
    assert 0.0 <= dev["pad_waste_pct"] <= 100.0


def test_explain_analyze_renders_device_line(cluster):
    _coord, client = cluster
    res = client.execute(
        "EXPLAIN ANALYZE SELECT n.n_name, COUNT(*) FROM nation n "
        "JOIN region r ON n.n_regionkey = r.r_regionkey "
        "GROUP BY n.n_name"
    )
    text = "\n".join(r[0] for r in res.rows())
    (line,) = [
        ln
        for ln in text.splitlines()
        if ln.strip().startswith("device:")
    ]
    assert "dispatches" in line and "compiles" in line
    assert "h2d" in line and "d2h" in line and "pad waste" in line
    # nonzero dispatch/transfer on the analyzed join (acceptance
    # criterion)
    import re

    disp = int(re.search(r"dispatches (\d+)", line).group(1))
    assert disp > 0


def test_disabled_plane_is_bit_exact_off():
    """telemetry.enabled=false: EXACTLY zero counter delta and
    identical query results."""
    runner = LocalQueryRunner()
    sql = (
        "SELECT r_name, COUNT(*) FROM tpch.tiny.nation, "
        "tpch.tiny.region WHERE n_regionkey = r_regionkey "
        "GROUP BY r_name ORDER BY r_name"
    )
    enabled_res = runner.execute(sql)
    DEVICE.set_enabled(False)
    try:
        before = device_snapshot()
        disabled_res = runner.execute(sql)
        after = device_snapshot()
        assert after == before  # zero delta, every field, bit-exact
    finally:
        DEVICE.set_enabled(True)
    assert disabled_res.rows() == enabled_res.rows()


def test_local_query_stats_device_section():
    runner = LocalQueryRunner()
    runner.execute("SELECT COUNT(*) FROM tpch.tiny.orders")
    qs = runner.history.snapshot()[-1]
    d = qs.device_dict()
    assert d["dispatches"] >= 1
    assert d["h2d_bytes"] > 0 or d["d2h_bytes"] > 0


def test_pad_waste_pct_math():
    assert pad_waste_pct(0, 0) == 0.0
    assert pad_waste_pct(25, 75) == 25.0
    assert pad_waste_pct(10, 0) == 100.0


# ------------------------------------------------- event-sink compat


def test_event_sink_back_compat(tmp_path):
    """The JSONL QueryCompletedEvent record keeps every pre-existing
    top-level field AND gains the device section — old consumers keep
    parsing."""
    from presto_tpu.exec.stats import JsonlQueryEventListener

    path = tmp_path / "events.jsonl"
    runner = LocalQueryRunner()
    runner.history.add_listener(JsonlQueryEventListener(str(path)))
    runner.execute("SELECT COUNT(*) FROM tpch.tiny.nation")
    rec = json.loads(path.read_text().splitlines()[-1])
    # the pre-PR contract fields, all still present
    for field in (
        "event", "query_id", "state", "elapsed_ms", "planning_ms",
        "staging_ms", "execution_ms", "compile_cache_hit",
        "input_rows", "input_bytes", "output_rows", "operators",
        "stages", "spilled_bytes", "peak_memory_bytes",
    ):
        assert field in rec, field
    assert rec["event"] == "query_completed"
    # the additive device section
    for field in (
        "dispatches", "compiles", "compile_ms", "h2d_bytes",
        "d2h_bytes", "pad_rows", "live_rows", "pad_waste_pct",
    ):
        assert field in rec["device"], field


# --------------------------------------------------------- federation


def test_parse_prometheus_skips_noise():
    text = (
        "# HELP x_total help\n"
        "# TYPE x_total counter\n"
        "x_total 3\n"
        'y_ms{quantile="0.5"} 1.5\n'
        "torn line without value\n"
        "z_total not_a_number\n"
    )
    samples = parse_prometheus(text)
    assert ("x_total", "", 3.0) in samples
    assert ("y_ms", 'quantile="0.5"', 1.5) in samples
    assert len(samples) == 2


def test_federation_merge_math():
    """Per-node labels + node="cluster" sums of monotone families;
    quantiles are labeled but never summed."""
    expos = {
        "w1": 'a_total 3\nlat{quantile="0.5"} 10\n',
        "w2": 'a_total 4\nlat{quantile="0.5"} 20\n',
    }
    fed = MetricsFederation(lambda uri: expos[uri])
    by_node = fed.scrape([("w1", "w1"), ("w2", "w2")])
    out = fed.render(by_node)
    assert 'a_total{node="w1"} 3.0' in out
    assert 'a_total{node="w2"} 4.0' in out
    assert 'a_total{node="cluster"} 7.0' in out
    # quantile stream re-labeled per node, NOT cluster-summed
    assert 'lat{node="w1",quantile="0.5"} 10.0' in out
    assert 'lat{node="cluster"' not in out


def test_federation_drops_failed_scrapes():
    def fetch(uri):
        if uri == "dead":
            raise OSError("connection refused")
        return "ok_total 1\n"

    fed = MetricsFederation(fetch)
    by_node = fed.scrape([("w1", "live"), ("w2", "dead")])
    assert set(by_node) == {"w1"}  # dead node dropped, not fatal


# ------------------------------------------------------------ sampler


def test_sampler_retention_and_rate():
    samp = MetricsSampler(retention=4)
    samp.observe("n1", [("c_total", 10.0)], ts=100.0)
    samp.observe("n1", [("c_total", 40.0)], ts=110.0)
    rows = samp.rows()
    assert rows[-1]["rate"] == pytest.approx(3.0)  # (40-10)/10s
    # retention bounds TOTAL rows: oldest drop first
    for i in range(5):
        samp.observe("n1", [("c_total", 50.0 + i)], ts=120.0 + i)
    rows = samp.rows()
    assert len(rows) == 4
    assert rows[0]["value"] == 51.0  # the 10.0/40.0 rows aged out


def test_sampler_rate_resets_on_counter_restart():
    """A restarted worker's counter going backwards rates 0, never
    negative."""
    samp = MetricsSampler(retention=8)
    samp.observe("w", [("c_total", 100.0)], ts=10.0)
    samp.observe("w", [("c_total", 5.0)], ts=20.0)
    assert samp.rows()[-1]["rate"] == 0.0


def test_sampler_persistence_rotation_and_torn_tail(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    samp = MetricsSampler(retention=16, path=path)
    samp.observe("n", [("a_total", 1.0)], ts=1.0)
    samp.observe("n", [("a_total", 2.0)], ts=2.0)
    # torn tail: a partial line must not poison the replay
    with open(path, "a") as f:
        f.write('{"node": "n", "ts": 3.0, "na')
    rows = MetricsSampler.read_persisted(path)
    assert [r["value"] for r in rows] == [1.0, 2.0]


def test_metrics_history_system_table_local_is_empty():
    """No cluster / sampler off: an empty view, not an error."""
    runner = LocalQueryRunner()
    res = runner.execute(
        "SELECT * FROM system.runtime.metrics_history"
    )
    assert res.rows() == []


# ------------------------------------------------------ live progress


def test_progress_monotone_mid_query(cluster):
    """Poll the progress endpoint WHILE a distributed query runs: the
    done counts and byte/dispatch counters must never go backwards,
    and the terminal observation is complete."""
    coord, client = cluster
    polls = []
    stop = threading.Event()
    seen_qid = {}

    def poll():
        while not stop.is_set():
            qs = client.list_queries()
            running = [
                q for q in qs if q["state"] not in ("FINISHED", "FAILED")
            ]
            for q in running:
                try:
                    p = client.query_progress(q["query_id"])
                except Exception:
                    continue  # query finished between list and get
                polls.append(p)
                seen_qid[q["query_id"]] = True
            time.sleep(0.02)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        res = client.execute(
            "SELECT l.l_returnflag, COUNT(*), SUM(l.l_quantity) "
            "FROM lineitem l JOIN orders o "
            "ON l.l_orderkey = o.o_orderkey "
            "GROUP BY l.l_returnflag"
        )
        assert len(res.rows()) > 0
    finally:
        stop.set()
        t.join(timeout=5)
    final = client.query_progress(res.query_id)
    assert final["done"] and final["progress"] == 1.0
    assert final["eta_ms"] == 0.0
    assert final["splits_done"] == final["splits_total"] > 0
    assert final["device_dispatches"] > 0
    # monotonicity over the mid-query observations of THIS query
    series = [
        p for p in polls if p["query_id"] == res.query_id
    ] + [final]
    for a, b in zip(series, series[1:]):
        for key in ("splits_done", "rows", "bytes",
                    "device_dispatches", "elapsed_ms"):
            assert b[key] >= a[key], (key, a, b)


def test_progress_unknown_query_404s(cluster):
    _coord, client = cluster
    with pytest.raises(Exception):
        client.query_progress("q_nope_000000")


# --------------------------------------------------------- diagnosis


def test_backend_diag_ok_shape():
    diag = devicediag.probe_backend()
    d = diag.to_dict()
    assert d["ok"] is True and d["phase"] == "ok"
    assert d["backend"] != "" and d["device_count"] >= 1
    assert d["probed_at"] > 0


def test_backend_diag_forced_failure_shape():
    """A dead platform produces a structured diagnosis — failing
    phase, error class, truncated error — and never raises."""
    diag = devicediag.probe_backend(platform="no_such_platform")
    d = diag.to_dict()
    assert d["ok"] is False
    assert d["phase"] == "enumerate"
    assert d["error_class"] != "" and d["error"] != ""
    assert len(d["error"]) <= 300
    # fallback note lands on the failed diag...
    devicediag.note_fallback("cpu")
    assert devicediag.last_diag_dict()["fallback"] == "cpu"
    # ...and survives the successful re-probe (the bench's force-CPU
    # path must keep "runs degraded" on record)
    again = devicediag.probe_backend()
    assert again.ok and again.fallback == "cpu"
    # leave a clean diag for other tests in this process
    devicediag.probe_backend()


def test_backend_diag_on_worker_status_and_nodes(cluster):
    coord, client = cluster
    import urllib.request

    w = coord.active_workers()[0]
    st = json.loads(
        urllib.request.urlopen(w.uri + "/v1/status").read()
    )
    assert st["backend_diag"]["phase"] in ("ok", "enumerate",
                                           "compile", "execute")
    res = client.execute(
        "SELECT node_id, backend_diag FROM system.runtime.nodes"
    )
    for _node, diag_json in res.rows():
        diag = json.loads(diag_json)
        assert diag == {} or "phase" in diag
