"""Connector SPI + TPC-H generator tests (SURVEY.md §4.4: deterministic
fixtures are the test data)."""

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.connectors.tpch import (
    DictColumn,
    TABLE_SCHEMAS,
    _counts,
    _lineitem_count,
    _lineitem_order,
    _orderkey,
)
from presto_tpu.exec import bucket_capacity, stage_page


def test_counts_closed_form():
    c = _counts(0.01)
    assert c["lineitem"] == _lineitem_count(c["orders"])
    # closed form vs brute force
    for n in [1, 6, 7, 8, 20, 100]:
        brute = sum((k % 7) + 1 for k in range(n))
        assert _lineitem_count(n) == brute


def test_lineitem_order_mapping_bijective():
    n_orders = 50
    total = _lineitem_count(n_orders)
    rows = np.arange(total)
    order_idx, linenumber = _lineitem_order(rows)
    # each order k has (k%7)+1 lines numbered 1..count
    for k in range(n_orders):
        mask = order_idx == k
        expect = (k % 7) + 1
        assert mask.sum() == expect
        assert sorted(linenumber[mask]) == list(range(1, expect + 1))


def test_tpch_split_determinism_and_fk_validity():
    conn = create_connector("tpch")
    h = TableHandle("tpch", "tiny", "lineitem")
    counts = _counts(0.01)
    src = conn.get_splits(h, target_split_rows=10_000)
    s1 = src.next_batch(100)
    assert not src.exhausted or len(s1) > 0
    cols = ["l_orderkey", "l_partkey", "l_suppkey", "l_quantity", "l_shipdate", "l_returnflag"]
    a = conn.create_page_source(s1[0], cols)
    b = conn.create_page_source(s1[0], cols)  # regenerate: identical
    assert np.array_equal(a["l_orderkey"], b["l_orderkey"])
    assert np.array_equal(a["l_returnflag"].ids, b["l_returnflag"].ids)
    assert (a["l_partkey"] >= 1).all() and (a["l_partkey"] <= counts["part"]).all()
    assert (a["l_suppkey"] >= 1).all() and (a["l_suppkey"] <= counts["supplier"]).all()
    assert (a["l_quantity"] >= 100).all() and (a["l_quantity"] <= 5000).all()


def test_tpch_orderkeys_sparse_unique():
    ok = _orderkey(np.arange(100))
    assert len(np.unique(ok)) == 100
    assert ok.max() > 100  # sparse


def test_tpch_orders_dates_in_range():
    from presto_tpu.connectors.tpch import ENDDATE, STARTDATE

    conn = create_connector("tpch")
    h = TableHandle("tpch", "tiny", "orders")
    split = conn.get_splits(h).next_batch(1)[0]
    d = conn.create_page_source(split, ["o_orderdate"])["o_orderdate"]
    assert (d >= STARTDATE).all() and (d <= ENDDATE - 151).all()


def test_tpch_q13_q16_patterns_reachable():
    conn = create_connector("tpch")
    h = TableHandle("tpch", "tiny", "orders")
    split = conn.get_splits(h).next_batch(1)[0]
    c = conn.create_page_source(split, ["o_comment"])["o_comment"]
    assert isinstance(c, DictColumn)
    phrases = c.values[np.unique(c.ids)]
    assert any("special" in p and "requests" in p for p in phrases)


def test_stage_page_roundtrip():
    conn = create_connector("tpch")
    h = TableHandle("tpch", "tiny", "nation")
    split = conn.get_splits(h).next_batch(1)[0]
    schema = conn.metadata().get_table_schema(h)
    data = conn.create_page_source(split, list(schema))
    page = stage_page(data, schema)
    assert page.capacity == bucket_capacity(25)
    rows = page.to_pylist()
    assert len(rows) == 25
    assert rows[0]["n_nationkey"] == 0 and rows[0]["n_name"] == "ALGERIA"
    assert rows[24]["n_name"] == "UNITED STATES" and rows[24]["n_regionkey"] == 1


def test_memory_connector_write_read():
    conn = create_connector("memory")
    h = TableHandle("mem", "default", "t")
    schema = {"a": T.BIGINT, "b": T.VARCHAR}
    conn.create_table(h, schema)
    conn.append_rows(h, {"a": np.asarray([1, 2]), "b": np.asarray(["x", "y"], dtype=object)})
    conn.append_rows(h, {"a": np.asarray([3]), "b": np.asarray([None], dtype=object)})
    split = conn.get_splits(h).next_batch(10)[0]
    data = conn.create_page_source(split, ["a", "b"])
    page = stage_page(data, schema)
    rows = page.to_pylist()
    assert [r["a"] for r in rows] == [1, 2, 3]
    assert [r["b"] for r in rows] == ["x", "y", None]


def test_blackhole_connector():
    conn = create_connector("blackhole", rows_per_table=100)
    h = TableHandle("bh", "default", "t")
    conn.create_table(h, {"x": T.BIGINT, "s": T.VARCHAR})
    splits = conn.get_splits(h).next_batch(10)
    data = conn.create_page_source(splits[0], ["x", "s"])
    assert len(data["x"]) == 100
    page = stage_page(data, {"x": T.BIGINT, "s": T.VARCHAR})
    assert int(page.num_valid) == 100


def test_all_tables_generate_all_columns():
    conn = create_connector("tpch")
    for table, schema in TABLE_SCHEMAS.items():
        h = TableHandle("tpch", "tiny", table)
        split = conn.get_splits(h, target_split_rows=1000).next_batch(1)[0]
        data = conn.create_page_source(split, list(schema))
        assert set(data) == set(schema), table
        page = stage_page(data, schema)
        assert int(page.num_valid) == split.num_rows
