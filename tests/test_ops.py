"""Kernel operator tests: aggregation, sort/topN/limit/distinct, join,
window (SURVEY.md §7 step 3), in the reference's hand-built-page style
(SURVEY.md §4.1)."""

import jax
import numpy as np

from presto_tpu import types as T
from presto_tpu.expr import ColumnRef, Literal, arith
from presto_tpu.ops import (
    AggCall,
    SortKey,
    WindowCall,
    distinct,
    hash_aggregate,
    hash_join,
    limit,
    order_by,
    window,
)
from presto_tpu.page import Page


def make_page(capacity=None, **cols):
    data = {k: v[0] for k, v in cols.items()}
    schema = {k: v[1] for k, v in cols.items()}
    return Page.from_pydict(data, schema, capacity=capacity)


def col(page, name):
    return ColumnRef(name, page.schema()[name])


# ----------------------------------------------------------- aggregation


def test_hash_aggregate_basic():
    p = make_page(
        capacity=8,
        k=(["a", "b", "a", "c", "b", "a"], T.VARCHAR),
        x=([1, 2, 3, 4, 5, None], T.BIGINT),
    )
    out, overflow = jax.jit(
        lambda pg: hash_aggregate(
            pg,
            [("k", col(p, "k"))],
            [
                AggCall("sum", col(p, "x"), "s"),
                AggCall("count", col(p, "x"), "c"),
                AggCall("count_star", None, "cs"),
                AggCall("min", col(p, "x"), "mn"),
                AggCall("max", col(p, "x"), "mx"),
                AggCall("avg", col(p, "x"), "a"),
            ],
            max_groups=8,
        )
    )(p)
    assert not bool(overflow)
    rows = {r["k"]: r for r in out.to_pylist()}
    assert set(rows) == {"a", "b", "c"}
    # group a: x = 1, 3, NULL
    assert rows["a"]["s"] == 4 and rows["a"]["c"] == 2 and rows["a"]["cs"] == 3
    assert rows["a"]["mn"] == 1 and rows["a"]["mx"] == 3
    assert abs(rows["a"]["a"] - 2.0) < 1e-12
    assert rows["b"]["s"] == 7 and rows["c"]["s"] == 4


def test_hash_aggregate_decimal_exact_and_null_group():
    p = make_page(
        capacity=8,
        g=([1, 1, None, None, 2], T.BIGINT),
        d=([10.25, 0.75, 5.00, 1.00, 3.50], T.decimal(10, 2)),
    )
    out, _ = hash_aggregate(
        p, [("g", col(p, "g"))], [AggCall("sum", col(p, "d"), "s")], 8
    )
    rows = {r["g"]: r["s"] for r in out.to_pylist()}
    # nulls form ONE group
    assert rows[1] == 11.0 and rows[None] == 6.0 and rows[2] == 3.5


def test_hash_aggregate_overflow_flag():
    p = make_page(capacity=8, k=([1, 2, 3, 4, 5], T.BIGINT))
    out, overflow = hash_aggregate(
        p, [("k", col(p, "k"))], [AggCall("count_star", None, "c")], 3
    )
    assert bool(overflow)
    assert int(out.num_valid) == 3


def test_global_aggregate_empty_input():
    p = make_page(capacity=4, x=([], T.BIGINT))
    out, _ = hash_aggregate(
        p,
        [],
        [AggCall("count_star", None, "c"), AggCall("sum", col(p, "x"), "s")],
        1,
    )
    rows = out.to_pylist()
    assert rows == [{"c": 0, "s": None}]  # SQL: sum over empty = NULL


# ----------------------------------------------------------------- sort


def test_order_by_multi_key_desc_nulls():
    p = make_page(
        capacity=8,
        a=([2, 1, 2, None, 1], T.BIGINT),
        b=([1.5, 9.9, 0.5, 7.7, 1.1], T.DOUBLE),
    )
    out = order_by(
        p, [SortKey(col(p, "a")), SortKey(col(p, "b"), descending=True)]
    )
    rows = out.to_pylist()
    assert [r["a"] for r in rows] == [1, 1, 2, 2, None]  # nulls last (ASC)
    assert [r["b"] for r in rows][:4] == [9.9, 1.1, 1.5, 0.5]


def test_topn_and_limit():
    p = make_page(capacity=8, x=([5, 3, 9, 1, 7], T.BIGINT))
    out = order_by(p, [SortKey(col(p, "x"))], limit=3)
    assert out.capacity == 3
    assert [r["x"] for r in out.to_pylist()] == [1, 3, 5]
    l = limit(p, 2)
    assert int(l.num_valid) == 2


def test_distinct():
    p = make_page(capacity=8, x=([1, 2, 1, 3, 2], T.BIGINT))
    out, _ = distinct(p)
    assert sorted(r["x"] for r in out.to_pylist()) == [1, 2, 3]


# ----------------------------------------------------------------- join


def _join_pages():
    probe = make_page(
        capacity=8,
        pk=([10, 20, 30, 40, 10], T.BIGINT),
        pv=(["a", "b", "c", "d", "e"], T.VARCHAR),
    )
    build = make_page(
        capacity=4,
        bk=([10, 20, 50], T.BIGINT),
        bv=([100.0, 200.0, 500.0], T.DOUBLE),
    )
    return probe, build


def test_join_inner_unique():
    probe, build = _join_pages()
    out, ov = jax.jit(
        lambda p, b: hash_join(
            p, b, ["pk"], ["bk"],
            join_type="inner", build_payload=["bv"], build_unique=True,
        )
    )(probe, build)
    rows = sorted(out.to_pylist(), key=lambda r: (r["pk"], r["pv"]))
    assert [(r["pk"], r["bv"]) for r in rows] == [
        (10, 100.0), (10, 100.0), (20, 200.0),
    ]


def test_join_left_unique():
    probe, build = _join_pages()
    out, _ = hash_join(
        probe, build, ["pk"], ["bk"],
        join_type="left", build_payload=["bv"], build_unique=True,
    )
    rows = {(r["pk"], r["pv"]): r["bv"] for r in out.to_pylist()}
    assert rows[(30, "c")] is None and rows[(40, "d")] is None
    assert rows[(10, "a")] == 100.0


def test_join_semi_anti():
    probe, build = _join_pages()
    semi, _ = hash_join(probe, build, ["pk"], ["bk"], join_type="semi")
    assert sorted(r["pk"] for r in semi.to_pylist()) == [10, 10, 20]
    anti, _ = hash_join(probe, build, ["pk"], ["bk"], join_type="anti")
    assert sorted(r["pk"] for r in anti.to_pylist()) == [30, 40]


def test_join_duplicates_expansion():
    probe = make_page(capacity=4, k=([1, 2, 3], T.BIGINT))
    build = make_page(
        capacity=8,
        k2=([1, 1, 2, 9, 1], T.BIGINT),
        w=([10, 11, 20, 90, 12], T.BIGINT),
    )
    out, ov = hash_join(
        probe, build, ["k"], ["k2"],
        join_type="inner", build_payload=["w"], out_capacity=8,
    )
    assert not bool(ov)
    got = sorted((r["k"], r["w"]) for r in out.to_pylist())
    assert got == [(1, 10), (1, 11), (1, 12), (2, 20)]
    # overflow: capacity 2 < 4 matches
    out, ov = hash_join(
        probe, build, ["k"], ["k2"],
        join_type="inner", build_payload=["w"], out_capacity=2,
    )
    assert bool(ov) and int(out.num_valid) == 2


def test_join_left_duplicates():
    probe = make_page(capacity=4, k=([1, 7], T.BIGINT))
    build = make_page(capacity=4, k2=([1, 1], T.BIGINT), w=([10, 11], T.BIGINT))
    out, _ = hash_join(
        probe, build, ["k"], ["k2"],
        join_type="left", build_payload=["w"], out_capacity=4,
    )
    got = sorted(
        ((r["k"], r["w"]) for r in out.to_pylist()),
        key=lambda t: (t[0], t[1] if t[1] is not None else -1),
    )
    assert got == [(1, 10), (1, 11), (7, None)]


def test_join_null_keys_never_match():
    probe = make_page(capacity=4, k=([1, None], T.BIGINT))
    build = make_page(capacity=4, k2=([1, None], T.BIGINT), w=([10, 99], T.BIGINT))
    out, _ = hash_join(
        probe, build, ["k"], ["k2"],
        join_type="inner", build_payload=["w"], out_capacity=4,
    )
    assert [(r["k"], r["w"]) for r in out.to_pylist()] == [(1, 10)]
    anti, _ = hash_join(probe, build, ["k"], ["k2"], join_type="anti")
    # NOT EXISTS semantics: the null-key probe row is kept
    assert [r["k"] for r in anti.to_pylist()] == [None]


def test_join_two_column_key():
    probe = make_page(
        capacity=4, a=([1, 1, 2], T.INTEGER), b=([5, 6, 5], T.INTEGER)
    )
    build = make_page(
        capacity=4, a2=([1, 2], T.INTEGER), b2=([5, 5], T.INTEGER),
        w=([100, 200], T.BIGINT),
    )
    out, _ = hash_join(
        probe, build, ["a", "b"], ["a2", "b2"],
        join_type="inner", build_payload=["w"], build_unique=True,
    )
    got = sorted((r["a"], r["b"], r["w"]) for r in out.to_pylist())
    assert got == [(1, 5, 100), (2, 5, 200)]


def test_join_two_column_key_rejects_wide_types():
    import pytest

    probe = make_page(capacity=4, a=([1], T.BIGINT), b=([5], T.BIGINT))
    build = make_page(capacity=4, a2=([1], T.BIGINT), b2=([5], T.BIGINT))
    with pytest.raises(NotImplementedError):
        hash_join(probe, build, ["a", "b"], ["a2", "b2"], join_type="semi")


# --------------------------------------------------------------- window


def test_window_row_number_rank():
    p = make_page(
        capacity=8,
        g=(["x", "x", "x", "y", "y"], T.VARCHAR),
        v=([10, 10, 20, 5, 7], T.BIGINT),
    )
    out = window(
        p,
        [col(p, "g")],
        [SortKey(col(p, "v"))],
        [
            WindowCall("row_number", None, "rn"),
            WindowCall("rank", None, "rk"),
            WindowCall("dense_rank", None, "dr"),
        ],
    )
    rows = out.to_pylist()
    by_g = {}
    for r in rows:
        by_g.setdefault(r["g"], []).append((r["v"], r["rn"], r["rk"], r["dr"]))
    assert by_g["x"] == [(10, 1, 1, 1), (10, 2, 1, 1), (20, 3, 3, 2)]
    assert by_g["y"] == [(5, 1, 1, 1), (7, 2, 2, 2)]


def test_window_partition_aggregate():
    p = make_page(
        capacity=8,
        g=([1, 1, 2], T.BIGINT),
        v=([10.0, 30.0, 5.0], T.DOUBLE),
    )
    out = window(
        p, [col(p, "g")], [], [WindowCall("sum", col(p, "v"), "s")]
    )
    rows = {(r["g"], r["v"]): r["s"] for r in out.to_pylist()}
    assert rows[(1, 10.0)] == 40.0 and rows[(1, 30.0)] == 40.0
    assert rows[(2, 5.0)] == 5.0


def test_window_running_sum_with_peers():
    p = make_page(
        capacity=8,
        g=([1, 1, 1, 1], T.BIGINT),
        o=([1, 2, 2, 3], T.BIGINT),
        v=([10, 20, 30, 40], T.BIGINT),
    )
    out = window(
        p,
        [col(p, "g")],
        [SortKey(col(p, "o"))],
        [WindowCall("sum", col(p, "v"), "s")],
    )
    rows = [(r["o"], r["s"]) for r in out.to_pylist()]
    # RANGE frame: peers (o=2) share the running total including both
    assert rows == [(1, 10), (2, 60), (2, 60), (3, 100)]


def test_window_running_min():
    p = make_page(
        capacity=4,
        g=([1, 1, 2], T.BIGINT),
        o=([1, 2, 1], T.BIGINT),
        v=([5, 3, 9], T.BIGINT),
    )
    out = window(
        p,
        [col(p, "g")],
        [SortKey(col(p, "o"))],
        [WindowCall("min", col(p, "v"), "m")],
    )
    rows = [(r["g"], r["o"], r["m"]) for r in out.to_pylist()]
    assert rows == [(1, 1, 5), (1, 2, 3), (2, 1, 9)]


def test_window_running_min_peer_sharing():
    # RANGE frame: tied ORDER BY rows are peers and share the frame value
    p = make_page(
        capacity=4, g=([1, 1], T.BIGINT), o=([1, 1], T.BIGINT),
        v=([5, 3], T.BIGINT),
    )
    out = window(
        p, [col(p, "g")], [SortKey(col(p, "o"))],
        [WindowCall("min", col(p, "v"), "m")],
    )
    assert [r["m"] for r in out.to_pylist()] == [3, 3]


def test_window_running_min_null_frame():
    # first row's frame contains only NULL -> result NULL
    p = make_page(
        capacity=4, g=([1, 1], T.BIGINT), o=([1, 2], T.BIGINT),
        v=([None, 5], T.BIGINT),
    )
    out = window(
        p, [col(p, "g")], [SortKey(col(p, "o"))],
        [WindowCall("min", col(p, "v"), "m")],
    )
    assert [r["m"] for r in out.to_pylist()] == [None, 5]


def test_sorted_sum_overflow_trap():
    """A group whose TRUE sum exceeds int64 must raise through the error
    channel; groups whose sums fit must stay exact and silent even when
    the page-wide running cumsum wraps (modular arithmetic makes the
    span difference exact in that case)."""
    big = (1 << 62) + 7
    # group 1 sums to 2^63+14 -> real per-group overflow -> trap
    p = make_page(
        capacity=8,
        k=([1, 1, 2], T.BIGINT),
        x=([big, big, 10], T.BIGINT),
    )
    errors = []
    hash_aggregate(
        p,
        [("k", col(p, "k"))],
        [AggCall("sum", col(p, "x"), "s")],
        8,
        errors_out=errors,
    )
    assert errors, "sum must register an overflow trap"
    assert any(bool(flag) for _, flag in errors)

    # page-wide cumsum wraps (4 * (2^62+7) > 2^64) but every per-group
    # sum is representable: exact results, NO trap (the reference only
    # overflows per group)
    p2 = make_page(
        capacity=8,
        k=([1, 2, 3, 4], T.BIGINT),
        x=([big, big, big, big], T.BIGINT),
    )
    errors2 = []
    out2, _ = hash_aggregate(
        p2,
        [("k", col(p2, "k"))],
        [AggCall("sum", col(p2, "x"), "s")],
        8,
        errors_out=errors2,
    )
    assert not any(bool(flag) for _, flag in errors2)
    rows = {r["k"]: r["s"] for r in out2.to_pylist()}
    assert rows == {1: big, 2: big, 3: big, 4: big}

    # and a benign page must NOT trip the trap
    p3 = make_page(
        capacity=8,
        k=([1, 2, 1, 2], T.BIGINT),
        x=([10, 20, 30, 40], T.BIGINT),
    )
    errors3 = []
    out3, _ = hash_aggregate(
        p3,
        [("k", col(p3, "k"))],
        [AggCall("sum", col(p3, "x"), "s")],
        8,
        errors_out=errors3,
    )
    assert not any(bool(flag) for _, flag in errors3)
    rows = {r["k"]: r["s"] for r in out3.to_pylist()}
    assert rows == {1: 40, 2: 60}
