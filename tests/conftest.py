"""Test harness configuration.

Reference parity: the DistributedQueryRunner pattern (SURVEY.md §4.3) —
multi-node testing without a cluster. TPU analogue: force 8 virtual CPU
devices so every sharding/collective test exercises a real 8-device mesh
on any machine (no TPU needed for correctness CI).

Must set env vars BEFORE jax initialises its backends.
"""

import os

# Force-set (not setdefault): the environment may pin JAX_PLATFORMS to a
# real accelerator platform; correctness CI must run CPU-only.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Config-level override as well: an accelerator plugin loaded at
# interpreter startup (sitecustomize) may have called
# jax.config.update("jax_platforms", ...), which outranks the env var.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
