"""Test harness configuration.

Reference parity: the DistributedQueryRunner pattern (SURVEY.md §4.3) —
multi-node testing without a cluster. TPU analogue: force 8 virtual CPU
devices so every sharding/collective test exercises a real 8-device mesh
on any machine (no TPU needed for correctness CI).

Must set env vars BEFORE jax initialises its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
