"""Driver-hook contract tests for ``__graft_entry__``.

The multichip dryrun is the driver's multi-chip correctness signal and
must be obtainable with the accelerator plugin unreachable (SURVEY.md §7
step 6). Round-4 regression: ``dryrun_multichip`` called ``jax.devices()``
before deciding to re-exec the CPU-mesh subprocess, initialising a wedged
TPU plugin and hanging until the driver's timeout killed it.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as G  # noqa: E402


class _PoisonedModule:
    """Stands in for ``jax`` in sys.modules: ANY attribute access (devices,
    device_count, default_backend, jit, ...) fails loudly, so any use of
    any jax API on the calling-process path is caught — not just the two
    names round 4 happened to use."""

    def __getattr__(self, name):  # pragma: no cover - must never run
        raise AssertionError(
            f"dryrun_multichip touched jax.{name} in the calling process "
            "— this initialises the (possibly wedged) TPU plugin"
        )


def test_dryrun_never_initializes_device_plugin(monkeypatch):
    """Simulate a wedged accelerator plugin: the whole jax module is
    poisoned in the calling process. The dryrun must complete anyway via
    the forced-CPU subprocess (which imports its own, real jax)."""
    monkeypatch.setitem(sys.modules, "jax", _PoisonedModule())
    monkeypatch.delenv("PRESTO_TPU_DRYRUN_INPROC", raising=False)
    G.dryrun_multichip(2)


def test_dryrun_inproc_escape_hatch(monkeypatch):
    """PRESTO_TPU_DRYRUN_INPROC=1 runs the body in-process (for runtimes
    that really do expose >= n devices — here the 8-CPU test mesh)."""
    monkeypatch.setenv("PRESTO_TPU_DRYRUN_INPROC", "1")
    G.dryrun_multichip(2)


def test_dryrun_subprocess_failure_surfaces(monkeypatch):
    """A failing subprocess must raise with its stderr, not pass silently."""
    monkeypatch.delenv("PRESTO_TPU_DRYRUN_INPROC", raising=False)
    import subprocess

    real_run = subprocess.run

    def fake_run(*a, **k):
        cp = real_run(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            capture_output=True,
            text=True,
        )
        return cp

    monkeypatch.setattr(subprocess, "run", fake_run)
    with pytest.raises(RuntimeError, match="rc=3"):
        G.dryrun_multichip(2)
