"""Fault-tolerance chaos suite: deterministic fault injection
(utils.faults), the unified RPC retry/backoff plane (server.rpc),
per-worker circuit breaking, straggler speculation, task-retry
budgets, announce backoff, and coordinator-local graceful degradation.

Reference parity: node failure detection + recoverable execution as
coordinator duties (SURVEY.md §5.3; Sethi et al. ICDE 2019) and
speculative backup tasks (Dean & Ghemawat, OSDI 2004) — proven here
under injected chaos, forever, in tier-1.
"""

import os
import time

import pytest

from presto_tpu.server import CoordinatorServer, PrestoTpuClient, WorkerServer
from presto_tpu.server import rpc
from presto_tpu.server.client import QueryFailed
from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY
from presto_tpu.verifier import SqliteOracle, verify_query

from tpch_queries import QUERIES


@pytest.fixture(autouse=True)
def clear_fault_plane():
    """Every test leaves the process chaos-free."""
    yield
    faults.configure(None)


def _wait_workers(coord, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(coord.active_workers()) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError("workers not discovered")


@pytest.fixture(scope="module")
def cluster():
    """Healthy 2-worker cluster for the non-destructive tests."""
    coord = CoordinatorServer().start()
    workers = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    _wait_workers(coord, 2)
    yield coord, workers
    faults.configure(None)
    for w in workers:
        w.shutdown(graceful=False)
    coord.shutdown()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


# -------------------------------------------------- fault plane (unit)


def test_fault_plane_disabled_by_default():
    assert faults.active() is None
    # hooks are no-ops without a plane (the zero-cost hot path)
    faults.maybe_inject_rpc("GET", "http://x/v1/status")
    faults.maybe_inject_task("node", "task")


def test_fault_rule_validation():
    with pytest.raises(ValueError):
        faults.configure({"rules": [{"action": "explode"}]})
    faults.configure(None)
    with pytest.raises(ValueError):
        faults.configure({"rules": [{"action": "error", "nope": 1}]})


def test_fault_rule_skip_count_and_match():
    plane = faults.configure(
        {
            "seed": 1,
            "rules": [
                {
                    "action": "error",
                    "method": "GET",
                    "url": "/v1/task",
                    "skip": 1,
                    "count": 2,
                }
            ],
        }
    )
    # wrong method / url: never fires
    plane.on_rpc("POST", "http://h/v1/task")
    plane.on_rpc("GET", "http://h/v1/status")
    # first match skipped, next two fire, then exhausted
    plane.on_rpc("GET", "http://h/v1/task/t/results/0/0")
    for _ in range(2):
        with pytest.raises(faults.FaultInjectedError):
            plane.on_rpc("GET", "http://h/v1/task/t/results/0/0")
    plane.on_rpc("GET", "http://h/v1/task/t/results/0/0")
    assert plane.injected == 2


# ------------------------------------------------ backoff determinism


def test_backoff_full_jitter_bounds():
    pol = rpc.RpcPolicy(backoff_base_s=0.1, backoff_max_s=1.0)
    for attempt in range(8):
        d = rpc.compute_backoff(attempt, pol)
        assert 0.0 <= d <= min(1.0, 0.1 * 2 ** attempt)


def test_backoff_deterministic_under_seeded_plane():
    pol = rpc.RpcPolicy(backoff_base_s=0.1, backoff_max_s=1.0)
    faults.configure({"seed": 42, "rules": []})
    a = [rpc.compute_backoff(i, pol) for i in range(6)]
    faults.configure({"seed": 42, "rules": []})
    b = [rpc.compute_backoff(i, pol) for i in range(6)]
    assert a == b
    assert len(set(a)) > 1  # jitter actually jitters


def test_announce_backoff_schedule():
    w = WorkerServer(coordinator_uri="http://127.0.0.1:9")
    try:
        w._announce_interval = 0.5
        assert w._announce_backoff(0) == 0.5
        faults.configure({"seed": 11, "rules": []})
        a = [w._announce_backoff(i) for i in range(1, 9)]
        faults.configure({"seed": 11, "rules": []})
        assert a == [w._announce_backoff(i) for i in range(1, 9)]
        for i, d in enumerate(a, 1):
            cap = min(
                0.5 * 2 ** min(i, 6), WorkerServer.ANNOUNCE_MAX_BACKOFF_S
            )
            assert 0.5 <= d <= cap + 1e-9
    finally:
        w.httpd.server_close()


def test_announce_failures_counted_with_backoff():
    """A worker facing a dead coordinator keeps retrying, counts each
    failure, and backs off instead of hammering at the fixed cadence
    (seeded plane makes the delay sequence deterministic)."""
    faults.configure({"seed": 5, "rules": []})
    before = REGISTRY.counter("worker.announce_failures").total
    w = WorkerServer(
        coordinator_uri="http://127.0.0.1:9",
        config=NodeConfig(
            {
                "announcement.interval-s": "0.05",
                "announcement.timeout-s": "0.2",
            }
        ),
    )
    w.start()
    try:
        time.sleep(1.2)
    finally:
        w.shutdown(graceful=False)
    n = REGISTRY.counter("worker.announce_failures").total - before
    assert n >= 2  # it kept retrying
    assert n <= 15  # but backed off (fixed 0.05 s cadence would be ~24)


# ---------------------------------------------- circuit breaker (unit)


def test_circuit_breaker_cycle():
    b = rpc.CircuitBreaker(threshold=2, open_s=0.05)
    assert b.allow() and b.peek() == "CLOSED"
    b.record_failure()
    assert b.allow()  # below threshold
    assert b.record_failure()  # OPENs
    assert b.peek() == "OPEN" and not b.allow()
    time.sleep(0.06)
    assert b.allow()  # the half-open probe
    assert b.peek() == "HALF_OPEN"
    assert not b.allow()  # only ONE probe in flight
    b.record_success()
    assert b.peek() == "CLOSED" and b.allow()
    assert b.transitions == ["OPEN", "HALF_OPEN", "CLOSED"]


def test_circuit_breaker_probe_failure_reopens():
    b = rpc.CircuitBreaker(threshold=1, open_s=0.05)
    b.record_failure()
    assert b.peek() == "OPEN"
    time.sleep(0.06)
    assert b.allow()
    b.record_failure()  # probe failed
    assert b.peek() == "OPEN"
    assert b.transitions == ["OPEN", "HALF_OPEN", "OPEN"]


def test_circuit_breaker_success_resets_consecutive_count():
    b = rpc.CircuitBreaker(threshold=2, open_s=1.0)
    for _ in range(5):
        b.record_failure()
        b.record_success()
    assert b.peek() == "CLOSED"  # never opened: failures not consecutive


# ----------------------------------------------------- rpc-level retry


def test_rpc_retries_heal_error_burst(cluster):
    """Connection-level failures on idempotent calls retry with
    backoff and heal once the burst passes."""
    coord, _ = cluster
    faults.configure(
        {
            "seed": 3,
            "rules": [
                {"action": "error", "url": "/v1/cluster", "count": 2}
            ],
        }
    )
    before = REGISTRY.counter("rpc.retries").total
    out = rpc.call_json(
        "GET",
        coord.uri + "/v1/cluster",
        policy=rpc.RpcPolicy(
            retries=3, backoff_base_s=0.005, backoff_max_s=0.01
        ),
    )
    assert "workers" in out
    assert REGISTRY.counter("rpc.retries").total - before == 2


def test_rpc_post_never_retries(cluster):
    coord, _ = cluster
    faults.configure(
        {
            "seed": 3,
            "rules": [
                {"action": "drop", "url": "/v1/statement", "count": 1}
            ],
        }
    )
    with pytest.raises(faults.FaultInjectedError):
        rpc.call_json(
            "POST",
            coord.uri + "/v1/statement",
            policy=rpc.RpcPolicy(retries=5),
        )


# ------------------------------------------------------- chaos: kills


def test_chaos_kill_and_burst_with_breaker_cycle(oracle):
    """The acceptance chaos regression: one worker killed mid-execute
    plus an RPC error burst against a second worker; the TPC-H gather
    query still answers correctly, the failed attempts' TaskStats are
    visible in QueryInfo next to the successful retries, and the
    bursted worker's breaker walks OPEN -> HALF_OPEN -> CLOSED."""
    cfg = NodeConfig(
        {
            "rpc.retries": "1",
            "rpc.backoff-base-s": "0.01",
            "rpc.backoff-max-s": "0.05",
            "failure-detector.threshold": "2",
            "failure-detector.open-s": "0.3",
        }
    )
    coord = CoordinatorServer(config=cfg).start()
    ws = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(3)
    ]
    w_kill, w_burst = ws[1], ws[2]
    try:
        _wait_workers(coord, 3)
        faults.configure(
            {
                "seed": 7,
                "rules": [
                    {
                        "action": "kill_worker",
                        "node": w_kill.node_id,
                        "count": 1,
                    },
                    {
                        "action": "error",
                        "url": f":{w_burst.port}/",
                        "count": 8,
                    },
                ],
            }
        )
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        before = REGISTRY.counter("coordinator.tasks_retried").total
        diff = verify_query(client, oracle, QUERIES[6], rel_tol=1e-6)
        assert diff is None, f"chaos Q6 mismatch: {diff}"
        assert (
            REGISTRY.counter("coordinator.tasks_retried").total > before
        )
        # every scheduled attempt is accounted for: the kills/bursts
        # surface as FAILED TaskStats beside the successful retries
        qid = client.list_queries()[-1]["query_id"]
        info = client.query_info(qid)
        states = [
            t["state"] for st in info["stages"] for t in st["tasks"]
        ]
        breaker = coord.breakers[w_burst.node_id]
        # the burst OPENed the circuit (it may already have walked on
        # to HALF_OPEN — or even CLOSED, if the burst exhausted and a
        # probe succeeded while query 1 was still running)
        assert breaker.transitions[0] == "OPEN"
        # burst over: the half-open probe must re-admit the worker
        faults.configure(None)
        time.sleep(0.35)
        deadline = time.monotonic() + 20
        while (
            breaker.peek() != "CLOSED"
            and time.monotonic() < deadline
        ):
            client.execute("select count(*) c from tpch.tiny.nation")
            time.sleep(0.05)
        # the recorded cycle ends OPEN -> ... -> HALF_OPEN -> CLOSED
        assert breaker.transitions[-2:] == ["HALF_OPEN", "CLOSED"]
    finally:
        faults.configure(None)
        for w in ws:
            w.shutdown(graceful=False)
        coord.shutdown()


def test_kill_task_is_an_execution_error_not_retried():
    """A task that FAILS on a healthy worker is an execution error:
    it would fail anywhere, so the query fails instead of retrying."""
    coord = CoordinatorServer().start()
    w = WorkerServer(coordinator_uri=coord.uri).start()
    try:
        _wait_workers(coord, 1)
        faults.configure(
            {"rules": [{"action": "kill_task", "count": 1}]}
        )
        before = REGISTRY.counter("coordinator.tasks_retried").total
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        with pytest.raises(QueryFailed):
            client.execute("select count(*) c from tpch.tiny.lineitem")
        assert (
            REGISTRY.counter("coordinator.tasks_retried").total == before
        )
    finally:
        faults.configure(None)
        w.shutdown(graceful=False)
        coord.shutdown()


def test_retry_budget_exhaustion_fails_query():
    """task_retry_budget=0 disables reassignment: a killed worker
    fails the query even though a live spare exists (and local
    fallback must NOT mask it — workers are alive)."""
    coord = CoordinatorServer().start()
    ws = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    try:
        _wait_workers(coord, 2)
        coord.local.session.set("task_retry_budget", 0)
        faults.configure(
            {
                "rules": [
                    {
                        "action": "kill_worker",
                        "node": ws[1].node_id,
                        "count": 1,
                    }
                ]
            }
        )
        client = PrestoTpuClient(coord.uri, timeout_s=60)
        with pytest.raises(QueryFailed):
            client.execute("select count(*) c from tpch.tiny.lineitem")
    finally:
        coord.local.session.reset("task_retry_budget")
        faults.configure(None)
        for w in ws:
            w.shutdown(graceful=False)
        coord.shutdown()


# ------------------------------------------------- straggler speculation


def test_speculation_winner_loser_accounting(oracle):
    """A range whose pull stalls past the quantile threshold gets a
    backup attempt on another worker; the first result wins, the
    duplicate is aborted, and the backup is flagged speculative in the
    QueryInfo rollup."""
    coord = CoordinatorServer().start()
    ws = [
        WorkerServer(coordinator_uri=coord.uri).start() for _ in range(2)
    ]
    try:
        _wait_workers(coord, 2)
        coord.local.session.set("speculation_min_s", 0.3)
        coord.local.session.set("speculation_multiplier", 2.0)
        faults.configure(
            {
                "seed": 3,
                "rules": [
                    {
                        "action": "delay",
                        "method": "GET",
                        "url": f":{ws[1].port}/v1/task",
                        "delay_s": 3.0,
                        # pipelined pulls (rpc.pull-depth) keep 2
                        # requests in flight and ride out ONE slow
                        # response; a genuine straggler needs every
                        # in-flight pull + the stall-path status poll
                        # delayed
                        "count": 4,
                    }
                ],
            }
        )
        b_spec = REGISTRY.counter("coordinator.tasks_speculated").total
        b_wins = REGISTRY.counter("coordinator.speculation_wins").total
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        res = client.execute(
            "select count(*) as c from tpch.tiny.lineitem"
        )
        assert res.rows() == [(59997,)]
        assert (
            REGISTRY.counter("coordinator.tasks_speculated").total
            > b_spec
        )
        assert (
            REGISTRY.counter("coordinator.speculation_wins").total
            > b_wins
        )
        info = client.query_info(res.query_id)
        tasks = [t for st in info["stages"] for t in st["tasks"]]
        assert any(t.get("speculative") for t in tasks)
    finally:
        coord.local.session.reset("speculation_min_s")
        coord.local.session.reset("speculation_multiplier")
        faults.configure(None)
        for w in ws:
            w.shutdown(graceful=False)
        coord.shutdown()


# The lint wiring that lived here moved to tests/test_static_analysis.py
# (the one gate running every tools/analysis pass; the tools/check_*.py CLI
# this suite used to invoke is now a shim over the same framework).
