"""Serving-plane result reuse (server/result_cache.py + the
coordinator serving seam + the local_runner planning seam).

Contracts under test:

- ``result-cache.enabled=false`` (the default) is bit-exact pre-PR:
  zero cache consultation, scalar-shaped compile keys, empty
  ``result_cache`` status in QueryInfo, identical results.
- Hit/miss flow: a repeated statement answers from the cache with
  ZERO device dispatches, distinct hoisted literals mint distinct
  keys, and non-cacheable (system.runtime.*) scans are never stored.
- Invalidation: a legacy INSERT and a streaming-ingest commit both
  mark entries stale through the one audited write seam; a reader
  NEVER sees a pre-commit result beyond its session staleness bound.
- Stale-tolerant serving: within ``result_cache_max_staleness_s`` the
  stale entry serves (counted) while ONE background refresh
  re-executes and replaces it.
- MV-aware rewrite: every eligible aggregate shape answers
  bit-identically with ``mview_auto_rewrite`` on vs off, and the
  rewrite actually retargets the scan onto the maintained view.
- Microbatch interplay: the first concurrent round of a hot
  fingerprint executes once and populates; the second round is all
  hits with zero dispatches.
- Kill-coordinator chaos: a failed-over peer starts COLD — no stale
  entry ever crosses a coordinator boundary.
- Observability: result_cache.* metrics, the ``result.cache`` row of
  system.runtime.caches, the ``cached`` column of
  system.runtime.queries, the EXPLAIN ANALYZE line, the QueryInfo /
  JSONL event section (legacy fields intact), and the PR 6 follow-up:
  prepared-statement headers are absorbed once and re-encoded only
  when the map actually changed.
"""

import json
import socket
import threading
import time

import pytest

from presto_tpu import types as T
from presto_tpu.connectors import create_connector
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.server import result_cache as rc_mod
from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.server.result_cache import ResultCache
from presto_tpu.session import NodeConfig
from presto_tpu.sql import parse_statement
from presto_tpu.utils.metrics import REGISTRY
from presto_tpu.utils.telemetry import device_snapshot

POINT = (
    "select c_custkey, c_name, c_acctbal "
    "from tpch.tiny.customer where c_custkey = ?"
)
PREPARED = {"point": POINT}


def _mem_runner():
    """A runner with a fresh writable memory catalog beside tpch."""
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    mem = create_connector("memory")
    catalogs.register("mem", mem)
    return LocalQueryRunner(catalogs=catalogs), mem


def _events(runner, mem, name="ev"):
    mem.create_table(
        TableHandle("mem", "default", name),
        {"k": T.BIGINT, "v": T.BIGINT},
    )
    runner.execute(
        f"insert into mem.default.{name} values "
        "(1, 10), (1, 20), (2, 5), (3, 7)"
    )
    return TableHandle("mem", "default", name)


def _coord(enabled=True, **session):
    """An unstarted coordinator (local dispatch) with a writable
    memory catalog; the result cache toggles per test."""
    coord = CoordinatorServer()
    mem = create_connector("memory")
    coord.local.catalogs.register("mem", mem)
    if enabled:
        coord.local.session.set("enable_result_cache", True)
    for k, v in session.items():
        coord.local.session.set(k, v)
    return coord, mem


def _run(coord, sql, prepared=None):
    q = coord.submit(sql, prepared=dict(prepared or {}))
    assert q.done.wait(120)
    assert q.state == "FINISHED", q.error
    return q


def _submit_concurrent(coord, sqls, prepared=None):
    out = [None] * len(sqls)
    barrier = threading.Barrier(len(sqls))

    def run(i):
        barrier.wait(30)
        q = coord.submit(sqls[i], prepared=dict(prepared or {}))
        q.done.wait(180)
        out[i] = q

    threads = [
        threading.Thread(target=run, args=(i,))
        for i in range(len(sqls))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
    return out


# --------------------------------------------------------- off = legacy


def test_off_by_default_bit_exact():
    """Default config: the cache is never consulted, never populated;
    compile keys stay scalar-shaped; QueryInfo carries the (empty)
    additive section; results match a plain runner."""
    coord, _ = _coord(enabled=False)
    try:
        sql = "select count(*) as c from tpch.tiny.region"
        expected = [list(r) for r in LocalQueryRunner().execute(sql).rows()]
        h0 = REGISTRY.counter("result_cache.hits").total
        m0 = REGISTRY.counter("result_cache.misses").total
        for _ in range(2):
            q = _run(coord, sql)
            assert q.rows == [expected[0]]
            assert q.stats.result_cache == ""
            d = q.stats.to_dict()
            assert d["result_cache"] == {
                "status": "",
                "age_ms": 0.0,
                "snapshot": "",
                "mview_rewritten": "",
            }
        assert REGISTRY.counter("result_cache.hits").total == h0
        assert REGISTRY.counter("result_cache.misses").total == m0
        assert coord.result_cache.stats()["entries"] == 0
        for key in coord.local._compiled:
            assert len(key) == 4 and "batch" not in key
    finally:
        coord.shutdown()


# -------------------------------------------------------- hit/miss flow


def test_hit_zero_dispatch_and_distinct_literal_keys():
    coord, mem = _coord()
    try:
        _events(coord.local, mem)
        sql1 = "select sum(v) as s from mem.default.ev where k = 1"
        sql2 = "select sum(v) as s from mem.default.ev where k = 2"
        q1 = _run(coord, sql1)
        assert q1.stats.result_cache == "miss"
        assert q1.rows == [[30]]
        d0 = device_snapshot()["dispatches"]
        q2 = _run(coord, sql1)
        assert device_snapshot()["dispatches"] == d0, (
            "a result-cache hit must dispatch NOTHING"
        )
        assert q2.stats.result_cache == "hit"
        assert q2.stats.result_cache_age_ms >= 0.0
        assert q2.stats.result_cache_snapshot
        assert q2.rows == q1.rows
        assert q2.stats.output_rows == 1
        # same canonical shape, different hoisted literal: its OWN key
        q3 = _run(coord, sql2)
        assert q3.stats.result_cache == "miss"
        assert q3.rows == [[5]]
        st = coord.result_cache.stats()
        assert st["entries"] == 2
        assert st["hits"] == 1 and st["misses"] == 2
        assert st["bytes"] > 0
    finally:
        coord.shutdown()


def test_non_cacheable_system_scan_never_stored():
    coord, _ = _coord()
    try:
        sql = "select node_id from system.runtime.nodes"
        for _ in range(2):
            q = _run(coord, sql)
            assert q.stats.result_cache == "miss"
        assert coord.result_cache.stats()["entries"] == 0
    finally:
        coord.shutdown()


# --------------------------------------------------------- invalidation


def test_insert_invalidates_strict_session_never_stale():
    """Staleness bound 0 (the default): a write means the very next
    read re-executes and sees the post-write rows."""
    coord, mem = _coord()
    try:
        _events(coord.local, mem)
        sql = "select sum(v) as s from mem.default.ev"
        assert _run(coord, sql).rows == [[42]]
        assert _run(coord, sql).stats.result_cache == "hit"
        _run(coord, "insert into mem.default.ev values (9, 100)")
        q = _run(coord, sql)
        assert q.stats.result_cache == "miss"
        assert q.rows == [[142]]
    finally:
        coord.shutdown()


def test_ingest_commit_bounded_staleness_contract():
    """THE invalidation acceptance: an ingest commit lands mid-flight;
    a bounded-stale session may see the pre-commit result only within
    its bound, NEVER beyond it."""
    from presto_tpu.server.ingest import IngestManager

    coord, mem = _coord(result_cache_max_staleness_s=0.5)
    tmp = None
    try:
        import tempfile

        tmp = tempfile.mkdtemp(prefix="rc-wal-")
        _events(coord.local, mem)
        ing = IngestManager(coord.local, tmp, start_thread=False)
        sql = "select sum(v) as s from mem.default.ev"
        assert _run(coord, sql).rows == [[42]]  # populate
        ing.append("mem.default.ev", columns={"k": [5], "v": [58]})
        ing.commit_tick()  # fold: snapshot minted, fan-in fires
        q_stale = _run(coord, sql)
        assert q_stale.stats.result_cache == "stale"
        assert q_stale.rows == [[42]]  # bounded-stale pre-commit serve
        assert coord.result_cache.stats()["stale_served"] == 1
        time.sleep(0.6)  # past the bound
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            q = _run(coord, sql)
            assert q.rows == [[100]], (
                "pre-commit result served beyond the staleness bound"
            )
            if q.stats.result_cache == "hit":
                break  # the background refresh landed a fresh entry
            time.sleep(0.05)
        ing.close(final_flush=False)
    finally:
        coord.shutdown()


def test_stale_serve_spawns_one_refresh_then_hits_fresh():
    coord, mem = _coord(result_cache_max_staleness_s=30.0)
    try:
        _events(coord.local, mem)
        sql = "select sum(v) as s from mem.default.ev"
        _run(coord, sql)
        _run(coord, "insert into mem.default.ev values (4, 8)")
        q = _run(coord, sql)
        assert q.stats.result_cache == "stale"
        assert q.rows == [[42]]
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if coord.result_cache.stats()["refreshes"] == 1:
                break
            time.sleep(0.05)
        assert coord.result_cache.stats()["refreshes"] == 1
        q2 = _run(coord, sql)
        assert q2.stats.result_cache == "hit"
        assert q2.rows == [[50]]  # the refresh replaced the entry
    finally:
        coord.shutdown()


# ----------------------------------------------------- microbatch × cache


def test_microbatch_first_round_populates_second_all_hits():
    coord, _ = _coord()
    try:
        coord.local.session.set("microbatch_wait_ms", 200.0)
        coord.local.session.set("microbatch_max", 32)
        # warm plan/compile so round 1 isn't racing a cold XLA compile
        _run(coord, "execute point using 3", PREPARED)
        vals = [5, 118, 700, 42, 1499, 12]
        sqls = [f"execute point using {v}" for v in vals]
        qs1 = _submit_concurrent(coord, sqls, PREPARED)
        for q in qs1:
            assert q.state == "FINISHED", q.error
            assert q.stats.result_cache == "miss"
        st = coord.result_cache.stats()
        assert st["entries"] == len(vals) + 1
        d0 = device_snapshot()["dispatches"]
        b0 = REGISTRY.counter("serving.batches").total
        qs2 = _submit_concurrent(coord, sqls, PREPARED)
        assert device_snapshot()["dispatches"] == d0, (
            "the all-hit round must not touch the device"
        )
        assert REGISTRY.counter("serving.batches").total == b0
        for q1, q2 in zip(qs1, qs2):
            assert q2.state == "FINISHED", q2.error
            assert q2.stats.result_cache == "hit"
            assert q2.rows == q1.rows
    finally:
        coord.shutdown()


def test_hot_fingerprint_collapses_to_one_execution():
    """N concurrent clients of ONE fingerprint: the first round
    executes once (one batch), later statements answer from the
    cache."""
    coord, _ = _coord()
    try:
        coord.local.session.set("microbatch_wait_ms", 150.0)
        _run(coord, "execute point using 3", PREPARED)
        sqls = ["execute point using 77"] * 8
        qs = _submit_concurrent(coord, sqls, PREPARED)
        rows0 = qs[0].rows
        for q in qs:
            assert q.state == "FINISHED", q.error
            assert q.rows == rows0
        # one resident entry for the hot key (beside the warmup's)
        assert coord.result_cache.stats()["entries"] == 2
        d0 = device_snapshot()["dispatches"]
        qs2 = _submit_concurrent(coord, sqls, PREPARED)
        assert device_snapshot()["dispatches"] == d0
        assert all(q.stats.result_cache == "hit" for q in qs2)
    finally:
        coord.shutdown()


# ------------------------------------------------------- MV-aware rewrite


MV_SQL = (
    "create materialized view mem.default.mv as "
    "select k, sum(v) as sv, count(*) as c, min(v) as mn, "
    "max(v) as mx from mem.default.ev group by k"
)
ELIGIBLE_SHAPES = [
    "select k, sum(v) as sv, count(*) as c, min(v) as mn, max(v) as mx"
    " from mem.default.ev group by k",
    "select k, sum(v) as sv from mem.default.ev group by k",
    "select count(*) as c, k from mem.default.ev group by k",  # reorder
    "select k, max(v) from mem.default.ev group by k",  # unaliased
]
INELIGIBLE_SHAPES = [
    # filter the MV does not maintain
    "select k, sum(v) as sv from mem.default.ev where k > 1 group by k",
    # aggregate the MV does not maintain
    "select k, avg(v) as a from mem.default.ev group by k",
    # no grouping
    "select sum(v) as sv from mem.default.ev",
]


def test_mview_rewrite_bit_equality_every_shape():
    runner, mem = _mem_runner()
    _events(runner, mem)
    runner.execute(MV_SQL)
    runner.execute("refresh materialized view mem.default.mv")
    for sql in ELIGIBLE_SHAPES + INELIGIBLE_SHAPES:
        off = sorted(runner.execute(sql).rows())
        runner.session.set("mview_auto_rewrite", True)
        on = sorted(runner.execute(sql).rows())
        runner.session.set("mview_auto_rewrite", False)
        assert off == on, sql
    # the eligible shapes really retargeted: their plan-cache key is
    # the REWRITTEN statement scanning the view
    runner.session.set("mview_auto_rewrite", True)
    for sql in ELIGIBLE_SHAPES:
        _p, _h, key = runner.plan_cached_keyed(parse_statement(sql))
        assert "'mv'" in (key or ""), sql
    for sql in INELIGIBLE_SHAPES:
        _p, _h, key = runner.plan_cached_keyed(parse_statement(sql))
        assert "'mv'" not in (key or ""), sql


def test_mview_rewrite_staleness_gate_discipline():
    """A dirty/stale view only rewrites under an explicit read gate —
    a base-table reader never opts into staleness silently."""
    runner, mem = _mem_runner()
    _events(runner, mem)
    runner.execute(MV_SQL)
    runner.execute("refresh materialized view mem.default.mv")
    runner.session.set("mview_auto_rewrite", True)
    sql = "select k, sum(v) as sv from mem.default.ev group by k"
    _p, _h, key = runner.plan_cached_keyed(parse_statement(sql))
    assert "'mv'" in (key or "")
    runner.execute("insert into mem.default.ev values (8, 1)")
    # base epoch moved past the view state + no gate: NO rewrite, and
    # the reader sees the new row immediately
    _p, _h, key = runner.plan_cached_keyed(parse_statement(sql))
    assert "'mv'" not in (key or "")
    assert (8, 1) in {
        (k, s) for k, s in runner.execute(sql).rows()
    }


def test_mview_rewrite_surfaces_in_stats_via_coordinator():
    """Tier (b) composes with tier (a): the rewritten execution is
    attributed on the serving stats, and the result-cache entry keys
    on the ORIGINAL statement, so the repeat is a plain hit."""
    coord, mem = _coord()
    try:
        coord.local.session.set("mview_auto_rewrite", True)
        _events(coord.local, mem)
        coord.local.execute(MV_SQL)
        coord.local.execute("refresh materialized view mem.default.mv")
        sql = "select k, sum(v) as sv from mem.default.ev group by k"
        q = _run(coord, sql)
        assert q.stats.mview_rewritten == "mem.default.mv"
        assert sorted(q.rows) == [[1, 30], [2, 5], [3, 7]]
        q2 = _run(coord, sql)
        assert q2.stats.result_cache == "hit"
        assert sorted(q2.rows) == sorted(q.rows)
    finally:
        coord.shutdown()


# ----------------------------------------------------- kill-coordinator


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_kill_coordinator_failover_starts_cold(tmp_path):
    """Chaos: the rebooted/failed-over coordinator's result cache is
    COLD — cached results never survive a coordinator death, so a
    survivor can never serve a dead peer's stale entry."""
    from presto_tpu.utils import faults

    ctl = str(tmp_path / "ctl")
    ports = _free_ports(2)
    uris = [f"http://127.0.0.1:{p}" for p in ports]
    coords = []
    for i in range(2):
        cfg = NodeConfig(
            {
                "node.id": f"coord-{i}",
                "coordinator.journal-path": ctl,
                "coordinator.peers": ",".join(
                    u for j, u in enumerate(uris) if j != i
                ),
                "lease.ttl-s": "0.6",
                "result-cache.enabled": "true",
            }
        )
        coords.append(
            CoordinatorServer(port=ports[i], config=cfg).start()
        )
    c0, c1 = coords
    try:
        sql = "select count(*) as c from tpch.tiny.region"
        assert _run(c0, sql).stats.result_cache == "miss"
        assert _run(c0, sql).stats.result_cache == "hit"
        assert c0.result_cache.stats()["entries"] == 1
        c0._fault_kill()  # abrupt: the lease expires
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if c1.failover_claims >= 1:
                break
            time.sleep(0.05)
        assert c1.failover_claims >= 1
        # the survivor serves the same statement from a COLD cache
        assert c1.result_cache.stats()["entries"] == 0
        q = _run(c1, sql)
        assert q.stats.result_cache == "miss"
        assert q.rows == [[5]]
        assert _run(c1, sql).stats.result_cache == "hit"
    finally:
        faults.configure(None)
        for c in coords:
            try:
                c.shutdown()
            except Exception:
                pass


# -------------------------------------------------- eviction / budget


def test_lru_eviction_byte_budget_and_pool_mirror():
    from presto_tpu.plan import canonical
    from presto_tpu.utils.memory import MemoryPool

    runner, mem = _mem_runner()
    _events(runner, mem)
    pool = MemoryPool(1 << 20)
    rc = ResultCache(runner, budget_bytes=3000, pool=pool)

    def entry(i):
        stmt = parse_statement(
            f"select sum(v) as s from mem.default.ev where k = {i}"
        )
        key = rc_mod.statement_key(stmt, runner.session)
        plan, _h, _k = runner.plan_cached_keyed(stmt)
        res = runner.execute_plan(plan)
        handles = canonical.plan_handles(plan)
        return key, stmt, res, handles

    keys = []
    for i in range(12):
        key, stmt, res, handles = entry(i)
        assert rc.put(key, stmt, res.columns, res.rows(), handles)
        keys.append(key)
        assert rc.bytes <= rc.budget_bytes
        assert pool.used_bytes("result-cache") == rc.bytes
    st = rc.stats()
    assert st["evictions"] > 0
    assert st["entries"] < 12
    # LRU: the oldest resident was evicted, the newest survives
    assert rc.get(keys[0]) is None
    assert rc.get(keys[-1]) is not None
    rc.clear()
    assert rc.bytes == 0
    assert pool.used_bytes("result-cache") == 0


def test_oversized_entry_skipped_never_thrashes():
    runner, mem = _mem_runner()
    _events(runner, mem)
    rc = ResultCache(runner, budget_bytes=3000)
    stmt = parse_statement("select k, v from mem.default.ev")
    key = rc_mod.statement_key(stmt, runner.session)
    plan, _h, _k = runner.plan_cached_keyed(stmt)
    from presto_tpu.plan import canonical

    handles = canonical.plan_handles(plan)
    big = [[i, "x" * 64] for i in range(50)]  # > budget // 8
    assert not rc.put(key, stmt, ("k", "v"), big, handles)
    assert rc.stats()["entries"] == 0 and rc.bytes == 0


# ------------------------------------------------------- observability


def test_runtime_views_explain_and_jsonl_events(tmp_path):
    from presto_tpu.exec.explain import render_query_analyze
    from presto_tpu.exec.stats import JsonlQueryEventListener

    coord, mem = _coord()
    path = tmp_path / "events.jsonl"
    coord.local.history.add_listener(JsonlQueryEventListener(str(path)))
    try:
        _events(coord.local, mem)
        sql = "select sum(v) as s from mem.default.ev"
        _run(coord, sql)
        hit = _run(coord, sql)
        # system.runtime.caches: the result.cache row
        rows = coord.local.execute(
            "select cache, entries, hits, misses "
            "from system.runtime.caches"
        ).rows()
        by_name = {r[0]: r for r in rows}
        assert "result.cache" in by_name
        assert by_name["result.cache"][1] == 1  # one resident entry
        assert by_name["result.cache"][2] >= 1
        # system.runtime.queries: the cached column
        qrows = coord.local.execute(
            "select query_id, cached from system.runtime.queries"
        ).rows()
        cached = {qid for qid, c in qrows if c}
        assert hit.stats.query_id in cached
        # EXPLAIN ANALYZE line
        text = render_query_analyze(hit.stats)
        assert "result cache: HIT (snapshot" in text
        assert "age" in text
        # JSONL events: legacy fields intact + the additive section
        recs = [
            json.loads(line)
            for line in path.read_text().splitlines()
        ]
        for rec in recs:
            for field in (
                "event", "query_id", "state", "elapsed_ms",
                "planning_ms", "execution_ms", "input_rows",
                "output_rows", "operators", "stages",
                "peak_memory_bytes",
            ):
                assert field in rec, field
            assert set(rec["result_cache"]) == {
                "status", "age_ms", "snapshot", "mview_rewritten",
            }
        assert any(
            r["result_cache"]["status"] == "hit" for r in recs
        )
    finally:
        coord.shutdown()


def test_metrics_families_move():
    coord, mem = _coord()
    try:
        _events(coord.local, mem)
        h0 = REGISTRY.counter("result_cache.hits").total
        m0 = REGISTRY.counter("result_cache.misses").total
        b0 = REGISTRY.counter("result_cache.bytes").total
        sql = "select count(*) as c from mem.default.ev"
        _run(coord, sql)
        _run(coord, sql)
        assert REGISTRY.counter("result_cache.hits").total == h0 + 1
        assert REGISTRY.counter("result_cache.misses").total == m0 + 1
        assert REGISTRY.counter("result_cache.bytes").total > b0
    finally:
        coord.shutdown()


# ------------------------------------- PR 6 follow-up: header absorption


def test_prepared_header_absorbed_once_and_memoized():
    """EXECUTE must not re-serialize the full client prepared map per
    request: the server echoes X-Presto-Added-Prepare only on the
    first page of the PREPARE, and the client re-encodes its request
    header only when the map actually changed."""
    from presto_tpu.server import protocol
    from presto_tpu.server.client import PrestoTpuClient

    coord = CoordinatorServer().start()
    try:
        client = PrestoTpuClient(coord.uri, timeout_s=120)
        encodes = []
        real_encode = protocol.encode_prepared

        def counting_encode(name, text):
            encodes.append(name)
            return real_encode(name, text)

        protocol.encode_prepared = counting_encode
        try:
            client.execute(f"prepare point from {POINT}")
            assert client.prepared == {"point": POINT}
            # the server echoed the added statement exactly once (the
            # PREPARE's first page) — one server-side encode
            assert encodes.count("point") == 1
            for v in (3, 7, 11):
                rows = client.execute(f"execute point using {v}").rows()
                assert rows and rows[0][0] == v
            # plus ONE client-side encode when the map first changed:
            # the request header is memoized across every later
            # request, not re-serialized per EXECUTE
            hdr = client._prepared_header
            assert hdr is not None
            assert encodes.count("point") == 2
            before = list(encodes)
            client.execute("execute point using 42")
            # no re-encode for a warm map, and replayed echo headers
            # (the statement is already in the map verbatim) did not
            # dirty the memo
            assert encodes == before
            assert client._prepared_header is hdr
        finally:
            protocol.encode_prepared = real_encode
    finally:
        coord.shutdown()
