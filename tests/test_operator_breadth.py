"""Operator/function breadth (VERDICT r2 item 5): right/full outer
joins, navigation window functions (lag/lead/first_value/last_value/
ntile), stddev/variance aggregates, scalar math functions.

Joins and navigation windows verify against the sqlite oracle (sqlite
3.39+ has FULL JOIN and the full window set); stddev/variance verify
against numpy (sqlite has no stdev) plus the tpu_offload cross-backend
diff (SURVEY.md §4.7)."""

import math

import numpy as np
import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.verifier import SqliteOracle, verify_query, verify_offload


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def oracle():
    return SqliteOracle("tiny")


# ------------------------------------------------------------- outer joins

#: two subqueries with partial key overlap: [1,10] vs [5,15] customers
_FULL_JOIN = """
select c.ck, o.oc, o.n
from (select c_custkey as ck from tpch.tiny.customer where c_custkey <= 10) c
full join (select o_custkey as oc, count(*) as n from tpch.tiny.orders
           where o_custkey between 5 and 15 group by o_custkey) o
  on c.ck = o.oc
order by c.ck nulls last, o.oc nulls last
"""

_RIGHT_JOIN = """
select c.ck, o.oc, o.n
from (select c_custkey as ck from tpch.tiny.customer where c_custkey <= 10) c
right join (select o_custkey as oc, count(*) as n from tpch.tiny.orders
            where o_custkey between 5 and 15 group by o_custkey) o
  on c.ck = o.oc
order by o.oc
"""


def test_full_outer_join(runner, oracle):
    diff = verify_query(runner, oracle, _FULL_JOIN)
    assert diff is None, diff
    rows = runner.execute(_FULL_JOIN).rows()
    # both preserved sides must actually appear
    assert any(r[0] is not None and r[1] is None for r in rows), rows
    assert any(r[0] is None and r[1] is not None for r in rows), rows
    assert any(r[0] is not None and r[1] is not None for r in rows), rows


def test_right_outer_join(runner, oracle):
    diff = verify_query(runner, oracle, _RIGHT_JOIN)
    assert diff is None, diff


def test_full_join_duplicate_build_keys(runner, oracle):
    # non-unique build side exercises the expansion + append path
    sql = """
    select a.k, b.v
    from (select n_regionkey as k from tpch.tiny.nation
          where n_nationkey < 5) a
    full join (select r_regionkey as v from tpch.tiny.region) b
      on a.k = b.v
    order by a.k nulls last, b.v nulls last
    """
    diff = verify_query(runner, oracle, sql)
    assert diff is None, diff


# ------------------------------------------------- navigation window funcs

_NAV_WINDOW = """
select o_orderkey,
  lag(o_totalprice) over (partition by o_custkey order by o_orderdate,
                          o_orderkey) as prev_price,
  lead(o_totalprice, 2) over (partition by o_custkey order by o_orderdate,
                              o_orderkey) as next2,
  first_value(o_orderkey) over (partition by o_custkey order by
                                o_orderdate, o_orderkey) as first_ok,
  ntile(4) over (partition by o_orderpriority order by o_totalprice,
                 o_orderkey) as quartile
from tpch.tiny.orders
where o_custkey <= 100
order by o_orderkey
"""


def test_navigation_windows(runner, oracle):
    diff = verify_query(runner, oracle, _NAV_WINDOW)
    assert diff is None, diff


def test_lag_default(runner, oracle):
    sql = """
    select o_orderkey,
      lag(o_shippriority, 1, -1) over (partition by o_custkey
        order by o_orderdate, o_orderkey) as p
    from tpch.tiny.orders where o_custkey <= 50
    order by o_orderkey
    """
    diff = verify_query(runner, oracle, sql)
    assert diff is None, diff
    rows = runner.execute(sql).rows()
    assert any(r[1] == -1 for r in rows)  # default engaged


def test_last_value_frame(runner, oracle):
    # default RANGE frame: last_value = value at the last PEER row
    sql = """
    select o_orderkey,
      last_value(o_orderkey) over (partition by o_custkey
        order by o_orderdate) as lv
    from tpch.tiny.orders where o_custkey <= 50
    order by o_orderkey
    """
    diff = verify_query(runner, oracle, sql)
    assert diff is None, diff


# --------------------------------------------------- stddev / variance

def test_stddev_variance_global(runner):
    sql = """
    select stddev(o_totalprice) as sd, stddev_pop(o_totalprice) as sdp,
           variance(o_totalprice) as v, var_pop(o_totalprice) as vp
    from tpch.tiny.orders
    """
    (sd, sdp, v, vp), = runner.execute(sql).rows()
    x = np.array(
        [r[0] for r in runner.execute(
            "select o_totalprice from tpch.tiny.orders"
        ).rows()]
    )
    assert math.isclose(v, x.var(ddof=1), rel_tol=1e-9)
    assert math.isclose(vp, x.var(ddof=0), rel_tol=1e-9)
    assert math.isclose(sd, x.std(ddof=1), rel_tol=1e-9)
    assert math.isclose(sdp, x.std(ddof=0), rel_tol=1e-9)


def test_stddev_grouped(runner):
    sql = """
    select o_orderpriority as p, var_samp(o_totalprice) as v, count(*) as n
    from tpch.tiny.orders group by o_orderpriority order by p
    """
    rows = runner.execute(sql).rows()
    base = runner.execute(
        "select o_orderpriority, o_totalprice from tpch.tiny.orders"
    ).rows()
    for p, v, n in rows:
        x = np.array([tp for pp, tp in base if pp == p])
        assert len(x) == n
        assert math.isclose(v, x.var(ddof=1), rel_tol=1e-9), p


def test_stddev_offload_diff():
    assert verify_offload(
        "select o_orderpriority as p, stddev(o_totalprice) as sd "
        "from tpch.tiny.orders group by o_orderpriority order by p"
    ) is None


def test_stddev_distributed():
    import jax

    from presto_tpu.parallel import DistributedQueryRunner

    assert len(jax.devices()) == 8
    d = DistributedQueryRunner(
        broadcast_threshold=1 << 11, repl_threshold=1 << 10
    )
    local = LocalQueryRunner()
    sql = (
        "select o_orderpriority as p, stddev(o_totalprice) as sd, "
        "var_pop(o_totalprice) as vp from tpch.tiny.orders "
        "group by o_orderpriority order by p"
    )
    a = d.execute(sql).rows()
    b = local.execute(sql).rows()
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra[0] == rb[0]
        assert math.isclose(ra[1], rb[1], rel_tol=1e-6)
        assert math.isclose(ra[2], rb[2], rel_tol=1e-6)


# ----------------------------------------------------- scalar math funcs

def test_math_functions(runner):
    rows = runner.execute(
        "select sqrt(o_totalprice) as s, abs(0 - o_shippriority) as a, "
        "ln(o_totalprice) as l, floor(o_totalprice) as f, "
        "ceiling(o_totalprice) as c "
        "from tpch.tiny.orders where o_orderkey = 1"
    ).rows()
    base = runner.execute(
        "select o_totalprice from tpch.tiny.orders where o_orderkey = 1"
    ).rows()
    tp = base[0][0]
    s, a, l, f, c = rows[0]
    assert math.isclose(s, math.sqrt(tp), rel_tol=1e-9)
    assert a == 0
    assert math.isclose(l, math.log(tp), rel_tol=1e-9)
    assert f == math.floor(tp) and c == math.ceil(tp)


def test_sqrt_negative_is_null(runner):
    rows = runner.execute(
        "select sqrt(0 - o_totalprice) as s from tpch.tiny.orders "
        "where o_orderkey = 1"
    ).rows()
    assert rows[0][0] is None


# --------------------------------------------------- general cross join


def test_general_cross_join(runner, oracle):
    """Multi-row CROSS JOIN takes the nested-loop expansion kernel
    (VERDICT r3 missing 10: was a single-row-build planner error)."""
    q = (
        "select n.n_name, r.r_name from tpch.tiny.nation n "
        "cross join tpch.tiny.region r "
        "order by n.n_name, r.r_name"
    )
    diff = verify_query(runner, oracle, q)
    assert diff is None, diff
    rows = runner.execute(q).rows()
    assert len(rows) == 25 * 5


def test_implicit_cross_join_with_filter(runner, oracle):
    """Comma-join with a non-equi conjunct: cross join + residual
    filter, oracle-exact."""
    q = (
        "select count(*) as c from tpch.tiny.nation a, "
        "tpch.tiny.nation b where a.n_nationkey < b.n_nationkey"
    )
    diff = verify_query(runner, oracle, q)
    assert diff is None, diff


# ------------------------------------------- composite-key packed joins


def test_multi_key_join_packs_bijectively(runner, oracle):
    """A 4-column equi-join packs into ONE synthetic bigint key when
    stats bound every column's range (no residual demotion, no
    out_capacity skew risk) — and stays oracle-exact."""
    from presto_tpu.plan import nodes as PN
    from presto_tpu.plan.planner import plan_statement
    from presto_tpu.sql import parse_statement

    q = (
        "select count(*) as c from tpch.tiny.lineitem a, "
        "tpch.tiny.lineitem b "
        "where a.l_orderkey = b.l_orderkey "
        "and a.l_partkey = b.l_partkey "
        "and a.l_suppkey = b.l_suppkey "
        "and a.l_linenumber = b.l_linenumber"
    )
    plan = plan_statement(
        parse_statement(q), runner.catalogs, runner.session
    )
    joins = [
        n for n in PN.walk(plan.root) if isinstance(n, PN.JoinNode)
    ]
    assert len(joins) == 1
    assert len(joins[0].left_keys) == 1  # packed, not demoted
    assert joins[0].left_keys[0].startswith("$pack")
    assert joins[0].residual is None
    diff = verify_query(runner, oracle, q)
    assert diff is None, diff
