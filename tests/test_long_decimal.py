"""Long decimal (p>18): int128 limb-pair representation
(types.LongDecimalType, presto_tpu.int128). Exactness is asserted
against Python's arbitrary-precision ints/Decimals — sqlite cannot hold
int128, so the oracle here is the host language itself."""

import decimal

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.page import Page
from presto_tpu.plan.planner import PlanningError


def test_decimal_factory_routes_long():
    t = T.decimal(25, 2)
    assert t.is_long_decimal and t.is_decimal and t.precision == 25
    s = T.decimal(18, 2)
    assert not s.is_long_decimal
    assert T.parse_type("decimal(30,4)").is_long_decimal


def test_int128_limbs_roundtrip():
    vals = [
        0, 1, -1, (1 << 64), -(1 << 64), (1 << 100) + 12345,
        -(1 << 100) - 999, (1 << 126), -(1 << 126),
        12345678901234567890123456789,
    ]
    limbs = T.int128_limbs(vals)
    assert limbs.shape == (len(vals), 2)
    back = [T.int128_value(h, l) for h, l in limbs]
    assert back == vals


def test_int128_device_ops_match_python():
    from presto_tpu import int128
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    a = [int(x) for x in rng.randint(-(1 << 62), 1 << 62, 40)]
    b = [int(x) for x in rng.randint(-(1 << 62), 1 << 62, 40)]
    # spread across the 128-bit range
    a = [x * ((1 << 50) + 7) for x in a]
    b = [x * ((1 << 33) + 11) for x in b]
    la, lb = T.int128_limbs(a), T.int128_limbs(b)
    ah, al = jnp.asarray(la[:, 0]), jnp.asarray(la[:, 1])
    bh, bl = jnp.asarray(lb[:, 0]), jnp.asarray(lb[:, 1])
    sh, sl = int128.add(ah, al, bh, bl)
    assert [
        T.int128_value(int(h), int(l)) for h, l in zip(sh, sl)
    ] == [x + y for x, y in zip(a, b)]
    dh, dl = int128.sub(ah, al, bh, bl)
    assert [
        T.int128_value(int(h), int(l)) for h, l in zip(dh, dl)
    ] == [x - y for x, y in zip(a, b)]
    nh, nl = int128.neg(ah, al)
    assert [
        T.int128_value(int(h), int(l)) for h, l in zip(nh, nl)
    ] == [-x for x in a]
    assert list(map(bool, int128.lt(ah, al, bh, bl))) == [
        x < y for x, y in zip(a, b)
    ]
    # a <= ~2^112; x4 decimal digits stays inside int128
    mh, ml = int128.mul_pow10(ah, al, 4)
    assert [
        T.int128_value(int(h), int(l)) for h, l in zip(mh, ml)
    ] == [x * 10 ** 4 for x in a]


def test_int128_div_pow10_half_up():
    from presto_tpu import int128
    import jax.numpy as jnp

    vals = [
        0, 1, 5, -5, 12345, -12345, (1 << 100) + 987654321,
        -(1 << 100) - 987654321, 10 ** 30 + 5 * 10 ** 11,
        -(10 ** 30) - 5 * 10 ** 11, 15, 25, -15, -25, 449, 450, -450,
    ]
    limbs = T.int128_limbs(vals)
    h, l = jnp.asarray(limbs[:, 0]), jnp.asarray(limbs[:, 1])
    for k in (1, 2, 9, 12, 18):
        qh, ql = int128.div_pow10_half_up(h, l, k)
        got = [T.int128_value(int(a), int(b)) for a, b in zip(qh, ql)]
        f = 10 ** k
        expect = [
            (abs(v) + f // 2) // f * (1 if v >= 0 else -1) for v in vals
        ]
        assert got == expect, (k, got, expect)


def test_cast_downscale_and_to_bigint(runner):
    rows = runner.execute(
        "select cast(cast(123.456 as decimal(30,6)) as decimal(10,2)) "
        "as a, cast(cast(987654321.987 as decimal(25,3)) as bigint) as b, "
        "cast(cast(-2.5 as decimal(20,1)) as bigint) as c"
    ).rows()
    # half-up away from zero, matching the engine's ingest rounding
    assert rows == [(123.46, 987654322, -3)]


def test_page_roundtrip_exact():
    t = T.decimal(30, 2)
    vals = [
        decimal.Decimal("123456789012345678901234567.89"),
        decimal.Decimal("-99999999999999999999.99"),
        None,
        decimal.Decimal("0.01"),
    ]
    p = Page.from_pydict({"x": vals}, {"x": t}, capacity=8)
    out = [r["x"] for r in p.to_pylist()]
    assert out == vals


@pytest.fixture(scope="module")
def lake(tmp_path_factory):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    root = tmp_path_factory.mktemp("ldlake")
    (root / "s").mkdir()
    n = 3000
    rng = np.random.RandomState(17)
    # values straddling the int64 boundary: |v| up to ~10^27
    base = rng.randint(-(1 << 62), 1 << 62, n)
    # |unscaled| < 2^62 * 2^33 = 2^95 ~ 4e28, inside decimal(30)
    mult = rng.choice([1, 1 << 20, (1 << 33) + 3], n)
    unscaled = [int(x) * int(m) for x, m in zip(base, mult)]
    vals = [decimal.Decimal(u).scaleb(-3) for u in unscaled]
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "amt": pa.array(vals, type=pa.decimal128(30, 3)),
        }
    )
    pq.write_table(table, root / "s" / "t.parquet")
    return root, vals


@pytest.fixture(scope="module")
def runner(lake):
    from presto_tpu.connectors import create_connector
    from presto_tpu.exec.staging import CatalogManager

    root, _ = lake
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("lake", create_connector("parquet", root=str(root)))
    return LocalQueryRunner(catalogs=catalogs)


def test_scan_count_and_filter(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select count(*) as n from lake.s.t where amt > 0"
    ).rows()
    assert rows == [(sum(1 for v in vals if v > 0),)]
    # comparison against a >int64 decimal literal
    big = decimal.Decimal(1 << 70).scaleb(-3)
    rows = runner.execute(
        "select count(*) as n from lake.s.t "
        f"where amt > {big}"
    ).rows()
    assert rows == [(sum(1 for v in vals if v > big),)]


def test_projection_exact_roundtrip(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, amt from lake.s.t where id < 50"
    ).rows()
    got = {i: a for i, a in rows}
    for i in range(50):
        assert got[i] == vals[i], i


def test_arithmetic_exact(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, amt + amt as dbl, amt - amt as zero, -amt as neg "
        "from lake.s.t where id < 20"
    ).rows()
    for i, dbl, zero, neg in rows:
        assert dbl == vals[i] * 2
        assert zero == 0
        assert neg == -vals[i]


def test_literal_arithmetic_exact(runner):
    rows = runner.execute(
        "select 12345678901234567890.12 + 98765432109876543210.88 as s"
    ).rows()
    assert rows[0][0] == decimal.Decimal("111111111011111111101.00")


def test_cast_to_double_approx(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, cast(amt as double) as d from lake.s.t where id < 10"
    ).rows()
    for i, d in rows:
        expect = float(vals[i])
        assert d == pytest.approx(expect, rel=1e-12)


def test_cast_short_to_long_and_back(runner):
    rows = runner.execute(
        "select cast(cast(12345.67 as decimal(30,4)) as double) as d"
    ).rows()
    assert rows[0][0] == pytest.approx(12345.67)


def test_documented_gates(runner):
    """Remaining long-decimal gates: accumulators and membership tests
    (semi/anti keys cannot residual-verify the 128->64 key mix)."""
    for sql in [
        "select sum(amt) from lake.s.t",
        "select id from lake.s.t where amt in "
        "(select amt from lake.s.t where id < 5)",
    ]:
        with pytest.raises(Exception) as ei:
            runner.execute(sql).rows()
        assert "long" in str(ei.value).lower(), sql


def test_group_by_long_decimal_exact(runner, lake):
    """GROUP BY decimal(30,3): limb-pair key lanes (ops.common.key_lanes)
    — every distinct int128 value is its own group, exactly."""
    _, vals = lake
    rows = runner.execute(
        "select amt, count(*) as n from lake.s.t group by amt"
    ).rows()
    import collections

    expect = collections.Counter(vals)
    got = {a: n for a, n in rows}
    assert len(got) == len(expect)
    assert got == dict(expect)


def test_group_by_long_decimal_with_nulls(runner):
    t = T.decimal(25, 2)
    vals = [
        decimal.Decimal("123456789012345678901.01"),
        None,
        decimal.Decimal("123456789012345678901.01"),
        decimal.Decimal("-0.02"),
        None,
        None,
    ]
    from presto_tpu.connectors import create_connector
    from presto_tpu.connectors.spi import TableHandle

    mem = create_connector("memory")
    runner.catalogs.register("ldmem", mem)
    h = TableHandle("ldmem", "s", "g")
    mem.create_table(h, {"x": t})
    mem.append_rows(h, {"x": np.asarray(vals, dtype=object)})
    rows = runner.execute(
        "select x, count(*) as n from ldmem.s.g group by x"
    ).rows()
    got = dict(rows)
    assert got == {
        decimal.Decimal("123456789012345678901.01"): 2,
        decimal.Decimal("-0.02"): 1,
        None: 3,
    }


def test_order_by_long_decimal_exact(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, amt from lake.s.t order by amt desc, id limit 50"
    ).rows()
    expect = sorted(
        enumerate(vals), key=lambda p: (-p[1], p[0])
    )[:50]
    assert [(i, a) for i, a in rows] == expect


def test_distinct_long_decimal(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select distinct amt from lake.s.t where id < 100"
    ).rows()
    assert sorted(r[0] for r in rows) == sorted(set(vals[:100]))


def test_inner_join_on_long_decimal(runner, lake):
    """Inner equi-join on decimal(30,3): kernel key is the 128->64 mix
    with a residual limb-equality filter (plan/planner.py ld_pairs) —
    exact regardless of mix collisions."""
    _, vals = lake
    rows = runner.execute(
        "select a.id, b.id from lake.s.t a, lake.s.t b "
        "where a.amt = b.amt and a.id < 30 and b.id < 30"
    ).rows()
    expect = sorted(
        (i, j)
        for i in range(30)
        for j in range(30)
        if vals[i] == vals[j]
    )
    assert sorted(rows) == expect


def test_long_plus_double_is_double(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, amt + 0.5e0 as s from lake.s.t where id < 5"
    ).rows()
    for i, s in rows:
        assert s == pytest.approx(float(vals[i]) + 0.5, rel=1e-12)


def test_case_over_long_decimal(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select id, case when id < 2 then amt else -amt end as v "
        "from lake.s.t where id < 4"
    ).rows()
    for i, v in rows:
        assert v == (vals[i] if i < 2 else -vals[i])


def test_unnest_page_with_long_decimal_column(runner, lake):
    """Row expansion must repeat (cap, 2) limb blocks row-wise."""
    _, vals = lake
    rows = runner.execute(
        "select id, amt, m from lake.s.t "
        "cross join unnest(array[1, 2]) as u(m) where id < 3"
    ).rows()
    assert len(rows) == 6
    for i, a, m in rows:
        assert a == vals[i], (i, m)


def test_join_on_long_decimal_count(runner, lake):
    _, vals = lake
    rows = runner.execute(
        "select count(*) as n from lake.s.t a, lake.s.t b "
        "where a.amt = b.amt"
    ).rows()
    import collections

    cnt = collections.Counter(vals)
    assert rows == [(sum(c * c for c in cnt.values()),)]


def test_element_at_negative_index(runner):
    rows = runner.execute(
        "select element_at(array[10, 20, 30], -1) as a, "
        "array[10, 20, 30][-2] as b, "
        "element_at(array[10, 20], -5) as c"
    ).rows()
    assert rows == [(30, 20, None)]


def test_element_at_negative_column_index(runner):
    rows = runner.execute(
        "select r_regionkey, "
        "element_at(array[100, 200], r_regionkey - 3) as e "
        "from tpch.tiny.region order by r_regionkey"
    ).rows()
    # keys 0..4 -> indices -3,-2,-1,0,1 -> NULL,100,200,NULL,100
    assert rows == [
        (0, None), (1, 100), (2, 200), (3, None), (4, 100),
    ]


def test_aggregate_after_cast_down(runner, lake):
    """The documented workaround: cast to double to aggregate."""
    _, vals = lake
    rows = runner.execute(
        "select sum(cast(amt as double)) as s from lake.s.t"
    ).rows()
    assert rows[0][0] == pytest.approx(float(sum(vals)), rel=1e-9)
