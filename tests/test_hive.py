"""Hive-style connector (SURVEY.md §2.2 production connectors): a table
is a partitioned directory of parquet files; key=value path components
are virtual columns; files map into one global row space so splits stay
format-agnostic."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from presto_tpu.connectors import create_connector  # noqa: E402
from presto_tpu.connectors.spi import TableHandle  # noqa: E402
from presto_tpu.exec.local_runner import LocalQueryRunner  # noqa: E402
from presto_tpu.exec.staging import CatalogManager  # noqa: E402


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    root = tmp_path_factory.mktemp("warehouse")
    rng = np.random.RandomState(23)
    rows = []  # (region, year, id, amount, tag)
    i = 0
    for region in ("east", "west"):
        for year in (2023, 2024):
            d = root / "sales" / "orders" / f"region={region}" / f"year={year}"
            d.mkdir(parents=True)
            # two files per partition: multi-file global row space
            for fidx in range(2):
                n = int(rng.randint(50, 150))
                ids = np.arange(i, i + n, dtype=np.int64)
                i += n
                amt = rng.randint(1, 1000, n).astype(np.int64)
                tag = rng.choice(["a", "b", "c"], n)
                pq.write_table(
                    pa.table(
                        {
                            "id": pa.array(ids),
                            "amount": pa.array(amt),
                            "tag": pa.array(tag.tolist()),
                        }
                    ),
                    d / f"part-{fidx}.parquet",
                    row_group_size=64,
                )
                rows += [
                    (region, year, int(a), int(b), str(c))
                    for a, b, c in zip(ids, amt, tag)
                ]
    return root, rows


@pytest.fixture(scope="module")
def runner(warehouse):
    root, _ = warehouse
    catalogs = CatalogManager()
    catalogs.register("tpch", create_connector("tpch"))
    catalogs.register("hive", create_connector("hive", root=str(root)))
    return LocalQueryRunner(catalogs=catalogs)


def test_schema_includes_partition_keys(warehouse):
    root, _ = warehouse
    conn = create_connector("hive", root=str(root))
    md = conn.metadata()
    assert md.list_schemas() == ["sales"]
    assert md.list_tables("sales") == ["orders"]
    schema = md.get_table_schema(TableHandle("hive", "sales", "orders"))
    assert schema["region"].is_string
    assert schema["year"].name == "bigint"  # all values parse as ints
    assert schema["id"].name == "bigint"
    st = md.get_table_stats(TableHandle("hive", "sales", "orders"))
    assert st.row_count == len(warehouse[1])


def test_full_scan_counts(runner, warehouse):
    _, rows = warehouse
    got = runner.execute(
        "select count(*) as n, sum(amount) as s from hive.sales.orders"
    ).rows()
    assert got == [(len(rows), sum(r[3] for r in rows))]


def test_group_by_partition_column(runner, warehouse):
    _, rows = warehouse
    got = runner.execute(
        "select region, year, count(*) as n, sum(amount) as s "
        "from hive.sales.orders group by region, year "
        "order by region, year"
    ).rows()
    import collections

    expect = collections.defaultdict(lambda: [0, 0])
    for region, year, _id, amt, _tag in rows:
        e = expect[(region, year)]
        e[0] += 1
        e[1] += amt
    assert got == [
        (r, y, n, s)
        for (r, y), (n, s) in sorted(expect.items())
    ]


def test_filter_on_partition_column(runner, warehouse):
    _, rows = warehouse
    got = runner.execute(
        "select count(*) as n from hive.sales.orders "
        "where region = 'east' and year = 2024"
    ).rows()
    expect = sum(1 for r in rows if r[0] == "east" and r[1] == 2024)
    assert got == [(expect,)]


def test_string_column_across_files(runner, warehouse):
    """tag dictionaries differ per file: the shared-dictionary re-encode
    must keep values exact across the whole table."""
    _, rows = warehouse
    got = runner.execute(
        "select tag, count(*) as n from hive.sales.orders "
        "group by tag order by tag"
    ).rows()
    import collections

    expect = collections.Counter(r[4] for r in rows)
    assert got == sorted(expect.items())


def test_split_ranges_align_to_files(warehouse):
    root, rows = warehouse
    conn = create_connector("hive", root=str(root))
    h = TableHandle("hive", "sales", "orders")
    src = conn.get_splits(h, target_split_rows=64)
    splits = []
    while not src.exhausted:
        splits.extend(src.next_batch(64))
    assert splits[0].row_start == 0
    assert splits[-1].row_end == len(rows)
    for a, b in zip(splits, splits[1:]):
        assert a.row_end == b.row_start


def test_join_with_tpch(runner, warehouse):
    _, rows = warehouse
    got = runner.execute(
        "select r_name, count(*) as n from "
        "(select amount % 5 as k from hive.sales.orders) t, "
        "tpch.tiny.region where k = r_regionkey "
        "group by r_name order by r_name"
    ).rows()
    assert sum(n for _, n in got) == len(rows)


def test_partition_pruning_skips_files(runner, warehouse, monkeypatch):
    """`where region = 'east' and year = 2024` must open only that
    partition's files (TupleDomain-lite pushdown into get_splits) and
    still be exact."""
    from presto_tpu.connectors import hive as hive_mod

    _, rows = warehouse
    opened = []
    orig = hive_mod.HiveConnector._append_file_range

    def spy(self, f, lo, hi, columns, schema, part_types, out):
        opened.append(f.keys.copy())
        return orig(self, f, lo, hi, columns, schema, part_types, out)

    monkeypatch.setattr(
        hive_mod.HiveConnector, "_append_file_range", spy
    )
    got = runner.execute(
        "select count(*) as n, sum(amount) as s from hive.sales.orders "
        "where region = 'east' and year = 2024"
    ).rows()
    expect = [
        (
            sum(1 for r in rows if r[0] == "east" and r[1] == 2024),
            sum(r[3] for r in rows if r[0] == "east" and r[1] == 2024),
        )
    ]
    assert got == expect
    assert opened, "no files read at all?"
    assert all(
        k == {"region": "east", "year": "2024"} for k in opened
    ), f"pruning leaked partitions: {opened}"


def test_pruned_page_not_cached_for_unconstrained_scan(runner, warehouse):
    """The table cache must key on the constraint: a full scan after a
    pruned scan sees ALL partitions."""
    _, rows = warehouse
    runner.execute(
        "select count(*) as n from hive.sales.orders "
        "where region = 'west' and year = 2023"
    ).rows()
    got = runner.execute(
        "select count(*) as n from hive.sales.orders"
    ).rows()
    assert got == [(len(rows),)]


def test_in_list_pruning(runner, warehouse):
    _, rows = warehouse
    got = runner.execute(
        "select count(*) as n from hive.sales.orders "
        "where region in ('west', 'north')"
    ).rows()
    assert got == [(sum(1 for r in rows if r[0] == "west"),)]


def test_decimal_scale_evolution_across_files(tmp_path):
    """Schema evolution: a later file storing the decimal at a finer
    scale must normalize to the table schema (derived from the first
    file) — the raw-buffer read keeps the as_py-era rescale."""
    import decimal

    d = tmp_path / "s" / "t"
    d.mkdir(parents=True)
    pq.write_table(
        pa.table(
            {
                "v": pa.array(
                    [decimal.Decimal("1.25")], type=pa.decimal128(12, 2)
                )
            }
        ),
        d / "a.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "v": pa.array(
                    [decimal.Decimal("2.375")],
                    type=pa.decimal128(12, 3),
                )
            }
        ),
        d / "b.parquet",
    )
    catalogs = CatalogManager()
    catalogs.register("hive", create_connector("hive", root=str(tmp_path)))
    r = LocalQueryRunner(catalogs=catalogs)
    rows = r.execute("select sum(v) as s from hive.s.t").rows()
    # 1.25 + round_half_up(2.375 -> 2.38) at scale 2
    assert rows[0][0] == pytest.approx(3.63)


def test_merge_column_chunks_unit():
    """Split payload merging: differing dictionaries union + remap,
    masked and unmasked chunks mix, same-dictionary fast path holds
    (the latent multi-split bug fixed alongside the hive connector)."""
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec.staging import MaskedColumn, merge_column_chunks

    a = DictColumn(
        ids=np.array([0, 1], np.int32),
        values=np.asarray(["x", "y"], object),
    )
    b = DictColumn(
        ids=np.array([0, 1], np.int32),
        values=np.asarray(["a", "x"], object),
    )
    m = merge_column_chunks([a, b])
    vals = [str(m.values[i]) for i in m.ids]
    assert vals == ["x", "y", "a", "x"]
    # masked + dict mix
    c = MaskedColumn(
        data=np.array([0, 0], np.int32),
        valid=np.array([True, False]),
        values=("zz",),
    )
    m2 = merge_column_chunks([a, c])
    assert [str(m2.values[i]) for i in m2.data] == ["x", "y", "zz", "zz"]
    assert list(m2.valid) == [True, True, True, False]
    # numeric masked + plain
    m3 = merge_column_chunks(
        [
            np.array([1, 2], np.int64),
            MaskedColumn(
                data=np.array([3, 0], np.int64),
                valid=np.array([True, False]),
            ),
        ]
    )
    assert list(m3.data) == [1, 2, 3, 0]
    assert list(m3.valid) == [True, True, True, False]
    # same-dictionary fast path keeps values identical
    m4 = merge_column_chunks(
        [a, DictColumn(ids=np.array([1], np.int32), values=a.values)]
    )
    assert [str(m4.values[i]) for i in m4.ids] == ["x", "y", "y"]


def test_metastore_declares_key_types(tmp_path):
    """metastore.json at the root declares partition-key types (the
    reference's Hive Metastore as a file): a zero-padded numeric-ish
    key stays VARCHAR when declared, and a DATE key materializes as a
    real date column — neither is reachable by inference."""
    import json

    import pyarrow as pa
    import pyarrow.parquet as pq

    from presto_tpu import types as T
    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.exec.staging import CatalogManager
    from presto_tpu.exec.local_runner import LocalQueryRunner

    root = tmp_path / "wh"
    for code, day, vals in (
        ("001", "2024-01-01", [1, 2]),
        ("002", "2024-02-01", [3]),
    ):
        d = root / "sales" / "events" / f"code={code}" / f"day={day}"
        d.mkdir(parents=True)
        pq.write_table(
            pa.table({"v": pa.array(vals, pa.int64())}),
            d / "part-0.parquet",
        )
    (root / "metastore.json").write_text(json.dumps({
        "schemas": {"sales": {"events": {
            "partition_keys": {"code": "varchar", "day": "date"},
        }}},
    }))
    conn = create_connector("hive", root=str(root))
    schema = conn.metadata().get_table_schema(
        TableHandle("hive", "sales", "events")
    )
    assert schema["code"] == T.VARCHAR
    assert schema["day"].name == "date"

    catalogs = CatalogManager()
    catalogs.register("hive", conn)
    r = LocalQueryRunner(catalogs=catalogs)
    rows = r.execute(
        "select code, day, sum(v) as s from hive.sales.events "
        "group by code, day order by code"
    ).rows()
    import datetime

    assert rows == [
        ("001", datetime.date(2024, 1, 1), 3),
        ("002", datetime.date(2024, 2, 1), 3),
    ]
    # date-key predicate: correct rows despite no enumeration pruning
    assert r.execute(
        "select sum(v) as s from hive.sales.events "
        "where day = date '2024-02-01'"
    ).rows() == [(3,)]


def test_metastore_layout_mismatch_fails(tmp_path):
    import json

    import pyarrow as pa
    import pyarrow.parquet as pq

    from presto_tpu.connectors.spi import TableHandle

    root = tmp_path / "wh"
    d = root / "s" / "t" / "region=east"
    d.mkdir(parents=True)
    pq.write_table(
        pa.table({"v": pa.array([1], pa.int64())}), d / "p.parquet"
    )
    (root / "metastore.json").write_text(json.dumps({
        "schemas": {"s": {"t": {
            "partition_keys": {"zone": "varchar"},
        }}},
    }))
    conn = create_connector("hive", root=str(root))
    with pytest.raises(ValueError, match="metastore declares"):
        conn.metadata().get_table_schema(TableHandle("hive", "s", "t"))
