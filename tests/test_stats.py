"""Stats tree / EXPLAIN ANALYZE / system catalog / metrics registry.

Reference parity: QueryStats rollup + EXPLAIN ANALYZE inline stats
(SURVEY.md §5.1), system.runtime tables + jmx-style metrics (§5.5).
"""

import pytest

from presto_tpu.exec.local_runner import LocalQueryRunner
from presto_tpu.utils.metrics import (
    CounterStat,
    DistributionStat,
    MetricsRegistry,
    REGISTRY,
    TimeStat,
)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def test_query_history_records_stats(runner):
    res = runner.execute(
        "select count(*) as c from tpch.tiny.region"
    )
    assert res.rows() == [(5,)]
    hist = runner.history.snapshot()
    q = [h for h in hist if "region" in h.sql][-1]
    assert q.state == "FINISHED"
    assert q.output_rows == 1
    assert q.input_rows == 5
    assert q.planning_ms > 0
    assert q.execution_ms > 0
    assert q.error is None


def test_query_history_records_failure(runner):
    with pytest.raises(Exception):
        runner.execute("select * from tpch.tiny.nonexistent_table")
    q = runner.history.snapshot()[-1]
    assert q.state == "FAILED"
    assert q.error


def test_explain_analyze_row_counts(runner):
    res = runner.execute(
        "explain analyze select l_returnflag, count(*) c "
        "from tpch.tiny.lineitem group by l_returnflag"
    )
    text = "\n".join(r[0] for r in res.rows())
    assert "Aggregate" in text
    assert "[rows: 3" in text  # 3 distinct return flags
    assert "TableScan" in text
    assert "EXPLAIN ANALYZE:" in text


def test_explain_analyze_repeat_keeps_annotations(runner):
    """Second run hits the compiled-program cache; row annotations must
    survive (regression: node-identity keyed stats went stale)."""
    sql = (
        "explain analyze select l_linestatus, count(*) c "
        "from tpch.tiny.lineitem group by l_linestatus"
    )
    runner.execute(sql)
    text = "\n".join(r[0] for r in runner.execute(sql).rows())
    assert "[rows: 2" in text


def test_explain_analyze_host_root_stage_annotated(runner):
    text = "\n".join(
        r[0]
        for r in runner.execute(
            "explain analyze select n_name from tpch.tiny.nation "
            "order by n_name limit 3"
        ).rows()
    )
    assert "host root stage" in text
    assert "[rows: 3, host root stage]" in text


def test_system_runtime_queries(runner):
    runner.execute("select count(*) as c from tpch.tiny.nation")
    res = runner.execute(
        "select query_id, state, output_rows from system.runtime.queries "
        "where state = 'FINISHED'"
    )
    rows = res.rows()
    assert len(rows) >= 1
    assert all(r[1] == "FINISHED" for r in rows)


def test_system_tables_are_live_not_cached(runner):
    n1 = runner.execute(
        "select count(*) as c from system.runtime.queries"
    ).rows()[0][0]
    runner.execute("select count(*) as c from tpch.tiny.nation")
    n2 = runner.execute(
        "select count(*) as c from system.runtime.queries"
    ).rows()[0][0]
    assert n2 > n1  # new queries visible: pages must not be cached


def test_repeat_query_still_reports_input_rows(runner):
    runner.execute("select count(*) as c from tpch.tiny.region")
    runner.execute("select count(*) as c from tpch.tiny.region")
    q = [h for h in runner.history.snapshot() if "region" in h.sql][-1]
    assert q.input_rows == 5  # cache hit must still attribute input


def test_system_runtime_nodes(runner):
    rows = runner.execute(
        "select node_id, coordinator from system.runtime.nodes"
    ).rows()
    assert len(rows) == 1
    assert rows[0][1] is True


def test_system_metadata_catalogs(runner):
    rows = runner.execute(
        "select catalog_name from system.metadata.catalogs"
    ).rows()
    names = {r[0] for r in rows}
    assert {"tpch", "system"} <= names


def test_system_runtime_metrics_sqlable(runner):
    runner.execute("select count(*) as c from tpch.tiny.region")
    rows = runner.execute(
        "select name, value from system.runtime.metrics "
        "where name = 'queries.finished.total'"
    ).rows()
    assert len(rows) == 1
    assert rows[0][1] >= 1.0


def test_metrics_registry_primitives():
    reg = MetricsRegistry()
    reg.counter("c").update(3)
    reg.counter("c").update()
    assert reg.counter("c").total == 4
    d = reg.distribution("d")
    for v in (1.0, 2.0, 3.0):
        d.add(v)
    assert d.values()["mean"] == 2.0
    with reg.timer("t").time():
        pass
    assert reg.timer("t").count == 1
    text = reg.render_prometheus()
    assert "presto_tpu_c_total 4.0" in text
    with pytest.raises(TypeError):
        reg.timer("c")


def test_registry_is_process_wide():
    REGISTRY.counter("test.probe").update()
    assert any(
        n.startswith("test.probe") for n, _, _ in REGISTRY.snapshot()
    )
